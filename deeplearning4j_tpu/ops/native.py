"""ctypes bindings for the native host-runtime library.

TPU-native equivalent of the reference's JNI seam to libnd4j host ops
(SURVEY.md §2.8 item 1): gradient wire codec (thresholdEncode/bitmapEncode —
``EncodingHandler.java:136-178``), IDX parsing, CSV parsing. Pure-numpy
fallbacks keep everything working when the library isn't built; ``make -C
native`` produces ``libdl4jtpu.so`` beside this module and the fast paths
activate automatically.
"""
from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

_LIB_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "libdl4jtpu.so")
_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        return None
    lib = ctypes.CDLL(_LIB_PATH)
    i8p = ctypes.POINTER(ctypes.c_int8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f32p = ctypes.POINTER(ctypes.c_float)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)

    lib.threshold_encode_f32.restype = ctypes.c_int64
    lib.threshold_encode_f32.argtypes = [f32p, ctypes.c_int64, ctypes.c_float,
                                         i32p, i8p, f32p]
    lib.threshold_decode_f32.restype = None
    lib.threshold_decode_f32.argtypes = [i32p, i8p, ctypes.c_int64,
                                         ctypes.c_float, f32p, ctypes.c_int64]
    lib.bitmap_encode_f32.restype = ctypes.c_int64
    lib.bitmap_encode_f32.argtypes = [f32p, ctypes.c_int64, ctypes.c_float,
                                      u32p, f32p]
    lib.bitmap_decode_f32.restype = None
    lib.bitmap_decode_f32.argtypes = [u32p, ctypes.c_int64, ctypes.c_float,
                                      f32p]
    lib.idx_read_header.restype = ctypes.c_int
    lib.idx_read_header.argtypes = [ctypes.c_char_p, i32p, i32p, i64p]
    lib.idx_read_u8.restype = ctypes.c_int
    lib.idx_read_u8.argtypes = [ctypes.c_char_p, u8p, ctypes.c_int64]
    lib.csv_parse_f32.restype = ctypes.c_int64
    lib.csv_parse_f32.argtypes = [ctypes.c_char_p, ctypes.c_char,
                                  ctypes.c_int64, f32p, ctypes.c_int64, i64p]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def _ptr(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


# ------------------------------------------------------------ gradient codec
def threshold_encode(grad: np.ndarray, threshold: float
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(indices, signs, residual) — native when built, numpy fallback."""
    g = np.ascontiguousarray(grad, np.float32).ravel()
    lib = _load()
    if lib is None:
        idx = np.flatnonzero(np.abs(g) >= threshold).astype(np.int32)
        signs = np.sign(g[idx]).astype(np.int8)
        residual = g.copy()
        residual[idx] -= signs.astype(np.float32) * threshold
        return idx, signs, residual.reshape(grad.shape)
    idx = np.empty(g.size, np.int32)
    signs = np.empty(g.size, np.int8)
    residual = np.empty(g.size, np.float32)
    k = lib.threshold_encode_f32(_ptr(g, ctypes.c_float), g.size,
                                 ctypes.c_float(threshold),
                                 _ptr(idx, ctypes.c_int32),
                                 _ptr(signs, ctypes.c_int8),
                                 _ptr(residual, ctypes.c_float))
    return idx[:k].copy(), signs[:k].copy(), residual.reshape(grad.shape)


def threshold_decode(idx: np.ndarray, signs: np.ndarray, threshold: float,
                     shape) -> np.ndarray:
    n = int(np.prod(shape))
    lib = _load()
    if lib is None:
        out = np.zeros(n, np.float32)
        out[idx] = signs.astype(np.float32) * threshold
        return out.reshape(shape)
    idx = np.ascontiguousarray(idx, np.int32)
    signs = np.ascontiguousarray(signs, np.int8)
    out = np.empty(n, np.float32)
    lib.threshold_decode_f32(_ptr(idx, ctypes.c_int32),
                             _ptr(signs, ctypes.c_int8), idx.size,
                             ctypes.c_float(threshold),
                             _ptr(out, ctypes.c_float), n)
    return out.reshape(shape)


def bitmap_encode(grad: np.ndarray, threshold: float
                  ) -> Tuple[np.ndarray, int, np.ndarray]:
    """(bitmap u32 words, nonzero count, residual) — 2 bits/element wire
    format (reference bitmapEncode)."""
    g = np.ascontiguousarray(grad, np.float32).ravel()
    words = (g.size + 15) // 16
    lib = _load()
    if lib is None:
        bitmap = np.zeros(words, np.uint32)
        residual = g.copy()
        pos = g >= threshold
        neg = g <= -threshold
        codes = np.where(pos, 1, np.where(neg, 2, 0)).astype(np.uint32)
        residual[pos] -= threshold
        residual[neg] += threshold
        for i in np.flatnonzero(codes):
            bitmap[i // 16] |= codes[i] << ((i % 16) * 2)
        return bitmap, int(pos.sum() + neg.sum()), residual.reshape(grad.shape)
    bitmap = np.empty(words, np.uint32)
    residual = np.empty(g.size, np.float32)
    k = lib.bitmap_encode_f32(_ptr(g, ctypes.c_float), g.size,
                              ctypes.c_float(threshold),
                              _ptr(bitmap, ctypes.c_uint32),
                              _ptr(residual, ctypes.c_float))
    return bitmap, int(k), residual.reshape(grad.shape)


def bitmap_decode(bitmap: np.ndarray, n: int, threshold: float) -> np.ndarray:
    lib = _load()
    if lib is None:
        out = np.zeros(n, np.float32)
        for i in range(n):
            code = (int(bitmap[i // 16]) >> ((i % 16) * 2)) & 3
            out[i] = threshold if code == 1 else (-threshold if code == 2
                                                  else 0.0)
        return out
    bitmap = np.ascontiguousarray(bitmap, np.uint32)
    out = np.empty(n, np.float32)
    lib.bitmap_decode_f32(_ptr(bitmap, ctypes.c_uint32), n,
                          ctypes.c_float(threshold),
                          _ptr(out, ctypes.c_float))
    return out


# ------------------------------------------------------------------- parsers
def idx_read(path: str) -> Optional[np.ndarray]:
    """Native IDX read for uncompressed u8 files; None → caller should use
    the Python parser (gz files, other dtypes)."""
    lib = _load()
    if lib is None or path.endswith(".gz"):
        return None
    dtype_code = ctypes.c_int32()
    ndim = ctypes.c_int32()
    dims = (ctypes.c_int64 * 8)()
    rc = lib.idx_read_header(path.encode(), ctypes.byref(dtype_code),
                             ctypes.byref(ndim), dims)
    if rc != 0 or dtype_code.value != 0x08:
        return None
    shape = tuple(dims[i] for i in range(ndim.value))
    n = int(np.prod(shape))
    out = np.empty(n, np.uint8)
    if lib.idx_read_u8(path.encode(), _ptr(out, ctypes.c_uint8), n) != 0:
        return None
    return out.reshape(shape)


def csv_read_f32(path: str, delimiter: str = ",",
                 skip_lines: int = 0) -> Optional[np.ndarray]:
    """Native float CSV parse → [rows, cols] array; None when the library is
    absent or the file has non-numeric fields."""
    lib = _load()
    if lib is None:
        return None
    cols = ctypes.c_int64()
    rows = lib.csv_parse_f32(path.encode(), ctypes.c_char(delimiter.encode()),
                             skip_lines, None, 0, ctypes.byref(cols))
    if rows < 0:
        return None
    out = np.empty(rows * cols.value, np.float32)
    rows2 = lib.csv_parse_f32(path.encode(), ctypes.c_char(delimiter.encode()),
                              skip_lines, _ptr(out, ctypes.c_float), out.size,
                              ctypes.byref(cols))
    if rows2 != rows:
        return None
    return out.reshape(rows, cols.value)
