"""Fused two-layer persistent LSTM kernel — one grid step per U timesteps
of BOTH stacked layers.

Why: a stack of two LSTMs (the char-RNN headline config, reference
``GravesLSTM`` twice) otherwise runs as two *sequential* persistent-kernel
chains (``ops/lstm_cell.py``) with the inter-layer activation doing a full
HBM round trip: layer 1 writes ys1 [T, b, H], a hoisted gemm turns it into
layer 2's xp2 [T, b, 4H] (another write + read). The measured bound at the
char-RNN shape is per-grid-step latency x chain length (PERF.md round-5:
unroll saturates at U=2, ~580k chars/s = 7.5% of the HBM roofline), so
halving the chain and deleting the xp2 stream attacks both terms at once:
one grid step computes layer-1 cell -> layer-2 cell back-to-back with
h1 handed over in registers, all three weight matrices (RW1, W2, RW2 — and
their transposes in the backward) VMEM-resident.

The cell math here is the UNMASKED core of ``lstm_cell._fwd_kernel`` /
``_bwd_kernel`` (tanh/sigmoid, Graves peepholes); step masks route pairs to
the per-layer kernels instead (``supported2`` returns False) — masked
batches are padding-dominated anyway, and keeping this kernel mask-free
keeps its VMEM budget honest. Backward is the same hand-written BPTT with
the extra inter-layer term: dh1_t += dz2_t @ W2^T. Parity for BOTH passes
is pinned against the composition of two ``lstm_cell.lstm_scan`` calls
(tests/test_lstm_fused.py), which are themselves pinned against the
``lax.scan`` oracle.

Reference: ``CudnnLSTMHelper.java`` (persistent RNN promise) — realized
here across the layer boundary, which cuDNN never fused.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention import _vspec, _scratch, _interpret
from .lstm_cell import _sig, _stream_dtype, _unroll_factor

__all__ = ["lstm_scan2", "supported2"]


def _vmem_fits2(b: int, H: int, weight_bytes: int, u: int = 1) -> bool:
    """Budget for the fused pair: THREE resident [H, 4H] matrices (RW1, W2,
    RW2; the backward holds their transposes instead) plus ~1.7x the
    single-kernel streamed-block footprint (xp1 in; ys/gates/cseq reserves
    for BOTH layers + dz1/dz2 out) -> 12*H^2*wb + 50*sb*u*b*H bytes under
    the same 12 MB cap as ``lstm_cell._vmem_fits`` (VMEM is ~16 MB/core;
    the slack absorbs double-buffering + scratch). At the char-RNN shape
    (b=64, H=512, bf16 weights) this admits the fusion only under bf16
    streams — exactly the pairing the stream-dtype policy exists for."""
    sb = jnp.dtype(_stream_dtype()).itemsize
    return 12 * H * H * weight_bytes + 50 * sb * u * b * H <= 12 * 2 ** 20


def _unroll2(T: int, b: int, H: int, weight_bytes: int) -> int:
    """Same cap/decrement rule as ``lstm_cell._unroll_factor`` but against
    the fused budget."""
    u = _unroll_factor(T, b, H, weight_bytes)   # honors DL4J_TPU_LSTM_UNROLL
    while u > 1 and (T % u or not _vmem_fits2(b, H, weight_bytes, u)):
        u -= 1
    return u


def _cell_fwd(z, c, H, pi, pf, po):
    """Unmasked LSTM cell from pre-activations z [b, 4H] (f32): returns
    (h_new, c_new, gates [b, 4H] as i|f|o|g). Peephole terms apply when
    pi/pf/po are not None (Graves variant, lstm_cell._fwd_kernel:114)."""
    zi, zf, zo, zg = (z[:, :H], z[:, H:2 * H], z[:, 2 * H:3 * H],
                      z[:, 3 * H:])
    if pi is not None:
        zi = zi + c * pi[None, :]
        zf = zf + c * pf[None, :]
    i = _sig(zi)
    f = _sig(zf)
    g = jnp.tanh(zg)
    c_new = f * c + i * g
    if po is not None:
        zo = zo + c_new * po[None, :]
    o = _sig(zo)
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new, jnp.concatenate([i, f, o, g], axis=-1)


def _cell_bwd(dh_tot, dc_tot, gts, c_out, c_prev, H, pi, pf, po):
    """Unmasked LSTM cell BPTT (lstm_cell._bwd_kernel core): returns
    (dz [b, 4H], dc_prev, (dzi, dzf, dzo)) — the dz* tuple feeds the
    peephole-gradient accumulators."""
    i, f, o, g = (gts[:, :H], gts[:, H:2 * H], gts[:, 2 * H:3 * H],
                  gts[:, 3 * H:])
    tc = jnp.tanh(c_out)
    do = dh_tot * tc
    dzo = do * o * (1.0 - o)
    dc = dc_tot + dh_tot * o * (1.0 - tc * tc)
    if po is not None:
        dc = dc + dzo * po[None, :]
    di = dc * g
    df = dc * c_prev
    dg = dc * i
    dzi = di * i * (1.0 - i)
    dzf = df * f * (1.0 - f)
    dzg = dg * (1.0 - g * g)
    dc_prev = dc * f
    if pi is not None:
        dc_prev = dc_prev + dzi * pi[None, :] + dzf * pf[None, :]
    return (jnp.concatenate([dzi, dzf, dzo, dzg], axis=-1), dc_prev,
            (dzi, dzf, dzo))


# ------------------------------------------------------------------ forward
def _fwd2_kernel(xp_ref, rw1_ref, w2_ref, b2_ref, rw2_ref, peep_ref,
                 h0_ref, ys1_ref, ys2_ref, g1_ref, c1_ref, g2_ref, c2_ref,
                 hc_ref, h1_s, c1_s, h2_s, c2_s, *, nb, H, peep, U, save):
    """One grid step: U timesteps of BOTH layers. ``h0_ref`` packs the four
    initial states [4, b, H] (h01, c01, h02, c02); ``peep_ref`` packs both
    layers' peepholes [8, H] (rows 0-2 layer 1, rows 3-5 layer 2);
    ``b2_ref`` is layer 2's bias broadcast row [8, 4H] (row 0)."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h1_s[:] = h0_ref[0].astype(jnp.float32)
        c1_s[:] = h0_ref[1].astype(jnp.float32)
        h2_s[:] = h0_ref[2].astype(jnp.float32)
        c2_s[:] = h0_ref[3].astype(jnp.float32)

    h1, c1, h2, c2 = h1_s[:], c1_s[:], h2_s[:], c2_s[:]
    rw1 = rw1_ref[...]            # resident, source (bf16-policy) dtype
    w2 = w2_ref[...]
    rw2 = rw2_ref[...]
    b2 = b2_ref[0].astype(jnp.float32)                    # [4H]
    if peep:
        p1 = tuple(peep_ref[r].astype(jnp.float32) for r in range(3))
        p2 = tuple(peep_ref[r].astype(jnp.float32) for r in range(3, 6))
    else:
        p1 = p2 = (None, None, None)
    for u in range(U):
        z1 = xp_ref[u].astype(jnp.float32) + jax.lax.dot_general(
            h1.astype(rw1.dtype), rw1, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        h1, c1, gts1 = _cell_fwd(z1, c1, H, *p1)
        # the inter-layer handoff: h1 stays in registers — no ys1->xp2 HBM
        # round trip, no second sequential pass
        z2 = (b2[None, :]
              + jax.lax.dot_general(h1.astype(w2.dtype), w2,
                                    (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
              + jax.lax.dot_general(h2.astype(rw2.dtype), rw2,
                                    (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32))
        h2, c2, gts2 = _cell_fwd(z2, c2, H, *p2)
        ys2_ref[u] = h2.astype(ys2_ref.dtype)
        if save:
            # ys1 is a training residual (dW2 = ys1^T dz2, dRW1 h_prev) —
            # the inference primal never writes it (no dead HBM stream)
            ys1_ref[u] = h1.astype(ys1_ref.dtype)
            g1_ref[u] = gts1.astype(g1_ref.dtype)
            c1_ref[u] = c1.astype(c1_ref.dtype)
            g2_ref[u] = gts2.astype(g2_ref.dtype)
            c2_ref[u] = c2.astype(c2_ref.dtype)
    h1_s[:], c1_s[:], h2_s[:], c2_s[:] = h1, c1, h2, c2

    @pl.when(t == nb - 1)
    def _():
        hc_ref[0] = h1.astype(hc_ref.dtype)
        hc_ref[1] = c1.astype(hc_ref.dtype)
        hc_ref[2] = h2.astype(hc_ref.dtype)
        hc_ref[3] = c2.astype(hc_ref.dtype)


def _fwd2(xp, rw1, w2, b2, rw2, peep, h0, save_reserve=True):
    """xp: [T, b, 4H] (layer-1 input projection + bias), rw1/w2/rw2:
    [H, 4H], b2: [8, 4H] (row 0 = layer-2 bias), peep: [8, H] or None,
    h0: [4, b, H] -> (ys1, ys2 [T, b, H], reserves g1/c1/g2/c2, hcT
    [4, b, H]); ``save_reserve=False`` omits the four reserve outputs."""
    T, b, H4 = xp.shape
    H = H4 // 4
    U = _unroll2(T, b, H, jnp.dtype(rw1.dtype).itemsize)
    nb = T // U
    kern = functools.partial(_fwd2_kernel, nb=nb, H=H,
                             peep=peep is not None, U=U, save=save_reserve)
    stream = lambda t: (t, 0, 0)
    const2 = lambda t: (0, 0)
    const3 = lambda t: (0, 0, 0)
    specs = [
        _vspec((U, b, H4), stream),                       # xp (streamed)
        _vspec((H, H4), const2),                          # RW1 (resident)
        _vspec((H, H4), const2),                          # W2 (resident)
        _vspec((8, H4), const2),                          # b2 row
        _vspec((H, H4), const2),                          # RW2 (resident)
    ]
    ops = [xp, rw1, w2, b2, rw2]
    if peep is not None:
        specs.append(_vspec((8, H), const2))
        ops.append(peep)
    specs.append(_vspec((4, b, H), const3))               # h0 pack
    ops.append(h0)

    def shim(*refs):
        n_in = 5 + int(peep is not None) + 1
        ins, rest = refs[:n_in], refs[n_in:]
        peep_ref = ins[5] if peep is not None else None
        h0_ref = ins[-1]
        if save_reserve:
            (ys1_ref, ys2_ref, g1_ref, c1_ref, g2_ref, c2_ref, hc_ref,
             h1_s, c1_s, h2_s, c2_s) = rest
        else:
            (ys2_ref, hc_ref, h1_s, c1_s, h2_s, c2_s) = rest
            ys1_ref = g1_ref = c1_ref = g2_ref = c2_ref = None
        return kern(ins[0], ins[1], ins[2], ins[3], ins[4], peep_ref,
                    h0_ref, ys1_ref, ys2_ref, g1_ref, c1_ref, g2_ref,
                    c2_ref, hc_ref, h1_s, c1_s, h2_s, c2_s)

    sd = _stream_dtype()
    out_specs = []
    out_shape = []
    if save_reserve:
        out_specs += [_vspec((U, b, H), stream)]          # ys1 (residual)
        out_shape += [jax.ShapeDtypeStruct((T, b, H), sd)]
    out_specs += [_vspec((U, b, H), stream)]              # ys2
    out_shape += [jax.ShapeDtypeStruct((T, b, H), sd)]
    if save_reserve:
        out_specs += [_vspec((U, b, H4), stream),         # gates1
                      _vspec((U, b, H), stream),          # cseq1
                      _vspec((U, b, H4), stream),         # gates2
                      _vspec((U, b, H), stream)]          # cseq2
        out_shape += [jax.ShapeDtypeStruct((T, b, H4), sd),
                      jax.ShapeDtypeStruct((T, b, H), sd),
                      jax.ShapeDtypeStruct((T, b, H4), sd),
                      jax.ShapeDtypeStruct((T, b, H), sd)]
    out_specs.append(_vspec((4, b, H), const3))           # final states
    out_shape.append(jax.ShapeDtypeStruct((4, b, H), jnp.float32))
    res = pl.pallas_call(
        shim,
        grid=(nb,),
        in_specs=specs,
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        scratch_shapes=[_scratch((b, H))] * 4,
        interpret=_interpret(),
    )(*ops)
    if save_reserve:
        return res
    ys2, hc = res
    return None, ys2, None, None, None, None, hc


# ----------------------------------------------------------------- backward
def _bwd2_kernel(dy_ref, g1_ref, c1_ref, c1p_ref, g2_ref, c2_ref, c2p_ref,
                 rw1t_ref, w2t_ref, rw2t_ref, peep_ref, c0_ref, dhcT_ref,
                 dz1_ref, dz2_ref, dhc0_ref, dpeep_ref,
                 dh1_s, dc1_s, dh2_s, dc2_s, dp_s, *, nb, H, peep, U):
    """Reverse BPTT for the fused pair, U timesteps per grid step walked
    u = U-1..0. ``c0_ref`` packs (c01, c02) [2, b, H] for the sequence
    start; ``dhcT_ref`` packs the four incoming state cotangents
    [4, b, H]; ``c1p_ref``/``c2p_ref`` stream the previous block's last c
    row (lstm_cell._bwd_kernel's clamped-stream trick, per layer)."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        dh1_s[:] = dhcT_ref[0].astype(jnp.float32)
        dc1_s[:] = dhcT_ref[1].astype(jnp.float32)
        dh2_s[:] = dhcT_ref[2].astype(jnp.float32)
        dc2_s[:] = dhcT_ref[3].astype(jnp.float32)
        if peep:
            dp_s[:] = jnp.zeros_like(dp_s)

    rt_is_first = t == nb - 1
    rw1t = rw1t_ref[...]
    w2t = w2t_ref[...]
    rw2t = rw2t_ref[...]
    if peep:
        p1 = tuple(peep_ref[r].astype(jnp.float32) for r in range(3))
        p2 = tuple(peep_ref[r].astype(jnp.float32) for r in range(3, 6))
    else:
        p1 = p2 = (None, None, None)
    dh1, dc1 = dh1_s[:], dc1_s[:]
    dh2, dc2 = dh2_s[:], dc2_s[:]
    for u in reversed(range(U)):
        g1 = g1_ref[u].astype(jnp.float32)
        g2 = g2_ref[u].astype(jnp.float32)
        c1o = c1_ref[u].astype(jnp.float32)
        c2o = c2_ref[u].astype(jnp.float32)
        if u > 0:
            c1prev = c1_ref[u - 1].astype(jnp.float32)
            c2prev = c2_ref[u - 1].astype(jnp.float32)
        else:
            c1prev = jnp.where(rt_is_first, c0_ref[0].astype(jnp.float32),
                               c1p_ref[0].astype(jnp.float32))
            c2prev = jnp.where(rt_is_first, c0_ref[1].astype(jnp.float32),
                               c2p_ref[0].astype(jnp.float32))
        # layer 2 first (it owns the incoming dy), then its dz feeds
        # layer 1 through W2^T — the reverse of the forward handoff
        dh2_tot = dy_ref[u].astype(jnp.float32) + dh2
        dz2, dc2, dpz2 = _cell_bwd(dh2_tot, dc2, g2, c2o, c2prev, H, *p2)
        dh2 = jax.lax.dot_general(dz2.astype(rw2t.dtype), rw2t,
                                  (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        dh1_tot = dh1 + jax.lax.dot_general(
            dz2.astype(w2t.dtype), w2t, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dz1, dc1, dpz1 = _cell_bwd(dh1_tot, dc1, g1, c1o, c1prev, H, *p1)
        dh1 = jax.lax.dot_general(dz1.astype(rw1t.dtype), rw1t,
                                  (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        if peep:
            dp_s[0] = dp_s[0] + jnp.sum(dpz1[0] * c1prev, axis=0)
            dp_s[1] = dp_s[1] + jnp.sum(dpz1[1] * c1prev, axis=0)
            dp_s[2] = dp_s[2] + jnp.sum(dpz1[2] * c1o, axis=0)
            dp_s[3] = dp_s[3] + jnp.sum(dpz2[0] * c2prev, axis=0)
            dp_s[4] = dp_s[4] + jnp.sum(dpz2[1] * c2prev, axis=0)
            dp_s[5] = dp_s[5] + jnp.sum(dpz2[2] * c2o, axis=0)
        dz1_ref[u] = dz1.astype(dz1_ref.dtype)
        dz2_ref[u] = dz2.astype(dz2_ref.dtype)
    dh1_s[:], dc1_s[:] = dh1, dc1
    dh2_s[:], dc2_s[:] = dh2, dc2

    @pl.when(t == nb - 1)
    def _():
        dhc0_ref[0] = dh1.astype(dhc0_ref.dtype)
        dhc0_ref[1] = dc1.astype(dhc0_ref.dtype)
        dhc0_ref[2] = dh2.astype(dhc0_ref.dtype)
        dhc0_ref[3] = dc2.astype(dhc0_ref.dtype)
        if peep:
            dpeep_ref[...] = dp_s[:].astype(dpeep_ref.dtype)
        else:
            dpeep_ref[...] = jnp.zeros(dpeep_ref.shape, dpeep_ref.dtype)


def _bwd2_call(dy, g1, c1seq, g2, c2seq, rw1t, w2t, rw2t, peep, c0, dhcT):
    T, b, H = dy.shape
    H4 = 4 * H
    U = _unroll2(T, b, H, jnp.dtype(rw1t.dtype).itemsize)
    nb = T // U
    kern = functools.partial(_bwd2_kernel, nb=nb, H=H,
                             peep=peep is not None, U=U)
    rev = lambda t: (nb - 1 - t, 0, 0)
    rev_prev = lambda t: (jnp.maximum((nb - 1 - t) * U - 1, 0), 0, 0)
    const2 = lambda t: (0, 0)
    const3 = lambda t: (0, 0, 0)
    specs = [
        _vspec((U, b, H), rev),                           # dy (= dys2)
        _vspec((U, b, H4), rev),                          # gates1
        _vspec((U, b, H), rev),                           # cseq1
        _vspec((1, b, H), rev_prev),                      # c1_{t-1} stream
        _vspec((U, b, H4), rev),                          # gates2
        _vspec((U, b, H), rev),                           # cseq2
        _vspec((1, b, H), rev_prev),                      # c2_{t-1} stream
        _vspec((H4, H), const2),                          # RW1^T
        _vspec((H4, H), const2),                          # W2^T
        _vspec((H4, H), const2),                          # RW2^T
    ]
    ops = [dy, g1, c1seq, c1seq, g2, c2seq, c2seq, rw1t, w2t, rw2t]
    if peep is not None:
        specs.append(_vspec((8, H), const2))
        ops.append(peep)
    specs += [_vspec((2, b, H), const3),                  # (c01, c02)
              _vspec((4, b, H), const3)]                  # dhcT pack
    ops += [c0, dhcT]

    def shim(*refs):
        n_in = 10 + int(peep is not None) + 2
        ins, rest = refs[:n_in], refs[n_in:]
        peep_ref = ins[10] if peep is not None else None
        return kern(ins[0], ins[1], ins[2], ins[3], ins[4], ins[5], ins[6],
                    ins[7], ins[8], ins[9], peep_ref, ins[-2], ins[-1],
                    *rest)

    sd = _stream_dtype()
    f32 = jnp.float32
    return pl.pallas_call(
        shim,
        grid=(nb,),
        in_specs=specs,
        out_specs=(
            _vspec((U, b, H4), rev),                      # dz1
            _vspec((U, b, H4), rev),                      # dz2
            _vspec((4, b, H), const3),                    # dhc0 pack
            _vspec((8, H), const2),                       # dpeep pack
        ),
        out_shape=(jax.ShapeDtypeStruct((T, b, H4), sd),
                   jax.ShapeDtypeStruct((T, b, H4), sd),
                   jax.ShapeDtypeStruct((4, b, H), f32),
                   jax.ShapeDtypeStruct((8, H), f32)),
        scratch_shapes=[_scratch((b, H))] * 4 + [_scratch((8, H))],
        interpret=_interpret(),
    )(*ops)


# ------------------------------------------------------------- public entry
@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _lstm2(xp, rw1, w2, b2, rw2, peep, h0):
    ys1, ys2, _, _, _, _, hc = _fwd2(xp, rw1, w2, b2, rw2, peep, h0,
                                     save_reserve=False)
    return ys2, hc


def _lstm2_fwd(xp, rw1, w2, b2, rw2, peep, h0):
    ys1, ys2, g1, c1, g2, c2, hc = _fwd2(xp, rw1, w2, b2, rw2, peep, h0)
    return (ys2, hc), (rw1, w2, b2, rw2, peep, h0, ys1, ys2, g1, c1, g2, c2)


def _lstm2_bwd(res, grads):
    rw1, w2, b2, rw2, peep, h0, ys1, ys2, g1, c1seq, g2, c2seq = res
    dy2, dhc = grads
    dy2 = dy2.astype(jnp.float32)
    c0pack = jnp.stack([h0[1].astype(jnp.float32),
                        h0[3].astype(jnp.float32)])
    dz1, dz2, dhc0, dpeep = _bwd2_call(
        dy2, g1, c1seq, g2, c2seq,
        jnp.swapaxes(rw1, 0, 1), jnp.swapaxes(w2, 0, 1),
        jnp.swapaxes(rw2, 0, 1), peep, c0pack, dhc.astype(jnp.float32))
    # batched-over-time weight gradients as single MXU gemms (outside):
    #   z1 = xp + h1_{t-1} @ RW1          -> dRW1 = sum h1_{t-1}^T dz1
    #   z2 = h1_t @ W2 + b2 + h2_{t-1} @ RW2
    #     -> dW2 = sum ys1_t^T dz2,  db2 = sum dz2,
    #        dRW2 = sum h2_{t-1}^T dz2
    h1_prev = jnp.concatenate([h0[0].astype(ys1.dtype)[None], ys1[:-1]], 0)
    h2_prev = jnp.concatenate([h0[2].astype(ys2.dtype)[None], ys2[:-1]], 0)
    drw1 = jnp.einsum("tbh,tbg->hg", h1_prev.astype(rw1.dtype),
                      dz1.astype(rw1.dtype),
                      preferred_element_type=jnp.float32).astype(rw1.dtype)
    dw2 = jnp.einsum("tbh,tbg->hg", ys1.astype(w2.dtype),
                     dz2.astype(w2.dtype),
                     preferred_element_type=jnp.float32).astype(w2.dtype)
    drw2 = jnp.einsum("tbh,tbg->hg", h2_prev.astype(rw2.dtype),
                      dz2.astype(rw2.dtype),
                      preferred_element_type=jnp.float32).astype(rw2.dtype)
    db2 = jnp.zeros_like(b2).at[0].set(
        jnp.sum(dz2.astype(jnp.float32), axis=(0, 1)).astype(b2.dtype))
    dpeep_out = None if peep is None else dpeep.astype(peep.dtype)
    return (dz1, drw1, dw2, db2, drw2, dpeep_out, dhc0)


_lstm2.defvjp(_lstm2_fwd, _lstm2_bwd)


def supported2(b: int, T: int, H: int, weight_bytes: int = 4) -> bool:
    """Whether the fused two-layer kernel applies (the caller must already
    have checked each layer's ``lstm_cell.supported`` contract: tanh cell +
    sigmoid gates, aligned dims). ``DL4J_TPU_NO_FUSED_LSTM=1`` is the
    escape hatch (same first-hardware insurance as the per-layer kernel's
    ``DL4J_TPU_NO_PERSISTENT_LSTM``)."""
    import os
    if os.environ.get("DL4J_TPU_NO_FUSED_LSTM"):
        return False
    if os.environ.get("DL4J_TPU_NO_PERSISTENT_LSTM"):
        return False
    from . import flash_attention as _fa
    if not _fa._FORCE_INTERPRET:
        try:
            if jax.default_backend() not in ("tpu", "axon"):
                return False
        except Exception:  # pragma: no cover
            return False
    if not _vmem_fits2(b, H, weight_bytes) or b > 1024:
        return False
    return H % 128 == 0 and b % 8 == 0 and T >= 1


def lstm_scan2(xp1, rw1, peep1, w2, b2, rw2, peep2, h01, c01, h02, c02):
    """Fused two-layer LSTM sequence step. ``xp1``: [b, T, 4H] (layer-1
    hoisted input projection + bias), ``rw1``/``rw2``: [H, 4H] recurrent
    weights, ``w2``: [H, 4H] layer-2 input weights, ``b2``: [4H] layer-2
    bias, ``peep1``/``peep2``: (pi, pf, po) tuples or None (must agree on
    None-ness — mixed stacks take the per-layer path), ``h01``..``c02``:
    [b, H] initial states. No step masks (route masked batches to
    ``lstm_cell.lstm_scan`` per layer). Returns
    (ys2 [b, T, H] in the stream dtype, (h1T, c1T), (h2T, c2T) in f32)."""
    b, T, H4 = xp1.shape
    H = H4 // 4
    xp_tm = jnp.swapaxes(xp1, 0, 1)
    pk = None
    if peep1 is not None:
        pk = jnp.zeros((8, H), jnp.float32)
        for r, v in enumerate(peep1 + tuple(peep2)):
            pk = pk.at[r].set(v.astype(jnp.float32))
    b2row = jnp.zeros((8, H4), jnp.float32).at[0].set(
        b2.astype(jnp.float32))
    h0 = jnp.stack([h01.astype(jnp.float32), c01.astype(jnp.float32),
                    h02.astype(jnp.float32), c02.astype(jnp.float32)])
    ys2, hc = _lstm2(xp_tm.astype(_stream_dtype()), rw1, w2, b2row, rw2,
                     pk, h0)
    return (jnp.swapaxes(ys2, 0, 1), (hc[0], hc[1]), (hc[2], hc[3]))
