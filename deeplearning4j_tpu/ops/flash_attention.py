"""Pallas flash attention for TPU — the framework's hot-op custom kernel.

``nn/layers/attention.py``'s dense ``mha`` materializes the [b, h, T, T]
logits tensor: O(T²) HBM traffic and memory, which is exactly what caps
long-context training. This module implements blockwise (flash) attention as
Pallas TPU kernels — online softmax over K/V blocks streamed through VMEM,
O(T) memory, with the standard FlashAttention-2 backward (recompute
probabilities per block from the saved log-sum-exp instead of storing them).

Streaming structure: every kernel runs on a 3-D grid (bh, out-block,
in-block) whose innermost dimension walks the streamed blocks; the
BlockSpec index maps stage exactly ONE 128-row block of each operand into
VMEM per grid step (no full-sequence VMEM residency — T is bounded by HBM,
not VMEM), and the running accumulators (m/l/acc, dq, dk/dv) live in VMEM
scratch that persists across the innermost grid sweep: initialized at the
first in-block, written out at the last.

Layout: kernels work on [bh, T, d] (batch×heads flattened); the public
:func:`flash_attention` takes the layer's [b, T, h, d] and
transposes/reshapes at the boundary (XLA fuses these). f32 accumulation
throughout; inputs/outputs keep the caller's dtype (bf16 on TPU).

Used automatically by ``SelfAttentionLayer`` when applicable (TPU backend,
T divisible by the 128 block; [b, T] key-padding masks AND attention-
probability dropout both run in-kernel — streamed/regenerated blockwise, no
dense fallback) — the cuDNN-helper pattern (reference
``ConvolutionLayer.java:76`` reflective helper swap) realized as a Pallas
kernel behind the same layer math, with the dense path as the
always-available fallback for short/odd-length sequences.
"""
from __future__ import annotations

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
try:  # TPU-specific memory spaces; absent on some backends
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

# q/k block edge. 128 is the MXU lane-aligned minimum; LARGER blocks divide
# the sequential grid-step count quadratically (grid = bh * (T/B)^2), which
# is what bounds throughput at head_dim 64 (each 128x64x128 dot is ~2 MFLOP
# of MXU work against fixed per-step DMA/launch latency). BLOCK is the CAP:
# each kernel call picks the largest 128-multiple <= BLOCK that divides its
# T (:func:`pick_block`), so odd-length-but-lane-aligned sequences degrade
# to a smaller block instead of losing the flash path. IMPORT-TIME knob:
# DL4J_TPU_FLASH_BLOCK must be set before the first import (same trace-time
# caveat as DL4J_TPU_LSTM_UNROLL, read once here so behavior is
# predictable); snapped to the 128 grid — a non-multiple would mis-tile
# every BlockSpec.
import os as _os
MIN_BLOCK = 128
try:
    BLOCK = max(MIN_BLOCK,
                int(_os.environ.get("DL4J_TPU_FLASH_BLOCK", "128")))
except ValueError:  # pragma: no cover - malformed override
    BLOCK = MIN_BLOCK
BLOCK -= BLOCK % MIN_BLOCK
_NEG = -1e30


def pick_block(T: int, d: int) -> int:
    """Largest 128-multiple <= the BLOCK cap that divides ``T``, bounded by
    a VMEM budget covering BOTH the [blk, d] operand tiles (blk*d <= 64k
    elements) and the dominant in-kernel [blk, blk] f32 intermediates
    (s/p/keep: 12*blk^2 bytes <= 8 MB, which caps picks at 768; at d=128
    the operand term caps at 512 first, at d=256 at 256). Dropout
    coordinates hash GLOBAL positions, so forward/backward kernels may
    legally pick different blocks without changing any semantics."""
    cap = min(BLOCK, T)
    cap -= cap % MIN_BLOCK
    while cap > MIN_BLOCK and (cap * d > 65536
                               or 12 * cap * cap > 8 * 2 ** 20):
        cap -= MIN_BLOCK
    for b in range(cap, MIN_BLOCK, -MIN_BLOCK):
        if T % b == 0:
            return b
    return MIN_BLOCK

# ---------------------------------------------------------------- dropout RNG
# Counter-based hash PRNG for attention-probability dropout INSIDE the
# kernels. The keep decision for softmax cell (bh, qpos, kpos) is a pure
# function of (seed, bh, qpos, kpos), so the forward kernel and BOTH backward
# kernels regenerate bit-identical masks with no [T, T] mask ever touching
# HBM — the standard FlashAttention dropout scheme. A murmur3-finalizer mix
# over global coordinates is used instead of the TPU PRNG primitive
# (pltpu.prng_random_bits) because it is platform-portable: plain int32 VPU
# ops lower on TPU AND under interpret mode, so the CPU test suite exercises
# the exact arithmetic the TPU runs (prng_seed has no CPU lowering).
# numpy scalars (NOT jnp arrays): they embed as literals in the kernel
# body — a jnp constant would be a captured device value, which pallas_call
# rejects
_PHI = np.int32(-1640531527)       # 0x9E3779B9: golden-ratio odd constant
_FMIX1 = np.int32(-2048144789)     # 0x85EBCA6B: murmur3 fmix32
_FMIX2 = np.int32(-1028477387)     # 0xC2B2AE35: murmur3 fmix32
_FNV = np.int32(0x01000193)        # FNV prime: row stride > any kpos


def _fmix32(h):
    """murmur3 32-bit finalizer (full avalanche); int32 wraparound == the
    uint32 arithmetic (two's complement), shifts logical."""
    h = h ^ lax.shift_right_logical(h, 16)
    h = h * _FMIX1
    h = h ^ lax.shift_right_logical(h, 13)
    h = h * _FMIX2
    h = h ^ lax.shift_right_logical(h, 16)
    return h


def _keep_from_coords(seed, bh, qpos, kpos, rate):
    """Keep mask (f32 0/1, broadcast shape of qpos/kpos) for softmax cells at
    global coordinates (bh, qpos, kpos). Single source of truth: the Pallas
    kernels call this with block-local iotas, :func:`dropout_keep_mask` with
    full-range iotas — identical values by construction."""
    h = _fmix32(seed ^ (bh * _PHI))
    x = _fmix32(h ^ (qpos * _FNV + kpos))
    x = _fmix32(x ^ (kpos * _PHI))
    u = (x & np.int32(0x7FFFFF)).astype(jnp.float32) * (1.0 / (1 << 23))
    return (u >= rate).astype(jnp.float32)


def _block_keep(seed_ref, bh, qi, kj, rate, blk):
    """[blk, blk] keep mask for attention block (bh, qi, kj). The SMEM
    seed operand is [3] i32: (seed, q_offset, k_offset) — the offsets make
    the hashed coordinates GLOBAL, so a kernel running on a ring shard
    draws bit-identical decisions to a single kernel over the full
    sequence (``parallel.sequence.ring_flash_attention`` passes each ring
    step's shard offsets; single-device callers pass 0, 0). Hashing global
    positions also makes the decisions independent of the block size the
    calling kernel happened to pick."""
    qpos = (seed_ref[1] + qi * blk
            + lax.broadcasted_iota(jnp.int32, (blk, blk), 0))
    kpos = (seed_ref[2] + kj * blk
            + lax.broadcasted_iota(jnp.int32, (blk, blk), 1))
    return _keep_from_coords(seed_ref[0], bh, qpos, kpos, rate)


def seed3(seed, q_off=0, k_off=0):
    """Pack the kernels' [3] i32 SMEM dropout operand:
    (seed, global q offset, global k offset)."""
    return jnp.stack([jnp.asarray(seed, jnp.int32).reshape(()),
                      jnp.asarray(q_off, jnp.int32).reshape(()),
                      jnp.asarray(k_off, jnp.int32).reshape(())])


def dropout_keep_mask(bh, Tq, Tk, seed, rate, q_off=0, k_off=0):
    """Materialize the exact [bh, Tq, Tk] keep mask the kernels regenerate
    blockwise — test/debug oracle only (O(T²) memory, which the kernels
    never allocate). ``q_off``/``k_off`` shift the hashed coordinates the
    way the ring passes shard offsets."""
    qpos = q_off + jnp.arange(Tq, dtype=jnp.int32)[:, None]
    kpos = k_off + jnp.arange(Tk, dtype=jnp.int32)[None, :]
    seed = jnp.asarray(seed, jnp.int32).reshape(())
    return jax.vmap(lambda i: _keep_from_coords(
        seed, i, qpos, kpos, rate))(jnp.arange(bh, dtype=jnp.int32))


def _smem_spec():
    if pltpu is None:  # pragma: no cover - interpret-only fallback
        return pl.BlockSpec()
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _vspec(block_shape, index_map):
    if _VMEM is None:
        return pl.BlockSpec(block_shape, index_map)
    return pl.BlockSpec(block_shape, index_map, memory_space=_VMEM)


def _scratch(shape, dtype=jnp.float32):
    if pltpu is None:  # pragma: no cover - pallas-tpu unavailable
        raise RuntimeError("flash attention needs pallas TPU support; "
                           "supported() should have routed to the dense path")
    return pltpu.VMEM(shape, dtype)


def _when_visible(causal, cond, fn):
    """Run ``fn`` only for visible blocks: always when not causal (static),
    predicated on ``cond`` when causal."""
    if causal:
        pl.when(cond)(fn)
    else:
        fn()


def _causal_mask(s, qi, kj, block):
    Bq, Bk = s.shape
    qpos = qi * block + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 0)
    kpos = kj * block + jax.lax.broadcasted_iota(jnp.int32, (Bq, Bk), 1)
    return jnp.where(kpos <= qpos, s, _NEG)


# ------------------------------------------------------------------ forward
def _fwd_kernel(q_ref, k_ref, v_ref, *rest, causal, scale, nk, rate, has_km,
                blk):
    has_seed = rate > 0.0
    km_ref = rest[0] if has_km else None
    seed_ref = rest[int(has_km)] if has_seed else None
    o_ref, lse_ref, m_s, l_s, acc_s = rest[int(has_km) + int(has_seed):]
    bh, qi, kj = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(kj == 0)
    def _():
        m_s[:] = jnp.full_like(m_s, _NEG)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    def _compute():
        # matmuls run in the SOURCE dtype (bf16 → native MXU pass) with f32
        # accumulation via preferred_element_type; softmax stats stay f32.
        # The scale moves after the dot so bf16 q is not pre-rounded by it.
        q = q_ref[0]                                      # [Bq, d]
        k = k_ref[0]                                      # [Bk, d]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, kj, blk)
        if km_ref is not None:
            s = jnp.where(km_ref[0, :, 0][None, :] > 0, s, _NEG)
        m = m_s[:, 0]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))       # [Bq]
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        # softmax denominator accumulates UNDROPPED p — dropout applies to
        # the normalized probabilities (out = drop(softmax(s)) @ v), and
        # division by l at the end distributes over the linear accumulator
        l_s[:, 0] = l_s[:, 0] * alpha + jnp.sum(p, axis=-1)
        m_s[:, 0] = m_new
        if rate > 0.0:
            keep = _block_keep(seed_ref, bh, qi, kj, rate, blk)
            p = p * keep * (1.0 / (1.0 - rate))
        acc_s[:] = acc_s[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _when_visible(causal, kj <= qi, _compute)

    @pl.when(kj == nk - 1)
    def _():
        # Rows whose visible keys were ALL masked never raise m above _NEG;
        # for them every p above was exp(_NEG - _NEG) = 1, so acc/l is a
        # uniform average over the masked block — garbage. Define the
        # semantics instead: no visible key -> output 0, lse = _NEG (the
        # ring merge's no-contribution identity), and the backward's
        # s-guard (see _dq_kernel) makes the row's gradients exactly 0.
        m = m_s[:, 0]
        l = jnp.maximum(l_s[:, 0], 1e-30)
        valid = (m > _NEG * 0.5).astype(jnp.float32)
        o_ref[0] = (acc_s[:] * (valid / l)[:, None]).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(
            jnp.where(valid > 0, m + jnp.log(l), _NEG)[:, None],
            lse_ref.shape[1:])


def _fwd(q, k, v, km, seed, causal, scale, rate):
    """q/k/v: [bh, T, d], km: [bh, T, 8] key mask or None, seed: [3] i32
    (seed, q_off, k_off — :func:`seed3`) or None (rate > 0) →
    (o [bh, T, d], lse [bh, T, 8])."""
    bh, T, d = q.shape
    blk = pick_block(T, d)
    nq = T // blk
    kern = functools.partial(_fwd_kernel, causal=causal, scale=scale, nk=nq,
                             rate=rate, has_km=km is not None, blk=blk)
    if causal:
        # invisible (kj > qj) steps clamp to the diagonal block: same index
        # as the previous visible step → Pallas skips the DMA entirely
        kv_idx = lambda i, qj, kj: (i, jnp.minimum(kj, qj), 0)
    else:
        kv_idx = lambda i, qj, kj: (i, kj, 0)
    # lse is lane-padded to [bh, T, 8]: TPU block shapes need their last two
    # dims (8·k, 128·m) or full-dim; a (1, blk) slice of [bh, T] is
    # unlowerable. 8 f32 lanes per position is noise next to q/k/v
    in_specs = [
        _vspec((1, blk, d), lambda i, qj, kj: (i, qj, 0)),
        _vspec((1, blk, d), kv_idx),
        _vspec((1, blk, d), kv_idx),
    ]
    operands = [q, k, v]
    if km is not None:
        in_specs.append(_vspec((1, blk, 8), kv_idx))
        operands.append(km)
    if rate > 0.0:
        in_specs.append(_smem_spec())
        operands.append(seed)
    return pl.pallas_call(
        kern,
        grid=(bh, nq, nq),
        in_specs=in_specs,
        out_specs=(
            _vspec((1, blk, d), lambda i, qj, kj: (i, qj, 0)),
            _vspec((1, blk, 8), lambda i, qj, kj: (i, qj, 0)),
        ),
        out_shape=(jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct((bh, T, 8), jnp.float32)),
        scratch_shapes=[_scratch((blk, 8)), _scratch((blk, 8)),
                        _scratch((blk, d))],
        interpret=_interpret(),
    )(*operands)


# ----------------------------------------------------------------- backward
def _dq_kernel(q_ref, k_ref, v_ref, *rest, causal, scale, nk, rate,
               has_km, blk):
    has_seed = rate > 0.0
    km_ref = rest[0] if has_km else None
    seed_ref = rest[int(has_km)] if has_seed else None
    do_ref, delta_ref, lse_ref, dq_ref, dq_s = \
        rest[int(has_km) + int(has_seed):]
    bh, qi, kj = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(kj == 0)
    def _():
        dq_s[:] = jnp.zeros_like(dq_s)

    def _compute():
        # source-dtype matmul operands (bf16 MXU pass), f32 accumulation —
        # same policy as the forward kernel; softmax/ds math stays f32
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, :, 0]
        delta = delta_ref[0, :, 0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, kj, blk)
        if km_ref is not None:
            s = jnp.where(km_ref[0, :, 0][None, :] > 0, s, _NEG)
        # s-guard: masked cells get p = 0 even on fully-masked rows, where
        # lse is the _NEG sentinel and exp(s - lse) would be exp(0) = 1
        p = jnp.where(s > _NEG * 0.5, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if rate > 0.0:
            # dP flows only through kept cells: dP = (do·vᵀ)·keep/(1-r);
            # delta already equals rowsum(P∘dP) = rowsum(do∘o) unchanged
            keep = _block_keep(seed_ref, bh, qi, kj, rate, blk)
            dp = dp * keep * (1.0 / (1.0 - rate))
        ds = p * (dp - delta[:, None]) * scale
        dq_s[:] = dq_s[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _when_visible(causal, kj <= qi, _compute)

    @pl.when(kj == nk - 1)
    def _():
        dq_ref[0] = dq_s[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, *rest, causal, scale, nq, rate,
                has_km, blk):
    has_seed = rate > 0.0
    km_ref = rest[0] if has_km else None
    seed_ref = rest[int(has_km)] if has_seed else None
    do_ref, delta_ref, lse_ref, dk_ref, dv_ref, dk_s, dv_s = \
        rest[int(has_km) + int(has_seed):]
    bh, ki, qj = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(qj == 0)
    def _():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    def _compute():
        # source-dtype matmul operands (bf16 MXU pass), f32 accumulation
        k = k_ref[0]
        v = v_ref[0]
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, :, 0]
        delta = delta_ref[0, :, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qj, ki, blk)
        if km_ref is not None:
            s = jnp.where(km_ref[0, :, 0][None, :] > 0, s, _NEG)
        # same s-guard as _dq_kernel (fully-masked rows: lse = _NEG)
        p = jnp.where(s > _NEG * 0.5,
                      jnp.exp(s - lse[:, None]), 0.0)    # [Bq, Bk]
        if rate > 0.0:
            # same (bh, q-block, k-block) seeding as the fwd kernel: the
            # grid here is (bh, k, q), so the id order swaps
            keep = _block_keep(seed_ref, bh, qj, ki, rate, blk)
            pd = p * keep * (1.0 / (1.0 - rate))          # = drop(P)
        else:
            pd = p
        dv_s[:] = dv_s[:] + jax.lax.dot_general(
            pd.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if rate > 0.0:
            dp = dp * keep * (1.0 / (1.0 - rate))
        ds = p * (dp - delta[:, None]) * scale
        dk_s[:] = dk_s[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _when_visible(causal, qj >= ki, _compute)

    @pl.when(qj == nq - 1)
    def _():
        dk_ref[0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[:].astype(dv_ref.dtype)


def dq_block(q, k, v, km, do, delta, lse, causal, scale, seed=None,
             rate=0.0):
    """dq for one q-shard against one k/v block ([bh, Tq, d] × [bh, Tk, d]).
    ``delta``/``lse`` are the GLOBAL rowwise Δ and log-sum-exp ([bh, Tq, 8]
    lane-padded) — with them, per-block probabilities recompute exactly, so
    per-block gradients sum to the full-attention gradient. Used by the
    in-kernel backward below AND per ring step by
    ``parallel.sequence.ring_flash_attention``."""
    bh, Tq, d = q.shape
    # one block size must tile BOTH the q shard and the k/v block (the ring
    # passes different lengths): pick on the gcd
    blk = pick_block(math.gcd(Tq, k.shape[1]), d)
    nq, nk = Tq // blk, k.shape[1] // blk
    kern = functools.partial(_dq_kernel, causal=causal, scale=scale, nk=nk,
                             rate=rate, has_km=km is not None, blk=blk)
    if causal:
        kv_idx = lambda i, qj, kj: (i, jnp.minimum(kj, qj), 0)
    else:
        kv_idx = lambda i, qj, kj: (i, kj, 0)
    specs = [
        _vspec((1, blk, d), lambda i, qj, kj: (i, qj, 0)),     # q
        _vspec((1, blk, d), kv_idx),                           # k
        _vspec((1, blk, d), kv_idx),                           # v
    ]
    ops = [q, k, v]
    if km is not None:
        specs.append(_vspec((1, blk, 8), kv_idx))              # key mask
        ops.append(km)
    if rate > 0.0:
        specs.append(_smem_spec())
        ops.append(seed)
    specs += [
        _vspec((1, blk, d), lambda i, qj, kj: (i, qj, 0)),     # do
        _vspec((1, blk, 8), lambda i, qj, kj: (i, qj, 0)),     # delta
        _vspec((1, blk, 8), lambda i, qj, kj: (i, qj, 0)),     # lse
    ]
    ops += [do, delta, lse]
    return pl.pallas_call(
        kern,
        grid=(bh, nq, nk),
        in_specs=specs,
        out_specs=_vspec((1, blk, d), lambda i, qj, kj: (i, qj, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[_scratch((blk, d))],
        interpret=_interpret(),
    )(*ops)


def dkv_block(q, k, v, km, do, delta, lse, causal, scale, seed=None,
              rate=0.0):
    """(dk, dv) for one k/v block against one q-shard; see :func:`dq_block`
    for the global-``lse``/``delta`` contract."""
    bh, Tk, d = k.shape
    blk = pick_block(math.gcd(q.shape[1], Tk), d)
    nq, nk = q.shape[1] // blk, Tk // blk
    kern = functools.partial(_dkv_kernel, causal=causal, scale=scale, nq=nq,
                             rate=rate, has_km=km is not None, blk=blk)
    if causal:
        q_idx = lambda i, kj, qj: (i, jnp.maximum(qj, kj), 0)
    else:
        q_idx = lambda i, kj, qj: (i, qj, 0)
    specs = [
        _vspec((1, blk, d), q_idx),                            # q
        _vspec((1, blk, d), lambda i, kj, qj: (i, kj, 0)),     # k
        _vspec((1, blk, d), lambda i, kj, qj: (i, kj, 0)),     # v
    ]
    ops = [q, k, v]
    if km is not None:
        specs.append(_vspec((1, blk, 8),
                            lambda i, kj, qj: (i, kj, 0)))     # key mask
        ops.append(km)
    if rate > 0.0:
        specs.append(_smem_spec())
        ops.append(seed)
    specs += [
        _vspec((1, blk, d), q_idx),                            # do
        _vspec((1, blk, 8), q_idx),                            # delta
        _vspec((1, blk, 8), q_idx),                            # lse
    ]
    ops += [do, delta, lse]
    return pl.pallas_call(
        kern,
        grid=(bh, nk, nq),
        in_specs=specs,
        out_specs=(
            _vspec((1, blk, d), lambda i, kj, qj: (i, kj, 0)),
            _vspec((1, blk, d), lambda i, kj, qj: (i, kj, 0)),
        ),
        out_shape=(jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)),
        scratch_shapes=[_scratch((blk, d)), _scratch((blk, d))],
        interpret=_interpret(),
    )(*ops)


def rowwise_delta(do, o):
    """Δ_i = Σ_d do·o — rowwise, cheap in plain XLA; lane-padded like lse."""
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    return jnp.broadcast_to(delta[..., None], delta.shape + (8,))


def _bwd(causal, scale, rate, res, g):
    q, k, v, km, seed, o, lse = res
    do = g.astype(q.dtype)
    delta = rowwise_delta(do, o)
    dq = dq_block(q, k, v, km, do, delta, lse, causal, scale, seed, rate)
    dk, dv = dkv_block(q, k, v, km, do, delta, lse, causal, scale, seed,
                       rate)
    dkm = None if km is None else jnp.zeros_like(km)
    # int32 primal → float0 cotangent (the JAX convention for non-float args)
    dseed = (None if seed is None
             else np.zeros(seed.shape, jax.dtypes.float0))
    return dq, dk, dv, dkm, dseed


# ------------------------------------------------------------- public entry
def normalize_operand_dtypes(q, k, v):
    """Uniform source-dtype operands for the dtype-strict kernels
    (``dot_general`` rejects mixed dtypes; uniform bf16 is what takes the
    native MXU pass): promote to the WIDEST operand dtype, so an f32 k/v
    alongside a bf16 q keeps its precision instead of being silently
    downcast. ``DL4J_TPU_FLASH_F32=1`` forces f32 — the first-hardware
    rollback hatch restoring the pre-bf16 kernel behavior should a Mosaic
    bf16 lowering gap surface on a new jaxlib. Returns
    ``(q, k, v, out_dtype)`` with ``out_dtype`` = q's ORIGINAL dtype;
    callers cast the kernel output back to it so neither the promotion nor
    the hatch ever changes downstream activation dtypes. Shared by
    :func:`flash_attention` and ``parallel.sequence.ring_flash_attention``
    — one policy, one place."""
    import os
    out_dtype = q.dtype
    common = jnp.promote_types(jnp.promote_types(q.dtype, k.dtype), v.dtype)
    if os.environ.get("DL4J_TPU_FLASH_F32"):
        common = jnp.float32
    return (q.astype(common), k.astype(common), v.astype(common), out_dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash(q, k, v, km, seed, causal, scale, rate):
    o, _ = _fwd(q, k, v, km, seed, causal, scale, rate)
    return o


def _flash_fwd(q, k, v, km, seed, causal, scale, rate):
    o, lse = _fwd(q, k, v, km, seed, causal, scale, rate)
    return o, (q, k, v, km, seed, o, lse)


_flash.defvjp(_flash_fwd, _bwd)


_FORCE_INTERPRET = False  # tests flip this to run kernels off-TPU


def _interpret() -> bool:
    if _FORCE_INTERPRET:
        return True
    try:
        return jax.default_backend() not in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return True


#: below this sequence length the dense einsum is faster on-chip (measured:
#: T=2048 dense 12.4 ms vs flash 14.1 ms; T=8192 dense 490 ms vs flash 65 ms)
MIN_SEQ = 4096


def supported(T: int, d: int, dropout_rate: float, key_mask) -> bool:
    """Whether the flash path applies: TPU backend (the interpreter would be
    far slower than the dense einsum — except under the tests' forced
    interpret mode), block-divisible sequence long enough to beat the dense
    path, head dim within VMEM tiling. Both [b, T] key-padding masks
    (round-3 VERDICT item 5) AND attention-probability dropout (round-3
    "ideally dropout"; in-kernel counter-hash PRNG) stream through the
    kernels — neither falls back to dense anymore."""
    min_seq = 2 * MIN_BLOCK if _FORCE_INTERPRET else MIN_SEQ
    if not _FORCE_INTERPRET:
        try:
            if jax.default_backend() not in ("tpu", "axon"):
                return False
        except Exception:  # pragma: no cover
            return False
    if key_mask is not None and getattr(key_mask, "ndim", None) != 2:
        return False
    return (T % MIN_BLOCK == 0 and T >= min_seq and d <= 256
            and 0.0 <= dropout_rate < 1.0)


def flash_attention(q, k, v, causal: bool = True, scale: float | None = None,
                    key_mask=None, dropout_rate: float = 0.0,
                    dropout_seed=None):
    """Blockwise attention. q/k/v: [b, T, h, d] → [b, T, h, d].
    ``key_mask``: optional [b, T] (1 = real key, 0 = padding) — masked keys
    are excluded from the softmax inside the kernels (no dense fallback).
    ``dropout_rate`` > 0 applies dropout to the normalized attention
    probabilities in-kernel, regenerated mask-free in the backward;
    ``dropout_seed`` (int32 scalar, may be traced — e.g. derived from the
    layer's PRNG key per step) is then required."""
    b, T, h, d = q.shape
    q, k, v, out_dtype = normalize_operand_dtypes(q, k, v)
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    rate = float(dropout_rate)
    seed = None
    if rate > 0.0:
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 needs dropout_seed")
        seed = seed3(dropout_seed)

    def to_bh(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, T, d)

    km = None
    if key_mask is not None:
        km = jnp.broadcast_to(jnp.asarray(key_mask, jnp.float32)[:, None, :],
                              (b, h, T)).reshape(b * h, T)
        km = jnp.broadcast_to(km[..., None], (b * h, T, 8))
    o = _flash(to_bh(q), to_bh(k), to_bh(v), km, seed, bool(causal),
               float(scale), rate)
    return jnp.transpose(o.reshape(b, h, T, d), (0, 2, 1, 3)).astype(out_dtype)
