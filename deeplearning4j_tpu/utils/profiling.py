"""Profiling utilities — the build's tracing subsystem (SURVEY.md §5).

The reference has no in-framework tracer; deep profiling is delegated to
ND4J's external ``OpProfiler`` and throughput to ``PerformanceListener``.
Here the device is XLA, so the natural equivalents are:

- :func:`trace` / :class:`ProfilerListener` — capture a ``jax.profiler``
  device trace (viewable in TensorBoard/Perfetto) around a code block or a
  chosen window of training iterations.
- :func:`step_cost` — XLA's static cost model for a container's compiled
  train step (flops / bytes accessed / peak memory), the numbers behind the
  roofline analysis in PERF.md.
- :class:`StepTimerListener` — honest per-iteration wall times using a
  device→host value fetch as the barrier (``jax.block_until_ready`` can
  return early on the axon tunnel — PERF.md addendum 2).
- :class:`ParamServerMetricsListener` (re-exported from
  ``paramserver/metrics.py``) — push/pull counters, wire bytes, retries and
  op-latency histograms for server-mediated async training, on the same
  listener bus.

This module covers *device* traces and per-step timing; the process-wide
metrics/span/health layer lives in ``deeplearning4j_tpu/monitor/`` (one
``MetricsRegistry`` scraped at ``GET /metrics``, a host-side span tracer
exporting Chrome trace JSON, and a NaN/divergence/stall watchdog) — see
docs/OBSERVABILITY.md. The value-fetch barrier rule stated on
:class:`StepTimerListener` applies to the monitor's spans identically.
"""
from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..optimize.listeners import TrainingListener


def __getattr__(name):
    # lazy re-export: pulling the PS listener eagerly would make a plain
    # profiling import pay for the whole paramserver+parallel stack
    if name == "ParamServerMetricsListener":
        from ..paramserver.metrics import ParamServerMetricsListener
        return ParamServerMetricsListener
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a jax.profiler device trace for the enclosed block."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class ProfilerListener(TrainingListener):
    """Trace a window of training iterations: starts a jax.profiler trace at
    ``start_iteration`` and stops it ``num_iterations`` later. Attach like
    any listener (reference listener-bus pattern,
    ``optimize/api/IterationListener.java``)."""

    def __init__(self, log_dir: str, start_iteration: int = 3,
                 num_iterations: int = 3):
        self.log_dir = log_dir
        self.start_iteration = start_iteration
        self.num_iterations = num_iterations
        self._active = False
        self.done = False

    def iteration_done(self, model, iteration, score):
        import jax

        if self.done:
            return
        if not self._active and iteration >= self.start_iteration:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
            self._until = iteration + self.num_iterations
        elif self._active and iteration >= self._until:
            # completion barrier: the fit loops evaluate float(loss) — a
            # device→host VALUE fetch of this step's output — before
            # dispatching listeners, so the traced step has already finished
            # when we get here. (block_until_ready would NOT be a valid
            # substitute on the axon tunnel — PERF.md addendum 2.)
            self.close()

    def close(self):
        """Stop the trace if still active — called automatically when the
        window fills or the epoch ends, and safe to call explicitly when
        training stops early (an active jax profiler trace is process-global;
        leaking it breaks the next start_trace)."""
        import jax

        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self.done = True

    def on_epoch_end(self, model, epoch):
        self.close()

    def on_training_error(self, model, exception):
        # fit raised mid-window: an active jax.profiler trace is
        # process-global and leaking it breaks the NEXT start_trace —
        # the fit loops' error seam guarantees this close runs
        self.close()


class StepTimerListener(TrainingListener):
    """Per-iteration wall-clock times with a value-fetch barrier.

    Why not ``jax.block_until_ready``: on remote/tunneled TPU backends (e.g.
    the axon tunnel this project benches through) ``block_until_ready`` can
    return before the device program actually finishes, silently producing
    near-zero step times. Only a device→host VALUE fetch (``np.asarray`` /
    ``float()`` of a result) is a reliable completion barrier. The fit loops
    evaluate ``float(loss)`` before dispatching ``iteration_done``, so the
    score this listener receives IS post-barrier — timing here is honest by
    construction. User code timing its own steps outside a listener must do
    its own value fetch (see PERF.md addendum 2)."""

    def __init__(self):
        self.times_ms: List[float] = []
        self._t0: Optional[float] = None

    def iteration_done(self, model, iteration, score):
        # score arrives as a host float — the caller's float(loss) was the
        # completion barrier for this step (see class docstring)
        now = time.perf_counter()
        if self._t0 is not None:
            self.times_ms.append((now - self._t0) * 1e3)
        self._t0 = now

    def summary(self) -> Dict[str, float]:
        if not self.times_ms:
            return {}
        arr = np.asarray(self.times_ms)
        return {"mean_ms": float(arr.mean()), "p50_ms": float(np.median(arr)),
                "p95_ms": float(np.percentile(arr, 95)),
                "n": float(arr.size)}


#: attribute name for per-net step_cost state: ONE jitwatch wrapper per
#: net (the wrapper's cached_lowering memoizes the trace by abstract
#: signature) plus the finished cost dicts per shape key. Stored ON the
#: net object — its lifetime IS the net's (a module-level
#: WeakKeyDictionary would never evict here: the wrapper's step closure
#: captures the net, so the value would strongly reference its own key).
#: Repeated step_cost(net, ds) with the same shapes therefore pays ZERO
#: re-trace and ZERO re-compile — the pre-fix code built a fresh wrapper
#: every call, so even an already-compiled step paid a full second trace
#: per cost query.
_STEP_COST_ATTR = "_step_cost_state"


def step_cost(net, ds) -> Dict[str, Any]:
    """XLA cost analysis of the container's compiled train step on this
    DataSet's shapes: {'flops', 'bytes_accessed', ...} plus derived
    per-example numbers. Works for MultiLayerNetwork and ComputationGraph.
    Memoized per (net, shapes) — see ``_STEP_COST_ATTR``; with the
    persistent compile cache enabled (``DL4J_TPU_COMPILE_CACHE_DIR``,
    ``compilecache/``) even the first call's ``.compile()`` rides the
    disk cache."""
    import jax
    import jax.numpy as jnp

    from ..datasets.dataset import DataSet

    if isinstance(ds, DataSet):
        f = jnp.asarray(ds.features)
        l = jnp.asarray(ds.labels)
        feats, labels = f, l
        is_graph = hasattr(net, "conf") and hasattr(net.conf, "vertices")
        if is_graph:
            feats, labels = (f,), (l,)
        batch = int(f.shape[0])
    else:  # MultiDataSet
        feats = tuple(jnp.asarray(x) for x in ds.features)
        labels = tuple(jnp.asarray(x) for x in ds.labels)
        batch = int(ds.features[0].shape[0])

    state = getattr(net, _STEP_COST_ATTR, None)
    if state is None:
        from ..monitor.jitwatch import monitored_jit
        # both containers take with_rnn_state
        state = {"wrapper": monitored_jit(net._raw_step(False),
                                          name="profiling/step_cost"),
                 "costs": {}}
        setattr(net, _STEP_COST_ATTR, state)

    def leaf_key(tree):
        return tuple((tuple(x.shape), str(x.dtype))
                     for x in jax.tree_util.tree_leaves(tree))

    key = (leaf_key(feats), leaf_key(labels))
    cached = state["costs"].get(key)
    if cached is None:
        lowered = state["wrapper"].cached_lowering(
            net.params, net.states, net.updater_state,
            jnp.asarray(0, jnp.int32), jax.random.PRNGKey(0),
            feats, labels, None, None)
        from ..compat import cost_analysis
        cached = state["costs"][key] = dict(cost_analysis(
            lowered.compile()))
    ca = cached
    flops = float(ca.get("flops", 0.0))
    by = float(ca.get("bytes accessed", 0.0))
    return {"flops": flops, "bytes_accessed": by, "batch": batch,
            "gflop_per_example": flops / batch / 1e9,
            "mb_per_example": by / batch / 1e6,
            "raw": dict(ca)}
