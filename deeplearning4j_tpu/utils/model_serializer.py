"""Model checkpoint/resume: zip container with config + params + updater state.

TPU-native equivalent of reference ``deeplearning4j-nn/.../util/ModelSerializer.java``
(:37-41 container layout, ``writeModel`` :52): the zip holds ``configuration.json``
(self-describing config via :mod:`..nn.conf.serde`), ``coefficients.bin`` (params),
``updaterState.bin`` and ``normalizer.bin``. Where the reference stores ONE
flattened f32 buffer per file, we store an ``.npz`` of keypath→array so restore is
shape-checked per parameter and dtype-preserving (bfloat16/f64 params round-trip).
An extra ``states.bin`` member persists non-trainable layer state (BN running
stats) — the reference keeps those inside ``coefficients.bin`` views.

Resume is exact: updater state (Adam moments etc.) round-trips, matching the
reference's explicit promise (SURVEY.md §5 checkpoint/resume).
"""
from __future__ import annotations

import io
import json
import zipfile
from typing import Optional

import numpy as np
import jax

CONFIG_JSON = "configuration.json"
COEFFICIENTS_BIN = "coefficients.bin"
UPDATER_BIN = "updaterState.bin"
STATES_BIN = "states.bin"
NORMALIZER_BIN = "normalizer.bin"


def _path_str(keypath) -> str:
    parts = []
    for k in keypath:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def tree_to_npz_bytes(tree) -> bytes:
    """Serialize a pytree of arrays to npz keyed by keypath."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    buf = io.BytesIO()
    arrays = {}
    for keypath, leaf in leaves:
        a = np.asarray(leaf)
        if a.dtype == np.dtype("bfloat16"):
            # npz has no bfloat16; store as uint16 bit pattern with marker
            arrays["__bf16__" + _path_str(keypath)] = a.view(np.uint16)
        else:
            arrays[_path_str(keypath)] = a
    np.savez(buf, **arrays)
    return buf.getvalue()


def npz_bytes_into_tree(data: bytes, template):
    """Rebuild ``template``'s leaf values from npz bytes (keypath-matched,
    shape-checked)."""
    import jax.numpy as jnp
    with np.load(io.BytesIO(data)) as npz:
        stored = dict(npz)

    def lookup(keypath, leaf):
        p = _path_str(keypath)
        if "__bf16__" + p in stored:
            a = stored["__bf16__" + p].view(jnp.bfloat16.dtype)
        elif p in stored:
            a = stored[p]
        else:
            raise KeyError(f"Saved model is missing parameter '{p}'")
        if tuple(a.shape) != tuple(np.shape(leaf)):
            raise ValueError(f"Shape mismatch restoring '{p}': saved "
                             f"{a.shape} vs model {np.shape(leaf)}")
        return jnp.asarray(a, dtype=np.asarray(leaf).dtype)

    return jax.tree_util.tree_map_with_path(lookup, template)


class ModelSerializer:
    """Static facade mirroring the reference API (``writeModel``/``restore*``)."""

    @staticmethod
    def write_model(model, path, save_updater: bool = True, normalizer=None):
        from ..nn.multilayer import MultiLayerNetwork
        from ..nn.conf.serde import to_json

        kind = ("MultiLayerNetwork" if isinstance(model, MultiLayerNetwork)
                else "ComputationGraph")
        conf_doc = {"type": kind, "config": json.loads(to_json(model.conf)),
                    "iteration_count": model.iteration_count,
                    "epoch_count": model.epoch_count}
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr(CONFIG_JSON, json.dumps(conf_doc, indent=2))
            z.writestr(COEFFICIENTS_BIN, tree_to_npz_bytes(model.params))
            z.writestr(STATES_BIN, tree_to_npz_bytes(model.states))
            if save_updater and model.updater_state is not None:
                z.writestr(UPDATER_BIN, tree_to_npz_bytes(model.updater_state))
            if normalizer is not None:
                z.writestr(NORMALIZER_BIN, normalizer.to_bytes())
        return path

    writeModel = write_model

    # ------------------------------------------------------------------
    @staticmethod
    def _read(path):
        with zipfile.ZipFile(path, "r") as z:
            names = set(z.namelist())
            conf_doc = json.loads(z.read(CONFIG_JSON).decode("utf-8"))
            coeff = z.read(COEFFICIENTS_BIN)
            states = z.read(STATES_BIN) if STATES_BIN in names else None
            upd = z.read(UPDATER_BIN) if UPDATER_BIN in names else None
            norm = z.read(NORMALIZER_BIN) if NORMALIZER_BIN in names else None
        return conf_doc, coeff, states, upd, norm

    @staticmethod
    def restore_multi_layer_network(path, load_updater: bool = True):
        from ..nn.multilayer import MultiLayerNetwork
        from ..nn.conf import MultiLayerConfiguration
        from ..nn.conf.serde import decode

        conf_doc, coeff, states, upd, _ = ModelSerializer._read(path)
        if conf_doc["type"] != "MultiLayerNetwork":
            raise ValueError(f"Saved model is a {conf_doc['type']}; use "
                             f"restore_computation_graph")
        conf = decode(conf_doc["config"])
        net = MultiLayerNetwork(conf).init()
        ModelSerializer._restore_into(net, conf_doc, coeff, states,
                                      upd if load_updater else None)
        return net

    restoreMultiLayerNetwork = restore_multi_layer_network

    @staticmethod
    def restore_computation_graph(path, load_updater: bool = True):
        from ..nn.graph import ComputationGraph
        from ..nn.conf.serde import decode

        conf_doc, coeff, states, upd, _ = ModelSerializer._read(path)
        if conf_doc["type"] != "ComputationGraph":
            raise ValueError(f"Saved model is a {conf_doc['type']}; use "
                             f"restore_multi_layer_network")
        conf = decode(conf_doc["config"])
        net = ComputationGraph(conf).init()
        ModelSerializer._restore_into(net, conf_doc, coeff, states,
                                      upd if load_updater else None)
        return net

    restoreComputationGraph = restore_computation_graph

    @staticmethod
    def restore_model(path, load_updater: bool = True):
        """Type-dispatching restore (reference ``restoreMultiLayerNetwork`` /
        ``restoreComputationGraph`` pair behind ``ModelGuesser``)."""
        with zipfile.ZipFile(path, "r") as z:
            kind = json.loads(z.read(CONFIG_JSON).decode("utf-8"))["type"]
        if kind == "MultiLayerNetwork":
            return ModelSerializer.restore_multi_layer_network(path, load_updater)
        return ModelSerializer.restore_computation_graph(path, load_updater)

    @staticmethod
    def restore_normalizer(path):
        from ..datasets.normalizers import Normalizer
        _, _, _, _, norm = ModelSerializer._read(path)
        return None if norm is None else Normalizer.from_bytes(norm)

    restoreNormalizer = restore_normalizer

    @staticmethod
    def _restore_into(net, conf_doc, coeff, states, upd):
        net.params = npz_bytes_into_tree(coeff, net.params)
        if states is not None:
            net.states = npz_bytes_into_tree(states, net.states)
        if upd is not None:
            net.updater_state = npz_bytes_into_tree(upd, net.updater_state)
        net.iteration_count = int(conf_doc.get("iteration_count", 0))
        net.epoch_count = int(conf_doc.get("epoch_count", 0))


write_model = ModelSerializer.write_model
restore_multi_layer_network = ModelSerializer.restore_multi_layer_network
restore_computation_graph = ModelSerializer.restore_computation_graph
restore_model = ModelSerializer.restore_model
