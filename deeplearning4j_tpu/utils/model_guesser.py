"""ModelGuesser — load a model/config from a path without knowing its kind.

Reference: ``deeplearning4j-core/.../util/ModelGuesser.java`` (loadModelGuess
tries DL4J zip then Keras HDF5; loadConfigGuess tries MultiLayerConfiguration
JSON, then Keras config, then ComputationGraphConfiguration JSON). Here the
format is sniffed from magic bytes first — zip (``PK``) → ModelSerializer,
HDF5 (``\\x89HDF``) → KerasModelImport — so no load is attempted blind; bare
JSON files fall through to the config guess.
"""
from __future__ import annotations

import json

from .model_serializer import ModelSerializer

_ZIP_MAGIC = b"PK"
_HDF5_MAGIC = b"\x89HDF\r\n\x1a\n"


def _magic(path: str, n: int = 8) -> bytes:
    with open(path, "rb") as fh:
        return fh.read(n)


class ModelGuesser:
    """Format-sniffing loaders (reference ``ModelGuesser.java``)."""

    @staticmethod
    def load_model_guess(path: str, load_updater: bool = True):
        """A trained model from ``path``: DL4J zip (either container, with
        coefficients/updater), Keras HDF5 (Sequential→MLN, functional→CG),
        or a bare config JSON (returns a freshly ``init()``-ed net)."""
        head = _magic(path)
        if head.startswith(_ZIP_MAGIC):
            return ModelSerializer.restore_model(path, load_updater)
        if head.startswith(_HDF5_MAGIC):
            from ..keras.model_import import KerasModelImport
            return KerasModelImport.import_keras_model_and_weights(path)
        conf = ModelGuesser.load_config_guess(path)
        from ..nn.conf import MultiLayerConfiguration
        from ..nn.multilayer import MultiLayerNetwork
        from ..nn.graph import ComputationGraph
        if isinstance(conf, MultiLayerConfiguration):
            return MultiLayerNetwork(conf).init()
        return ComputationGraph(conf).init()

    loadModelGuess = load_model_guess

    @staticmethod
    def load_config_guess(path: str):
        """A network CONFIGURATION from a JSON file: tries
        ``MultiLayerConfiguration`` then ``ComputationGraphConfiguration``
        (reference tries "json before YAML" for the same reason: the first
        parser that accepts wins)."""
        from ..nn.conf import MultiLayerConfiguration
        from ..nn.conf.graph import ComputationGraphConfiguration

        with open(path, encoding="utf-8") as fh:
            text = fh.read()
        json.loads(text)  # fail fast with a JSON error, not a serde error
        errors = []
        for cls in (MultiLayerConfiguration, ComputationGraphConfiguration):
            try:
                return cls.from_json(text)
            except Exception as e:  # noqa: BLE001 — collect and report all
                errors.append(f"{cls.__name__}: {e}")
        raise ValueError(
            "Could not interpret the JSON as either container configuration:\n"
            + "\n".join(errors))

    loadConfigGuess = load_config_guess

    @staticmethod
    def load_normalizer(path: str):
        """Facade for ``ModelSerializer.restore_normalizer`` (reference
        ``ModelGuesser.loadNormalizer``)."""
        return ModelSerializer.restore_normalizer(path)

    loadNormalizer = load_normalizer
