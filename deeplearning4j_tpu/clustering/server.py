"""Nearest-neighbors REST server.

TPU-native equivalent of reference
``deeplearning4j-nearestneighbors-parent/nearestneighbor-server/.../
NearestNeighborsServer.java`` (Play-based) + the client and base64-NDArray
wire model: a stdlib HTTP server exposing VPTree kNN over a loaded point set.

 - POST /knn       {"index": i, "k": n}           → neighbors of stored point
 - POST /knnnew    {"point": [...], "k": n}       → neighbors of a new point
 - GET  /status    → {"numPoints": ..., "dim": ...}
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import urlparse

import numpy as np

from .trees import VPTree


class NearestNeighborsServer:
    def __init__(self, points: np.ndarray, distance: str = "euclidean",
                 port: int = 9200):
        self.points = np.asarray(points, np.float64)
        self.tree = VPTree(self.points, distance=distance)
        self.port = port
        self._httpd = None
        self._thread = None

    def start(self, port: Optional[int] = None) -> int:
        if port is not None:
            self.port = port
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _json(self, obj, code=200):
                payload = json.dumps(obj).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                if urlparse(self.path).path == "/status":
                    self._json({"numPoints": len(server.points),
                                "dim": int(server.points.shape[1])})
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):
                path = urlparse(self.path).path
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length).decode("utf-8"))
                    k = int(body.get("k", 5))
                    if path == "/knn":
                        q = server.points[int(body["index"])]
                    elif path == "/knnnew":
                        q = np.asarray(body["point"], np.float64)
                    else:
                        self._json({"error": "not found"}, 404)
                        return
                    idxs, dists = server.tree.search(q, k)
                    self._json({"results": [
                        {"index": int(i), "distance": float(d)}
                        for i, d in zip(idxs, dists)]})
                except Exception as e:
                    self._json({"error": str(e)}, 400)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


class NearestNeighborsClient:
    """HTTP client (reference ``nearestneighbor-client``)."""

    def __init__(self, address: str):
        self.address = address.rstrip("/")

    def _post(self, path, body):
        import urllib.request
        req = urllib.request.Request(
            self.address + path, data=json.dumps(body).encode("utf-8"),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def knn(self, index: int, k: int):
        return self._post("/knn", {"index": index, "k": k})

    def knn_new(self, point, k: int):
        return self._post("/knnnew", {"point": list(map(float, point)),
                                      "k": k})

    knnNew = knn_new
