"""K-Means clustering with jitted assignment/update steps.

TPU-native equivalent of reference
``clustering/kmeans/KMeansClustering.java`` + cluster strategies
(``clustering/algorithm/``): Lloyd iterations where the O(n·k·d) distance
matrix + argmin and the centroid reduction run as one jitted XLA computation
(the reference loops point-by-point in Java over ND4J ops).
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..monitor.jitwatch import monitored_jit


@monitored_jit(name="clustering/kmeans_step")
def _assign_update(points, centroids):
    """(assignments, new centroids, inertia) — one Lloyd iteration."""
    d2 = (jnp.sum(points ** 2, axis=1)[:, None]
          - 2.0 * points @ centroids.T
          + jnp.sum(centroids ** 2, axis=1)[None, :])
    assign = jnp.argmin(d2, axis=1)
    inertia = jnp.sum(jnp.min(d2, axis=1))
    k = centroids.shape[0]
    onehot = jax.nn.one_hot(assign, k, dtype=points.dtype)       # [n, k]
    counts = onehot.sum(axis=0)                                   # [k]
    sums = onehot.T @ points                                      # [k, d]
    new_centroids = jnp.where(counts[:, None] > 0,
                              sums / jnp.maximum(counts[:, None], 1.0),
                              centroids)
    return assign, new_centroids, inertia


class Cluster:
    def __init__(self, center: np.ndarray, points: np.ndarray,
                 indices: np.ndarray):
        self.center = center
        self.points = points
        self.indices = indices


class ClusterSet:
    def __init__(self, centroids: np.ndarray, assignments: np.ndarray,
                 points: np.ndarray, inertia: float):
        self.centroids = centroids
        self.assignments = assignments
        self.points = points
        self.inertia = inertia

    def get_clusters(self):
        out = []
        for i in range(len(self.centroids)):
            sel = np.flatnonzero(self.assignments == i)
            out.append(Cluster(self.centroids[i], self.points[sel], sel))
        return out

    getClusters = get_clusters

    def nearest_cluster(self, point) -> int:
        d = np.linalg.norm(self.centroids - np.asarray(point), axis=1)
        return int(np.argmin(d))

    nearestCluster = nearest_cluster


class KMeansClustering:
    """Reference ``KMeansClustering.setup(k, maxIterations, distance)``."""

    def __init__(self, k: int, max_iterations: int = 100, tol: float = 1e-4,
                 seed: int = 123):
        self.k = k
        self.max_iterations = max_iterations
        self.tol = tol
        self.seed = seed

    @staticmethod
    def setup(k: int, max_iterations: int = 100,
              distance: str = "euclidean", seed: int = 123):
        if distance not in ("euclidean", "sqeuclidean"):
            raise ValueError("Only euclidean distance is supported")
        return KMeansClustering(k, max_iterations, seed=seed)

    def apply_to(self, points) -> ClusterSet:
        """Run Lloyd's algorithm (k-means++ init)."""
        x = np.asarray(points, np.float32)
        rng = np.random.default_rng(self.seed)
        centroids = self._kmeans_pp_init(x, rng)
        xj = jnp.asarray(x)
        cj = jnp.asarray(centroids)
        prev_inertia = np.inf
        for _ in range(self.max_iterations):
            assign, cj, inertia = _assign_update(xj, cj)
            inertia = float(inertia)
            if abs(prev_inertia - inertia) <= self.tol * max(abs(inertia), 1.0):
                break
            prev_inertia = inertia
        return ClusterSet(np.asarray(cj), np.asarray(assign), x, inertia)

    applyTo = apply_to

    def _kmeans_pp_init(self, x, rng) -> np.ndarray:
        n = len(x)
        centroids = [x[rng.integers(0, n)]]
        for _ in range(1, self.k):
            d2 = np.min([np.sum((x - c) ** 2, axis=1) for c in centroids],
                        axis=0)
            total = d2.sum()
            if total <= 0:  # all remaining points coincide with centroids
                centroids.append(x[rng.integers(0, n)])
                continue
            centroids.append(x[rng.choice(n, p=d2 / total)])
        return np.stack(centroids)
