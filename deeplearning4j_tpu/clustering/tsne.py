"""t-SNE: exact (device-jitted) and Barnes-Hut (SpTree-approximated).

TPU-native equivalent of reference ``deeplearning4j-core/.../plot/``
(``BarnesHutTsne.java`` 868 LoC using SpTree, and exact ``Tsne``): the exact
variant keeps the O(n²) force computation as ONE jitted XLA step (ideal MXU
shape — the reference does this op-by-op); the Barnes-Hut variant reproduces
the reference's theta-condition tree approximation for large n where O(n²)
memory is the binding constraint.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from .trees import SpTree, VPTree
from ..monitor.jitwatch import monitored_jit


# ------------------------------------------------------------ P construction
def _h_beta(d2_row: np.ndarray, beta: float):
    p = np.exp(-d2_row * beta)
    sum_p = max(p.sum(), 1e-12)
    h = np.log(sum_p) + beta * float(d2_row @ p) / sum_p
    return h, p / sum_p


def _search_beta(d2_row: np.ndarray, target: float, tol: float = 1e-5,
                 max_tries: int = 50) -> np.ndarray:
    """Bisection on the Gaussian precision for ONE row of squared distances
    until the entropy hits ``target`` (= log perplexity). Returns the row's
    conditional probabilities."""
    beta, lo, hi = 1.0, -np.inf, np.inf
    h, p = _h_beta(d2_row, beta)
    for _ in range(max_tries):
        if abs(h - target) < tol:
            break
        if h > target:
            lo = beta
            beta = beta * 2 if hi == np.inf else (beta + hi) / 2
        else:
            hi = beta
            beta = beta / 2 if lo == -np.inf else (beta + lo) / 2
        h, p = _h_beta(d2_row, beta)
    return p


def _binary_search_p(d2: np.ndarray, perplexity: float, tol: float = 1e-5,
                     max_tries: int = 50) -> np.ndarray:
    """Per-row precision search to hit the target perplexity (reference
    ``Tsne.computeGaussianPerplexity``)."""
    n = d2.shape[0]
    target = np.log(perplexity)
    P = np.zeros((n, n))
    for i in range(n):
        idx = np.concatenate([np.arange(i), np.arange(i + 1, n)])
        P[i, idx] = _search_beta(d2[i, idx], target, tol, max_tries)
    P = (P + P.T) / (2 * n)
    return np.maximum(P, 1e-12)


# ------------------------------------------------------------- exact stepper
@monitored_jit(name="clustering/tsne_step")
def _tsne_step(y, P, gains, vel, lr, momentum):
    d2 = (jnp.sum(y ** 2, 1)[:, None] - 2 * y @ y.T + jnp.sum(y ** 2, 1)[None, :])
    num = 1.0 / (1.0 + d2)
    num = num - jnp.diag(jnp.diag(num))
    Q = jnp.maximum(num / jnp.sum(num), 1e-12)
    PQ = (P - Q) * num
    grad = 4.0 * (jnp.diag(PQ.sum(axis=1)) - PQ) @ y
    gains = jnp.where(jnp.sign(grad) != jnp.sign(vel),
                      gains + 0.2, gains * 0.8)
    gains = jnp.maximum(gains, 0.01)
    vel = momentum * vel - lr * gains * grad
    y = y + vel
    y = y - y.mean(axis=0)
    kl = jnp.sum(P * jnp.log(P / Q))
    return y, gains, vel, kl


class Tsne:
    """Exact t-SNE (reference ``plot/Tsne.java``)."""

    def __init__(self, n_components: int = 2, perplexity: float = 30.0,
                 learning_rate: float = 200.0, n_iter: int = 500,
                 momentum: float = 0.8, early_exaggeration: float = 12.0,
                 seed: int = 123):
        self.n_components = n_components
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.n_iter = n_iter
        self.momentum = momentum
        self.early_exaggeration = early_exaggeration
        self.seed = seed
        self.kl_ = None

    def fit_transform(self, x) -> np.ndarray:
        x = np.asarray(x, np.float64)
        n = len(x)
        d2 = ((x ** 2).sum(1)[:, None] - 2 * x @ x.T + (x ** 2).sum(1)[None, :])
        P = _binary_search_p(d2, min(self.perplexity, (n - 1) / 3))
        rng = np.random.default_rng(self.seed)
        y = jnp.asarray(rng.normal(scale=1e-4, size=(n, self.n_components)),
                        jnp.float32)
        gains = jnp.ones_like(y)
        vel = jnp.zeros_like(y)
        Pj = jnp.asarray(P, jnp.float32)
        exag_until = min(250, self.n_iter // 2)
        for it in range(self.n_iter):
            P_eff = Pj * self.early_exaggeration if it < exag_until else Pj
            mom = 0.5 if it < exag_until else self.momentum
            y, gains, vel, kl = _tsne_step(y, P_eff, gains, vel,
                                           jnp.float32(self.learning_rate),
                                           jnp.float32(mom))
        self.kl_ = float(kl)
        return np.asarray(y)

    fitTransform = fit_transform


class BarnesHutTsne(Tsne):
    """Barnes-Hut t-SNE (reference ``plot/BarnesHutTsne.java``): sparse
    attractive forces over a kNN graph (VPTree, 3·perplexity neighbors) and
    SpTree-approximated repulsive forces with the theta condition."""

    def __init__(self, theta: float = 0.5, **kw):
        # the host-loop BH dynamics are stabler at a lower rate than the
        # jitted exact stepper's default
        kw.setdefault("learning_rate", 100.0)
        super().__init__(**kw)
        self.theta = theta

    def fit_transform(self, x) -> np.ndarray:
        if self.theta <= 0:
            return super().fit_transform(x)
        x = np.asarray(x, np.float64)
        n = len(x)
        k = min(int(3 * self.perplexity), n - 1)
        tree = VPTree(x, seed=self.seed)
        rows = np.zeros((n, k), np.int64)
        d2 = np.zeros((n, k))
        for i in range(n):
            idxs, dists = tree.search(x[i], k + 1)
            sel = [(j, dd) for j, dd in zip(idxs, dists) if j != i][:k]
            rows[i] = [j for j, _ in sel]
            d2[i] = [dd ** 2 for _, dd in sel]
        # per-row perplexity search on the kNN distances
        P = {}
        target = np.log(min(self.perplexity, (n - 1) / 3))
        for i in range(n):
            p = _search_beta(d2[i], target)
            for jpos, j in enumerate(rows[i]):
                P[(i, int(j))] = P.get((i, int(j)), 0.0) + p[jpos] / (2 * n)
                P[(int(j), i)] = P.get((int(j), i), 0.0) + p[jpos] / (2 * n)

        pairs = np.asarray(list(P.keys()), np.int64)
        pvals = np.asarray(list(P.values()))
        rng = np.random.default_rng(self.seed)
        y = rng.normal(scale=1e-4, size=(n, self.n_components))
        vel = np.zeros_like(y)
        gains = np.ones_like(y)
        exag_until = min(250, self.n_iter // 2)
        for it in range(self.n_iter):
            exag = self.early_exaggeration if it < exag_until else 1.0
            mom = 0.5 if it < exag_until else self.momentum
            # attractive (sparse, exact)
            diff = y[pairs[:, 0]] - y[pairs[:, 1]]
            qz = 1.0 / (1.0 + (diff ** 2).sum(1))
            att = np.zeros_like(y)
            np.add.at(att, pairs[:, 0],
                      (exag * pvals * qz)[:, None] * diff)
            # repulsive (Barnes-Hut via SpTree)
            sptree = SpTree(y)
            rep = np.zeros_like(y)
            sum_q = 0.0
            for i in range(n):
                neg, sq = sptree.compute_non_edge_forces(i, self.theta)
                rep[i] = neg
                sum_q += sq
            grad = 4.0 * (att - rep / max(sum_q, 1e-12))
            gains = np.where(np.sign(grad) != np.sign(vel), gains + 0.2,
                             gains * 0.8)
            gains = np.maximum(gains, 0.01)
            vel = mom * vel - self.learning_rate * gains * grad
            y = y + vel
            y = y - y.mean(axis=0)
        return y
