"""Nearest neighbors & clustering (reference nearestneighbors-parent + core
t-SNE — SURVEY.md §2.7/§2.2): VPTree, KDTree, QuadTree, SpTree, K-Means,
exact + Barnes-Hut t-SNE."""
from .trees import VPTree, KDTree, QuadTree, SpTree
from .kmeans import KMeansClustering, ClusterSet, Cluster
from .tsne import Tsne, BarnesHutTsne
from .server import NearestNeighborsServer, NearestNeighborsClient

__all__ = ["VPTree", "KDTree", "QuadTree", "SpTree", "KMeansClustering",
           "ClusterSet", "Cluster", "Tsne", "BarnesHutTsne", "NearestNeighborsServer",
           "NearestNeighborsClient"]
