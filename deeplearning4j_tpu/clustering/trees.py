"""Spatial trees: VPTree, KDTree, QuadTree, SpTree.

TPU-native equivalents of reference ``deeplearning4j-nearestneighbors-parent/
nearestneighbor-core/.../clustering/`` (SURVEY.md §2.7): ``vptree/VPTree.java``
(+``VPTreeFillSearch``), ``kdtree/KDTree.java``, ``quadtree/QuadTree.java``,
``sptree/SpTree.java`` (the Barnes-Hut dual tree used by t-SNE).

Tree *construction* is host-side recursion (pointer-chasing, wrong shape for
the MXU — same layering as the reference, where these are pure-Java); bulk
distance evaluations inside search go through vectorized numpy.
"""
from __future__ import annotations

import heapq
from typing import List, Optional, Sequence, Tuple

import numpy as np


# ------------------------------------------------------------------- VPTree
class VPTree:
    """Vantage-point tree for metric kNN (reference ``VPTree.java``;
    euclidean / cosine similarity like the reference's distance functions)."""

    class _Node:
        __slots__ = ("index", "threshold", "left", "right")

        def __init__(self, index):
            self.index = index
            self.threshold = 0.0
            self.left = None
            self.right = None

    def __init__(self, items: np.ndarray, distance: str = "euclidean",
                 seed: int = 123):
        self.items = np.asarray(items, np.float64)
        self.distance = distance
        self._rng = np.random.default_rng(seed)
        idx = list(range(len(self.items)))
        self.root = self._build(idx)

    def _dist(self, a_idx: int, points: np.ndarray) -> np.ndarray:
        a = self.items[a_idx]
        if self.distance == "cosine":
            na = np.linalg.norm(a) or 1e-12
            nb = np.linalg.norm(points, axis=1)
            return 1.0 - points @ a / (na * np.maximum(nb, 1e-12))
        return np.linalg.norm(points - a, axis=1)

    def _build(self, idx: List[int]):
        if not idx:
            return None
        if len(idx) == 1:
            return VPTree._Node(idx[0])
        vp_pos = int(self._rng.integers(0, len(idx)))
        idx[0], idx[vp_pos] = idx[vp_pos], idx[0]
        vp = idx[0]
        rest = idx[1:]
        d = self._dist(vp, self.items[rest])
        median = float(np.median(d))
        node = VPTree._Node(vp)
        node.threshold = median
        inner = [rest[i] for i in range(len(rest)) if d[i] <= median]
        outer = [rest[i] for i in range(len(rest)) if d[i] > median]
        node.left = self._build(inner)
        node.right = self._build(outer)
        return node

    def _dist_point(self, q: np.ndarray, idx: int) -> float:
        p = self.items[idx]
        if self.distance == "cosine":
            nq = np.linalg.norm(q) or 1e-12
            np_ = np.linalg.norm(p) or 1e-12
            return float(1.0 - q @ p / (nq * np_))
        return float(np.linalg.norm(q - p))

    def search(self, query, k: int) -> Tuple[List[int], List[float]]:
        """k nearest (indices, distances) — reference ``search(INDArray, k,
        results, distances)``."""
        q = np.asarray(query, np.float64)
        heap: List[Tuple[float, int]] = []  # max-heap via negation
        tau = [np.inf]

        def visit(node):
            if node is None:
                return
            d = self._dist_point(q, node.index)
            if d < tau[0] or len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
                if len(heap) > k:
                    heapq.heappop(heap)
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            if node.left is None and node.right is None:
                return
            if d < node.threshold:
                visit(node.left)
                if d + tau[0] >= node.threshold:
                    visit(node.right)
            else:
                visit(node.right)
                if d - tau[0] <= node.threshold:
                    visit(node.left)

        visit(self.root)
        out = sorted(((-nd, i) for nd, i in heap))
        return [i for _, i in out], [d for d, _ in out]


# ------------------------------------------------------------------- KDTree
class KDTree:
    """Axis-aligned kd-tree (reference ``kdtree/KDTree.java``)."""

    class _Node:
        __slots__ = ("index", "axis", "left", "right")

        def __init__(self, index, axis):
            self.index = index
            self.axis = axis
            self.left = None
            self.right = None

    def __init__(self, points: np.ndarray):
        self.points = np.asarray(points, np.float64)
        self.dims = self.points.shape[1]
        self.root = self._build(list(range(len(self.points))), 0)

    def _build(self, idx: List[int], depth: int):
        if not idx:
            return None
        axis = depth % self.dims
        idx.sort(key=lambda i: self.points[i, axis])
        mid = len(idx) // 2
        node = KDTree._Node(idx[mid], axis)
        node.left = self._build(idx[:mid], depth + 1)
        node.right = self._build(idx[mid + 1:], depth + 1)
        return node

    def nn(self, query) -> Tuple[int, float]:
        idxs, dists = self.knn(query, 1)
        return idxs[0], dists[0]

    def knn(self, query, k: int) -> Tuple[List[int], List[float]]:
        q = np.asarray(query, np.float64)
        heap: List[Tuple[float, int]] = []

        def visit(node):
            if node is None:
                return
            d = float(np.linalg.norm(self.points[node.index] - q))
            if len(heap) < k or d < -heap[0][0]:
                heapq.heappush(heap, (-d, node.index))
                if len(heap) > k:
                    heapq.heappop(heap)
            diff = q[node.axis] - self.points[node.index, node.axis]
            near, far = (node.left, node.right) if diff <= 0 else (node.right,
                                                                   node.left)
            visit(near)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                visit(far)

        visit(self.root)
        out = sorted(((-nd, i) for nd, i in heap))
        return [i for _, i in out], [d for d, _ in out]


# ------------------------------------------------------------ QuadTree/SpTree
class SpTree:
    """n-dimensional Barnes-Hut tree (reference ``sptree/SpTree.java``):
    center-of-mass aggregation per cell; used by t-SNE's repulsive-force
    approximation. 2-D instance ≡ the reference's QuadTree."""

    MAX_LEAF = 8

    class _Cell:
        __slots__ = ("center", "width", "children", "indices", "com", "mass")

        def __init__(self, center, width):
            self.center = center          # [d]
            self.width = width            # [d] half-extent
            self.children = None
            self.indices: List[int] = []
            self.com = np.zeros_like(center)
            self.mass = 0

    def __init__(self, data: np.ndarray):
        self.data = np.asarray(data, np.float64)
        lo = self.data.min(axis=0)
        hi = self.data.max(axis=0)
        center = (lo + hi) / 2
        width = np.maximum((hi - lo) / 2, 1e-9) * (1 + 1e-6)
        self.root = SpTree._Cell(center, width)
        for i in range(len(self.data)):
            self._insert(self.root, i)

    def _insert(self, cell, i):
        cell.mass += 1
        cell.com += (self.data[i] - cell.com) / cell.mass
        if cell.children is None:
            cell.indices.append(i)
            if len(cell.indices) > self.MAX_LEAF and np.all(cell.width > 1e-12):
                self._subdivide(cell)
            return
        self._insert(cell.children[self._child_of(cell, i)], i)

    def _child_of(self, cell, i) -> int:
        code = 0
        for d in range(self.data.shape[1]):
            if self.data[i, d] > cell.center[d]:
                code |= 1 << d
        return code

    def _subdivide(self, cell):
        d = self.data.shape[1]
        cell.children = []
        for code in range(1 << d):
            offset = np.array([(1 if code >> k & 1 else -1)
                               for k in range(d)], np.float64)
            child = SpTree._Cell(cell.center + offset * cell.width / 2,
                                 cell.width / 2)
            cell.children.append(child)
        idxs = cell.indices
        cell.indices = []
        for i in idxs:
            child = cell.children[self._child_of(cell, i)]
            child.mass += 1
            child.com += (self.data[i] - child.com) / child.mass
            child.indices.append(i)
        for child in cell.children:
            # width guard stops infinite subdivision when > MAX_LEAF points
            # coincide (duplicate rows) — same guard as _insert
            if (len(child.indices) > self.MAX_LEAF
                    and np.all(child.width > 1e-12)):
                self._subdivide(child)

    # -------------------------------------------------------------- queries
    def compute_non_edge_forces(self, point_idx: int, theta: float
                                ) -> Tuple[np.ndarray, float]:
        """Barnes-Hut negative-force accumulation for t-SNE (reference
        ``SpTree.computeNonEdgeForces``): returns (neg_force[d], sum_Q
        contribution)."""
        q = self.data[point_idx]
        neg = np.zeros_like(q)
        sum_q = 0.0
        stack = [self.root]
        while stack:
            cell = stack.pop()
            if cell.mass == 0:
                continue
            diff = q - cell.com
            dist2 = float(diff @ diff)
            max_width = float(cell.width.max() * 2)
            if (cell.children is not None and dist2 > 0
                    and max_width / np.sqrt(dist2) < theta):
                # far enough: the whole cell acts as one point at its COM
                qq = 1.0 / (1.0 + dist2)
                sum_q += cell.mass * qq
                neg += cell.mass * qq * qq * diff
            elif cell.children is not None:
                stack.extend(cell.children)
            else:
                # leaf: exact accumulation over its points (minus self) —
                # COM-approximating near leaves corrupts the repulsion as
                # soon as clusters tighten
                for i in cell.indices:
                    if i == point_idx:
                        continue
                    df = q - self.data[i]
                    d2 = float(df @ df)
                    qq = 1.0 / (1.0 + d2)
                    sum_q += qq
                    neg += qq * qq * df
        return neg, sum_q


class QuadTree(SpTree):
    """2-D SpTree (reference ``quadtree/QuadTree.java``)."""

    def __init__(self, data):
        data = np.asarray(data)
        if data.shape[1] != 2:
            raise ValueError("QuadTree requires 2-D points")
        super().__init__(data)
