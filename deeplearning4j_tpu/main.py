"""Operational CLI — the reference's ``ParallelWrapperMain`` (
``deeplearning4j-scaleout/deeplearning4j-scaleout-parallelwrapper/src/main/
java/org/deeplearning4j/parallelism/main/ParallelWrapperMain.java``: load a
model file, build a data iterator from a factory, train it under
ParallelWrapper with arg-controlled workers/averaging, save the result,
optionally post stats to a UI) as a TPU-native entry point:

    python -m deeplearning4j_tpu train \
        --model-path model.zip --model-output-path trained.zip \
        --data mnist --epochs 2 --averaging-frequency 1 --report-score

Differences from the reference, by design:
- ``--workers`` is advisory: the device mesh defines parallelism (every
  addressable device trains; the reference's per-GPU worker threads are an
  artifact of its dispatch model). A value != device count warns.
- ``--data`` names a built-in dataset (mnist/emnist/iris/cifar) or
  ``--data-factory module:callable`` imports a factory returning a
  DataSetIterator — the Python spelling of ``dataSetIteratorFactoryClazz``.
- Multi-host: ``--coordinator host:port --num-processes N --process-id i``
  forms the jax.distributed cluster first (``initialize_distributed``).
- ``serve-ui`` starts the training UI server over a stats file the run
  wrote (``--stats-file``), standing in for the reference's play UI.

Both reference camelCase flags (``--modelPath``) and kebab-case work.
"""
from __future__ import annotations

import argparse
import sys


def _factory(spec: str):
    """``module:callable`` → the callable's return value (the Python
    spelling of the reference's dataSetIteratorFactoryClazz)."""
    mod, _, fn = spec.partition(":")
    if not fn:
        raise SystemExit(f"--data-factory needs module:callable, got {spec!r}")
    import importlib
    return getattr(importlib.import_module(mod), fn)()


def _builtin_data(name: str, batch_size: int, num_examples=None,
                  train: bool = True):
    from .datasets.impl import (MnistDataSetIterator, EmnistDataSetIterator,
                                IrisDataSetIterator, CifarDataSetIterator)
    name = name.lower()
    if name == "mnist":
        return MnistDataSetIterator(batch_size, num_examples, train=train)
    if name.startswith("emnist"):
        # emnist or emnist-<split> (balanced/byclass/bymerge/digits/letters)
        split = name.partition("-")[2] or "balanced"
        return EmnistDataSetIterator(split, batch_size, num_examples,
                                     train=train)
    if name == "iris":
        return IrisDataSetIterator(batch_size, num_examples or 150)
    if name == "cifar":
        return CifarDataSetIterator(batch_size, num_examples, train=train)
    raise SystemExit(f"unknown --data {name!r} (mnist/emnist/iris/cifar, "
                     f"or use --data-factory module:callable)")


def _add_train_args(p: argparse.ArgumentParser):
    # required pair, exactly like the reference
    p.add_argument("--model-path", "--modelPath", required=True,
                   help="model to train: DL4J zip, Keras .h5, or config "
                        "JSON (ModelGuesser sniffs the format)")
    p.add_argument("--model-output-path", "--modelOutputPath", required=True,
                   help="where the trained model zip is written")
    p.add_argument("--data", default=None,
                   help="built-in dataset: mnist/emnist/iris/cifar")
    p.add_argument("--data-factory", "--dataSetIteratorFactory", default=None,
                   help="module:callable returning a DataSetIterator")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--num-examples", type=int, default=None,
                   help="cap the built-in dataset size")
    p.add_argument("--workers", type=int, default=None,
                   help="advisory; the device mesh defines parallelism")
    p.add_argument("--prefetch-size", "--prefetchSize", type=int, default=16)
    p.add_argument("--averaging-frequency", "--averagingFrequency",
                   type=int, default=1)
    p.add_argument("--report-score", "--reportScore", action="store_true")
    p.add_argument("--no-average-updaters", dest="average_updaters",
                   action="store_false", default=True)
    p.add_argument("--mode", choices=("averaging", "shared_gradients"),
                   default="averaging")
    p.add_argument("--fsdp", action="store_true",
                   help="ZeRO-3-style sharded param+optimizer storage")
    p.add_argument("--weight-update-sharding", action="store_true",
                   help="ZeRO-1-style sharded optimizer state")
    p.add_argument("--ui-url", "--uiUrl", default=None,
                   help="host:port of a UI server to post stats to")
    p.add_argument("--stats-file", default=None,
                   help="write training stats to this sqlite/json file "
                        "(serve later with `serve-ui`)")
    # multi-host cluster formation
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)


def cmd_train(args) -> int:
    import jax
    from .parallel import (ParallelWrapper, TrainingMode,
                           initialize_distributed, is_chief)
    from .utils.model_guesser import ModelGuesser
    from .utils.model_serializer import ModelSerializer
    from .optimize.listeners import ScoreIterationListener

    if args.coordinator:
        initialize_distributed(args.coordinator,
                               num_processes=args.num_processes,
                               process_id=args.process_id)
    n_dev = len(jax.devices())
    if args.workers and args.workers != n_dev:
        print(f"# --workers {args.workers} is advisory: the mesh has "
              f"{n_dev} devices and all of them train", file=sys.stderr)

    # data first: bad --data args fail fast, before the (possibly large)
    # model load
    if args.data and args.data_factory:
        raise SystemExit("--data and --data-factory are mutually exclusive "
                         "(the factory would silently win)")
    data = (_factory(args.data_factory) if args.data_factory
            else _builtin_data(args.data or "mnist", args.batch_size,
                               args.num_examples))

    net = ModelGuesser.load_model_guess(args.model_path)

    listeners = []
    if args.report_score:
        listeners.append(ScoreIterationListener(1))
    if args.ui_url or args.stats_file:
        from .ui import (StatsListener, FileStatsStorage,
                         RemoteUIStatsStorageRouter)
        if args.ui_url:
            url = args.ui_url
            if "://" not in url:
                url = f"http://{url}"
            listeners.append(StatsListener(RemoteUIStatsStorageRouter(url)))
        if args.stats_file:
            listeners.append(StatsListener(FileStatsStorage(args.stats_file)))
    if listeners:
        net.set_listeners(*listeners)

    if not args.average_updaters:
        # reference knob with no seam here: updater-state averaging is
        # fused into the jitted step (freq>1 pmean), not a separate pass
        print("# --no-average-updaters has no effect: updater averaging "
              "is fused into the step", file=sys.stderr)
    mode = (TrainingMode.SHARED_GRADIENTS
            if args.mode == "shared_gradients" else TrainingMode.AVERAGING)
    b = (ParallelWrapper.Builder(net)
         .training_mode(mode)
         .averaging_frequency(args.averaging_frequency)
         .prefetch_buffer(args.prefetch_size))
    if args.report_score:
        b = b.report_score_after_averaging()
    if args.fsdp:
        b = b.fsdp()
    if args.weight_update_sharding:
        b = b.weight_update_sharding()
    pw = b.build()
    pw.fit(data, epochs=args.epochs)

    if args.fsdp or args.weight_update_sharding:
        pw.gather_model()
    if is_chief():
        ModelSerializer.write_model(net, args.model_output_path,
                                    save_updater=True)
        print(f"model written to {args.model_output_path} "
              f"(last score {pw.last_score})")
    return 0


def cmd_serve_ui(args, block: bool = True) -> int:
    import time
    from .ui import UIServer, FileStatsStorage, InMemoryStatsStorage
    storage = (FileStatsStorage(args.stats_file) if args.stats_file
               else InMemoryStatsStorage())
    server = UIServer.get_instance()
    server.attach(storage)
    host = getattr(args, "host", None) or "127.0.0.1"
    port = server.start(args.port, host=host)  # /remote receiver included
    print(f"training UI on http://{host}:{port}", flush=True)
    if not block:                          # tests: caller owns the server
        return port
    try:
        while True:                        # serve_forever runs in a thread
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


def cmd_monitor(args) -> int:
    """Dump an observability snapshot (docs/OBSERVABILITY.md): metrics +
    health from a running server's ``/metrics``+``/healthz`` when ``--url``
    is given, else this process's own monitor registry/health state.
    ``--trace-out`` additionally writes the Chrome trace-event JSON
    (``/trace`` remotely, the local tracer otherwise) to a file for
    Perfetto. ``--fleet`` switches to the aggregated per-worker view
    (``/fleet``); ``--events`` prints the flight recorder's structured
    event log as JSONL; ``--profile`` prints the step-anatomy report
    (per-fn jit compiles/times/flops + device memory + step/ETL split,
    ``/profile`` remotely); ``--alerts`` prints the alert engine's rule
    states (``/alerts`` remotely — docs/OBSERVABILITY.md "Alerting &
    SLOs"); ``--control`` prints the control plane's policy states and
    recent actions (``/control`` remotely — docs/CONTROL.md);
    ``--history`` prints the metric-history ring meta (``/history``
    remotely); ``--probes`` prints the probe plane's target table —
    golden-set versions, last outcomes, deadman ages (``/probes``
    remotely — docs/OBSERVABILITY.md "Probe plane"); ``--incidents``
    prints the incident recorder's table — one line per merged
    incident with its rules, status, and bundle path (``/incidents``
    remotely — docs/OBSERVABILITY.md "Incident plane");
    ``--collect LABEL=URL[,...]`` runs one scrape-plane tick
    over the given ``/telemetry`` targets and prints the merged fleet
    view (exit 1 if any scrape failed)."""
    import json
    import urllib.error
    import urllib.request

    def _fetch(base, path):
        try:
            with urllib.request.urlopen(base + path, timeout=10) as r:
                return r.read().decode("utf-8")
        except urllib.error.HTTPError as e:
            # /healthz answers 503 WITH a body when unhealthy — still a dump
            return e.read().decode("utf-8")

    base = None
    if args.url:
        base = args.url if "://" in args.url else f"http://{args.url}"
        base = base.rstrip("/")

    if args.collect:
        # one-shot scrape-plane tick (monitor/collector.py): poll each
        # target's /telemetry into a PRIVATE FleetState and print the
        # merged view — the daemonized version of this is
        # TelemetryCollector.start() inside the serving process
        from .monitor.collector import TelemetryCollector
        from .monitor.fleet import FleetState
        collector = TelemetryCollector(fleet=FleetState())
        for spec in args.collect.split(","):
            spec = spec.strip()
            if not spec:
                continue
            label, sep, url = spec.partition("=")
            if not sep:
                # bare URL: derive the label from host:port
                url = spec
                label = (url.split("://", 1)[-1].rstrip("/")
                         .replace("/", "_"))
            collector.add_target(label.strip(), url.strip())
        summary = collector.tick()
        for label, err in sorted(summary.get("errors", {}).items()):
            print(f"# scrape {label} FAILED: {err}", file=sys.stderr)
        if args.format == "json":
            print(json.dumps({"targets": collector.snapshot(),
                              "liveness": collector.fleet.liveness()},
                             indent=2, default=repr))
        else:
            from .monitor import render_prometheus_dump
            print(render_prometheus_dump(collector.fleet_dump()), end="")
        return 0 if not summary.get("errors") else 1

    if args.profile:
        # step-anatomy view (docs/OBSERVABILITY.md "Compilation & memory")
        if base:
            if args.format == "json":
                print(json.dumps(json.loads(_fetch(base, "/profile")),
                                 indent=2))
            else:
                print(_fetch(base, "/profile?format=text"), end="")
        else:
            from .monitor import profile_report, render_profile_text
            rep = profile_report()
            if args.format == "json":
                print(json.dumps(rep, indent=2))
            else:
                print(render_profile_text(rep), end="")
        return 0

    if args.alerts:
        # alert-rule states: one line per rule in text mode, the full
        # /alerts JSON with --format json; exit 0 either way (the alert
        # is the GAUGE's job — a monitoring dump must stay scriptable)
        if base:
            doc = json.loads(_fetch(base, "/alerts"))
        else:
            from .monitor import get_alert_engine
            engine = get_alert_engine()
            engine.evaluate(strict=False)
            doc = engine.snapshot()
        if args.format == "json":
            print(json.dumps(doc, indent=2))
        else:
            rows = doc.get("alerts", [])
            if not rows:
                print("# no alert rules registered")
            for r in rows:
                print(f"{r['state']:<8} {r['rule']:<36} "
                      f"value={r.get('value')} {r.get('detail', '')}"
                      + (f" exemplar={r['exemplar_trace_id']}"
                         if r.get("exemplar_trace_id") else ""))
            if doc.get("firing"):
                print(f"# FIRING: {', '.join(doc['firing'])}")
        return 0

    if args.control:
        # control-plane view: policy state machines + recent actuator
        # invocations (/control remotely — docs/CONTROL.md runbook)
        if base:
            doc = json.loads(_fetch(base, "/control"))
        else:
            from .control import get_control_plane
            doc = get_control_plane().snapshot()
        if args.format == "json":
            print(json.dumps(doc, indent=2))
        else:
            rows = doc.get("policies", [])
            if not rows:
                print("# no control policies registered")
            for r in rows:
                trig = ", ".join(r.get("rules") or []) or r.get("event")
                print(f"{r['state']:<10} {r['policy']:<28} "
                      f"on={trig} fired={r.get('fired_count', 0)} "
                      f"suppressed={r.get('suppressed_count', 0)} "
                      f"cooldown_remaining="
                      f"{round(r.get('cooldown_remaining_s', 0.0), 1)}s")
            for a in doc.get("actions", []):
                print(f"# action {a.get('policy')}/{a.get('action')} "
                      f"outcome={a.get('outcome')} rule={a.get('rule')}"
                      + (f" exemplar={a['exemplar_trace_id']}"
                         if a.get("exemplar_trace_id") else ""))
            if doc.get("cooldowns_active"):
                print("# COOLDOWN: "
                      + ", ".join(doc["cooldowns_active"]))
        return 0

    if args.probes:
        # probe-plane view: per-target last outcome / consecutive
        # failures / deadman age (/probes remotely —
        # docs/OBSERVABILITY.md "Probe plane")
        if base:
            doc = json.loads(_fetch(base, "/probes"))
        else:
            from .monitor import get_prober
            doc = get_prober().snapshot()
        if args.format == "json":
            print(json.dumps(doc, indent=2))
        else:
            rows = doc.get("targets", {})
            if not rows:
                print("# no probe targets configured")
            for label, r in sorted(rows.items()):
                age = r.get("last_success_age_s")
                print(f"{(r.get('last_outcome') or 'never'):<10} "
                      f"{label:<24} model={r.get('model')} "
                      f"golden={r.get('golden_version')} "
                      f"fails={r.get('consecutive_failures', 0)} "
                      f"last_success_age="
                      f"{round(age, 1) if age is not None else '-'}s"
                      + (f" trace={r['last_trace_id']}"
                         if r.get("last_trace_id") else ""))
            print(f"# running={doc.get('running')} "
                  f"interval={doc.get('interval_s')}s "
                  f"fail_threshold={doc.get('fail_threshold')}")
        return 0

    if args.incidents:
        # incident-plane view: one line per merged incident — status,
        # member rules, capture count, persisted bundle path
        # (/incidents remotely — docs/OBSERVABILITY.md "Incident plane")
        if base:
            doc = json.loads(_fetch(base, "/incidents"))
        else:
            from .monitor import get_incident_recorder
            doc = get_incident_recorder().snapshot()
        if args.format == "json":
            print(json.dumps(doc, indent=2))
        else:
            rows = doc.get("incidents", [])
            if not rows:
                print("# no incidents recorded")
            for r in rows:
                print(f"{r['status']:<9} {r['id']:<10} "
                      f"rules={','.join(r.get('rules') or []) or '-'} "
                      f"captures={r.get('captures', 0)} "
                      f"events={r.get('flight_events', 0)}"
                      + (f" bundle={r['path']}" if r.get("path") else ""))
            print(f"# open={','.join(doc.get('open') or []) or 'none'} "
                  f"evicted={doc.get('evicted', 0)} "
                  f"running={doc.get('running')}")
        return 0

    if args.history:
        # metric-history ring meta (the per-series view is the HTTP
        # endpoint's ?metric= job — a terminal wants the shape, not
        # thousands of points)
        if base:
            doc = json.loads(_fetch(base, "/history"))
        else:
            from .monitor import get_history
            doc = get_history().describe()
        print(json.dumps(doc, indent=2))
        return 0

    if args.events:
        # flight-recorder view: one JSON object per line (JSONL — the same
        # shape the on-disk halt/crash dumps use, so tooling reads both)
        if base:
            events = json.loads(_fetch(base, "/events"))["events"]
        else:
            from .monitor import get_flight_recorder
            events = get_flight_recorder().events()
        for rec in events:
            print(json.dumps(rec, default=repr))
        return 0

    if args.fleet:
        # aggregated per-worker view: only meaningful where OP_TELEMETRY
        # reports land (the paramserver-server process, or --url to it)
        if args.format == "json":
            payload = (_fetch(base, "/fleet?format=json") if base
                       else None)
            if payload is None:
                from .monitor import get_fleet
                doc = get_fleet().liveness()
            else:
                doc = json.loads(payload)
            print(json.dumps(doc, indent=2))
        else:
            if base:
                print(_fetch(base, "/fleet"), end="")
            else:
                from .monitor import get_fleet
                print(get_fleet().render_prometheus(), end="")
        return 0

    if base:
        metrics_text = _fetch(base, "/metrics")
        health = json.loads(_fetch(base, "/healthz"))
        trace = _fetch(base, "/trace") if args.trace_out else None
    else:
        from .monitor import get_registry, get_health, get_tracer
        metrics_text = get_registry().render_prometheus()
        health = get_health().snapshot()
        trace = (json.dumps(get_tracer().export())
                 if args.trace_out else None)

    if args.format == "json":
        from .monitor import get_registry
        out = {"health": health}
        if args.url:
            out["metrics_text"] = metrics_text
        else:
            out["metrics"] = get_registry().snapshot()
        print(json.dumps(out, indent=2))
    else:
        print(metrics_text, end="")
        print("# health " + json.dumps(health))
    if args.trace_out and trace is not None:
        with open(args.trace_out, "w") as fh:
            fh.write(trace)
        print(f"# trace written to {args.trace_out}", file=sys.stderr)
    return 0


def cmd_incident(args) -> int:
    """Offline incident tooling: ``incident show <path>`` re-loads a
    persisted ``.dl4jinc`` bundle (content address verified from the
    filename) and renders the merged seq-ordered timeline — alert
    edges, probe outcomes, control actions, each rule's pinned exemplar
    trace tree — exactly what the responder reconstructs after the
    process is gone (docs/OBSERVABILITY.md "Incident plane")."""
    import json
    from .monitor.incidents import load_bundle, render_incident_text
    try:
        bundle = load_bundle(args.path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"incident show: cannot load {args.path}: {e}",
              file=sys.stderr)
        return 1
    if args.format == "json":
        print(json.dumps(bundle, indent=2, default=repr))
    else:
        print(render_incident_text(bundle))
    return 0


def cmd_cache(args) -> int:
    """Compile-once fleet operations (PERF.md "Compile-once fleet";
    ``deeplearning4j_tpu/compilecache/``):

    - ``--stats`` (the default): census of the cache directory — jax
      compile-cache entries, AOT warmup artifacts, total bytes, and this
      process's persistent hit/miss counts.
    - ``--gc``: evict AOT artifacts whose fingerprint no longer matches
      the RUNNING jax/backend (plus unreadable ones). DRY-RUN by default
      — the report lists what would go; ``--apply`` deletes. jax's own
      opaque cache entries are never touched (their key already encodes
      the toolchain version).
    - ``--export``: build a content-addressed AOT warmup artifact from a
      model file: ``cache --export --model-path m.zip --input-shape 784
      --out artifacts/`` (plus ``--buckets``/``--precision``/``--name``).
      Load it on a cold replica with ``register(...,
      warmup_artifact=path)``.

    The directory defaults to ``--dir``, else the active
    ``DL4J_TPU_COMPILE_CACHE_DIR``.
    """
    import json
    from .compilecache import cache_stats, gc_cache

    if args.export:
        if not (args.model_path and args.input_shape and args.out):
            raise SystemExit("cache --export needs --model-path, "
                             "--input-shape and --out")
        from .utils.model_guesser import ModelGuesser
        from .serving.registry import ServedModel
        net = ModelGuesser.load_model_guess(args.model_path)
        shape = tuple(int(d) for d in args.input_shape.split(",")
                      if d.strip())
        kw = {}
        if args.buckets:
            kw["batch_buckets"] = tuple(int(b) for b in
                                        args.buckets.split(",") if b.strip())
        served = ServedModel(args.name, net, input_shape=shape,
                             precision=args.precision, **kw)
        try:
            path = served.export_warmup(args.out)
        finally:
            served.close(drain=False)
        print(path)
        return 0
    if args.gc:
        report = gc_cache(args.dir, dry_run=not args.apply)
        print(json.dumps(report, indent=2))
        return 0
    print(json.dumps(cache_stats(args.dir), indent=2))
    return 0


def _changed_files(root: str) -> list:
    """Repo-relative ``git diff``-touched .py files (working tree vs HEAD,
    plus untracked), absolutized — the ``lint --changed`` scope."""
    import os
    import subprocess
    out: list = []
    # --relative: diff prints toplevel-relative paths by default, which
    # silently drop every match when this repo is nested inside an outer
    # git repository; ls-files --others is already cwd-relative
    for argv in (["git", "diff", "--name-only", "--relative", "HEAD",
                  "--"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            text = subprocess.run(
                argv, cwd=root, check=True, capture_output=True,
                text=True, timeout=30).stdout
        except (OSError, subprocess.SubprocessError) as e:
            raise SystemExit(f"lint --changed needs a git checkout: {e}")
        for line in text.splitlines():
            line = line.strip()
            if line.endswith(".py"):
                ap = os.path.join(root, line)
                if os.path.exists(ap) and ap not in out:
                    out.append(ap)
    return out


def cmd_lint(args) -> int:
    """tpulint (docs/STATIC_ANALYSIS.md): AST-check the package (or the
    given paths) for this stack's hazard classes — host-sync barriers in
    jitted code (JAX001), PRNG key reuse (JAX002), blocking calls under a
    lock (THR001), leaked threads (THR002), lock-order inversions and
    cross-function blocking-under-lock on the interprocedural lock graph
    (THR003/THR004), unguarded shared-field races via lockset guard
    inference (THR005), silent broad excepts (EXC001), leaked
    sockets/executors/servers (RES001), metric-name unit-suffix
    violations (MON001). Exit 0 iff no finding outside the
    baseline; deterministic output. ``--changed`` scopes the run to
    git-touched files for fast pre-commit checks (note: the
    interprocedural rules then only see those files — the tier-1 guard
    always runs the whole package)."""
    import json as _json
    import os
    from .analysis import (Linter, load_baseline, load_baseline_reasons,
                           save_baseline, DEFAULT_BASELINE_PATH,
                           PACKAGE_ROOT, REPO_ROOT)

    if args.write_baseline and (args.paths or args.select or args.changed):
        # a ratchet reset is inherently whole-package: a subset rewrite
        # would silently delete grandfathered entries for files/rules the
        # run never examined
        raise SystemExit("--write-baseline requires a full default run "
                         "(no explicit paths, no --select, no --changed)")
    if args.changed:
        if args.paths:
            raise SystemExit("--changed and explicit paths are mutually "
                             "exclusive")
        paths = _changed_files(REPO_ROOT)
        if not paths:
            print("tpulint: no changed python files")
            return 0
    else:
        paths = args.paths or [PACKAGE_ROOT]
    rules = ([r.strip() for r in args.select.split(",") if r.strip()]
             if args.select else None)
    try:
        linter = Linter(rules=rules)
    except KeyError as e:
        raise SystemExit(f"lint: {e.args[0]}")

    baseline = {}
    baseline_path = args.baseline or DEFAULT_BASELINE_PATH
    if not args.no_baseline and os.path.exists(baseline_path):
        baseline = load_baseline(baseline_path)
    res = linter.run(paths, baseline=baseline)

    if args.write_baseline:
        # ratchet reset: current findings become the new grandfather list,
        # keeping the surviving entries' written reasons
        reasons = (load_baseline_reasons(baseline_path)
                   if os.path.exists(baseline_path) else {})
        save_baseline(baseline_path, res.new + res.baselined,
                      reasons=reasons)
        print(f"# baseline written to {baseline_path} "
              f"({len(res.new) + len(res.baselined)} findings)",
              file=sys.stderr)
        return 0
    if args.format == "json":
        print(_json.dumps(res.to_dict(), indent=2))
    else:
        print(res.render_text())
    return res.exit_code


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="deeplearning4j_tpu",
        description="TPU-native DL4J operational entry points")
    sub = p.add_subparsers(dest="command", required=True)
    t = sub.add_parser("train",
                       help="ParallelWrapperMain: train a model file over "
                            "all devices")
    _add_train_args(t)
    t.set_defaults(fn=cmd_train)
    s = sub.add_parser("serve-ui", help="serve the training UI")
    s.add_argument("--stats-file", default=None)
    s.add_argument("--port", type=int, default=9000)
    s.add_argument("--host", default="127.0.0.1",
                   help="bind address (0.0.0.0 to allow remote scrapes)")
    s.set_defaults(fn=cmd_serve_ui)
    m = sub.add_parser("monitor",
                       help="dump a metrics/health snapshot (local process "
                            "or a running UI server's /metrics+/healthz)")
    m.add_argument("--url", default=None, metavar="HOST:PORT",
                   help="scrape a running UI server instead of this process")
    m.add_argument("--format", choices=("prometheus", "json"),
                   default="prometheus")
    m.add_argument("--trace-out", default=None, metavar="PATH",
                   help="also write Chrome trace-event JSON here")
    m.add_argument("--fleet", action="store_true",
                   help="aggregated per-worker fleet view (/fleet): "
                        "Prometheus text with a worker label, or the "
                        "liveness table with --format json")
    m.add_argument("--events", action="store_true",
                   help="print the crash flight recorder's structured "
                        "event log as JSONL")
    m.add_argument("--profile", action="store_true",
                   help="step-anatomy report: per-fn jit compile counts/"
                        "seconds/flops, device-memory gauges, step/ETL "
                        "timing split (text, or JSON with --format json)")
    m.add_argument("--alerts", action="store_true",
                   help="alert-rule states (OK/PENDING/FIRING) from the "
                        "SLO engine — one line per rule, or the /alerts "
                        "JSON with --format json")
    m.add_argument("--control", action="store_true",
                   help="control-plane policy states (OK/PENDING/"
                        "COOLDOWN) + recent actuator actions — one line "
                        "per policy, or the /control JSON with --format "
                        "json")
    m.add_argument("--history", action="store_true",
                   help="metric-history ring meta (/history): sampler "
                        "interval, capacity, sample count, family names")
    m.add_argument("--probes", action="store_true",
                   help="probe-plane target table (/probes): golden-set "
                        "versions, last outcomes, consecutive failures, "
                        "deadman ages — one line per target, or the "
                        "/probes JSON with --format json")
    m.add_argument("--incidents", action="store_true",
                   help="incident-recorder table (/incidents): one line "
                        "per merged incident — status, member rules, "
                        "captures, persisted bundle path — or the "
                        "/incidents JSON with --format json")
    m.add_argument("--collect", default=None, metavar="LABEL=URL[,...]",
                   help="one-shot scrape-plane tick: poll each target's "
                        "/telemetry, print the merged fleet view "
                        "(Prometheus text with worker labels, or the "
                        "liveness table with --format json); bare URLs "
                        "get host:port labels")
    m.set_defaults(fn=cmd_monitor)
    inc = sub.add_parser("incident",
                         help="offline incident-bundle tooling: render a "
                              "persisted .dl4jinc bundle as a merged "
                              "seq-ordered timeline (docs/OBSERVABILITY"
                              ".md 'Incident plane')")
    inc.add_argument("action", choices=("show",),
                     help="show: render one bundle")
    inc.add_argument("path", help="path to a .dl4jinc bundle file")
    inc.add_argument("--format", choices=("text", "json"),
                     default="text",
                     help="text: the human-readable timeline; json: the "
                          "verified raw bundle")
    inc.set_defaults(fn=cmd_incident)
    c = sub.add_parser("cache",
                       help="compile-once fleet: persistent XLA compile "
                            "cache stats/GC + AOT warmup-artifact export "
                            "(PERF.md 'Compile-once fleet')")
    c.add_argument("--dir", default=None, metavar="PATH",
                   help="cache directory (default: the active "
                        "DL4J_TPU_COMPILE_CACHE_DIR)")
    c.add_argument("--stats", action="store_true",
                   help="directory census: entries, artifacts, bytes "
                        "(the default action)")
    c.add_argument("--gc", action="store_true",
                   help="evict AOT artifacts whose fingerprint no longer "
                        "matches the running jax/backend — DRY-RUN unless "
                        "--apply")
    c.add_argument("--apply", action="store_true",
                   help="with --gc: actually delete the evictable "
                        "artifacts")
    c.add_argument("--export", action="store_true",
                   help="export an AOT warmup artifact from a model file "
                        "(needs --model-path, --input-shape, --out)")
    c.add_argument("--model-path", default=None,
                   help="model to export: DL4J zip, Keras .h5, or config "
                        "JSON")
    c.add_argument("--name", default="model",
                   help="served-model name recorded in the artifact")
    c.add_argument("--input-shape", default=None, metavar="D0[,D1...]",
                   help="per-example trailing shape, e.g. 784 or 50,16")
    c.add_argument("--buckets", default=None, metavar="B0[,B1...]",
                   help="batch buckets (default: the serving default set)")
    c.add_argument("--precision", choices=("f32", "bf16"), default="f32")
    c.add_argument("--out", default=None, metavar="PATH",
                   help="artifact output: a directory (content-addressed "
                        "name) or an exact file path")
    c.set_defaults(fn=cmd_cache)
    li = sub.add_parser("lint",
                        help="tpulint: AST static analysis for JAX/"
                             "concurrency/exception hazards "
                             "(docs/STATIC_ANALYSIS.md)")
    li.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the installed "
                         "deeplearning4j_tpu package)")
    li.add_argument("--format", choices=("text", "json"), default="text")
    li.add_argument("--baseline", default=None, metavar="PATH",
                    help="grandfather list (default: the shipped "
                         "analysis/baseline.json)")
    li.add_argument("--no-baseline", action="store_true",
                    help="report every finding, baselined or not")
    li.add_argument("--select", default=None, metavar="RULES",
                    help="comma-separated rule ids to run (default: all)")
    li.add_argument("--changed", action="store_true",
                    help="lint only git-diff-touched .py files (working "
                         "tree vs HEAD, plus untracked) — the fast "
                         "pre-commit scope; interprocedural rules see "
                         "only those files")
    li.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "(ratchet reset — review the diff!)")
    li.set_defaults(fn=cmd_lint)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
