"""Closed-loop control plane: alert edges in, actuator calls out.

PRs 2-10 built the sensors (metrics → history → :class:`AlertEngine`
with exemplar traces) and earlier PRs built the actuators
(``ShardedParameterServerGroup.scale_to``/``restart``, ``remap`` on the
training master, per-model serving admission caps); this module closes
the loop the ROADMAP carried since PR 10. A :class:`ControlPlane` is an
opt-in daemon (the :class:`~deeplearning4j_tpu.monitor.history.
MetricsHistory` sampler shape: nothing starts implicitly, ``start()`` is
idempotent, ``stop()`` joins) that maps alert firing/resolved edges and
flight-recorder events through declarative :class:`ControlPolicy` rules
to actuator invocations.

Anti-flap discipline — every policy runs an OK→COOLDOWN state machine:

- **edge-triggered**: a policy acts on the ``alert_firing`` EDGE (or a
  watched flight event), never on the level — one incident, one action.
- **hysteresis** (``sustain_s``): the alert must STAY firing that long
  past the edge before the action runs (on top of the rule's own
  ``for_seconds`` hold-down); a resolve inside the window cancels.
- **cooldown** (``cooldown_s``): after acting, the policy stays latched
  in COOLDOWN — further firing edges are counted as suppressed, never
  re-acted — and only re-arms once the cooldown has elapsed AND the
  triggering alert resolved (flight-event policies re-arm on cooldown
  alone; there is no resolve edge to wait for).

Threading shape (the lock-graph invariant tests/test_lockwatch.py pins):
the plane's subscription callback does nothing but append to a lock-free
deque — actuators must NEVER run on the alert-evaluation thread or under
``AlertEngine._eval_lock``. The plane's own tick thread drains the
queue, runs the pure state machine under ``ControlPlane._lock``, and
invokes actuators with **no lock held at all**; action bookkeeping
re-enters the lock afterwards. ``tick()`` is public — tests drive the
loop deterministically instead of sleeping.

Every action lands as a ``control_action`` flight event carrying the
triggering alert's rule name and exemplar trace id (the whole incident
reconstructs from ``GET /events``), bumps
``control_actions_total{policy,action,outcome}``, and flips the
``control_cooldown_active{policy}`` gauge for the latch's lifetime.
Surfaces: ``GET /control`` (both servers), ``monitor --control``, and
the ``control`` block on ``GET /profile``. Zero policies are installed
by default — tier-1 seed behavior is untouched until a caller adds a
pack (see :mod:`deeplearning4j_tpu.control.policies`).
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..monitor.lockwatch import make_lock

log = logging.getLogger(__name__)

__all__ = ["ControlPolicy", "ControlPlane", "get_control_plane",
           "control_block"]

OK, PENDING, COOLDOWN = "OK", "PENDING", "COOLDOWN"

#: default daemon cadence; tests bypass it entirely via tick()
DEFAULT_INTERVAL_S = 0.5


def _action_counter(policy: str, action: str, outcome: str):
    from ..monitor.registry import get_registry
    return get_registry().counter(
        "control_actions_total",
        "control-plane actuator invocations by policy, actuator, and "
        "outcome (suppressed = edge arrived while latched in cooldown)",
        policy=policy, action=action, outcome=outcome)


def _cooldown_gauge(policy: str):
    from ..monitor.registry import get_registry
    return get_registry().gauge(
        "control_cooldown_active",
        "1 while the policy's OK→COOLDOWN machine is latched — firing "
        "edges are suppressed until it re-arms", policy=policy)


class ControlPolicy:
    """One declarative rule: *when* (alert rule names or a flight event)
    → *what* (the actuator callable) under the anti-flap state machine.

    ``action(ctx)`` receives the triggering edge's payload (``rule``,
    ``exemplar_trace_id``, ``value``, ``detail`` for alert edges; the
    recorded fields for flight events) and returns a short outcome
    string (``None`` → ``"ok"``); raising records ``outcome="error"``
    and still latches the cooldown (a failed actuator retrying every
    tick is exactly the flapping the latch exists to stop).
    ``on_resolve(ctx)``, when given, runs on the triggering alert's
    resolved edge — the restore half of a step-down actuator."""

    def __init__(self, name: str, action: Callable[[Dict[str, Any]],
                                                   Optional[str]], *,
                 rules: Sequence[str] = (), event: Optional[str] = None,
                 action_name: Optional[str] = None,
                 on_resolve: Optional[Callable[[Dict[str, Any]],
                                               Optional[str]]] = None,
                 resolve_name: Optional[str] = None,
                 cooldown_s: float = 30.0, sustain_s: float = 0.0,
                 description: str = ""):
        if not rules and event is None:
            raise ValueError(f"policy {name!r} matches nothing: give "
                             f"rules=(...) and/or event=...")
        self.name = str(name)
        self.action = action
        self.action_name = str(action_name or getattr(
            action, "__name__", "action"))
        self.on_resolve = on_resolve
        self.resolve_name = str(resolve_name or self.action_name
                                + "_restore")
        self.rules = tuple(str(r) for r in rules)
        self.event = str(event) if event is not None else None
        self.cooldown_s = float(cooldown_s)
        self.sustain_s = float(sustain_s)
        self.description = description
        # ---- state machine (guarded by the owning plane's _lock) ----
        self.state = OK
        self.pending_since: Optional[float] = None
        self.pending_ctx: Optional[Dict[str, Any]] = None
        self.cooldown_until: Optional[float] = None
        self.resolved_seen = False
        self.fired_count = 0
        self.suppressed_count = 0
        self.last_action: Optional[Dict[str, Any]] = None

    def _reset(self):
        self.state = OK
        self.pending_since = None
        self.pending_ctx = None
        self.cooldown_until = None
        self.resolved_seen = False

    def to_dict(self, now: float) -> Dict[str, Any]:
        remaining = 0.0
        if self.state == COOLDOWN and self.cooldown_until is not None:
            remaining = max(0.0, self.cooldown_until - now)
        return {"policy": self.name, "state": self.state,
                "rules": list(self.rules), "event": self.event,
                "action": self.action_name,
                "cooldown_s": self.cooldown_s,
                "sustain_s": self.sustain_s,
                "cooldown_remaining_s": remaining,
                "fired_count": self.fired_count,
                "suppressed_count": self.suppressed_count,
                "last_action": self.last_action,
                "description": self.description}


class ControlPlane:
    """Holds policies, drives their state machines, invokes actuators.

    One plane per process (:func:`get_control_plane`). ``start()``
    subscribes to the alert engine's edge stream and runs the tick
    thread; ``tick()`` is the deterministic test seam. Policies may be
    added/removed live — removal while that policy's action is mid-
    flight is safe (the detached policy's bookkeeping is discarded and
    its cooldown gauge zeroed; see ``_finish_action``)."""

    def __init__(self, engine=None):
        self._lock = make_lock("ControlPlane._lock")
        self._engine = engine
        self._policies: Dict[str, ControlPolicy] = {}
        # lock-free handoff from the alert-engine fan-out thread: the
        # subscription callback must not take ANY lock (it runs under
        # AlertEngine._eval_lock — an actuator there would graft the
        # whole actuator lock tree onto the evaluation lock)
        self._edges: deque = deque(maxlen=1024)
        self._actions: deque = deque(maxlen=256)
        self._event_seq: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.interval_s = DEFAULT_INTERVAL_S
        self.last_tick: Optional[float] = None

    @property
    def engine(self):
        if self._engine is not None:
            return self._engine
        from ..monitor.alerts import get_alert_engine
        return get_alert_engine()

    # ------------------------------------------------------------ policies
    def add(self, *policies: ControlPolicy) -> "ControlPlane":
        with self._lock:
            for p in policies:
                if p.name in self._policies:
                    raise ValueError(f"control policy {p.name!r} already "
                                     f"registered")
                self._policies[p.name] = p
        return self

    def remove(self, name: str):
        """Detach a policy. An action already handed to the executor may
        still complete (the actuator ran for a real edge), but its state
        is discarded and no FUTURE edge can fire it."""
        with self._lock:
            p = self._policies.pop(name, None)
            if p is not None:
                p._reset()
        if p is not None:
            # outside the lock (registry takes its own): a removed
            # policy must not strand its cooldown gauge at 1
            _cooldown_gauge(name).set(0.0)

    def policies(self) -> List[ControlPolicy]:
        with self._lock:
            return [self._policies[n] for n in sorted(self._policies)]

    def clear(self):
        """Full reset: policies, pending edges, the action ring, and the
        flight-event cursor (the next tick re-primes) — a cleared plane
        must surface as empty, not replay a previous wiring's history."""
        with self._lock:
            names, self._policies = list(self._policies), {}
            self._actions.clear()
            self._edges.clear()
            self._event_seq = None
        for name in names:
            _cooldown_gauge(name).set(0.0)

    # ----------------------------------------------------------- lifecycle
    def _on_edge(self, event: str, payload: Dict[str, Any]):
        """AlertEngine subscription callback — enqueue only, never act:
        this runs on the evaluation thread under ``_eval_lock``."""
        self._edges.append((event, payload))

    def running(self) -> bool:
        with self._lock:
            return self._thread is not None and self._thread.is_alive()

    def start(self, interval_s: Optional[float] = None) -> "ControlPlane":
        """Subscribe + start the tick daemon (idempotent)."""
        if interval_s is not None:
            self.interval_s = float(interval_s)
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="control-plane", daemon=True)
            thread = self._thread
        # outside our lock: each takes its own (flight recorder, engine)
        self._prime_cursor()
        self.engine.subscribe(self._on_edge)
        thread.start()
        return self

    def stop(self, timeout: float = 5.0):
        """Unsubscribe and join the tick thread. Queued-but-unprocessed
        edges survive in the deque — a later start() resumes them."""
        self.engine.unsubscribe(self._on_edge)
        with self._lock:
            thread, self._thread = self._thread, None
            if thread is not None:
                # inside the lock for the same reason MetricsHistory.stop
                # sets inside: a concurrent start() serializes behind us
                self._stop.set()
        if thread is not None:
            thread.join(timeout=timeout)

    def _loop(self):
        self.tick()
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                log.exception("control-plane tick failed")

    # ---------------------------------------------------------------- tick
    def _prime_cursor(self):
        """Fast-forward the flight-event cursor to 'now' without
        reacting — the plane only answers for events recorded after it
        came up, never replays history as fresh incidents. The recorder
        read happens OUTSIDE ``_lock`` (it takes its own; ours stays a
        leaf), only the cursor store goes under it: the cursor is
        written here on start()'s thread AND on the tick thread, and
        cleared by clear() on any caller's — all under ``_lock``."""
        from ..monitor.flightrec import get_flight_recorder
        events = get_flight_recorder().events()
        seq = int(events[-1]["seq"]) if events else 0
        with self._lock:
            self._event_seq = seq

    def _new_flight_events(self) -> List[Dict[str, Any]]:
        with self._lock:
            watched = {p.event for p in self._policies.values()
                       if p.event is not None}
            cursor = self._event_seq
        if not watched:
            return []
        if cursor is None:
            self._prime_cursor()
            return []
        from ..monitor.flightrec import get_flight_recorder
        events = get_flight_recorder().events()
        fresh = [e for e in events
                 if int(e.get("seq", 0)) > cursor
                 and e.get("event") in watched]
        if events:
            with self._lock:
                if self._event_seq is not None:
                    # clear() raced the recorder read: stay reset so the
                    # next tick re-primes instead of resurrecting the
                    # pre-clear cursor
                    self._event_seq = max(cursor, int(events[-1]["seq"]))
        return fresh

    def tick(self, now: Optional[float] = None) -> int:
        """One control pass: drain queued alert edges, scan new flight
        events, run timers (sustain maturation, cooldown re-arm), then
        execute the surviving actions outside every lock. Returns the
        number of actuator/bookkeeping executions this pass."""
        now = float(now) if now is not None else time.time()
        flight = self._new_flight_events()
        drained: List[Tuple[str, Dict[str, Any]]] = []
        while True:
            try:
                drained.append(self._edges.popleft())
            except IndexError:
                break
        todo: List[Optional[Tuple[ControlPolicy, str, Dict[str, Any]]]] = []
        armed: Dict[str, int] = {}
        with self._lock:
            self.last_tick = now
            for event, payload in drained:
                self._edge_locked(event, payload, now, todo, armed)
            for ev in flight:
                self._flight_locked(ev, now, todo, armed)
            self._timers_locked(now, todo, armed)
        ran = 0
        for entry in todo:
            if entry is None:
                continue            # cancelled by a same-batch resolve
            self._execute(*entry, now=now)
            ran += 1
        return ran

    # ------------------------------------------------- state machine (locked)
    def _arm(self, p: ControlPolicy, ctx: Dict[str, Any], now: float,
             todo: list, armed: Dict[str, int]):
        p.state = COOLDOWN
        p.cooldown_until = now + p.cooldown_s
        # flight-event policies re-arm on cooldown alone: there is no
        # resolved edge to wait for (the restart IS the resolution)
        p.resolved_seen = ctx.get("_from_event", False)
        p.fired_count += 1
        armed[p.name] = len(todo)
        todo.append((p, "act", ctx))

    def _edge_locked(self, event: str, payload: Dict[str, Any],
                     now: float, todo: list, armed: Dict[str, int]):
        rule = payload.get("rule")
        firing = event == "alert_firing"
        for p in self._policies.values():
            if rule not in p.rules:
                continue
            if firing:
                if p.state == OK:
                    if p.sustain_s > 0:
                        p.state = PENDING
                        p.pending_since = now
                        p.pending_ctx = dict(payload)
                    else:
                        self._arm(p, dict(payload), now, todo, armed)
                elif p.state == COOLDOWN:
                    p.suppressed_count += 1
                    todo.append((p, "suppress", dict(payload)))
                # PENDING: already waiting out its sustain window
            else:
                if p.state == PENDING:
                    # resolve inside the sustain window: the hysteresis
                    # did its job — no action for a transient breach
                    p._reset()
                elif p.state == COOLDOWN:
                    idx = armed.pop(p.name, None)
                    if idx is not None:
                        # armed earlier in THIS batch, resolved before
                        # anything executed: cancel, never act
                        todo[idx] = None
                        p._reset()
                        continue
                    p.resolved_seen = True
                    if p.on_resolve is not None:
                        todo.append((p, "resolve", dict(payload)))
                    if p.cooldown_until is not None \
                            and now >= p.cooldown_until:
                        p._reset()
                        todo.append((p, "rearm", {}))

    def _flight_locked(self, ev: Dict[str, Any], now: float, todo: list,
                       armed: Dict[str, int]):
        kind = ev.get("event")
        for p in self._policies.values():
            if p.event != kind:
                continue
            if p.state == OK:
                ctx = {k: v for k, v in ev.items()
                       if k not in ("t", "seq", "event")}
                ctx.setdefault("rule", kind)
                ctx.setdefault("exemplar_trace_id", None)
                ctx["_from_event"] = True
                if p.sustain_s > 0:
                    p.state = PENDING
                    p.pending_since = now
                    p.pending_ctx = ctx
                else:
                    self._arm(p, ctx, now, todo, armed)
            elif p.state == COOLDOWN:
                p.suppressed_count += 1
                todo.append((p, "suppress", {"rule": kind}))

    def _timers_locked(self, now: float, todo: list,
                       armed: Dict[str, int]):
        for p in self._policies.values():
            if p.state == PENDING and p.pending_since is not None \
                    and now - p.pending_since >= p.sustain_s:
                # still firing: edges are reliable, so no resolved edge
                # since the firing one means the breach persists
                ctx = p.pending_ctx or {}
                p.pending_since = None
                p.pending_ctx = None
                self._arm(p, ctx, now, todo, armed)
            elif p.state == COOLDOWN and p.resolved_seen \
                    and p.cooldown_until is not None \
                    and now >= p.cooldown_until:
                p._reset()
                todo.append((p, "rearm", {}))

    # ------------------------------------------------- execution (unlocked)
    def _execute(self, p: ControlPolicy, kind: str, ctx: Dict[str, Any],
                 now: float):
        if kind == "rearm":
            _cooldown_gauge(p.name).set(0.0)
            return
        if kind == "suppress":
            _action_counter(p.name, p.action_name, "suppressed").inc()
            return
        if kind == "resolve":
            self._run_actuator(p, p.on_resolve, p.resolve_name, ctx, now)
            return
        _cooldown_gauge(p.name).set(1.0)
        self._run_actuator(p, p.action, p.action_name, ctx, now)

    def _run_actuator(self, p: ControlPolicy, fn, action_name: str,
                      ctx: Dict[str, Any], now: float):
        """Invoke one actuator with NO lock held, then record: flight
        event (rule + exemplar — the /events reconstruction contract),
        counter, and the plane's recent-actions ring."""
        try:
            outcome = fn(ctx) or "ok"
        except Exception as e:
            outcome = "error"
            log.exception("control policy %r actuator %s failed",
                          p.name, action_name)
            detail = f"{type(e).__name__}: {e}"
        else:
            detail = ctx.get("detail")
        from ..monitor.flightrec import get_flight_recorder
        row = {"t": now, "policy": p.name, "action": action_name,
               "outcome": str(outcome), "rule": ctx.get("rule"),
               "exemplar_trace_id": ctx.get("exemplar_trace_id"),
               "detail": detail}
        get_flight_recorder().record(
            "control_action", policy=p.name, action=action_name,
            outcome=str(outcome), rule=ctx.get("rule"),
            exemplar_trace_id=ctx.get("exemplar_trace_id"),
            detail=detail)
        _action_counter(p.name, action_name, str(outcome)).inc()
        with self._lock:
            still_installed = self._policies.get(p.name) is p
            if still_installed:
                p.last_action = row
                self._actions.append(row)
        if not still_installed:
            # removed mid-action: the actuator ran for a real edge (the
            # flight event stands), but the latch must not outlive the
            # policy — zero the gauge remove() may have raced with
            _cooldown_gauge(p.name).set(0.0)

    # -------------------------------------------------------------- reading
    def actions(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(a) for a in self._actions]

    def snapshot(self) -> Dict[str, Any]:
        """The ``GET /control`` payload (always HTTP 200, like
        ``/alerts`` — the control surface must stay readable exactly
        when the loop is busy)."""
        now = time.time()
        with self._lock:
            rows = [self._policies[n].to_dict(now)
                    for n in sorted(self._policies)]
            actions = [dict(a) for a in self._actions]
            last = self.last_tick
            running = self._thread is not None and self._thread.is_alive()
        return {"policies": rows,
                "cooldowns_active": [r["policy"] for r in rows
                                     if r["state"] == COOLDOWN],
                "actions": actions,
                "running": running,
                "evaluated_at": last}

    def block(self) -> Dict[str, Any]:
        """The compact ``control`` block for ``GET /profile``."""
        now = time.time()
        with self._lock:
            if not self._policies and not self._actions:
                return {}
            states = [p.state for p in self._policies.values()]
            fired = sum(p.fired_count for p in self._policies.values())
            last = self._actions[-1] if self._actions else None
            running = self._thread is not None and self._thread.is_alive()
        return {"policies": len(states), "running": running,
                "cooldowns_active": states.count(COOLDOWN),
                "pending": states.count(PENDING),
                "actions_total": fired,
                "last_action": dict(last) if last else None}


#: the process-global plane every surface reads (zero policies, not
#: started — tier-1 behavior is untouched until a caller opts in)
_PLANE = ControlPlane()


def get_control_plane() -> ControlPlane:
    return _PLANE


def control_block() -> Dict[str, Any]:
    """Module-level hook ``profile_report`` reads via ``sys.modules``
    (the mesh-block pattern: an un-imported control plane costs /profile
    nothing)."""
    return _PLANE.block()
