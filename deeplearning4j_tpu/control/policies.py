"""The shipped policy pack: fleet scale-out, shard auto-restart,
serving pressure relief.

Each builder returns a :class:`~.plane.ControlPolicy` closed over the
actuator objects the caller hands it — the pack never reaches for
globals, so one process can run several planes against several fleets
(tests do). Nothing here is installed by default; wiring is explicit::

    plane = get_control_plane()
    plane.add(fleet_scale_policy(group, master),
              shard_restart_policy(group),
              serving_pressure_policy(registry, "mnist"))
    plane.start()

Threshold/hysteresis defaults follow the scaling-knee shape of the MPI
characterization literature: act late (sustained breach), back off long
(cooldown ≫ actuation latency), and make every step reversible — the
serving policy restores the pre-incident admission knobs on the
triggering alert's resolved edge.
"""
from __future__ import annotations

import logging
from typing import Optional, Sequence

from .plane import ControlPolicy

log = logging.getLogger(__name__)

__all__ = ["fleet_scale_policy", "shard_restart_policy",
           "serving_pressure_policy", "fleet_replica_policy",
           "probe_failure_policy", "default_control_policies"]


def fleet_scale_policy(group, master, *, rule: str = "fleet_worker_stale",
                       step: int = 1, max_servers: int = 4,
                       cooldown_s: float = 60.0, sustain_s: float = 0.0,
                       name: str = "fleet_scale") -> ControlPolicy:
    """Scale the paramserver fleet out on a sustained staleness alert.

    The action is the rebalance runbook end to end: ``group.scale_to``
    re-splits the merged state across ``+step`` nodes, then
    ``master.remap`` rebinds the training master — which first drains
    any in-flight round on the PR 15 overlap pipeline, so the membership
    change never splits a logical push across two shard layouts."""

    def scale_fleet(ctx):
        new_n = min(group.num_servers + int(step), int(max_servers))
        if new_n <= group.num_servers:
            return "at_max"
        addrs = group.scale_to(new_n)
        master.remap(addrs)
        return f"scaled_to_{new_n}"

    return ControlPolicy(
        name, scale_fleet, rules=(rule,), action_name="scale_to",
        cooldown_s=cooldown_s, sustain_s=sustain_s,
        description=f"scale paramserver fleet +{step} (cap "
                    f"{max_servers}) on sustained {rule}")


def shard_restart_policy(group, *, event: str = "shard_server_down",
                         cooldown_s: float = 10.0,
                         name: str = "shard_restart") -> ControlPolicy:
    """Auto-restart a dead shard server from its latest latched snapshot
    when a client reports it down (the ``shard_server_down`` flight
    event). A still-running server is left alone — a transient transport
    error must not bounce a healthy node; the client's own retry loop
    owns that case. Restart-from-snapshot keeps version numbering
    intact, so rejoining clients resync one DELTA_FULL and ride frames
    again."""

    def restart_shard(ctx):
        shard = ctx.get("shard")
        if shard is None:
            return "no_shard_in_event"
        shard = int(shard)
        if not 0 <= shard < group.num_servers:
            return "unknown_shard"
        srv = group.servers[shard]
        if getattr(srv, "_running", False):
            return "still_running"
        group.restart(shard, snapshot=group.last_snapshot(shard))
        return "restarted"

    return ControlPolicy(
        name, restart_shard, event=event, action_name="restart",
        cooldown_s=cooldown_s,
        description="restart a down shard server from its latest "
                    "snapshot")


def serving_pressure_policy(registry, model: str, *,
                            rules: Sequence[str] = (
                                "serving_p99_breach",
                                "serving_queue_saturation"),
                            factor: float = 0.5, min_cap: int = 8,
                            initial_cap: int = 64,
                            linger_ms: float = 0.0,
                            cooldown_s: float = 30.0,
                            sustain_s: float = 0.0,
                            name: Optional[str] = None) -> ControlPolicy:
    """Relieve serving pressure on a sustained p99/queue alert: step the
    model's admission cap down (``factor`` of the current cap, floored
    at ``min_cap``; an uncapped model gets ``initial_cap``), drop linger
    to ``linger_ms`` and force a flush — shed load NOW, serve what was
    already admitted. The pre-incident knobs are restored on the
    triggering alert's resolved edge, so the step is an incident-scoped
    clamp, not a permanent downgrade."""
    state = {}

    def step_admission(ctx):
        served = registry.get(model)
        cap = served.batcher.max_queue_examples
        new_cap = (max(int(min_cap), int(cap * factor))
                   if cap is not None else int(initial_cap))
        prev = served.set_admission(max_queue_examples=new_cap,
                                    linger_ms=linger_ms)
        # the FIRST step's knobs are the pre-incident baseline; a
        # repeated step inside one long incident must not "restore" to
        # the already-clamped values
        state.setdefault("prev", prev)
        served.batcher.flush(wait=False)
        return f"cap_{new_cap}"

    def restore_admission(ctx):
        prev = state.pop("prev", None)
        if prev is None:
            return "nothing_to_restore"
        registry.get(model).set_admission(**prev)
        return "restored"

    return ControlPolicy(
        name or f"serving_pressure_{model}", step_admission,
        rules=tuple(rules), action_name="set_admission",
        on_resolve=restore_admission, resolve_name="restore_admission",
        cooldown_s=cooldown_s, sustain_s=sustain_s,
        description=f"step {model!r} admission cap ×{factor} (floor "
                    f"{min_cap}) + flush on sustained serving pressure; "
                    f"restore on resolve")


def fleet_replica_policy(collector, restart, *,
                         rule: str = "fleet_target_down",
                         cooldown_s: float = 30.0,
                         sustain_s: float = 0.0,
                         name: str = "fleet_replica_restart"
                         ) -> ControlPolicy:
    """Bounce unresponsive scraped replicas on a sustained
    ``fleet_target_down`` alert (the scrape-plane pack,
    ``monitor.alerts.default_fleet_scope_rules``).

    ``restart`` is the caller's actuator — ``fn(label, url)`` doing
    whatever "restart" means in its deployment (respawn a process,
    re-create a container, page a human). The policy asks the
    ``collector`` which targets are currently down at FIRE time rather
    than trusting the alert payload: between the rule sustaining and the
    plane acting, a replica may have recovered on its own, and bouncing
    a healthy node is the one thing a remediation loop must never do."""

    def restart_down(ctx):
        down = collector.down_targets()
        if not down:
            return "none_down"
        for t in down:
            restart(t.label, t.url)
        return "restarted_" + ",".join(t.label for t in down)

    return ControlPolicy(
        name, restart_down, rules=(rule,), action_name="restart_replica",
        cooldown_s=cooldown_s, sustain_s=sustain_s,
        description=f"restart scraped replicas that are down at fire "
                    f"time on sustained {rule}")


def probe_failure_policy(prober, restart, *,
                         rules: Sequence[str] = ("probe_mismatch",
                                                 "probe_deadman"),
                         cooldown_s: float = 30.0,
                         sustain_s: float = 0.0,
                         name: str = "probe_failure_restart"
                         ) -> ControlPolicy:
    """Bounce replicas that FAIL PROBES on a sustained probe-plane alert
    (``monitor.alerts.default_probe_rules``: mismatch — wrong answers vs
    the golden set — or deadman — no correct answer inside the window).

    This is the gray-failure remediation no self-reported signal can
    drive: the scrape-plane ``fleet_replica_policy`` only sees a replica
    that stops ANSWERING, while a wedged model keeps ``/telemetry``
    perfectly healthy. ``restart`` is the caller's actuator —
    ``fn(label, url)``, same contract as the scrape-plane policy. The
    policy asks the ``prober`` which targets are failing at FIRE time
    rather than trusting the alert payload: a replica whose probes
    recovered between the rule sustaining and the plane acting must not
    be bounced."""

    def restart_failing(ctx):
        failing = prober.failing_targets()
        if not failing:
            return "none_failing"
        for t in failing:
            restart(t.label, t.url)
        return "restarted_" + ",".join(t.label for t in failing)

    return ControlPolicy(
        name, restart_failing, rules=tuple(rules),
        action_name="restart_replica",
        cooldown_s=cooldown_s, sustain_s=sustain_s,
        description="restart replicas failing synthetic probes "
                    "(mismatch/deadman) at fire time — the gray-failure "
                    "remediation path")


def default_control_policies(*, group=None, master=None, registry=None,
                             model: Optional[str] = None, collector=None,
                             restart=None, prober=None, probe_restart=None,
                             **overrides):
    """The full shipped pack for whatever actuators the caller has:
    fleet scale + shard restart when a ``group`` (and ``master``) is
    given, serving pressure relief when a ``registry`` + ``model`` is,
    replica restart when a scrape-plane ``collector`` + ``restart``
    actuator is, probe-failure restart when a ``prober`` is (its
    actuator is ``probe_restart``, falling back to ``restart``).
    ``overrides`` are forwarded to every builder that accepts them."""
    import inspect
    out = []

    def _kw(fn):
        accepted = set(inspect.signature(fn).parameters)
        return {k: v for k, v in overrides.items() if k in accepted}

    if group is not None and master is not None:
        out.append(fleet_scale_policy(group, master,
                                      **_kw(fleet_scale_policy)))
    if group is not None:
        out.append(shard_restart_policy(group,
                                        **_kw(shard_restart_policy)))
    if registry is not None and model is not None:
        out.append(serving_pressure_policy(
            registry, model, **_kw(serving_pressure_policy)))
    if collector is not None and restart is not None:
        out.append(fleet_replica_policy(collector, restart,
                                        **_kw(fleet_replica_policy)))
    probe_actuator = probe_restart if probe_restart is not None else restart
    if prober is not None and probe_actuator is not None:
        out.append(probe_failure_policy(prober, probe_actuator,
                                        **_kw(probe_failure_policy)))
    return out
