"""Closed-loop control plane: alerts and flight events in, actuator
calls out. See :mod:`deeplearning4j_tpu.control.plane` for the state
machine and docs/CONTROL.md for the policy model and runbook."""
from .plane import (ControlPlane, ControlPolicy, control_block,
                    get_control_plane)
from .policies import (default_control_policies, fleet_replica_policy,
                       fleet_scale_policy, probe_failure_policy,
                       serving_pressure_policy, shard_restart_policy)

__all__ = ["ControlPlane", "ControlPolicy", "get_control_plane",
           "control_block", "fleet_scale_policy", "shard_restart_policy",
           "serving_pressure_policy", "fleet_replica_policy",
           "probe_failure_policy", "default_control_policies"]
