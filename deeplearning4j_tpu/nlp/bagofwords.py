"""Bag-of-words / TF-IDF text vectorizers + inverted index.

TPU-native equivalents of reference
``bagofwords/vectorizer/{BagOfWordsVectorizer,TfidfVectorizer}.java`` and the
``text/invertedindex`` package (SURVEY.md §2.5 "Text pipeline"). Formula
parity with the reference:

 - tf(word, doc)  = count / documentLength              (``MathUtils.tf``,
   ``deeplearning4j-nn/.../util/MathUtils.java:271``)
 - idf(word)      = log10(totalDocs / docAppearedIn)    (``MathUtils.idf``
   :258; 0 when the corpus is empty)
 - tfidf          = tf * idf                            (``MathUtils.tfidf``
   :283; ``TfidfVectorizer.tfidfWord`` :127)

``transform`` returns a dense [1, vocab] row exactly like the reference's
``INDArray transform(List<String> tokens)`` (``TfidfVectorizer.java:105``);
``vectorize(text, label)`` pairs it with a one-hot label row as a DataSet
(``vectorize`` :62). The vectorizers run on the same tokenizer pipeline
(``nlp/text.py``) the embedding models use.
"""
from __future__ import annotations

import math
from collections import Counter, defaultdict
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

from .text import DefaultTokenizerFactory, TokenizerFactory
from ..datasets.dataset import DataSet

__all__ = ["InvertedIndex", "BagOfWordsVectorizer", "TfidfVectorizer"]


class InvertedIndex:
    """word → sorted list of document ids (reference ``text/invertedindex``:
    the lookup behind ``docAppearedIn``)."""

    def __init__(self):
        self._postings: Dict[str, List[int]] = defaultdict(list)
        self.num_docs = 0

    def add_document(self, doc_id: int, tokens: Iterable[str]):
        for tok in set(tokens):
            self._postings[tok].append(doc_id)
        self.num_docs = max(self.num_docs, doc_id + 1)

    addDocument = add_document

    def documents(self, word: str) -> List[int]:
        return list(self._postings.get(word, ()))

    def doc_appeared_in(self, word: str) -> int:
        """Number of documents containing ``word`` (reference
        ``vocabCache.docAppearedIn``)."""
        return len(self._postings.get(word, ()))

    docAppearedIn = doc_appeared_in

    def query(self, *words: str) -> List[int]:
        """Documents containing ALL the words (postings intersection)."""
        if not words:
            return []
        sets = [set(self._postings.get(w, ())) for w in words]
        out = set.intersection(*sets) if sets else set()
        return sorted(out)


class _BaseTextVectorizer:
    """Shared fit machinery (reference ``BaseTextVectorizer``): vocab from
    min-frequency-filtered corpus counts + the inverted index for document
    frequencies."""

    class Builder:
        def __init__(self):
            self._kw = {}
            self._tokenizer = DefaultTokenizerFactory()
            self._stop = ()

        def set_tokenizer_factory(self, tf: TokenizerFactory):
            self._tokenizer = tf
            return self

        setTokenizerFactory = set_tokenizer_factory

        def set_min_word_frequency(self, n: int):
            self._kw["min_word_frequency"] = int(n)
            return self

        setMinWordFrequency = set_min_word_frequency

        def set_stop_words(self, words):
            self._stop = tuple(words)
            return self

        setStopWords = set_stop_words

        def build(self):
            v = self._cls(**self._kw)  # set by subclass Builder
            v.tokenizer_factory = self._tokenizer
            v.stop_words = set(self._stop)
            return v

    def __init__(self, min_word_frequency: int = 1):
        self.min_word_frequency = int(min_word_frequency)
        self.tokenizer_factory: TokenizerFactory = DefaultTokenizerFactory()
        self.stop_words = set()
        self.vocab: List[str] = []
        self._vocab_index: Dict[str, int] = {}
        self.index = InvertedIndex()
        self.labels: List[str] = []

    # ------------------------------------------------------------------ fit
    def _tokens(self, text: str) -> List[str]:
        toks = self.tokenizer_factory.create(text).get_tokens()
        return [t for t in toks if t and t not in self.stop_words]

    def fit(self, documents: Sequence[str],
            labels: Optional[Sequence[str]] = None):
        """Build vocab + inverted index over the corpus (reference
        ``BaseTextVectorizer.buildVocab``)."""
        counts: Counter = Counter()
        tokenized = []
        for doc_id, text in enumerate(documents):
            toks = self._tokens(text)
            tokenized.append(toks)
            counts.update(toks)
            self.index.add_document(doc_id, toks)
        self.vocab = sorted(w for w, c in counts.items()
                            if c >= self.min_word_frequency)
        self._vocab_index = {w: i for i, w in enumerate(self.vocab)}
        if labels is not None:
            self.labels = sorted(set(labels))
        return self

    fitTransform = None  # defined below per subclass

    def num_words(self) -> int:
        return len(self.vocab)

    def index_of(self, word: str) -> int:
        return self._vocab_index.get(word, -1)

    # ------------------------------------------------------------ transform
    def _weight(self, word: str, count: int, doc_len: int) -> float:
        raise NotImplementedError

    def transform(self, text) -> np.ndarray:
        """[1, vocab] weight row (reference ``transform``)."""
        toks = self._tokens(text) if isinstance(text, str) else list(text)
        counts = Counter(toks)
        row = np.zeros((1, len(self.vocab)), np.float32)
        for word, count in counts.items():
            idx = self.index_of(word)
            if idx >= 0:
                row[0, idx] = self._weight(word, count, len(toks))
        return row

    def vectorize(self, text: str, label: str) -> DataSet:
        """(weights row, one-hot label) DataSet (reference ``vectorize`` :62)."""
        features = self.transform(text)
        onehot = np.zeros((1, max(len(self.labels), 1)), np.float32)
        if label in self.labels:
            onehot[0, self.labels.index(label)] = 1.0
        return DataSet(features, onehot)


class BagOfWordsVectorizer(_BaseTextVectorizer):
    """Raw word-count weights (reference ``BagOfWordsVectorizer.java``)."""

    class Builder(_BaseTextVectorizer.Builder):
        _cls = None  # bound after class creation

    def _weight(self, word: str, count: int, doc_len: int) -> float:
        return float(count)


class TfidfVectorizer(_BaseTextVectorizer):
    """tf·idf weights (reference ``TfidfVectorizer.java:105-139``)."""

    class Builder(_BaseTextVectorizer.Builder):
        _cls = None

    def tf_for_word(self, count: int, doc_len: int) -> float:
        return count / doc_len if doc_len else 0.0

    def idf_for_word(self, word: str) -> float:
        total = self.index.num_docs
        df = self.index.doc_appeared_in(word)
        if total == 0 or df == 0:
            return 0.0
        return math.log10(total / df)

    def tfidf_word(self, word: str, count: int, doc_len: int) -> float:
        return self.tf_for_word(count, doc_len) * self.idf_for_word(word)

    tfidfWord = tfidf_word

    def _weight(self, word: str, count: int, doc_len: int) -> float:
        return self.tfidf_word(word, count, doc_len)


BagOfWordsVectorizer.Builder._cls = BagOfWordsVectorizer
TfidfVectorizer.Builder._cls = TfidfVectorizer
