"""SequenceVectors: the generic embedding trainer (word2vec engine).

TPU-native equivalent of reference ``models/sequencevectors/SequenceVectors.java``
(fit :192-310, AsyncSequencer :1021, VectorCalculationsThreads :1126) plus the
learning algorithms ``models/embeddings/learning/impl/elements/{SkipGram,CBOW}``
and ``InMemoryLookupTable``.

Idiom shift (SURVEY.md §3.6): the reference's hot loop builds batched native
``AggregateSkipGram`` ops dispatched thread-per-worker over JNI
(``SkipGram.java:176-283``). Here windows are collected into index arrays on
the host and ONE jitted update step performs the whole batch on device:
gather → sigmoid dot products → scatter-add updates, with buffer donation.
Both objective variants are provided: hierarchical softmax (Huffman
codes/points) and negative sampling (unigram^0.75 table).
"""
from __future__ import annotations

import logging
import math
from typing import Iterable, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from functools import partial

from .vocab import VocabCache, VocabWord, Huffman, build_vocab
from ..monitor.jitwatch import monitored_jit

log = logging.getLogger(__name__)


class InMemoryLookupTable:
    """Reference ``models/embeddings/inmemory/InMemoryLookupTable``: syn0
    (word vectors), syn1 (HS inner-node weights), syn1neg (NS weights)."""

    def __init__(self, vocab: VocabCache, vector_length: int, seed: int = 123,
                 use_hs: bool = True, use_neg: bool = False):
        self.vocab = vocab
        self.vector_length = vector_length
        n = vocab.num_words()
        rng = np.random.default_rng(seed)
        self.syn0 = ((rng.random((n, vector_length)) - 0.5)
                     / vector_length).astype(np.float32)
        self.syn1 = (np.zeros((max(n - 1, 1), vector_length), np.float32)
                     if use_hs else None)
        self.syn1neg = (np.zeros((n, vector_length), np.float32)
                        if use_neg else None)

    def reset_weights(self, seed: int = 123):
        n = self.vocab.num_words()
        rng = np.random.default_rng(seed)
        self.syn0 = ((rng.random((n, self.vector_length)) - 0.5)
                     / self.vector_length).astype(np.float32)
        if self.syn1 is not None:
            self.syn1 = np.zeros_like(self.syn1)
        if self.syn1neg is not None:
            self.syn1neg = np.zeros_like(self.syn1neg)

    resetWeights = reset_weights

    def vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return None if i < 0 else np.asarray(self.syn0[i])


# ------------------------------------------------------------- jitted kernels
#
# Transfer discipline (the tunnel's per-device_put latency dominated training
# before): pairs arrive as ONE packed [2, B] int32 array of fixed batch shape
# (the tail batch is padded; ``n_valid`` masks the padding on-device), the
# vocab-wide Huffman tables live in HBM and are gathered on-device, and the
# negative-sampling labels are synthesized on-device — so a batch costs one
# 64 KB transfer instead of seven, and one compiled shape serves every batch.

@monitored_jit(name="nlp/hs_step", donate_argnums=(0, 1))
def _hs_step(syn0, syn1, packed, hs_points, hs_codes, hs_mask):
    """Hierarchical-softmax skip-gram/CBOW update, batched.

    packed: [2, B+1] int32 — columns 0..B-1 are (input row ids;
    Huffman-target word ids); the LAST column carries the batch scalars
    (n_valid; lr float bit-cast to int32) so the whole batch arrives in ONE
    host→device transfer (each transfer costs ~5 ms of tunnel latency
    regardless of size). hs_points/codes/mask: [V, L] device-resident vocab
    tables. Classic w2v update rule: g = (1 - code - σ(h·v)).
    """
    n_valid = packed[0, -1]
    lr = jax.lax.bitcast_convert_type(packed[1, -1], jnp.float32)
    centers, targets = packed[0, :-1], packed[1, :-1]
    points = hs_points[targets]                        # [B, L]
    codes = hs_codes[targets]
    wmask = (jnp.arange(centers.shape[0]) < n_valid).astype(syn0.dtype)
    mask = hs_mask[targets] * wmask[:, None]
    h = syn0[centers]                                  # [B, d]
    v = syn1[points]                                   # [B, L, d]
    f = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", h, v))  # [B, L]
    g = (1.0 - codes - f) * mask * lr                  # [B, L]
    dh = jnp.einsum("bl,bld->bd", g, v)                # [B, d]
    dv = g[..., None] * h[:, None, :]                  # [B, L, d]
    syn0 = syn0.at[centers].add(dh * wmask[:, None])
    syn1 = syn1.at[points.reshape(-1)].add(
        dv.reshape(-1, dv.shape[-1]) * mask.reshape(-1, 1))
    return syn0, syn1


@monitored_jit(name="nlp/ns_step", donate_argnums=(0, 1))
def _ns_step(syn0, syn1neg, packed):
    """Negative-sampling update, single-transfer like :func:`_hs_step`.

    packed: [B+1, K+2] int32 — rows 0..B-1 are (center; positive target; K
    negatives); the LAST row carries (n_valid; lr bit-cast; 0...). Labels
    are synthesized on-device (column 0 = 1); rows ≥ n_valid are padding."""
    n_valid = packed[-1, 0]
    lr = jax.lax.bitcast_convert_type(packed[-1, 1], jnp.float32)
    centers = packed[:-1, 0]                            # [B]
    targets = packed[:-1, 1:]                           # [B, K+1]
    h = syn0[centers]                                   # [B, d]
    v = syn1neg[targets]                                # [B, K+1, d]
    f = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, v))
    labels = jnp.zeros_like(f).at[:, 0].set(1.0)
    wmask = (jnp.arange(centers.shape[0]) < n_valid).astype(syn0.dtype)
    g = (labels - f) * lr * wmask[:, None]              # [B, K+1]
    dh = jnp.einsum("bk,bkd->bd", g, v)
    dv = g[..., None] * h[:, None, :]
    syn0 = syn0.at[centers].add(dh)
    syn1neg = syn1neg.at[targets.reshape(-1)].add(dv.reshape(-1, dv.shape[-1]))
    return syn0, syn1neg


class SequenceVectors:
    """Configurable embedding trainer over sequences of tokens."""

    def __init__(self, vector_length: int = 100, window: int = 5,
                 min_word_frequency: int = 1, learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4, epochs: int = 1,
                 negative: int = 0,
                 use_hierarchic_softmax: Optional[bool] = None,
                 subsampling: float = 0.0, batch_size: int = 512,
                 seed: int = 123):
        self.vector_length = vector_length
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.epochs = epochs
        self.negative = negative
        # NS replaces HS unless HS is explicitly requested (word2vec
        # convention; combining both doubles device work for no benefit)
        if use_hierarchic_softmax is None:
            self.use_hs = negative == 0
        else:
            self.use_hs = use_hierarchic_softmax or negative == 0
        self.subsampling = subsampling
        self.batch_size = batch_size
        self.seed = seed
        self.vocab: Optional[VocabCache] = None
        self.lookup_table: Optional[InMemoryLookupTable] = None
        self._neg_table: Optional[np.ndarray] = None
        self._code_len = 0

    # ----------------------------------------------------------------- vocab
    def build_vocab(self, sequences: Iterable[Sequence[str]]):
        self.vocab = build_vocab(sequences,
                                 min_word_frequency=self.min_word_frequency,
                                 build_huffman=True)
        self.lookup_table = InMemoryLookupTable(
            self.vocab, self.vector_length, self.seed,
            use_hs=self.use_hs, use_neg=self.negative > 0)
        self._code_len = max((len(w.codes)
                              for w in self.vocab.vocab_words()), default=1)
        if self.use_hs:
            # vocab-wide Huffman tables: batch HS encoding becomes three
            # array gathers instead of a Python loop over targets
            V, L = self.vocab.num_words(), self._code_len
            self._hs_points = np.zeros((V, L), np.int32)
            self._hs_codes = np.zeros((V, L), np.float32)
            self._hs_mask = np.zeros((V, L), np.float32)
            for i, w in enumerate(self.vocab.vocab_words()):
                k = len(w.codes)
                self._hs_points[i, :k] = w.points
                self._hs_codes[i, :k] = w.codes
                self._hs_mask[i, :k] = 1.0
        if self.negative > 0:
            self._neg_table = self._build_unigram_table()
        self._hs_points_dev = None  # rebuilt tables invalidate device copies
        return self

    buildVocab = build_vocab

    def _build_unigram_table(self, size: int = 1 << 20) -> np.ndarray:
        """word2vec unigram^0.75 sampling table."""
        freqs = np.array([w.frequency for w in self.vocab.vocab_words()])
        p = freqs ** 0.75
        p /= p.sum()
        return np.random.default_rng(self.seed).choice(
            len(freqs), size=size, p=p).astype(np.int32)

    # ------------------------------------------------------------------- fit
    def fit(self, sequences_provider):
        """``sequences_provider``: callable returning an iterable of token
        sequences (re-iterable across epochs), or a list of sequences."""
        provider = (sequences_provider if callable(sequences_provider)
                    else (lambda: sequences_provider))
        if self.vocab is None:
            self.build_vocab(provider())
        total_words = max(self.vocab.total_word_count, 1.0)
        rng = np.random.default_rng(self.seed)
        words_seen = 0
        est_total = total_words * self.epochs
        for epoch in range(self.epochs):
            pend_c: List[np.ndarray] = []
            pend_t: List[np.ndarray] = []
            pending = 0
            for seq in provider():
                idxs = self._subsampled_indices(seq, rng)
                words_seen += len(idxs)
                c, t = self._sequence_pairs_arrays(idxs, rng)
                if c.size:
                    pend_c.append(c)
                    pend_t.append(t)
                    pending += c.size
                if pending >= self.batch_size:
                    # concatenate ONCE, then walk batch-size slices — the
                    # remainder is a view, so the copy cost stays linear in
                    # the number of pairs
                    cat_c = np.concatenate(pend_c)
                    cat_t = np.concatenate(pend_t)
                    off = 0
                    while pending - off >= self.batch_size:
                        lr = self._lr(words_seen, est_total)
                        self._apply_pairs(cat_c[off:off + self.batch_size],
                                          cat_t[off:off + self.batch_size],
                                          lr, rng)
                        off += self.batch_size
                    pend_c = [cat_c[off:]]
                    pend_t = [cat_t[off:]]
                    pending -= off
            if pending:
                lr = self._lr(words_seen, est_total)
                self._apply_pairs(np.concatenate(pend_c),
                                  np.concatenate(pend_t), lr, rng)
        return self

    def _sequence_pairs(self, idxs, rng):
        """Yield (center, context) training pairs for one sequence: dynamic
        windows, skip-gram convention. Overridden by doc2vec to add
        document-level pairs; the vectorized array path below is used when
        this method is NOT overridden."""
        for pos, center in enumerate(idxs):
            b = rng.integers(1, self.window + 1)  # dynamic window
            lo = max(0, pos - b)
            hi = min(len(idxs), pos + b + 1)
            for j in range(lo, hi):
                if j != pos:
                    yield center, idxs[j]

    def _sequence_pairs_arrays(self, idxs, rng):
        """(centers, contexts) int32 arrays for one sequence. Vectorized —
        the per-pair Python loop was the host-side bottleneck of training
        (the reference hits the same issue and batches into native
        ``AggregateSkipGram`` calls, ``SkipGram.java:176-283``). Subclasses
        that override ``_sequence_pairs`` (doc2vec) automatically fall back
        to the generator; ``_orient_pairs`` gives CBOW its row/target swap."""
        n = len(idxs)
        if n < 2:
            empty = np.empty(0, np.int32)
            return empty, empty
        if type(self)._sequence_pairs is not SequenceVectors._sequence_pairs:
            pairs = list(self._sequence_pairs(idxs, rng))
            if not pairs:
                empty = np.empty(0, np.int32)
                return empty, empty
            arr = np.asarray(pairs, np.int32)
            return self._orient_pairs(arr[:, 0], arr[:, 1])
        c, t = self._window_pairs_arrays(idxs, rng)
        return self._orient_pairs(c, t)

    def _window_pairs_arrays(self, idxs, rng):
        """Raw vectorized dynamic-window pairs (centers, contexts) — NO
        orientation, no override dispatch; subclasses with custom pair
        semantics (doc2vec) reuse this for their word-word portion."""
        n = len(idxs)
        if n < 2:
            empty = np.empty(0, np.int32)
            return empty, empty
        arr = np.asarray(idxs, np.int32)
        pos = np.arange(n)
        b = rng.integers(1, self.window + 1, size=n)
        lo = np.maximum(0, pos - b)
        hi = np.minimum(n, pos + b + 1)
        counts = hi - lo - 1                      # window size minus center
        total = int(counts.sum())
        if total == 0:
            empty = np.empty(0, np.int32)
            return empty, empty
        centers_pos = np.repeat(pos, counts)
        # within-window offsets 0..count-1 per center
        starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
        offs = np.arange(total) - np.repeat(starts, counts)
        ctx_pos = np.repeat(lo, counts) + offs
        ctx_pos += (ctx_pos >= centers_pos)       # skip the center slot
        return arr[centers_pos], arr[ctx_pos]

    def _orient_pairs(self, centers, contexts):
        """Skip-gram orientation: the CENTER row is updated against the
        context's objective. CBOW overrides to swap."""
        return centers, contexts

    def _lr(self, words_seen, est_total):
        frac = min(words_seen / est_total, 1.0)
        return max(self.learning_rate * (1 - frac), self.min_learning_rate)

    def _subsampled_indices(self, seq, rng) -> List[int]:
        out = []
        for tok in seq:
            i = self.vocab.index_of(tok)
            if i < 0:
                continue
            if self.subsampling > 0:
                f = self.vocab.word_at(i).frequency / self.vocab.total_word_count
                keep = (math.sqrt(f / self.subsampling) + 1) * self.subsampling / f
                if rng.random() > keep:
                    continue
            out.append(i)
        return out

    def _ensure_device_tables(self):
        """Huffman/vocab tables → HBM once; per-batch gathers run on-device."""
        if getattr(self, "_hs_points_dev", None) is None and self.use_hs:
            self._hs_points_dev = jnp.asarray(self._hs_points)
            self._hs_codes_dev = jnp.asarray(self._hs_codes)
            self._hs_mask_dev = jnp.asarray(self._hs_mask)

    def _apply_pairs(self, rows, targets, lr, rng):
        """Update syn0[rows] against targets' objective. Fixed-shape batches
        (tail padded, masked on-device) + packed single-transfer pairs: one
        compiled kernel and one small H2D per batch."""
        lt = self.lookup_table
        rows = np.ascontiguousarray(rows, np.int32)
        targets = np.ascontiguousarray(targets, np.int32)
        n = len(rows)
        B = max(self.batch_size, n)
        if n < B:
            rows = np.concatenate([rows, np.zeros(B - n, np.int32)])
            targets = np.concatenate([targets, np.zeros(B - n, np.int32)])
        if self.use_hs:
            self._ensure_device_tables()
            meta = np.array([n, np.float32(lr).view(np.int32)], np.int32)
            packed = jnp.asarray(np.concatenate(
                [np.stack([rows, targets]), meta[:, None]], axis=1))
            lt.syn0, lt.syn1 = _hs_step(
                jnp.asarray(lt.syn0), jnp.asarray(lt.syn1), packed,
                self._hs_points_dev, self._hs_codes_dev, self._hs_mask_dev)
        if self.negative > 0:
            K = self.negative
            negs = self._neg_table[rng.integers(0, len(self._neg_table),
                                                size=(B, K))]
            body = np.concatenate([rows[:, None], targets[:, None], negs],
                                  axis=1)                       # [B, K+2]
            meta = np.zeros((1, K + 2), np.int32)
            meta[0, 0] = n
            meta[0, 1] = np.float32(lr).view(np.int32)
            lt.syn0, lt.syn1neg = _ns_step(
                jnp.asarray(lt.syn0), jnp.asarray(lt.syn1neg),
                jnp.asarray(np.concatenate([body, meta])))

    # ------------------------------------------------------------- inference
    def word_vector(self, word: str) -> Optional[np.ndarray]:
        return self.lookup_table.vector(word)

    getWordVector = word_vector

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.word_vector(a), self.word_vector(b)
        if va is None or vb is None:
            return float("nan")
        na = np.linalg.norm(va)
        nb = np.linalg.norm(vb)
        if na == 0 or nb == 0:
            return 0.0
        return float(va @ vb / (na * nb))

    def words_nearest(self, word: str, n: int = 10) -> List[str]:
        v = self.word_vector(word)
        if v is None:
            return []
        syn0 = np.asarray(self.lookup_table.syn0)
        norms = np.linalg.norm(syn0, axis=1) * max(np.linalg.norm(v), 1e-9)
        sims = syn0 @ v / np.maximum(norms, 1e-9)
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.word_at(int(i)).word
            if w != word:
                out.append(w)
            if len(out) >= n:
                break
        return out

    wordsNearest = words_nearest

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and self.vocab.contains_word(word)

    hasWord = has_word
