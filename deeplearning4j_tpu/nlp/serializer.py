"""Word-vector serialization: text, binary (Google News), CSV.

TPU-native equivalent of reference
``models/embeddings/loader/WordVectorSerializer.java`` (SURVEY.md §2.5):
word2vec text format ("word v1 v2 ..."), the word2vec C binary format used by
the GoogleNews vectors, and round-trips of our own models.
"""
from __future__ import annotations

import struct
from typing import Optional, TextIO, Tuple

import numpy as np

from .vocab import VocabCache, VocabWord, Huffman
from .sequencevectors import SequenceVectors, InMemoryLookupTable


class WordVectorSerializer:
    # ------------------------------------------------------------------ text
    @staticmethod
    def write_word_vectors(model, path: str, include_header: bool = True):
        """word2vec text format; ``model`` is anything with vocab + syn0
        access (SequenceVectors family or Glove)."""
        vocab = model.vocab
        with open(path, "w", encoding="utf-8") as f:
            if include_header:
                v0 = model.word_vector(vocab.word_at(0).word)
                f.write(f"{vocab.num_words()} {len(v0)}\n")
            for w in vocab.vocab_words():
                vec = model.word_vector(w.word)
                f.write(w.word + " " + " ".join(f"{x:.6f}" for x in vec) + "\n")
        return path

    writeWordVectors = write_word_vectors

    @staticmethod
    def read_word_vectors(path: str) -> "StaticWordVectors":
        """Load text format (with or without the count header)."""
        words, vecs = [], []
        with open(path, encoding="utf-8") as f:
            first = f.readline().rstrip("\n")
            parts = first.split(" ")
            if len(parts) == 2 and all(p.isdigit() for p in parts):
                pass  # header line
            elif parts:
                words.append(parts[0])
                vecs.append([float(x) for x in parts[1:]])
            for line in f:
                parts = line.rstrip("\n").split(" ")
                if len(parts) < 2:
                    continue
                words.append(parts[0])
                vecs.append([float(x) for x in parts[1:]])
        return StaticWordVectors(words, np.asarray(vecs, np.float32))

    readWordVectors = read_word_vectors
    loadTxtVectors = read_word_vectors

    # ---------------------------------------------------------------- binary
    @staticmethod
    def write_binary(model, path: str):
        """word2vec C binary format (GoogleNews layout)."""
        vocab = model.vocab
        v0 = model.word_vector(vocab.word_at(0).word)
        with open(path, "wb") as f:
            f.write(f"{vocab.num_words()} {len(v0)}\n".encode("utf-8"))
            for w in vocab.vocab_words():
                vec = np.asarray(model.word_vector(w.word), np.float32)
                f.write(w.word.encode("utf-8") + b" ")
                f.write(vec.tobytes())
                f.write(b"\n")
        return path

    @staticmethod
    def read_binary(path: str) -> "StaticWordVectors":
        """Read the word2vec C binary format (also loads GoogleNews files)."""
        words, vecs = [], []
        with open(path, "rb") as f:
            header = f.readline().decode("utf-8").strip().split()
            n, d = int(header[0]), int(header[1])
            for _ in range(n):
                word = b""
                while True:
                    ch = f.read(1)
                    if ch in (b" ", b""):
                        break
                    word += ch
                vec = np.frombuffer(f.read(4 * d), np.float32)
                nl = f.peek(1)[:1] if hasattr(f, "peek") else b""
                if nl == b"\n":
                    f.read(1)
                words.append(word.decode("utf-8"))
                vecs.append(vec)
        return StaticWordVectors(words, np.stack(vecs))

    readBinary = read_binary
    loadGoogleModel = read_binary


class StaticWordVectors:
    """Read-only word vectors (reference ``WordVectors`` lookup view)."""

    def __init__(self, words, syn0: np.ndarray):
        self._index = {w: i for i, w in enumerate(words)}
        self.words = list(words)
        self.syn0 = syn0
        self.vocab = self._make_vocab(words)

    def _make_vocab(self, words) -> VocabCache:
        cache = VocabCache()
        for w in words:
            cache.add_token(w)
        cache.finish(1)
        # preserve file order (finish() sorts by frequency, all equal → word
        # order; re-map indices to file order)
        cache._index = [cache._words[w] for w in words]
        for i, vw in enumerate(cache._index):
            vw.index = i
        return cache

    def word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self._index.get(word)
        return None if i is None else self.syn0[i]

    getWordVector = word_vector

    def has_word(self, word: str) -> bool:
        return word in self._index

    hasWord = has_word

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.word_vector(a), self.word_vector(b)
        if va is None or vb is None:
            return float("nan")
        denom = max(np.linalg.norm(va) * np.linalg.norm(vb), 1e-9)
        return float(va @ vb / denom)

    def words_nearest(self, word: str, n: int = 10):
        v = self.word_vector(word)
        if v is None:
            return []
        norms = (np.linalg.norm(self.syn0, axis=1)
                 * max(np.linalg.norm(v), 1e-9))
        sims = self.syn0 @ v / np.maximum(norms, 1e-9)
        order = np.argsort(-sims)
        return [self.words[i] for i in order if self.words[i] != word][:n]

    wordsNearest = words_nearest
