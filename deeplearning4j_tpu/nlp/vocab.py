"""Vocabulary: SequenceElement/VocabWord, VocabCache, Huffman coding.

TPU-native equivalent of reference ``models/word2vec/wordstore/`` +
``models/sequencevectors/sequence/SequenceElement`` and
``models/word2vec/Huffman.java`` (SURVEY.md §2.5 "Vocab & lookup"): word→index
mapping with frequency counting and min-frequency filtering, plus the Huffman
tree that yields each word's hierarchical-softmax (codes, points) pair.
"""
from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence


@dataclass
class VocabWord:
    """Reference ``VocabWord`` (a SequenceElement): word, frequency, HS codes."""
    word: str
    frequency: float = 1.0
    index: int = -1
    codes: List[int] = field(default_factory=list)    # Huffman code bits
    points: List[int] = field(default_factory=list)   # inner-node indices

    def increment(self, by: float = 1.0):
        self.frequency += by


SequenceElement = VocabWord  # reference naming alias


class VocabCache:
    """Reference ``AbstractCache``: word store with counts + index."""

    def __init__(self):
        self._words: Dict[str, VocabWord] = {}
        self._index: List[VocabWord] = []
        self.total_word_count = 0.0

    def add_token(self, word: str, by: float = 1.0):
        if word in self._words:
            self._words[word].increment(by)
        else:
            self._words[word] = VocabWord(word, by)
        self.total_word_count += by

    addToken = add_token

    def finish(self, min_word_frequency: int = 1):
        """Drop rare words, assign indices by descending frequency (reference
        vocab constructor behavior)."""
        kept = [w for w in self._words.values()
                if w.frequency >= min_word_frequency]
        kept.sort(key=lambda w: (-w.frequency, w.word))
        self._index = kept
        self._words = {w.word: w for w in kept}
        for i, w in enumerate(kept):
            w.index = i
        return self

    def contains_word(self, word: str) -> bool:
        return word in self._words

    containsWord = contains_word

    def word_for(self, word: str) -> Optional[VocabWord]:
        return self._words.get(word)

    def word_at(self, index: int) -> VocabWord:
        return self._index[index]

    wordFor = word_for

    def index_of(self, word: str) -> int:
        w = self._words.get(word)
        return -1 if w is None else w.index

    indexOf = index_of

    def word_frequency(self, word: str) -> float:
        w = self._words.get(word)
        return 0.0 if w is None else w.frequency

    wordFrequency = word_frequency

    def num_words(self) -> int:
        return len(self._index)

    numWords = num_words

    def words(self) -> List[str]:
        return [w.word for w in self._index]

    def vocab_words(self) -> List[VocabWord]:
        return list(self._index)

    vocabWords = vocab_words


class Huffman:
    """Huffman tree over vocab frequencies → (codes, points) per word
    (reference ``models/word2vec/Huffman.java``). ``points`` index the
    hierarchical-softmax inner-node weight rows."""

    def __init__(self, words: Sequence[VocabWord]):
        self.words = list(words)

    def build(self):
        n = len(self.words)
        if n == 0:
            return
        if n == 1:
            self.words[0].codes = [0]
            self.words[0].points = [0]
            return
        # heap items: (freq, tiebreak, node_id); leaves are 0..n-1, inner
        # nodes n..2n-2
        heap = [(w.frequency, i, i) for i, w in enumerate(self.words)]
        heapq.heapify(heap)
        parent = {}
        bit = {}
        next_id = n
        while len(heap) > 1:
            f1, _, a = heapq.heappop(heap)
            f2, _, b = heapq.heappop(heap)
            parent[a] = next_id
            parent[b] = next_id
            bit[a] = 0
            bit[b] = 1
            heapq.heappush(heap, (f1 + f2, next_id, next_id))
            next_id += 1
        root = heap[0][2]
        for i, w in enumerate(self.words):
            codes, points = [], []
            node = i
            while node != root:
                codes.append(bit[node])
                node = parent[node]
                points.append(node - n)  # inner-node row index
            codes.reverse()
            points.reverse()
            w.codes = codes
            w.points = points
        return self


def build_vocab(sequences: Iterable[Sequence[str]],
                min_word_frequency: int = 1,
                build_huffman: bool = True) -> VocabCache:
    """Count tokens over sequences → finished VocabCache (+Huffman codes)."""
    cache = VocabCache()
    for seq in sequences:
        for tok in seq:
            cache.add_token(tok)
    cache.finish(min_word_frequency)
    if build_huffman:
        Huffman(cache.vocab_words()).build()
    return cache
