"""Word2Vec and ParagraphVectors on the SequenceVectors engine.

TPU-native equivalents of reference ``models/word2vec/Word2Vec.java:32``
(Builder :82), CBOW (``learning/impl/elements/CBOW.java``) and
``models/paragraphvectors/ParagraphVectors.java`` with the DBOW/DM sequence
algorithms (``learning/impl/sequence/{DBOW,DM}.java`` — doc2vec).
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np

from .sequencevectors import SequenceVectors, InMemoryLookupTable
from .text import (SentenceIterator, CollectionSentenceIterator,
                   DefaultTokenizerFactory, TokenizerFactory)
from .vocab import build_vocab


class Word2Vec(SequenceVectors):
    """Skip-gram (default) / CBOW word embeddings."""

    class Builder:
        def __init__(self):
            self._kw = {}
            self._iterator: Optional[SentenceIterator] = None
            self._tokenizer: TokenizerFactory = DefaultTokenizerFactory()
            self._cbow = False

        def layer_size(self, n):
            self._kw["vector_length"] = int(n)
            return self

        layerSize = layer_size

        def window_size(self, n):
            self._kw["window"] = int(n)
            return self

        windowSize = window_size

        def min_word_frequency(self, n):
            self._kw["min_word_frequency"] = int(n)
            return self

        minWordFrequency = min_word_frequency

        def learning_rate(self, v):
            self._kw["learning_rate"] = float(v)
            return self

        learningRate = learning_rate

        def min_learning_rate(self, v):
            self._kw["min_learning_rate"] = float(v)
            return self

        minLearningRate = min_learning_rate

        def epochs(self, n):
            self._kw["epochs"] = int(n)
            return self

        iterations = epochs  # reference exposes both; we treat as epochs

        def negative_sample(self, n):
            self._kw["negative"] = int(n)
            if n > 0:
                self._kw["use_hierarchic_softmax"] = False
            return self

        negativeSample = negative_sample

        def use_hierarchic_softmax(self, flag=True):
            self._kw["use_hierarchic_softmax"] = bool(flag)
            return self

        useHierarchicSoftmax = use_hierarchic_softmax

        def sampling(self, v):
            self._kw["subsampling"] = float(v)
            return self

        def batch_size(self, n):
            self._kw["batch_size"] = int(n)
            return self

        batchSize = batch_size

        def seed(self, n):
            self._kw["seed"] = int(n)
            return self

        def iterate(self, iterator: SentenceIterator):
            self._iterator = iterator
            return self

        def tokenizer_factory(self, tf: TokenizerFactory):
            self._tokenizer = tf
            return self

        tokenizerFactory = tokenizer_factory

        def elements_learning_algorithm(self, name: str):
            self._cbow = str(name).lower().endswith("cbow")
            return self

        elementsLearningAlgorithm = elements_learning_algorithm

        def build(self) -> "Word2Vec":
            cls = CBOW if self._cbow else Word2Vec
            w = cls(**self._kw)
            w._iterator = self._iterator
            w._tokenizer = self._tokenizer
            return w

    @staticmethod
    def builder() -> "Word2Vec.Builder":
        return Word2Vec.Builder()

    def __init__(self, *,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 **kw):
        super().__init__(**kw)
        self._iterator: Optional[SentenceIterator] = None
        # constructor kwarg mirrors Builder.tokenizer_factory (e.g. a CJK
        # factory with a user dictionary) so the short form works too
        self._tokenizer: TokenizerFactory = (tokenizer_factory
                                             or DefaultTokenizerFactory())

    def _sentences(self) -> Iterable[List[str]]:
        for sentence in self._iterator:
            yield self._tokenizer.create(sentence).get_tokens()

    def fit(self, sentences=None):
        """Train (reference ``fit()``). ``sentences``: optional list of raw
        sentences (else the Builder's ``iterate`` source)."""
        if sentences is not None:
            self._iterator = CollectionSentenceIterator(sentences)
        if self._iterator is None:
            raise ValueError("No sentence source: call .iterate(...) on the "
                             "builder or pass sentences to fit()")
        return super().fit(lambda: self._sentences())

    def fit_tokenized(self, token_sequences):
        """Train on pre-tokenized sequences against the existing vocab —
        the per-partition step of distributed training (reference
        ``FirstIterationFunction``; see ``nlp/distributed.py``)."""
        return SequenceVectors.fit(self, token_sequences)


class CBOW(Word2Vec):
    """Continuous bag-of-words: the averaged context predicts the center
    (reference ``CBOW.java``). Implemented by flipping the (row, target) pair
    orientation: context rows are updated against the center word's
    objective."""

    def _orient_pairs(self, centers, contexts):
        return contexts, centers  # row updated: context; objective: center


class ParagraphVectors(Word2Vec):
    """doc2vec (reference ``ParagraphVectors.java``, 1461 LoC): label (doc)
    vectors trained alongside word vectors. DBOW: doc vector predicts words
    in the doc (skip-gram with the doc as center). DM: doc vector joins the
    averaged context (approximated here by interleaving doc- and word-pair
    updates, the reference's DM-mean variant)."""

    def __init__(self, dm: bool = False, **kw):
        super().__init__(**kw)
        self.dm = dm
        self.labels: List[str] = []

    class Builder(Word2Vec.Builder):
        def __init__(self):
            super().__init__()
            self._dm = False

        def sequence_learning_algorithm(self, name: str):
            self._dm = str(name).lower().endswith("dm")
            return self

        sequenceLearningAlgorithm = sequence_learning_algorithm

        def build(self) -> "ParagraphVectors":
            p = ParagraphVectors(dm=self._dm, **self._kw)
            p._iterator = self._iterator
            p._tokenizer = self._tokenizer
            return p

    @staticmethod
    def builder() -> "ParagraphVectors.Builder":
        return ParagraphVectors.Builder()

    def fit_labelled(self, documents):
        """``documents``: iterable of (label, text). Labels become vocab
        entries (prefixed) trained with DBOW/DM."""
        docs = [(label, text) for label, text in documents]
        self.labels = [l for l, _ in docs]

        def provider():
            for label, text in docs:
                tokens = self._tokenizer.create(text).get_tokens()
                yield [self._label_token(label)] + tokens

        return super(Word2Vec, self).fit(provider)

    fitLabelled = fit_labelled

    @staticmethod
    def _label_token(label: str) -> str:
        return f"LBL::{label}"

    def _sequence_pairs(self, idxs, rng):
        """DBOW: the doc (label) vector predicts EVERY word of its document
        (reference ``DBOW.java`` samples the full document, not just the
        opening window); word-word skip-gram pairs run over the rest."""
        if idxs and self.vocab.word_at(idxs[0]).word.startswith("LBL::"):
            label, words = idxs[0], idxs[1:]
            for w in words:
                yield label, w
                if self.dm:  # DM: word rows also update against the doc
                    yield w, label
            yield from super()._sequence_pairs(words, rng)
        else:
            yield from super()._sequence_pairs(idxs, rng)

    def _sequence_pairs_arrays(self, idxs, rng):
        """Vectorized doc2vec pair generation (same semantics as
        ``_sequence_pairs``): the base class's fast array path is bypassed
        whenever ``_sequence_pairs`` is overridden, which left PV on the
        per-pair Python generator — the exact host bottleneck the
        vectorization removed for Word2Vec."""
        if not (idxs and self.vocab.word_at(idxs[0]).word.startswith("LBL::")):
            c, t = self._window_pairs_arrays(idxs, rng)
            return self._orient_pairs(c, t)
        label, words = idxs[0], np.asarray(idxs[1:], np.int32)
        if words.size == 0:
            empty = np.empty(0, np.int32)
            return empty, empty
        lbl = np.full(words.size, label, np.int32)
        # doc→word (DBOW) [+ word→doc for DM], then word-word skip-gram
        # pairs over the rest via the raw vectorized window path
        cs = [lbl] + ([words] if self.dm else [])
        ts = [words] + ([lbl] if self.dm else [])
        wc, wt = self._window_pairs_arrays(list(words), rng)
        c = np.concatenate(cs + [wc])
        t = np.concatenate(ts + [wt])
        return self._orient_pairs(c, t)

    # ------------------------------------------------------------- doc query
    def doc_vector(self, label: str):
        return self.word_vector(self._label_token(label))

    def similarity_to_label(self, text: str, label: str) -> float:
        """Cosine between an unseen text's inferred vector and a doc vector
        (reference ``predict``/``similarityToLabel``)."""
        v = self.infer_vector(text)
        d = self.doc_vector(label)
        if v is None or d is None:
            return float("nan")
        denom = max(np.linalg.norm(v) * np.linalg.norm(d), 1e-9)
        return float(v @ d / denom)

    similarityToLabel = similarity_to_label

    def infer_vector(self, text: str) -> Optional[np.ndarray]:
        """Mean of known word vectors (fast inference; the reference offers
        gradient-based inference, same neighborhood for mean-style DM)."""
        tokens = self._tokenizer.create(text).get_tokens()
        vecs = [self.word_vector(t) for t in tokens]
        vecs = [v for v in vecs if v is not None]
        if not vecs:
            return None
        return np.mean(vecs, axis=0)

    inferVector = infer_vector

    def predict(self, text: str) -> Optional[str]:
        """Nearest doc label for a text (reference ``predict``)."""
        sims = [(self.similarity_to_label(text, l), l) for l in self.labels]
        sims = [(s, l) for s, l in sims if not np.isnan(s)]
        return max(sims)[1] if sims else None
