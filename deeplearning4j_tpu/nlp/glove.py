"""GloVe: co-occurrence counting + AdaGrad-weighted least squares.

TPU-native equivalent of reference ``models/glove/Glove.java`` (429 LoC +
``glove/count/`` co-occurrence machinery): host-side co-occurrence dict over
windows, then jitted batched AdaGrad updates of the factorization
``w_i·w̃_j + b_i + b̃_j ≈ log X_ij`` with the f(X) weighting.
"""
from __future__ import annotations

from collections import defaultdict
from functools import partial
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from .vocab import VocabCache, build_vocab
from ..monitor.jitwatch import monitored_jit
from .text import (CollectionSentenceIterator, DefaultTokenizerFactory,
                   SentenceIterator, TokenizerFactory)


@monitored_jit(name="nlp/glove_step", donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def _glove_step(w, wc, b, bc, hw, hwc, hb, hbc, rows, cols, logx, fx, lr):
    """One AdaGrad batch: J = f(x) (w_i·wc_j + b_i + bc_j − log x)²."""
    wi = w[rows]
    wj = wc[cols]
    diff = (jnp.sum(wi * wj, axis=-1) + b[rows] + bc[cols] - logx)  # [B]
    g = fx * diff                                                   # [B]
    gwi = g[:, None] * wj
    gwj = g[:, None] * wi
    gbi = g
    gbj = g
    # AdaGrad accumulators
    hw = hw.at[rows].add(gwi * gwi)
    hwc = hwc.at[cols].add(gwj * gwj)
    hb = hb.at[rows].add(gbi * gbi)
    hbc = hbc.at[cols].add(gbj * gbj)
    w = w.at[rows].add(-lr * gwi / jnp.sqrt(hw[rows] + 1e-8))
    wc = wc.at[cols].add(-lr * gwj / jnp.sqrt(hwc[cols] + 1e-8))
    b = b.at[rows].add(-lr * gbi / jnp.sqrt(hb[rows] + 1e-8))
    bc = bc.at[cols].add(-lr * gbj / jnp.sqrt(hbc[cols] + 1e-8))
    loss = 0.5 * jnp.sum(fx * diff * diff)
    return w, wc, b, bc, hw, hwc, hb, hbc, loss


class Glove:
    """Reference ``Glove.java`` Builder surface (subset) + fit/query."""

    class Builder:
        def __init__(self):
            self._kw = {}
            self._iterator = None
            self._tokenizer = DefaultTokenizerFactory()

        def layer_size(self, n):
            self._kw["vector_length"] = int(n)
            return self

        layerSize = layer_size

        def window_size(self, n):
            self._kw["window"] = int(n)
            return self

        windowSize = window_size

        def min_word_frequency(self, n):
            self._kw["min_word_frequency"] = int(n)
            return self

        minWordFrequency = min_word_frequency

        def learning_rate(self, v):
            self._kw["learning_rate"] = float(v)
            return self

        learningRate = learning_rate

        def epochs(self, n):
            self._kw["epochs"] = int(n)
            return self

        def x_max(self, v):
            self._kw["x_max"] = float(v)
            return self

        xMax = x_max

        def alpha(self, v):
            self._kw["alpha"] = float(v)
            return self

        def iterate(self, it: SentenceIterator):
            self._iterator = it
            return self

        def tokenizer_factory(self, tf: TokenizerFactory):
            self._tokenizer = tf
            return self

        tokenizerFactory = tokenizer_factory

        def build(self) -> "Glove":
            g = Glove(**self._kw)
            g._iterator = self._iterator
            g._tokenizer = self._tokenizer
            return g

    @staticmethod
    def builder():
        return Glove.Builder()

    def __init__(self, vector_length: int = 100, window: int = 5,
                 min_word_frequency: int = 1, learning_rate: float = 0.05,
                 epochs: int = 5, x_max: float = 100.0, alpha: float = 0.75,
                 batch_size: int = 4096, seed: int = 123):
        self.vector_length = vector_length
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.x_max = x_max
        self.alpha = alpha
        self.batch_size = batch_size
        self.seed = seed
        self.vocab: Optional[VocabCache] = None
        self.syn0 = None
        self._iterator = None
        self._tokenizer = DefaultTokenizerFactory()

    def _sentences(self):
        for s in self._iterator:
            yield self._tokenizer.create(s).get_tokens()

    def fit(self, sentences: Optional[Sequence[str]] = None):
        if sentences is not None:
            self._iterator = CollectionSentenceIterator(sentences)
        seqs = list(self._sentences())
        self.vocab = build_vocab(seqs, self.min_word_frequency,
                                 build_huffman=False)
        cooc: Dict[Tuple[int, int], float] = defaultdict(float)
        for seq in seqs:
            idxs = [self.vocab.index_of(t) for t in seq]
            idxs = [i for i in idxs if i >= 0]
            for pos, i in enumerate(idxs):
                for off in range(1, self.window + 1):
                    j = pos + off
                    if j >= len(idxs):
                        break
                    # distance-weighted count, symmetric (GloVe convention)
                    cooc[(i, idxs[j])] += 1.0 / off
                    cooc[(idxs[j], i)] += 1.0 / off
        return self.fit_cooccurrences(cooc)

    def fit_cooccurrences(self, cooc: Dict[Tuple[int, int], float]):
        """Train the factorization from a co-occurrence map. Split out so
        distributed counting (``nlp/distributed.py``, reference
        ``glove/count/`` Spark jobs) can merge partition counts and feed the
        identical map on every process. Pairs are sorted canonically so the
        same counts always produce bit-identical vectors regardless of map
        insertion order."""
        n = self.vocab.num_words()
        d = self.vector_length
        rng = np.random.default_rng(self.seed)
        w = jnp.asarray((rng.random((n, d)) - 0.5) / d, jnp.float32)
        wc = jnp.asarray((rng.random((n, d)) - 0.5) / d, jnp.float32)
        b = jnp.zeros((n,), jnp.float32)
        bc = jnp.zeros((n,), jnp.float32)
        hw = jnp.ones((n, d), jnp.float32)
        hwc = jnp.ones((n, d), jnp.float32)
        hb = jnp.ones((n,), jnp.float32)
        hbc = jnp.ones((n,), jnp.float32)

        items = sorted(cooc.items())
        pairs = np.asarray([ij for ij, _ in items], np.int32).reshape(-1, 2)
        counts = np.asarray([v for _, v in items], np.float32)
        logx = np.log(counts)
        fx = np.minimum((counts / self.x_max) ** self.alpha, 1.0).astype(np.float32)
        B = self.batch_size
        for _ in range(self.epochs):
            order = rng.permutation(len(pairs))
            for s in range(0, len(order), B):
                sel = order[s:s + B]
                (w, wc, b, bc, hw, hwc, hb, hbc, _) = _glove_step(
                    w, wc, b, bc, hw, hwc, hb, hbc,
                    jnp.asarray(pairs[sel, 0]), jnp.asarray(pairs[sel, 1]),
                    jnp.asarray(logx[sel]), jnp.asarray(fx[sel]),
                    jnp.float32(self.learning_rate))
        # final vectors: w + wc (GloVe paper recommendation)
        self.syn0 = np.asarray(w) + np.asarray(wc)
        return self

    fitCooccurrences = fit_cooccurrences

    # ----------------------------------------------------------------- query
    def word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word) if self.vocab else -1
        return None if i < 0 else self.syn0[i]

    getWordVector = word_vector

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.word_vector(a), self.word_vector(b)
        if va is None or vb is None:
            return float("nan")
        denom = max(np.linalg.norm(va) * np.linalg.norm(vb), 1e-9)
        return float(va @ vb / denom)
