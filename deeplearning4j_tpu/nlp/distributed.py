"""Distributed NLP embeddings: multi-process Word2Vec and GloVe.

TPU-native equivalent of the reference's ``dl4j-spark-nlp`` module (5,255 LoC;
SURVEY.md §2.4 "Spark NLP"):

 - ``spark/text/functions/TextPipeline.java:1`` — cluster-wide tokenize +
   word-frequency count producing one vocab for all workers. Here every
   process tokenizes the full (shared) corpus deterministically, which yields
   the identical vocab the reference gets by building on the driver and
   broadcasting.
 - ``spark/models/embeddings/word2vec/FirstIterationFunction.java`` /
   ``SecondIterationFunction.java`` — map-partition training: each executor
   trains skip-gram on its own partition of sentences.
 - ``spark/models/embeddings/word2vec/Word2Vec.java:237`` ("Updating syn0
   second pass: average obtained vectors") — partition results are merged by
   *averaging* the trained vectors.

Architecture shift: Spark's driver/executor RDD machinery collapses into the
JAX multi-controller model (same SPMD program on every host,
``jax.distributed.initialize`` forms the cluster — see
``parallel/distributed.py``). The partition feed is the round-robin
``ProcessLocalIterator`` pattern; the driver-side aggregation is a
cross-process mean of the embedding tables over the global device mesh
(ICI/DCN collectives instead of Spark shuffle).

GloVe distributes the *co-occurrence counting* (the reference's
``glove/count/`` machinery runs it as Spark jobs): each process counts its
sentence share, the sparse COO counts are all-gathered and merged, and the
factorization then runs identically on every process from the identical
merged counts — bit-identical vectors everywhere without further
communication.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Dict, Optional, Tuple

import numpy as np
import jax

from .word2vec import Word2Vec
from .glove import Glove

__all__ = ["DistributedWord2Vec", "DistributedGlove", "SparkWord2Vec",
           "SparkGlove", "partition_sentences"]


def partition_sentences(sentences, process_index: Optional[int] = None,
                        process_count: Optional[int] = None):
    """Round-robin sentence partitioning: process ``p`` of ``P`` keeps
    sentences ``p, p+P, ...`` — the map-partition feed of
    ``FirstIterationFunction`` without materializing remote shards."""
    p = jax.process_index() if process_index is None else process_index
    P = jax.process_count() if process_count is None else process_count
    return [s for i, s in enumerate(sentences) if i % P == p]


def _mean_across_processes(arr: np.ndarray) -> np.ndarray:
    """Cross-process mean of a replicated host array (the reference's
    driver-side vector averaging, ``Word2Vec.java:237``), over the global
    mesh's collectives. Identity when single-process."""
    if jax.process_count() == 1:
        return arr
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(arr)  # [P, ...]
    return np.asarray(gathered).mean(axis=0)


def _allgather_varlen(rows: np.ndarray) -> np.ndarray:
    """All-gather variable-length per-process row blocks: pad to the global
    max, gather, strip padding. Used to merge sparse COO co-occurrence
    blocks whose lengths differ per partition."""
    from jax.experimental import multihost_utils
    n = np.asarray([rows.shape[0]], np.int64)
    counts = np.asarray(multihost_utils.process_allgather(n)).reshape(-1)
    m = int(counts.max())
    padded = np.zeros((m,) + rows.shape[1:], rows.dtype)
    padded[:rows.shape[0]] = rows
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    return np.concatenate([gathered[p, :int(c)]
                           for p, c in enumerate(counts)], axis=0)


class DistributedWord2Vec:
    """Multi-process Word2Vec (reference Spark ``Word2Vec.java:61``).

    Usage matches the single-host Builder; ``fit`` partitions sentences
    across processes, trains each partition locally with the existing jitted
    skip-gram engine (``nlp/sequencevectors.py``), and averages the embedding
    tables across processes after every epoch. All processes finish with
    bit-identical tables.
    """

    def __init__(self, word2vec: Optional[Word2Vec] = None, **kw):
        self.w2v = word2vec if word2vec is not None else Word2Vec(**kw)

    def fit(self, sentences):
        """``sentences``: the full corpus (every process passes the same
        list — the reference ships the RDD; we ship the stream and partition
        by index)."""
        w = self.w2v
        tokenized = [w._tokenizer.create(s).get_tokens() for s in sentences]
        # TextPipeline: one vocab for the whole cluster, built identically
        # on every process (driver-build + broadcast equivalent)
        w.build_vocab(tokenized)
        local = partition_sentences(tokenized)
        # epochs are driven here so tables average once per epoch (the
        # reference's per-iteration aggregation cadence); the local engine
        # runs single epochs over the partition
        epochs, w.epochs = w.epochs, 1
        try:
            for _ in range(epochs):
                if local:
                    w.fit_tokenized(local)
                lt = w.lookup_table
                lt.syn0 = _mean_across_processes(np.asarray(lt.syn0))
                if lt.syn1 is not None:
                    lt.syn1 = _mean_across_processes(np.asarray(lt.syn1))
                if lt.syn1neg is not None:
                    lt.syn1neg = _mean_across_processes(np.asarray(lt.syn1neg))
        finally:
            w.epochs = epochs
        return self

    # delegate the query surface
    def __getattr__(self, name):
        return getattr(self.w2v, name)


class DistributedGlove:
    """Multi-process GloVe (reference ``glove/count/`` Spark co-occurrence
    jobs feeding ``Glove.java``): counting is partitioned, counts are merged
    cluster-wide, training runs identically everywhere — the distributed
    model equals the single-process model on the same corpus exactly."""

    def __init__(self, glove: Optional[Glove] = None, **kw):
        self.glove = glove if glove is not None else Glove(**kw)

    def fit(self, sentences):
        g = self.glove
        tokenized = [g._tokenizer.create(s).get_tokens() for s in sentences]
        from .vocab import build_vocab
        # cluster-wide vocab, built identically everywhere (TextPipeline)
        g.vocab = build_vocab(tokenized, g.min_word_frequency,
                              build_huffman=False)
        local = partition_sentences(tokenized)
        cooc: Dict[Tuple[int, int], float] = defaultdict(float)
        for seq in local:
            idxs = [g.vocab.index_of(t) for t in seq]
            idxs = [i for i in idxs if i >= 0]
            for pos, i in enumerate(idxs):
                for off in range(1, g.window + 1):
                    j = pos + off
                    if j >= len(idxs):
                        break
                    cooc[(i, idxs[j])] += 1.0 / off
                    cooc[(idxs[j], i)] += 1.0 / off
        if cooc:
            block = np.asarray([(i, j, v) for (i, j), v in cooc.items()],
                               np.float64)
        else:
            block = np.zeros((0, 3), np.float64)
        if jax.process_count() > 1:
            block = _allgather_varlen(block)
        merged: Dict[Tuple[int, int], float] = defaultdict(float)
        for i, j, v in block:
            merged[(int(i), int(j))] += float(v)
        g.fit_cooccurrences(merged)
        return self

    def __getattr__(self, name):
        return getattr(self.glove, name)


# reference-name aliases (Spark facade naming)
SparkWord2Vec = DistributedWord2Vec
SparkGlove = DistributedGlove
