"""CJK + UIMA-style language modules for the text pipeline.

TPU-native equivalent of the reference's language modules (SURVEY.md §2.5
"Language modules"): ``deeplearning4j-nlp-chinese`` (bundled ansj segmenter),
``deeplearning4j-nlp-japanese`` (bundled Kuromoji), ``deeplearning4j-nlp-korean``
(arirang wrapper) and ``deeplearning4j-nlp-uima`` (ClearTK annotation
pipeline). The reference bundles ~24k LoC of third-party morphological
analyzers; re-bundling them is neither possible (zero egress) nor useful.
What the framework actually *needs* from those modules is the contract each
gives the NLP stack: a ``TokenizerFactory`` that turns CJK text (which has no
spaces) into word tokens, and a UIMA-like annotation pipeline (sentence
segmentation → tokenization → POS). This module implements those contracts
natively:

- ``ChineseTokenizerFactory`` — forward-maximum-matching segmentation over a
  user-extendable lexicon with single-character fallback (the core dictionary
  strategy of ansj's DAT segmenter, reference
  ``deeplearning4j-nlp-chinese/.../ChineseTokenizerFactory``), Latin/digit
  runs kept whole.
- ``JapaneseTokenizerFactory`` — dictionary-lattice Viterbi segmentation
  with connection costs and character-class unknown words (the Kuromoji
  algorithm class, reference
  ``deeplearning4j-nlp-japanese/.../JapaneseTokenizerFactory`` over
  ``com/atilika/kuromoji/viterbi/ViterbiBuilder.java``); script-run
  heuristic kept as ``algorithm="script"`` fallback.
- ``KoreanTokenizerFactory`` — whitespace eojeol split + eojeol-internal
  morpheme lattice (stem/josa/eomi decomposition with homograph edges —
  the arirang ``MorphAnalyzer`` algorithm class, reference
  ``deeplearning4j-nlp-korean/.../KoreanTokenizerFactory``); longest-josa
  strip kept as ``algorithm="simple"`` fallback.
- ``UimaTokenizerFactory`` / ``AnnotationPipeline`` — sentence segmenter +
  tokenizer + rule-based POS tagger behind one pipeline object (reference
  ``deeplearning4j-nlp-uima/.../UimaTokenizerFactory``,
  ``annotator/SentenceAnnotator``, ``annotator/PoStagger``).

All factories honor ``set_token_pre_processor`` like every other
``TokenizerFactory`` so they drop into Word2Vec/ParagraphVectors/TF-IDF
unchanged.
"""
from __future__ import annotations

import itertools
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .text import Tokenizer, TokenizerFactory, TokenPreProcess


# --------------------------------------------------------------- script tests
def _is_cjk(ch: str) -> bool:
    o = ord(ch)
    return (0x4E00 <= o <= 0x9FFF or 0x3400 <= o <= 0x4DBF
            or 0xF900 <= o <= 0xFAFF or 0x20000 <= o <= 0x2FA1F)


def _is_hiragana(ch: str) -> bool:
    return 0x3040 <= ord(ch) <= 0x309F


def _is_katakana(ch: str) -> bool:
    return 0x30A0 <= ord(ch) <= 0x30FF


def _is_hangul(ch: str) -> bool:
    o = ord(ch)
    return 0xAC00 <= o <= 0xD7A3 or 0x1100 <= o <= 0x11FF


def _script_class(ch: str) -> str:
    if _is_hiragana(ch):
        return "hira"
    if _is_katakana(ch):
        return "kata"
    if _is_cjk(ch):
        return "han"
    if _is_hangul(ch):
        return "hangul"
    if ch.isalnum():
        return "latin"
    if ch.isspace():
        return "space"
    return "punct"


def _script_runs(text: str) -> List[Tuple[str, str]]:
    """Split ``text`` into maximal same-script runs → [(run, class)]."""
    return [("".join(grp), cls)
            for cls, grp in itertools.groupby(text, key=_script_class)]


# ------------------------------------------------------------------- Chinese
#: Seed lexicon: common multi-character words so segmentation is useful out of
#: the box; extend per-corpus via ``ChineseTokenizerFactory(lexicon=...)``.
CHINESE_LEXICON = {
    "中国", "我们", "你们", "他们", "今天", "明天", "昨天", "时间", "工作",
    "学习", "深度", "深度学习", "机器", "机器学习", "神经", "网络",
    "神经网络", "数据", "模型", "训练", "语言", "自然", "自然语言",
    "处理", "计算", "计算机", "人工", "智能", "人工智能", "北京", "上海",
    "大学", "老师", "学生", "朋友", "喜欢", "可以", "没有", "什么",
    "知道", "现在", "因为", "所以", "如果", "但是", "已经", "开始",
}


def _iter_dict_lines(path: str, encoding: str = "utf-8"):
    """Shared dictionary-file line parser (jieba/ansj user-dict format):
    yields ``(word, freq, extra_columns)`` per non-blank non-``#`` line;
    commas normalize to spaces; freq defaults to 1 when the second column
    is missing/non-numeric. One parser for every load() so format fixes
    apply to all languages at once."""
    with open(path, encoding=encoding) as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.replace(",", " ").split()
            freq = (int(parts[1]) if len(parts) > 1
                    and parts[1].isdigit() else 1)
            yield parts[0], freq, parts[2:]


class Lexicon:
    """Frequency dictionary + character trie for segmentation.

    The reference bundles ansj's double-array-trie dictionaries
    (``deeplearning4j-nlp-chinese/.../org/ansj/``); this is the same
    capability at real scale without the 3rd-party bundle: load
    user-supplied dictionary files (one ``word [frequency]`` per line —
    jieba/ansj user-dict format, ``#`` comments allowed) into a plain dict
    trie. Frequencies feed the bidirectional max-match ambiguity scoring."""

    _END = "\0"

    def __init__(self, words: Optional[Iterable[str]] = None):
        self._freq: Dict[str, int] = {}
        self._trie: Dict = {}
        self._total = 0          # running Σfreq (O(1) total_freq)
        self.max_len = 1
        if words:
            for w in words:
                self.add(w)

    def add(self, word: str, freq: int = 1):
        word = word.strip()
        if not word:
            return
        old = self._freq.get(word, 0)
        new = max(old, int(freq))
        self._freq[word] = new
        self._total += new - old
        self.max_len = max(self.max_len, len(word))
        node = self._trie
        for ch in word:
            node = node.setdefault(ch, {})
        node[self._END] = True

    def load(self, path: str, encoding: str = "utf-8") -> "Lexicon":
        """Merge a dictionary file: ``word``, ``word freq`` or ``word,freq``
        per line; blank lines and ``#`` comments skipped."""
        for word, freq, _extra in _iter_dict_lines(path, encoding):
            self.add(word, freq)
        return self

    @classmethod
    def from_file(cls, path: str, encoding: str = "utf-8") -> "Lexicon":
        return cls().load(path, encoding)

    def __contains__(self, word: str) -> bool:
        return word in self._freq

    def __len__(self) -> int:
        return len(self._freq)

    def freq(self, word: str) -> int:
        return self._freq.get(word, 0)

    def longest_prefix(self, text: str, start: int) -> int:
        """Length of the longest lexicon word starting at ``start`` (0 if
        none) — one trie walk, no per-length hashing."""
        lengths = self.match_lengths(text, start)
        return lengths[-1] if lengths else 0

    def longest_suffix(self, text: str, end: int) -> int:
        """Length of the longest lexicon word ENDING at ``end`` (exclusive).
        Bounded backward scan (len ≤ max_len) for backward max-match."""
        lo = max(0, end - self.max_len)
        for start in range(lo, end - 1):
            if text[start:end] in self._freq:
                return end - start
        return 0

    def match_lengths(self, text: str, start: int) -> List[int]:
        """ALL lexicon-word lengths starting at ``start`` (one trie walk) —
        the lattice edges for Viterbi segmentation."""
        node = self._trie
        out: List[int] = []
        i, n = start, len(text)
        while i < n:
            node = node.get(text[i])
            if node is None:
                break
            i += 1
            if self._END in node:
                out.append(i - start)
        return out

    def total_freq(self) -> int:
        return self._total


class _MaxMatchSegmenter:
    """Bidirectional maximum matching with ambiguity scoring over a
    :class:`Lexicon` (the dictionary strategy of ansj's DAT segmenter
    without the 3rd-party bundle).

    Forward AND backward max-match are both computed; when they disagree the
    segmentation with (1) fewer words, then (2) fewer single-character
    leftovers, then (3) higher summed log-frequency wins — the classic
    disambiguation triple. Example the forward-only pass gets wrong:
    研究生命起源 → FMM 研究生|命|起源 vs BMM 研究|生命|起源 (picked: fewer
    singletons)."""

    def __init__(self, lexicon: Iterable[str], bidirectional: bool = True):
        self.lexicon = (lexicon if isinstance(lexicon, Lexicon)
                        else Lexicon(lexicon))
        self.bidirectional = bidirectional

    def add(self, *words: str):
        for w in words:
            self.lexicon.add(w)

    def _forward(self, run: str) -> List[str]:
        out: List[str] = []
        i, n = 0, len(run)
        while i < n:
            L = self.lexicon.longest_prefix(run, i)
            if L > 1:
                out.append(run[i:i + L])
                i += L
            else:
                out.append(run[i])
                i += 1
        return out

    def _backward(self, run: str) -> List[str]:
        out: List[str] = []
        i = len(run)
        while i > 0:
            L = self.lexicon.longest_suffix(run, i)
            if L > 1:
                out.append(run[i - L:i])
                i -= L
            else:
                out.append(run[i - 1])
                i -= 1
        out.reverse()
        return out

    def _score(self, seg: List[str]):
        import math
        singles = sum(1 for w in seg if len(w) == 1)
        logfreq = sum(math.log1p(self.lexicon.freq(w)) for w in seg
                      if len(w) > 1)
        return (-len(seg), -singles, logfreq)

    def segment(self, run: str) -> List[str]:
        fwd = self._forward(run)
        if not self.bidirectional:
            return fwd
        bwd = self._backward(run)
        if fwd == bwd:
            return fwd
        return max(fwd, bwd, key=self._score)


class _UnigramSegmenter:
    """Unigram-LM lattice (word-DAG) segmentation with Viterbi DP — the
    algorithm class behind the reference's bundled ansj/jieba-style
    segmenters (`deeplearning4j-nlp-chinese/.../org/ansj/` builds a word
    lattice over a double-array trie and picks the best-scoring path; same
    capability here over the plain :class:`Lexicon` trie).

    Every lexicon word starting at each position is a lattice edge scored
    ``log((freq+1)/total)``; unknown single characters get the floor score.
    ``route[i] = max_j logp(run[i:j]) + route[j]`` solved right-to-left in
    O(n · max_word_len). Unlike max-match (greedy, longest-first), the DP
    picks the globally most probable path, so frequency evidence can
    override a longer dictionary match: 北京大学生前来应聘 segments
    北京|大学生|前来|应聘 when 大学生 outweighs 北京大学, where FMM is
    stuck with 北京大学|生前|来|应聘."""

    def __init__(self, lexicon: Iterable[str]):
        self.lexicon = (lexicon if isinstance(lexicon, Lexicon)
                        else Lexicon(lexicon))

    def add(self, *words: str):
        for w in words:
            self.lexicon.add(w)

    def segment(self, run: str) -> List[str]:
        import math
        lex = self.lexicon
        n = len(run)
        if n == 0:
            return []
        logtot = math.log(lex.total_freq() + len(lex) + 1)
        floor = -logtot  # unknown char: count ~1 in the corpus

        def logp(w: str) -> float:
            f = lex.freq(w)
            return math.log(f + 1) - logtot if f > 0 else floor

        route: List[Tuple[float, int]] = [(0.0, n)] * (n + 1)
        for i in range(n - 1, -1, -1):
            best = (logp(run[i]) + route[i + 1][0], i + 1)
            for L in lex.match_lengths(run, i):
                if L == 1:
                    continue  # already covered by the char fallback
                cand = logp(run[i:i + L]) + route[i + L][0]
                if cand > best[0]:
                    best = (cand, i + L)
            route[i] = best
        out: List[str] = []
        i = 0
        while i < n:
            j = route[i][1]
            out.append(run[i:j])
            i = j
        return out


class ChineseTokenizerFactory(TokenizerFactory):
    """Dictionary forward-maximum-matching Chinese tokenizer (reference
    ``deeplearning4j-nlp-chinese/.../tokenization/tokenizerFactory/
    ChineseTokenizerFactory.java`` over the bundled ansj segmenter)."""

    def __init__(self, lexicon: Optional[Iterable[str]] = None,
                 dict_path: Optional[str] = None, bidirectional: bool = True,
                 algorithm: str = "bimm"):
        """``lexicon``: iterable of words or a :class:`Lexicon`;
        ``dict_path``: user dictionary file (``word [freq]`` per line,
        jieba/ansj format) merged on top; ``algorithm``: ``"unigram"`` for
        lattice-Viterbi unigram-LM segmentation (the ansj/jieba algorithm
        class — best when the dictionary carries real frequencies),
        ``"bimm"`` (default) for FMM+BMM with ambiguity scoring, ``"fmm"``
        for plain forward max-match. ``bidirectional=False`` is a
        back-compat alias for ``algorithm="fmm"``."""
        self._pre: Optional[TokenPreProcess] = None
        lex = lexicon if lexicon is not None else CHINESE_LEXICON
        if algorithm not in ("unigram", "bimm", "fmm"):
            raise ValueError(f"unknown segmentation algorithm {algorithm!r}"
                             " (expected 'unigram', 'bimm' or 'fmm')")
        if algorithm == "unigram":
            self._seg = _UnigramSegmenter(lex)
        else:
            self._seg = _MaxMatchSegmenter(
                lex, bidirectional=bidirectional and algorithm == "bimm")
        if dict_path is not None:
            self._seg.lexicon.load(dict_path)

    def add_words(self, *words: str):
        """Extend the lexicon (ansj's user-dictionary seam)."""
        self._seg.add(*words)
        return self

    addWords = add_words

    def load_dictionary(self, path: str):
        """Merge a user dictionary file at runtime (ansj's
        ``UserDefineLibrary`` seam)."""
        self._seg.lexicon.load(path)
        return self

    loadDictionary = load_dictionary

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        for run, cls in _script_runs(text):
            if cls == "han":
                tokens.extend(self._seg.segment(run))
            elif cls in ("latin", "kata", "hira", "hangul"):
                tokens.append(run)
            # space/punct dropped
        return self._finish(tokens)


# ------------------------------------------------------------------ Japanese
#: Common trailing hiragana particles/copulas split off kanji+hiragana runs
#: (Kuromoji segments these as separate morphemes).
JAPANESE_PARTICLES = (
    "でした", "ました", "です", "ます", "から", "まで", "には", "とは",
    "は", "が", "を", "に", "へ", "と", "で", "も", "の", "や", "ね", "よ",
    "か", "な",
)

#: Auxiliary verbs / copulas (connection category "a": attach after content).
JAPANESE_AUX = (
    "です", "ます", "でした", "ました", "だ", "である", "ない", "たい",
    "れる", "られる", "せる", "させる",
)

#: Seed lexicon for common multi-kanji words (legacy max-match seed).
JAPANESE_LEXICON = {
    "日本", "東京", "大学", "学生", "先生", "機械", "学習", "機械学習",
    "言語", "自然", "自然言語", "処理", "深層", "深層学習", "好き",
}

#: Seed dictionary for the LATTICE segmenter: (word, freq, category).
#: category: "c" content, "p" particle, "a" auxiliary/copula. Frequencies
#: are order-of-magnitude corpus ranks (particles ≫ common nouns ≫ rest) —
#: they set edge costs the way IPADIC word costs do for Kuromoji. Extend
#: per-corpus via ``dict_path`` / ``add_words``.
JAPANESE_SEED_ENTRIES: Tuple[Tuple[str, int, str], ...] = (
    # particles (the highest-frequency tokens in any Japanese corpus)
    ("の", 8000, "p"), ("は", 6000, "p"), ("が", 5500, "p"),
    ("を", 5000, "p"), ("に", 5000, "p"), ("と", 4000, "p"),
    ("で", 3800, "p"), ("も", 3500, "p"), ("へ", 1200, "p"),
    ("や", 1000, "p"), ("から", 1500, "p"), ("まで", 900, "p"),
    ("には", 800, "p"), ("とは", 500, "p"), ("ね", 600, "p"),
    ("よ", 600, "p"), ("か", 1200, "p"), ("な", 900, "p"),
    # auxiliaries / copulas
    ("です", 3000, "a"), ("ます", 2500, "a"), ("でした", 900, "a"),
    ("ました", 900, "a"), ("だ", 1500, "a"), ("である", 500, "a"),
    ("ない", 1500, "a"), ("たい", 500, "a"),
    # pronouns & everyday nouns
    ("私", 2000, "c"), ("あなた", 500, "c"), ("これ", 900, "c"),
    ("それ", 900, "c"), ("うち", 700, "c"), ("こと", 1500, "c"),
    ("もの", 1200, "c"), ("とき", 700, "c"), ("ところ", 600, "c"),
    ("今日", 800, "c"), ("明日", 500, "c"), ("昨日", 500, "c"),
    # common fruit/food (the classic lattice demo words — real IPADIC
    # entries, not test rigging: すもも = plum, もも = peach)
    ("すもも", 50, "c"), ("もも", 120, "c"), ("りんご", 150, "c"),
    # greetings / frequent hiragana content words (must beat particle
    # shredding: ありがとう vs あり|が|とう)
    ("ありがとう", 400, "c"), ("こんにちは", 300, "c"),
    ("さようなら", 150, "c"), ("おはよう", 200, "c"),
    # verbs/adjectives with okurigana (kanji+hira edges that cross script
    # boundaries — the case the script-run fallback cannot handle)
    ("好き", 600, "c"), ("食べる", 400, "c"), ("行く", 500, "c"),
    ("見る", 500, "c"), ("する", 1800, "c"), ("いる", 1500, "c"),
    ("ある", 1500, "c"), ("なる", 1000, "c"), ("言う", 600, "c"),
    ("思う", 600, "c"), ("大きい", 300, "c"), ("小さい", 250, "c"),
    ("新しい", 300, "c"),
    # domain nouns (mirror the Chinese seed)
    ("日本", 1000, "c"), ("東京", 700, "c"), ("大学", 600, "c"),
    ("学生", 500, "c"), ("先生", 500, "c"), ("機械", 300, "c"),
    ("学習", 350, "c"), ("機械学習", 200, "c"), ("言語", 300, "c"),
    ("自然", 300, "c"), ("自然言語", 150, "c"), ("処理", 300, "c"),
    ("深層", 100, "c"), ("深層学習", 120, "c"), ("計算", 300, "c"),
    ("研究", 400, "c"), ("時間", 500, "c"), ("問題", 500, "c"),
    ("世界", 500, "c"), ("仕事", 450, "c"),
)


class JapaneseLexicon(Lexicon):
    """:class:`Lexicon` + a connection category per word (``"c"`` content,
    ``"p"`` particle, ``"a"`` auxiliary). Dictionary files may carry the
    category as a third column (``word freq pos``); without one it is
    inferred from the particle/aux tables."""

    def __init__(self, entries: Optional[Iterable] = None):
        self._cat: Dict[str, str] = {}
        super().__init__()
        if entries:
            for e in entries:
                if isinstance(e, str):
                    self.add(e)
                else:
                    self.add(*e)

    def add(self, word: str, freq: int = 1, cat: Optional[str] = None):
        word = word.strip()
        if not word:
            return
        if cat is None:
            cat = self._cat.get(word) or (
                "p" if word in JAPANESE_PARTICLES
                else "a" if word in JAPANESE_AUX else "c")
        self._cat[word] = cat
        super().add(word, freq)

    def load(self, path: str, encoding: str = "utf-8") -> "JapaneseLexicon":
        """``word``, ``word freq`` or ``word freq pos`` per line (pos ∈
        c/p/a); ``#`` comments and blanks skipped."""
        for word, freq, extra in _iter_dict_lines(path, encoding):
            cat = extra[0] if extra and extra[0] in ("c", "p", "a") else None
            self.add(word, freq, cat)
        return self

    def category(self, word: str) -> str:
        return self._cat.get(word, "c")

    def categories(self, word: str) -> Tuple[str, ...]:
        """All lattice categories for a surface form (homographs get one
        edge per category; the base class tracks a single one)."""
        return (self.category(word),)


class _JapaneseLatticeSegmenter:
    """Dictionary-lattice Viterbi segmentation — the Kuromoji algorithm
    class (reference ``deeplearning4j-nlp-japanese/src/main/java/com/
    atilika/kuromoji/viterbi/ViterbiBuilder.java`` + ``ViterbiSearcher``:
    build a word lattice over the dictionary, add unknown-word edges by
    character class, pick the min-cost path under word + connection costs)
    without the 9k-LoC third-party bundle.

    Mechanics, mirrored structurally (not translated):

    - EDGES: every dictionary word starting at each position (one trie walk
      via :meth:`Lexicon.match_lengths` — the Chinese lattice machinery),
      with cost ``log(total) - log(freq+1)`` (unigram LM; the role of
      IPADIC word costs).
    - UNKNOWN EDGES: where the dictionary has no cover, candidates are
      generated by CHARACTER CLASS like Kuromoji's ``UnknownDictionary``:
      katakana and latin runs stay whole (loanwords, identifiers); kanji
      and hiragana get edges of every length up to the same-script run end
      (capped), costed ``UNK_BASE + UNK_PER_CHAR·len`` so any dictionary
      cover beats them.
    - CONNECTION COSTS: a small category matrix (content/particle/aux ×
      same, plus BOS/EOS) stands in for IPADIC's 1316² context-id matrix.
      It encodes what Japanese word order makes cheap — particle after
      content, content after particle — and penalizes particle-after-
      particle / content-after-content, which is exactly what
      disambiguates すもももももももものうち into
      すもも|も|もも|も|もも|の|うち (the alternating C-P-C-P… path) over
      equal-word-count rivals.
    - SEARCH: single left-to-right DP over (position, category) — Viterbi
      on the lattice, O(n · edges-per-position · categories²).
    """

    #: connection cost [prev][next] over categories c/p/a (+ B start/E end)
    _CONN = {
        "B": {"c": 0.0, "p": 3.0, "a": 3.0},
        "c": {"c": 1.0, "p": 0.0, "a": 0.0, "E": 0.0},
        "p": {"c": 0.0, "p": 2.0, "a": 1.5, "E": 0.5},
        "a": {"c": 0.5, "p": 0.5, "a": 1.0, "E": 0.0},
    }
    _UNK_BASE = 12.0
    _UNK_PER_CHAR = 2.0
    _UNK_MAX_LEN = 8          # cap unknown-edge fan-out per position
    _UNK_CAT = "c"            # category assigned to unknown edges

    #: subclasses (Korean) override these two to re-seed the machinery
    _LEX_CLS = None           # set below (JapaneseLexicon)
    _SEED: Tuple = ()

    def __init__(self, lexicon: Optional[Iterable] = None):
        # an instance of the language's lexicon class REPLACES the
        # dictionary (caller takes full control); any other iterable MERGES
        # into the seed entries — the lattice is useless without
        # particle/aux/frequency structure
        if isinstance(lexicon, self._LEX_CLS):
            self.lexicon = lexicon
        else:
            self.lexicon = self._LEX_CLS(self._SEED)
            if lexicon is not None:
                for w in lexicon:
                    self.lexicon.add(w) if isinstance(w, str) \
                        else self.lexicon.add(*w)

    def add(self, *words):
        for w in words:
            self.lexicon.add(w) if isinstance(w, str) \
                else self.lexicon.add(*w)

    def _edges(self, text: str, i: int, logtot: float,
               run_end: int) -> List[Tuple[int, float, str]]:
        """Outgoing lattice edges at position ``i`` → [(length, cost, cat)].
        Dictionary edges + character-class unknown edges (always generated:
        an out-of-vocabulary reading must be representable even where a
        dictionary word also starts). ``logtot`` and ``run_end`` (end of
        the same-script run containing ``i``) are hoisted to segment() —
        the lexicon cannot change mid-segmentation, and rescanning the run
        per position would make segmentation O(m²)."""
        import math
        lex = self.lexicon
        out: List[Tuple[int, float, str]] = []
        for L in lex.match_lengths(text, i):
            w = text[i:i + L]
            cost = logtot - math.log(lex.freq(w) + 1)
            for cat in lex.categories(w):
                out.append((L, cost, cat))
        cls = _script_class(text[i])
        R = run_end - i
        if cls in ("kata", "latin"):
            # loanwords / identifiers: the whole run, one edge
            out.append((R, self._UNK_BASE * 0.5 + self._UNK_PER_CHAR,
                        self._UNK_CAT))
        else:
            seen = {L for L, _, _ in out}
            for L in range(1, min(R, self._UNK_MAX_LEN) + 1):
                if L not in seen:
                    out.append((L, self._UNK_BASE + self._UNK_PER_CHAR * L,
                                self._UNK_CAT))
        return out

    def segment_with_categories(self, text: str) -> List[Tuple[str, str]]:
        """Best path as (morpheme, chosen-category) pairs — the category
        the VITERBI PATH selected, not the lexicon's primary reading
        (homographs like 가 = josa/verb differ per context)."""
        import math
        n = len(text)
        if n == 0:
            return []
        INF = float("inf")
        lex = self.lexicon
        logtot = math.log(lex.total_freq() + len(lex) + 1)
        # same-script run end per position, computed once (O(n))
        run_end = [0] * n
        pos = 0
        for run, _cls in _script_runs(text):
            end = pos + len(run)
            for j in range(pos, end):
                run_end[j] = end
            pos = end
        # best[i][cat] = (cost, back-pointer (prev_i, prev_cat, word))
        best: List[Dict[str, Tuple[float, Optional[Tuple]]]] = \
            [dict() for _ in range(n + 1)]
        best[0]["B"] = (0.0, None)
        for i in range(n):
            if not best[i]:
                continue
            for L, wcost, cat in self._edges(text, i, logtot, run_end[i]):
                j = i + L
                word = text[i:j]
                for pcat, (pcost, _) in best[i].items():
                    conn = self._CONN.get(pcat,
                                          self._CONN[self._UNK_CAT]).get(
                        cat, 1.0)
                    cand = pcost + conn + wcost
                    cur = best[j].get(cat, (INF, None))
                    if cand < cur[0]:
                        best[j][cat] = (cand, (i, pcat, word))
        # EOS connection picks the final category
        end_cat, end_cost = None, INF
        for cat, (cost, _) in best[n].items():
            total = cost + self._CONN.get(
                cat, self._CONN[self._UNK_CAT]).get("E", 0.0)
            if total < end_cost:
                end_cat, end_cost = cat, total
        out: List[Tuple[str, str]] = []
        i, cat = n, end_cat
        while i > 0:
            _, back = best[i][cat]
            pi, pcat, word = back
            out.append((word, cat))
            i, cat = pi, pcat
        out.reverse()
        return out

    def segment(self, text: str) -> List[str]:
        return [w for w, _ in self.segment_with_categories(text)]


_JapaneseLatticeSegmenter._LEX_CLS = JapaneseLexicon
_JapaneseLatticeSegmenter._SEED = JAPANESE_SEED_ENTRIES


class JapaneseTokenizerFactory(TokenizerFactory):
    """Japanese tokenizer behind the reference's ``TokenizerFactory`` seam
    (``deeplearning4j-nlp-japanese/.../JapaneseTokenizerFactory.java`` over
    bundled Kuromoji).

    ``algorithm="lattice"`` (default): dictionary-lattice Viterbi with
    connection costs and character-class unknown words — the Kuromoji
    algorithm class (see :class:`_JapaneseLatticeSegmenter`). Handles
    okurigana words crossing script boundaries (好き, 食べる) and classic
    ambiguities (すもももももももものうち).

    ``algorithm="script"``: the legacy script-run heuristic (kanji runs
    lexicon max-matched, ONE trailing particle peeled off hiragana runs) —
    kept as the dependency-free fallback and for callers pinned to the old
    behavior.

    ``lexicon`` semantics differ by mode: in ``lattice`` mode a plain
    iterable MERGES into the seed dictionary (the lattice needs particles,
    auxiliaries and frequencies to function — an unweighted word list alone
    would cripple it); pass a :class:`JapaneseLexicon` to take full control
    of the dictionary instead. In ``script`` mode it REPLACES the seed,
    as before."""

    def __init__(self, lexicon: Optional[Iterable] = None,
                 dict_path: Optional[str] = None,
                 bidirectional: Optional[bool] = None,
                 algorithm: str = "lattice"):
        self._pre: Optional[TokenPreProcess] = None
        if algorithm not in ("lattice", "script"):
            raise ValueError(f"unknown segmentation algorithm {algorithm!r}"
                             " (expected 'lattice' or 'script')")
        if bidirectional is not None and algorithm == "lattice":
            # a max-match knob makes no sense on the lattice; a caller
            # passing it is pinned to the old behavior — fail loudly
            # instead of silently segmenting differently
            raise ValueError(
                "bidirectional= only applies to algorithm='script' "
                "(max-match); the lattice default ignores it — pass "
                "algorithm='script' to keep the legacy behavior")
        self._algorithm = algorithm
        if algorithm == "lattice":
            self._lat = _JapaneseLatticeSegmenter(lexicon)
            if dict_path is not None:
                self._lat.lexicon.load(dict_path)
        else:
            self._seg = _MaxMatchSegmenter(lexicon if lexicon is not None
                                           else JAPANESE_LEXICON,
                                           bidirectional=bidirectional
                                           if bidirectional is not None
                                           else True)
            if dict_path is not None:
                self._seg.lexicon.load(dict_path)
        self._particles = sorted(JAPANESE_PARTICLES, key=len, reverse=True)

    def add_words(self, *words):
        """Extend the dictionary (Kuromoji user-dictionary seam). Entries
        are words or ``(word, freq[, cat])`` tuples; in ``script`` mode the
        category column is meaningless and ignored."""
        if self._algorithm == "lattice":
            self._lat.add(*words)
        else:
            for w in words:
                if isinstance(w, str):
                    self._seg.lexicon.add(w)
                else:
                    self._seg.lexicon.add(*w[:2])
        return self

    addWords = add_words

    def load_dictionary(self, path: str):
        """Merge a user dictionary file at runtime."""
        lex = (self._lat.lexicon if self._algorithm == "lattice"
               else self._seg.lexicon)
        lex.load(path)
        return self

    loadDictionary = load_dictionary

    def _split_hiragana(self, run: str) -> List[str]:
        """(script fallback) Peel ONE longest known particle off the END of
        the run. Splitting mid-word, or peeling repeatedly, would shred
        content words like ありがとう / もも whose characters double as
        particles."""
        for p in self._particles:
            if run.endswith(p) and run != p:
                return [run[:-len(p)], p]
        return [run]

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        if self._algorithm == "lattice":
            # lattice over maximal Japanese-script spans (han/hira/kata mixed
            # — okurigana edges cross script boundaries); latin runs whole;
            # space/punct separate
            for is_ja, run in itertools.groupby(
                    text, key=lambda ch: _script_class(ch)
                    in ("han", "hira", "kata")):
                chunk = "".join(run)
                if is_ja:
                    tokens.extend(self._lat.segment(chunk))
                else:
                    for sub, scls in _script_runs(chunk):
                        if scls in ("latin", "hangul"):
                            tokens.append(sub)
            return self._finish(tokens)
        for run, cls in _script_runs(text):
            if cls == "han":
                tokens.extend(self._seg.segment(run))
            elif cls == "hira":
                tokens.extend(self._split_hiragana(run))
            elif cls in ("kata", "latin", "hangul"):
                tokens.append(run)
        return self._finish(tokens)


# -------------------------------------------------------------------- Korean
#: Common josa (case particles) stripped from eojeol tails — arirang's
#: observable stemming behavior for embedding pipelines.
KOREAN_JOSA = (
    "에서는", "에서", "에게", "으로", "로", "은", "는", "이", "가", "을",
    "를", "에", "와", "과", "도", "만", "의",
)

#: Seed dictionary for the Korean morpheme lattice: (morpheme, freq, cat).
#: Categories: "n" noun/pronoun stem, "v" verb/adjective stem, "j" josa
#: (case particle), "e" eomi (verbal ending, incl. tense infixes and the
#: common CONTRACTED portmanteau forms like 했/갔 — arirang handles these
#: through its own tables too), "x" affix. Frequencies are corpus-rank
#: order-of-magnitude, like the Japanese seed.
KOREAN_SEED_ENTRIES: Tuple[Tuple[str, int, str], ...] = (
    # josa — the highest-frequency bound morphemes
    ("이", 6000, "j"), ("가", 5500, "j"), ("은", 5500, "j"),
    ("는", 5500, "j"), ("을", 5000, "j"), ("를", 5000, "j"),
    ("에", 4500, "j"), ("에서", 2500, "j"), ("에서는", 600, "j"),
    ("에게", 900, "j"), ("으로", 1500, "j"), ("로", 1500, "j"),
    ("와", 1200, "j"), ("과", 1200, "j"), ("도", 1800, "j"),
    ("만", 1000, "j"), ("의", 3000, "j"), ("보다", 500, "j"),
    ("처럼", 400, "j"), ("까지", 600, "j"), ("부터", 600, "j"),
    ("하고", 500, "j"),
    # eomi — endings and tense morphemes (syllable-aligned forms +
    # frequent contracted portmanteaus)
    ("다", 4000, "e"), ("요", 2500, "e"), ("고", 2000, "e"),
    ("지", 1200, "e"), ("면", 1000, "e"), ("서", 1000, "e"),
    ("니다", 1500, "e"), ("습니다", 2000, "e"),
    ("었", 2000, "e"), ("았", 1500, "e"), ("겠", 800, "e"),
    ("는다", 800, "e"), ("기", 900, "e"),
    ("게", 900, "e"), ("죠", 400, "e"), ("어요", 1500, "e"),
    ("아요", 900, "e"), ("어", 1200, "e"), ("아", 900, "e"),
    ("으면", 500, "e"), ("습니까", 400, "e"), ("세요", 700, "e"),
    # contracted stem+tense portmanteaus (the syllable fuses stem vowel and
    # 았/었 — listing them is how a syllable-level lattice covers them)
    ("했", 1500, "e"), ("갔", 600, "e"), ("왔", 600, "e"),
    ("됐", 400, "e"), ("합니다", 1800, "e"), ("갑니다", 400, "e"),
    ("해요", 900, "e"),
    ("한다", 700, "e"), ("하는", 900, "e"), ("하면", 500, "e"),
    # verb / adjective stems
    ("하", 3000, "v"), ("가", 1200, "v"), ("오", 800, "v"),
    ("먹", 800, "v"), ("보", 900, "v"), ("살", 500, "v"),
    ("알", 600, "v"), ("모르", 400, "v"), ("좋", 800, "v"),
    ("크", 400, "v"), ("작", 300, "v"), ("있", 2000, "v"),
    ("없", 1200, "v"), ("되", 1000, "v"), ("배우", 400, "v"), ("싶", 600, "v"),
    ("만들", 400, "v"), ("읽", 300, "v"), ("쓰", 400, "v"),
    # noun / pronoun stems
    ("사람", 1500, "n"), ("것", 2000, "n"), ("때", 1200, "n"),
    ("집", 700, "n"), ("학교", 700, "n"), ("학생", 600, "n"),
    ("선생님", 500, "n"), ("시간", 700, "n"), ("나라", 400, "n"),
    ("한국", 800, "n"), ("한국어", 300, "n"), ("서울", 500, "n"),
    ("말", 700, "n"), ("물", 400, "n"), ("밥", 300, "n"),
    ("나", 1500, "n"), ("너", 700, "n"), ("우리", 1200, "n"),
    ("저", 800, "n"), ("그", 1500, "n"), ("공부", 500, "n"),
    ("일", 900, "n"), ("오늘", 600, "n"), ("내일", 400, "n"),
    ("어제", 300, "n"), ("책", 400, "n"), ("친구", 600, "n"),
)


class KoreanLexicon(JapaneseLexicon):
    """:class:`Lexicon` + Korean morpheme categories (n/v/j/e/x). Reuses
    the 3-column dictionary format; uncategorized words default to noun
    (the open class), with the josa table as a fallback hint. Homographs
    keep EVERY category they were added with (가 is a josa and a verb
    stem; the lattice gets one edge per reading)."""

    _CATS = ("n", "v", "j", "e", "x")

    def add(self, word: str, freq: int = 1, cat: Optional[str] = None):
        word = word.strip()
        if not word:
            return
        if cat is None:
            cat = self._cat.get(word) or (
                "j" if word in KOREAN_JOSA else "n")
        self._cat.setdefault(word, cat)     # primary = first reading
        cats = self._all_cats.setdefault(word, [])
        if cat not in cats:
            cats.append(cat)
        Lexicon.add(self, word, freq)

    def __init__(self, entries: Optional[Iterable] = None):
        self._all_cats: Dict[str, List[str]] = {}
        super().__init__(entries)

    def categories(self, word: str) -> Tuple[str, ...]:
        return tuple(self._all_cats.get(word) or (self.category(word),))

    def load(self, path: str, encoding: str = "utf-8") -> "KoreanLexicon":
        for word, freq, extra in _iter_dict_lines(path, encoding):
            cat = extra[0] if extra and extra[0] in self._CATS else None
            self.add(word, freq, cat)
        return self

    def category(self, word: str) -> str:
        return self._cat.get(word, "n")


class _KoreanLatticeSegmenter(_JapaneseLatticeSegmenter):
    """Eojeol-internal morpheme lattice — the arirang algorithm class
    (reference ``deeplearning4j-nlp-korean`` bundles arirang's
    ``MorphAnalyzer``: decompose each eojeol into stem + particle/ending
    chains via dictionary tables and pick the best analysis). Same Viterbi
    machinery as the Japanese lattice, Korean category set + connection
    matrix:

    - ``B → n/v/x`` (an eojeol opens with a stem; bound morphemes first
      are penalized),
    - ``n → j`` (noun+josa, the dominant pattern), ``n → n`` mildly
      penalized (compounds exist: 한국+어),
    - ``v → e`` (verb stems must take an ending; ``v → E`` is heavily
      penalized — an unfinished verb is not a Korean word),
    - ``e → e`` cheap (ending chains: 먹+었+습니다), ``e → E`` free.

    Syllable-level honesty: Korean tense/politeness morphemes fuse INTO
    the preceding syllable when the stem ends in a vowel (가+았→갔,
    하+았→했, 하+ㅂ니다→합니다). A syllable lattice cannot split those, so
    the seed lists frequent portmanteau forms as single "e"/"v" entries —
    the same table-driven answer arirang uses — and everything
    syllable-aligned (먹/었/습니다, 학생/이) decomposes properly."""

    _CONN = {
        "B": {"n": 0.0, "v": 0.3, "x": 1.0, "j": 4.0, "e": 4.0},
        # n->j carries a small BONUS: noun+josa is the dominant eojeol
        # shape, and it must beat an unknown run absorbing its josa
        "n": {"n": 1.2, "v": 1.5, "j": -0.5, "e": 1.0, "x": 0.8, "E": 0.2},
        "v": {"e": 0.0, "n": 2.5, "v": 2.5, "j": 3.0, "x": 2.0, "E": 3.0},
        "j": {"n": 1.5, "v": 1.8, "j": 1.5, "e": 2.5, "x": 2.0, "E": 0.0},
        "e": {"e": 0.3, "n": 2.0, "v": 2.0, "j": 1.5, "x": 2.0, "E": 0.0},
        "x": {"n": 0.5, "v": 0.8, "j": 1.0, "e": 1.5, "x": 1.5, "E": 0.8},
    }
    _UNK_CAT = "n"            # unknown runs read as noun stems (open class)
    _UNK_PER_CHAR = 3.0       # steeper than Japanese: an unknown eojeol
                              # must not swallow its trailing josa/eomi
    _LEX_CLS = KoreanLexicon
    _SEED = KOREAN_SEED_ENTRIES


class KoreanTokenizerFactory(TokenizerFactory):
    """Korean tokenizer behind the reference's ``TokenizerFactory`` seam
    (``deeplearning4j-nlp-korean/.../KoreanTokenizerFactory.java`` over the
    arirang analyzer).

    ``algorithm="lattice"`` (default): whitespace eojeol split, then an
    eojeol-internal morpheme lattice (:class:`_KoreanLatticeSegmenter`) —
    stems, josa and endings come out as separate tokens, so 학생이 and
    학생을 both contribute 학생 to an embedding vocabulary.
    ``strip_particles=True`` (default) drops josa/eomi from the output,
    the arirang stemming contract for embedding pipelines; set False to
    keep the full morpheme stream.

    ``algorithm="simple"``: the legacy longest-josa suffix strip."""

    def __init__(self, strip_josa: bool = True, algorithm: str = "lattice",
                 lexicon: Optional[Iterable] = None,
                 dict_path: Optional[str] = None,
                 strip_particles: Optional[bool] = None):
        self._pre: Optional[TokenPreProcess] = None
        if algorithm not in ("lattice", "simple"):
            raise ValueError(f"unknown segmentation algorithm {algorithm!r}"
                             " (expected 'lattice' or 'simple')")
        self._algorithm = algorithm
        self._strip = strip_josa
        self._strip_particles = (strip_particles if strip_particles
                                 is not None else strip_josa)
        self._josa = sorted(KOREAN_JOSA, key=len, reverse=True)
        if algorithm == "lattice":
            self._lat = _KoreanLatticeSegmenter(lexicon)
            if dict_path is not None:
                self._lat.lexicon.load(dict_path)

    def add_words(self, *words):
        """Extend the dictionary (arirang user-dictionary seam); entries
        are words or ``(word, freq[, cat])`` tuples. Lattice mode only —
        the simple josa strip has no dictionary, so silently accepting
        words would lose them."""
        if self._algorithm != "lattice":
            raise ValueError("algorithm='simple' has no dictionary — use "
                             "the lattice for user words")
        self._lat.add(*words)
        return self

    addWords = add_words

    def load_dictionary(self, path: str):
        if self._algorithm != "lattice":
            raise ValueError("algorithm='simple' has no dictionary — the "
                             "josa strip is table-driven; use the lattice "
                             "for user dictionaries")
        self._lat.lexicon.load(path)
        return self

    loadDictionary = load_dictionary

    def _stem(self, word: str) -> str:
        if not self._strip or not all(_is_hangul(c) for c in word):
            return word
        for j in self._josa:
            if len(word) > len(j) and word.endswith(j):
                return word[:-len(j)]
        return word

    def _analyze(self, eojeol: str) -> List[str]:
        pairs = self._lat.segment_with_categories(eojeol)
        if not self._strip_particles:
            return [m for m, _ in pairs]
        # filter on the category the Viterbi PATH chose — a homograph verb
        # stem whose surface doubles as a josa (가고 → 가+고) must survive
        kept = [m for m, cat in pairs if cat not in ("j", "e")]
        # an eojeol that is ALL particles/endings (e.g. 합니다 alone)
        # keeps its surface form: dropping every token would lose it
        return kept or [eojeol]

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        for raw in text.split():
            # punctuation splits the eojeol (안녕,세상 → 안녕 / 세상)
            for word, cls in _script_runs(raw):
                if cls == "punct":
                    continue
                if self._algorithm == "lattice" and cls == "hangul":
                    tokens.extend(self._analyze(word))
                else:
                    tokens.append(self._stem(word))
        return self._finish(tokens)


# ------------------------------------------------------- UIMA-style pipeline
_ABBREV = {"mr", "mrs", "ms", "dr", "prof", "st", "vs", "etc", "e.g", "i.e",
           "fig", "jr", "sr"}


class SentenceAnnotator:
    """Rule-based sentence segmentation (reference
    ``deeplearning4j-nlp-uima/.../annotator/SentenceAnnotator.java``):
    split on ``.!?`` with abbreviation and decimal guards."""

    def annotate(self, text: str) -> List[str]:
        sentences: List[str] = []
        buf: List[str] = []
        i, n = 0, len(text)
        while i < n:
            ch = text[i]
            buf.append(ch)
            if ch in ".!?":
                prev = "".join(buf).rstrip(".!?").split()
                last = prev[-1].lower().rstrip(".") if prev else ""
                nxt = text[i + 1] if i + 1 < n else " "
                if ch == "." and (last in _ABBREV or nxt.isdigit()):
                    i += 1
                    continue
                if nxt.isspace() or i + 1 == n:
                    s = "".join(buf).strip()
                    if s:
                        sentences.append(s)
                    buf = []
            i += 1
        tail = "".join(buf).strip()
        if tail:
            sentences.append(tail)
        return sentences


class TokenizerAnnotator:
    """Penn-treebank-ish tokenization: words, numbers, punctuation tokens
    (reference ``annotator/TokenizerAnnotator.java``)."""

    _PAT = re.compile(
        r"[^\W\d_]+(?:'[^\W\d_]+)?|\d+(?:\.\d+)?|[^\w\s]", re.UNICODE)

    def annotate(self, sentence: str) -> List[str]:
        return self._PAT.findall(sentence)


class PoStagger:
    """Suffix-rule POS tagger over Penn tags (reference
    ``annotator/PoStagger.java`` via ClearTK; rule-based stand-in with the
    same annotation contract: token → tag)."""

    _DET = {"the", "a", "an", "this", "that", "these", "those"}
    _PRON = {"i", "you", "he", "she", "it", "we", "they", "me", "him", "her",
             "us", "them"}
    _PREP = {"in", "on", "at", "of", "to", "by", "for", "with", "from",
             "over", "under", "into"}
    _CONJ = {"and", "or", "but", "nor", "so", "yet"}
    _MODAL = {"can", "could", "will", "would", "shall", "should", "may",
              "might", "must"}
    _BE = {"is", "are", "was", "were", "be", "been", "am", "being"}

    def tag(self, token: str) -> str:
        t = token.lower()
        if re.fullmatch(r"\d+(\.\d+)?", t):
            return "CD"
        if not any(c.isalnum() for c in t):
            return "."
        if t in self._DET:
            return "DT"
        if t in self._PRON:
            return "PRP"
        if t in self._PREP:
            return "IN"
        if t in self._CONJ:
            return "CC"
        if t in self._MODAL:
            return "MD"
        if t in self._BE:
            return "VB"
        if t.endswith("ing"):
            return "VBG"
        if t.endswith("ed"):
            return "VBD"
        if t.endswith("ly"):
            return "RB"
        if t.endswith(("ous", "ful", "ive", "able", "ible", "al", "ic")):
            return "JJ"
        if t.endswith("s") and len(t) > 3 and not t.endswith("ss"):
            return "NNS"
        if token[:1].isupper():
            return "NNP"
        return "NN"

    def annotate(self, tokens: Sequence[str]) -> List[Tuple[str, str]]:
        return [(tok, self.tag(tok)) for tok in tokens]


class AnnotationPipeline:
    """Sentence → token → POS pipeline (the UIMA AnalysisEngine aggregate the
    reference builds in ``UimaResource``/``UimaTokenizerFactory``)."""

    def __init__(self):
        self.sentences = SentenceAnnotator()
        self.tokenizer = TokenizerAnnotator()
        self.pos = PoStagger()

    def process(self, text: str) -> List[Dict[str, object]]:
        out: List[Dict[str, object]] = []
        for sent in self.sentences.annotate(text):
            toks = self.tokenizer.annotate(sent)
            out.append({"sentence": sent, "tokens": toks,
                        "pos": self.pos.annotate(toks)})
        return out


class UimaTokenizerFactory(TokenizerFactory):
    """TokenizerFactory over the annotation pipeline (reference
    ``deeplearning4j-nlp-uima/.../UimaTokenizerFactory.java``)."""

    def __init__(self, pipeline: Optional[AnnotationPipeline] = None,
                 drop_punct: bool = True):
        self._pre: Optional[TokenPreProcess] = None
        self._pipeline = pipeline or AnnotationPipeline()
        self._drop_punct = drop_punct

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        for ann in self._pipeline.process(text):
            for tok, tag in ann["pos"]:
                if self._drop_punct and tag == ".":
                    continue
                tokens.append(tok)
        return self._finish(tokens)
