"""CJK + UIMA-style language modules for the text pipeline.

TPU-native equivalent of the reference's language modules (SURVEY.md §2.5
"Language modules"): ``deeplearning4j-nlp-chinese`` (bundled ansj segmenter),
``deeplearning4j-nlp-japanese`` (bundled Kuromoji), ``deeplearning4j-nlp-korean``
(arirang wrapper) and ``deeplearning4j-nlp-uima`` (ClearTK annotation
pipeline). The reference bundles ~24k LoC of third-party morphological
analyzers; re-bundling them is neither possible (zero egress) nor useful.
What the framework actually *needs* from those modules is the contract each
gives the NLP stack: a ``TokenizerFactory`` that turns CJK text (which has no
spaces) into word tokens, and a UIMA-like annotation pipeline (sentence
segmentation → tokenization → POS). This module implements those contracts
natively:

- ``ChineseTokenizerFactory`` — forward-maximum-matching segmentation over a
  user-extendable lexicon with single-character fallback (the core dictionary
  strategy of ansj's DAT segmenter, reference
  ``deeplearning4j-nlp-chinese/.../ChineseTokenizerFactory``), Latin/digit
  runs kept whole.
- ``JapaneseTokenizerFactory`` — script-class segmentation (kanji / hiragana /
  katakana / Latin runs) with lexicon longest-match and trailing-particle
  splitting (the observable behavior of the Kuromoji wrapper in
  ``deeplearning4j-nlp-japanese/.../JapaneseTokenizerFactory``).
- ``KoreanTokenizerFactory`` — whitespace eojeol split + josa/particle
  suffix stripping (arirang's stemming contract, reference
  ``deeplearning4j-nlp-korean/.../KoreanTokenizerFactory``).
- ``UimaTokenizerFactory`` / ``AnnotationPipeline`` — sentence segmenter +
  tokenizer + rule-based POS tagger behind one pipeline object (reference
  ``deeplearning4j-nlp-uima/.../UimaTokenizerFactory``,
  ``annotator/SentenceAnnotator``, ``annotator/PoStagger``).

All factories honor ``set_token_pre_processor`` like every other
``TokenizerFactory`` so they drop into Word2Vec/ParagraphVectors/TF-IDF
unchanged.
"""
from __future__ import annotations

import itertools
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .text import Tokenizer, TokenizerFactory, TokenPreProcess


# --------------------------------------------------------------- script tests
def _is_cjk(ch: str) -> bool:
    o = ord(ch)
    return (0x4E00 <= o <= 0x9FFF or 0x3400 <= o <= 0x4DBF
            or 0xF900 <= o <= 0xFAFF or 0x20000 <= o <= 0x2FA1F)


def _is_hiragana(ch: str) -> bool:
    return 0x3040 <= ord(ch) <= 0x309F


def _is_katakana(ch: str) -> bool:
    return 0x30A0 <= ord(ch) <= 0x30FF


def _is_hangul(ch: str) -> bool:
    o = ord(ch)
    return 0xAC00 <= o <= 0xD7A3 or 0x1100 <= o <= 0x11FF


def _script_class(ch: str) -> str:
    if _is_hiragana(ch):
        return "hira"
    if _is_katakana(ch):
        return "kata"
    if _is_cjk(ch):
        return "han"
    if _is_hangul(ch):
        return "hangul"
    if ch.isalnum():
        return "latin"
    if ch.isspace():
        return "space"
    return "punct"


def _script_runs(text: str) -> List[Tuple[str, str]]:
    """Split ``text`` into maximal same-script runs → [(run, class)]."""
    return [("".join(grp), cls)
            for cls, grp in itertools.groupby(text, key=_script_class)]


# ------------------------------------------------------------------- Chinese
#: Seed lexicon: common multi-character words so segmentation is useful out of
#: the box; extend per-corpus via ``ChineseTokenizerFactory(lexicon=...)``.
CHINESE_LEXICON = {
    "中国", "我们", "你们", "他们", "今天", "明天", "昨天", "时间", "工作",
    "学习", "深度", "深度学习", "机器", "机器学习", "神经", "网络",
    "神经网络", "数据", "模型", "训练", "语言", "自然", "自然语言",
    "处理", "计算", "计算机", "人工", "智能", "人工智能", "北京", "上海",
    "大学", "老师", "学生", "朋友", "喜欢", "可以", "没有", "什么",
    "知道", "现在", "因为", "所以", "如果", "但是", "已经", "开始",
}


class Lexicon:
    """Frequency dictionary + character trie for segmentation.

    The reference bundles ansj's double-array-trie dictionaries
    (``deeplearning4j-nlp-chinese/.../org/ansj/``); this is the same
    capability at real scale without the 3rd-party bundle: load
    user-supplied dictionary files (one ``word [frequency]`` per line —
    jieba/ansj user-dict format, ``#`` comments allowed) into a plain dict
    trie. Frequencies feed the bidirectional max-match ambiguity scoring."""

    _END = "\0"

    def __init__(self, words: Optional[Iterable[str]] = None):
        self._freq: Dict[str, int] = {}
        self._trie: Dict = {}
        self._total = 0          # running Σfreq (O(1) total_freq)
        self.max_len = 1
        if words:
            for w in words:
                self.add(w)

    def add(self, word: str, freq: int = 1):
        word = word.strip()
        if not word:
            return
        old = self._freq.get(word, 0)
        new = max(old, int(freq))
        self._freq[word] = new
        self._total += new - old
        self.max_len = max(self.max_len, len(word))
        node = self._trie
        for ch in word:
            node = node.setdefault(ch, {})
        node[self._END] = True

    def load(self, path: str, encoding: str = "utf-8") -> "Lexicon":
        """Merge a dictionary file: ``word``, ``word freq`` or ``word,freq``
        per line; blank lines and ``#`` comments skipped."""
        with open(path, encoding=encoding) as fh:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.replace(",", " ").split()
                freq = (int(parts[1]) if len(parts) > 1
                        and parts[1].isdigit() else 1)
                self.add(parts[0], freq)
        return self

    @classmethod
    def from_file(cls, path: str, encoding: str = "utf-8") -> "Lexicon":
        return cls().load(path, encoding)

    def __contains__(self, word: str) -> bool:
        return word in self._freq

    def __len__(self) -> int:
        return len(self._freq)

    def freq(self, word: str) -> int:
        return self._freq.get(word, 0)

    def longest_prefix(self, text: str, start: int) -> int:
        """Length of the longest lexicon word starting at ``start`` (0 if
        none) — one trie walk, no per-length hashing."""
        lengths = self.match_lengths(text, start)
        return lengths[-1] if lengths else 0

    def longest_suffix(self, text: str, end: int) -> int:
        """Length of the longest lexicon word ENDING at ``end`` (exclusive).
        Bounded backward scan (len ≤ max_len) for backward max-match."""
        lo = max(0, end - self.max_len)
        for start in range(lo, end - 1):
            if text[start:end] in self._freq:
                return end - start
        return 0

    def match_lengths(self, text: str, start: int) -> List[int]:
        """ALL lexicon-word lengths starting at ``start`` (one trie walk) —
        the lattice edges for Viterbi segmentation."""
        node = self._trie
        out: List[int] = []
        i, n = start, len(text)
        while i < n:
            node = node.get(text[i])
            if node is None:
                break
            i += 1
            if self._END in node:
                out.append(i - start)
        return out

    def total_freq(self) -> int:
        return self._total


class _MaxMatchSegmenter:
    """Bidirectional maximum matching with ambiguity scoring over a
    :class:`Lexicon` (the dictionary strategy of ansj's DAT segmenter
    without the 3rd-party bundle).

    Forward AND backward max-match are both computed; when they disagree the
    segmentation with (1) fewer words, then (2) fewer single-character
    leftovers, then (3) higher summed log-frequency wins — the classic
    disambiguation triple. Example the forward-only pass gets wrong:
    研究生命起源 → FMM 研究生|命|起源 vs BMM 研究|生命|起源 (picked: fewer
    singletons)."""

    def __init__(self, lexicon: Iterable[str], bidirectional: bool = True):
        self.lexicon = (lexicon if isinstance(lexicon, Lexicon)
                        else Lexicon(lexicon))
        self.bidirectional = bidirectional

    def add(self, *words: str):
        for w in words:
            self.lexicon.add(w)

    def _forward(self, run: str) -> List[str]:
        out: List[str] = []
        i, n = 0, len(run)
        while i < n:
            L = self.lexicon.longest_prefix(run, i)
            if L > 1:
                out.append(run[i:i + L])
                i += L
            else:
                out.append(run[i])
                i += 1
        return out

    def _backward(self, run: str) -> List[str]:
        out: List[str] = []
        i = len(run)
        while i > 0:
            L = self.lexicon.longest_suffix(run, i)
            if L > 1:
                out.append(run[i - L:i])
                i -= L
            else:
                out.append(run[i - 1])
                i -= 1
        out.reverse()
        return out

    def _score(self, seg: List[str]):
        import math
        singles = sum(1 for w in seg if len(w) == 1)
        logfreq = sum(math.log1p(self.lexicon.freq(w)) for w in seg
                      if len(w) > 1)
        return (-len(seg), -singles, logfreq)

    def segment(self, run: str) -> List[str]:
        fwd = self._forward(run)
        if not self.bidirectional:
            return fwd
        bwd = self._backward(run)
        if fwd == bwd:
            return fwd
        return max(fwd, bwd, key=self._score)


class _UnigramSegmenter:
    """Unigram-LM lattice (word-DAG) segmentation with Viterbi DP — the
    algorithm class behind the reference's bundled ansj/jieba-style
    segmenters (`deeplearning4j-nlp-chinese/.../org/ansj/` builds a word
    lattice over a double-array trie and picks the best-scoring path; same
    capability here over the plain :class:`Lexicon` trie).

    Every lexicon word starting at each position is a lattice edge scored
    ``log((freq+1)/total)``; unknown single characters get the floor score.
    ``route[i] = max_j logp(run[i:j]) + route[j]`` solved right-to-left in
    O(n · max_word_len). Unlike max-match (greedy, longest-first), the DP
    picks the globally most probable path, so frequency evidence can
    override a longer dictionary match: 北京大学生前来应聘 segments
    北京|大学生|前来|应聘 when 大学生 outweighs 北京大学, where FMM is
    stuck with 北京大学|生前|来|应聘."""

    def __init__(self, lexicon: Iterable[str]):
        self.lexicon = (lexicon if isinstance(lexicon, Lexicon)
                        else Lexicon(lexicon))

    def add(self, *words: str):
        for w in words:
            self.lexicon.add(w)

    def segment(self, run: str) -> List[str]:
        import math
        lex = self.lexicon
        n = len(run)
        if n == 0:
            return []
        logtot = math.log(lex.total_freq() + len(lex) + 1)
        floor = -logtot  # unknown char: count ~1 in the corpus

        def logp(w: str) -> float:
            f = lex.freq(w)
            return math.log(f + 1) - logtot if f > 0 else floor

        route: List[Tuple[float, int]] = [(0.0, n)] * (n + 1)
        for i in range(n - 1, -1, -1):
            best = (logp(run[i]) + route[i + 1][0], i + 1)
            for L in lex.match_lengths(run, i):
                if L == 1:
                    continue  # already covered by the char fallback
                cand = logp(run[i:i + L]) + route[i + L][0]
                if cand > best[0]:
                    best = (cand, i + L)
            route[i] = best
        out: List[str] = []
        i = 0
        while i < n:
            j = route[i][1]
            out.append(run[i:j])
            i = j
        return out


class ChineseTokenizerFactory(TokenizerFactory):
    """Dictionary forward-maximum-matching Chinese tokenizer (reference
    ``deeplearning4j-nlp-chinese/.../tokenization/tokenizerFactory/
    ChineseTokenizerFactory.java`` over the bundled ansj segmenter)."""

    def __init__(self, lexicon: Optional[Iterable[str]] = None,
                 dict_path: Optional[str] = None, bidirectional: bool = True,
                 algorithm: str = "bimm"):
        """``lexicon``: iterable of words or a :class:`Lexicon`;
        ``dict_path``: user dictionary file (``word [freq]`` per line,
        jieba/ansj format) merged on top; ``algorithm``: ``"unigram"`` for
        lattice-Viterbi unigram-LM segmentation (the ansj/jieba algorithm
        class — best when the dictionary carries real frequencies),
        ``"bimm"`` (default) for FMM+BMM with ambiguity scoring, ``"fmm"``
        for plain forward max-match. ``bidirectional=False`` is a
        back-compat alias for ``algorithm="fmm"``."""
        self._pre: Optional[TokenPreProcess] = None
        lex = lexicon if lexicon is not None else CHINESE_LEXICON
        if algorithm not in ("unigram", "bimm", "fmm"):
            raise ValueError(f"unknown segmentation algorithm {algorithm!r}"
                             " (expected 'unigram', 'bimm' or 'fmm')")
        if algorithm == "unigram":
            self._seg = _UnigramSegmenter(lex)
        else:
            self._seg = _MaxMatchSegmenter(
                lex, bidirectional=bidirectional and algorithm == "bimm")
        if dict_path is not None:
            self._seg.lexicon.load(dict_path)

    def add_words(self, *words: str):
        """Extend the lexicon (ansj's user-dictionary seam)."""
        self._seg.add(*words)
        return self

    addWords = add_words

    def load_dictionary(self, path: str):
        """Merge a user dictionary file at runtime (ansj's
        ``UserDefineLibrary`` seam)."""
        self._seg.lexicon.load(path)
        return self

    loadDictionary = load_dictionary

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        for run, cls in _script_runs(text):
            if cls == "han":
                tokens.extend(self._seg.segment(run))
            elif cls in ("latin", "kata", "hira", "hangul"):
                tokens.append(run)
            # space/punct dropped
        return self._finish(tokens)


# ------------------------------------------------------------------ Japanese
#: Common trailing hiragana particles/copulas split off kanji+hiragana runs
#: (Kuromoji segments these as separate morphemes).
JAPANESE_PARTICLES = (
    "でした", "ました", "です", "ます", "から", "まで", "には", "とは",
    "は", "が", "を", "に", "へ", "と", "で", "も", "の", "や", "ね", "よ",
    "か", "な",
)

#: Seed lexicon for common multi-kanji words.
JAPANESE_LEXICON = {
    "日本", "東京", "大学", "学生", "先生", "機械", "学習", "機械学習",
    "言語", "自然", "自然言語", "処理", "深層", "深層学習", "好き",
}


class JapaneseTokenizerFactory(TokenizerFactory):
    """Script-run + particle-split Japanese tokenizer (contract of reference
    ``deeplearning4j-nlp-japanese/.../JapaneseTokenizerFactory.java`` over
    bundled Kuromoji). Kanji runs are lexicon max-matched; hiragana runs are
    greedily split into known particles (longest first) where possible."""

    def __init__(self, lexicon: Optional[Iterable[str]] = None,
                 dict_path: Optional[str] = None, bidirectional: bool = True):
        self._pre: Optional[TokenPreProcess] = None
        self._seg = _MaxMatchSegmenter(lexicon if lexicon is not None
                                       else JAPANESE_LEXICON,
                                       bidirectional=bidirectional)
        if dict_path is not None:
            self._seg.lexicon.load(dict_path)
        self._particles = sorted(JAPANESE_PARTICLES, key=len, reverse=True)

    def _split_hiragana(self, run: str) -> List[str]:
        """Peel ONE longest known particle off the END of the run (a hiragana
        run after a kanji run is typically okurigana/content + a trailing
        particle; compound tails like でした are single lexicon entries).
        Splitting mid-word, or peeling repeatedly, would shred content words
        like ありがとう / もも whose characters double as particles."""
        for p in self._particles:
            if run.endswith(p) and run != p:
                return [run[:-len(p)], p]
        return [run]

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        for run, cls in _script_runs(text):
            if cls == "han":
                tokens.extend(self._seg.segment(run))
            elif cls == "hira":
                tokens.extend(self._split_hiragana(run))
            elif cls in ("kata", "latin", "hangul"):
                tokens.append(run)
        return self._finish(tokens)


# -------------------------------------------------------------------- Korean
#: Common josa (case particles) stripped from eojeol tails — arirang's
#: observable stemming behavior for embedding pipelines.
KOREAN_JOSA = (
    "에서는", "에서", "에게", "으로", "로", "은", "는", "이", "가", "을",
    "를", "에", "와", "과", "도", "만", "의",
)


class KoreanTokenizerFactory(TokenizerFactory):
    """Whitespace eojeol split + josa suffix strip (contract of reference
    ``deeplearning4j-nlp-korean/.../KoreanTokenizerFactory.java`` over the
    arirang analyzer)."""

    def __init__(self, strip_josa: bool = True):
        self._pre: Optional[TokenPreProcess] = None
        self._strip = strip_josa
        self._josa = sorted(KOREAN_JOSA, key=len, reverse=True)

    def _stem(self, word: str) -> str:
        if not self._strip or not all(_is_hangul(c) for c in word):
            return word
        for j in self._josa:
            if len(word) > len(j) and word.endswith(j):
                return word[:-len(j)]
        return word

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        for raw in text.split():
            # punctuation splits the eojeol (안녕,세상 → 안녕 / 세상)
            for word, cls in _script_runs(raw):
                if cls != "punct":
                    tokens.append(self._stem(word))
        return self._finish(tokens)


# ------------------------------------------------------- UIMA-style pipeline
_ABBREV = {"mr", "mrs", "ms", "dr", "prof", "st", "vs", "etc", "e.g", "i.e",
           "fig", "jr", "sr"}


class SentenceAnnotator:
    """Rule-based sentence segmentation (reference
    ``deeplearning4j-nlp-uima/.../annotator/SentenceAnnotator.java``):
    split on ``.!?`` with abbreviation and decimal guards."""

    def annotate(self, text: str) -> List[str]:
        sentences: List[str] = []
        buf: List[str] = []
        i, n = 0, len(text)
        while i < n:
            ch = text[i]
            buf.append(ch)
            if ch in ".!?":
                prev = "".join(buf).rstrip(".!?").split()
                last = prev[-1].lower().rstrip(".") if prev else ""
                nxt = text[i + 1] if i + 1 < n else " "
                if ch == "." and (last in _ABBREV or nxt.isdigit()):
                    i += 1
                    continue
                if nxt.isspace() or i + 1 == n:
                    s = "".join(buf).strip()
                    if s:
                        sentences.append(s)
                    buf = []
            i += 1
        tail = "".join(buf).strip()
        if tail:
            sentences.append(tail)
        return sentences


class TokenizerAnnotator:
    """Penn-treebank-ish tokenization: words, numbers, punctuation tokens
    (reference ``annotator/TokenizerAnnotator.java``)."""

    _PAT = re.compile(
        r"[^\W\d_]+(?:'[^\W\d_]+)?|\d+(?:\.\d+)?|[^\w\s]", re.UNICODE)

    def annotate(self, sentence: str) -> List[str]:
        return self._PAT.findall(sentence)


class PoStagger:
    """Suffix-rule POS tagger over Penn tags (reference
    ``annotator/PoStagger.java`` via ClearTK; rule-based stand-in with the
    same annotation contract: token → tag)."""

    _DET = {"the", "a", "an", "this", "that", "these", "those"}
    _PRON = {"i", "you", "he", "she", "it", "we", "they", "me", "him", "her",
             "us", "them"}
    _PREP = {"in", "on", "at", "of", "to", "by", "for", "with", "from",
             "over", "under", "into"}
    _CONJ = {"and", "or", "but", "nor", "so", "yet"}
    _MODAL = {"can", "could", "will", "would", "shall", "should", "may",
              "might", "must"}
    _BE = {"is", "are", "was", "were", "be", "been", "am", "being"}

    def tag(self, token: str) -> str:
        t = token.lower()
        if re.fullmatch(r"\d+(\.\d+)?", t):
            return "CD"
        if not any(c.isalnum() for c in t):
            return "."
        if t in self._DET:
            return "DT"
        if t in self._PRON:
            return "PRP"
        if t in self._PREP:
            return "IN"
        if t in self._CONJ:
            return "CC"
        if t in self._MODAL:
            return "MD"
        if t in self._BE:
            return "VB"
        if t.endswith("ing"):
            return "VBG"
        if t.endswith("ed"):
            return "VBD"
        if t.endswith("ly"):
            return "RB"
        if t.endswith(("ous", "ful", "ive", "able", "ible", "al", "ic")):
            return "JJ"
        if t.endswith("s") and len(t) > 3 and not t.endswith("ss"):
            return "NNS"
        if token[:1].isupper():
            return "NNP"
        return "NN"

    def annotate(self, tokens: Sequence[str]) -> List[Tuple[str, str]]:
        return [(tok, self.tag(tok)) for tok in tokens]


class AnnotationPipeline:
    """Sentence → token → POS pipeline (the UIMA AnalysisEngine aggregate the
    reference builds in ``UimaResource``/``UimaTokenizerFactory``)."""

    def __init__(self):
        self.sentences = SentenceAnnotator()
        self.tokenizer = TokenizerAnnotator()
        self.pos = PoStagger()

    def process(self, text: str) -> List[Dict[str, object]]:
        out: List[Dict[str, object]] = []
        for sent in self.sentences.annotate(text):
            toks = self.tokenizer.annotate(sent)
            out.append({"sentence": sent, "tokens": toks,
                        "pos": self.pos.annotate(toks)})
        return out


class UimaTokenizerFactory(TokenizerFactory):
    """TokenizerFactory over the annotation pipeline (reference
    ``deeplearning4j-nlp-uima/.../UimaTokenizerFactory.java``)."""

    def __init__(self, pipeline: Optional[AnnotationPipeline] = None,
                 drop_punct: bool = True):
        self._pre: Optional[TokenPreProcess] = None
        self._pipeline = pipeline or AnnotationPipeline()
        self._drop_punct = drop_punct

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        for ann in self._pipeline.process(text):
            for tok, tag in ann["pos"]:
                if self._drop_punct and tag == ".":
                    continue
                tokens.append(tok)
        return self._finish(tokens)
