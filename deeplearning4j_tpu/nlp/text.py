"""Text pipeline: sentence iterators, tokenizers, preprocessors.

TPU-native equivalent of reference ``deeplearning4j-nlp/.../text/``
(SURVEY.md §2.5 "Text pipeline"): ``SentenceIterator`` implementations
(BasicLineIterator, CollectionSentenceIterator, FileSentenceIterator),
``TokenizerFactory``/``Tokenizer`` (DefaultTokenizerFactory ≈ whitespace +
punctuation stripping), ``TokenPreProcess`` (CommonPreprocessor). The
reference's bundled CJK analyzers (ansj/Kuromoji — §2.5 "Language modules")
are out of scope for the core; the factory seam accepts any callable.
"""
from __future__ import annotations

import re
from typing import Callable, Iterable, Iterator, List, Optional


# ------------------------------------------------------------- preprocessors
class TokenPreProcess:
    def pre_process(self, token: str) -> str:
        raise NotImplementedError

    preProcess = pre_process

    def __call__(self, token: str) -> str:
        return self.pre_process(token)


class CommonPreprocessor(TokenPreProcess):
    """Reference ``text/tokenization/tokenizer/preprocessor/CommonPreprocessor``:
    lowercase + strip punctuation/digits."""

    _PAT = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PAT.sub("", token).lower()


class LowCasePreProcessor(TokenPreProcess):
    def pre_process(self, token: str) -> str:
        return token.lower()


# ----------------------------------------------------------------- tokenizer
class Tokenizer:
    def __init__(self, tokens: List[str]):
        self._tokens = tokens
        self._pos = 0

    def has_more_tokens(self) -> bool:
        return self._pos < len(self._tokens)

    hasMoreTokens = has_more_tokens

    def next_token(self) -> str:
        t = self._tokens[self._pos]
        self._pos += 1
        return t

    nextToken = next_token

    def get_tokens(self) -> List[str]:
        return list(self._tokens)

    getTokens = get_tokens

    def count_tokens(self) -> int:
        return len(self._tokens)

    countTokens = count_tokens


class TokenizerFactory:
    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError

    def set_token_pre_processor(self, pre: TokenPreProcess):
        self._pre = pre
        return self

    setTokenPreProcessor = set_token_pre_processor

    def _finish(self, tokens: List[str]) -> Tokenizer:
        """Apply the configured preprocessor and drop emptied tokens — the
        shared tail of every factory's ``create``."""
        pre = getattr(self, "_pre", None)
        if pre is not None:
            tokens = [pre(t) for t in tokens]
        return Tokenizer([t for t in tokens if t])


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace tokenization + optional preprocessor (reference
    ``DefaultTokenizerFactory``)."""

    def __init__(self):
        self._pre: Optional[TokenPreProcess] = None

    def create(self, text: str) -> Tokenizer:
        return self._finish(text.split())


class NGramTokenizerFactory(TokenizerFactory):
    """Reference ``NGramTokenizerFactory``: emits n-grams joined by space."""

    def __init__(self, base: TokenizerFactory, min_n: int, max_n: int):
        self._base = base
        self._min = min_n
        self._max = max_n
        self._pre = None

    def create(self, text: str) -> Tokenizer:
        tokens = self._base.create(text).get_tokens()
        out = []
        for n in range(self._min, self._max + 1):
            for i in range(len(tokens) - n + 1):
                out.append(" ".join(tokens[i:i + n]))
        return self._finish(out)


# ---------------------------------------------------------- sentence sources
class SentenceIterator:
    """Reference ``text/sentenceiterator/SentenceIterator``."""

    def __iter__(self) -> Iterator[str]:
        self.reset()
        return self

    def __next__(self) -> str:
        raise NotImplementedError

    def reset(self):
        pass


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Iterable[str]):
        self._sentences = list(sentences)
        self._pos = 0

    def __next__(self):
        if self._pos >= len(self._sentences):
            raise StopIteration
        s = self._sentences[self._pos]
        self._pos += 1
        return s

    def reset(self):
        self._pos = 0


class BasicLineIterator(SentenceIterator):
    """One sentence per line from a file (reference ``BasicLineIterator``)."""

    def __init__(self, path: str):
        self._path = path
        self._fh = None

    def reset(self):
        if self._fh is not None:
            self._fh.close()
        self._fh = open(self._path, encoding="utf-8")

    def __next__(self):
        if self._fh is None:
            self.reset()
        line = self._fh.readline()
        while line == "\n":
            line = self._fh.readline()
        if not line:
            raise StopIteration
        return line.rstrip("\n")


class StopWords:
    """Reference bundled english stopwords list (abbreviated core set)."""

    WORDS = {"a", "an", "and", "are", "as", "at", "be", "but", "by", "for",
             "if", "in", "into", "is", "it", "no", "not", "of", "on", "or",
             "such", "that", "the", "their", "then", "there", "these", "they",
             "this", "to", "was", "will", "with"}

    @staticmethod
    def get_stop_words():
        return set(StopWords.WORDS)

    getStopWords = get_stop_words
