"""NLP embeddings (reference ``deeplearning4j-nlp-parent`` — SURVEY.md §2.5):
SequenceVectors engine, Word2Vec/CBOW, ParagraphVectors, GloVe, vocab +
Huffman, tokenization pipeline, word-vector serialization."""
from .text import (SentenceIterator, CollectionSentenceIterator,
                   BasicLineIterator, Tokenizer, TokenizerFactory,
                   DefaultTokenizerFactory, NGramTokenizerFactory,
                   TokenPreProcess, CommonPreprocessor, LowCasePreProcessor,
                   StopWords)
from .vocab import VocabCache, VocabWord, SequenceElement, Huffman, build_vocab
from .sequencevectors import SequenceVectors, InMemoryLookupTable
from .word2vec import Word2Vec, CBOW, ParagraphVectors
from .glove import Glove
from .distributed import (DistributedWord2Vec, DistributedGlove,
                          SparkWord2Vec, SparkGlove, partition_sentences)
from .bagofwords import InvertedIndex, BagOfWordsVectorizer, TfidfVectorizer
from .serializer import WordVectorSerializer, StaticWordVectors
from .lang import (Lexicon,
                   ChineseTokenizerFactory, JapaneseTokenizerFactory,
                   KoreanTokenizerFactory, UimaTokenizerFactory,
                   AnnotationPipeline)

__all__ = ["SentenceIterator", "CollectionSentenceIterator", "BasicLineIterator",
           "Tokenizer", "TokenizerFactory", "DefaultTokenizerFactory",
           "NGramTokenizerFactory", "TokenPreProcess", "CommonPreprocessor",
           "LowCasePreProcessor", "StopWords", "VocabCache", "VocabWord",
           "SequenceElement", "Huffman", "build_vocab", "SequenceVectors",
           "InMemoryLookupTable", "Word2Vec", "CBOW", "ParagraphVectors",
           "Glove", "DistributedWord2Vec", "DistributedGlove",
           "SparkWord2Vec", "SparkGlove", "partition_sentences",
           "InvertedIndex", "BagOfWordsVectorizer", "TfidfVectorizer",
           "WordVectorSerializer", "StaticWordVectors",
           "Lexicon", "ChineseTokenizerFactory", "JapaneseTokenizerFactory",
           "KoreanTokenizerFactory", "UimaTokenizerFactory",
           "AnnotationPipeline"]
