"""Early stopping: config, score calculators, termination conditions, savers,
trainer.

TPU-native equivalent of reference ``deeplearning4j-nn/.../earlystopping/``
(1586 LoC; fit loop ``trainer/BaseEarlyStoppingTrainer.java:76``): train
epoch-by-epoch, score on a validation set every N epochs, keep the best model,
stop on any epoch/iteration termination condition.
"""
from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np


# --------------------------------------------------------------- calculators
class ScoreCalculator:
    """Reference ``earlystopping/scorecalc/ScoreCalculator.java``."""

    def calculate_score(self, net) -> float:
        raise NotImplementedError

    def minimize_score(self) -> bool:
        return True


class DataSetLossCalculator(ScoreCalculator):
    """Average loss over a validation iterator (reference
    ``scorecalc/DataSetLossCalculator.java``)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, net) -> float:
        total, n = 0.0, 0
        for ds in self.iterator:
            b = np.asarray(ds.features if not isinstance(ds.features, (list, tuple))
                           else ds.features[0]).shape[0]
            total += net.score(ds) * b
            n += b
        return total / n if (self.average and n) else total


class ClassificationScoreCalculator(ScoreCalculator):
    """Accuracy (maximized) on a validation iterator."""

    def __init__(self, iterator):
        self.iterator = iterator

    def calculate_score(self, net) -> float:
        return net.evaluate(self.iterator).accuracy()

    def minimize_score(self) -> bool:
        return False


# ----------------------------------------------- epoch termination conditions
class EpochTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = int(max_epochs)

    def terminate(self, epoch, score):
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after ``patience`` epochs without ≥``min_improvement`` improvement
    (reference class of the same name). ``minimize`` is set by the trainer from
    the score calculator's direction before the fit loop."""

    def __init__(self, patience: int, min_improvement: float = 0.0):
        self.patience = int(patience)
        self.min_improvement = float(min_improvement)
        self.minimize = True
        self.best = None
        self.best_epoch = -1

    def initialize(self):
        self.best = None
        self.best_epoch = -1

    def terminate(self, epoch, score):
        improvement = ((self.best - score) if self.minimize
                       else (score - self.best)) if self.best is not None else None
        if self.best is None or improvement > self.min_improvement:
            self.best = score
            self.best_epoch = epoch
            return False
        return (epoch - self.best_epoch) >= self.patience


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once the score is at least as good as ``target`` (reference keeps a
    lesser-better flag; we take minimize from the config at check time)."""

    def __init__(self, target: float, minimize: bool = True):
        self.target = float(target)
        self.minimize = minimize

    def terminate(self, epoch, score):
        return score <= self.target if self.minimize else score >= self.target


# ------------------------------------------- iteration termination conditions
class IterationTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, last_score: float) -> bool:
        raise NotImplementedError


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = float(max_seconds)
        self._start = None

    def initialize(self):
        self._start = time.time()

    def terminate(self, last_score):
        return (time.time() - self._start) > self.max_seconds


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Abort when the score exceeds a bound (divergence guard)."""

    def __init__(self, max_score: float):
        self.max_score = float(max_score)

    def terminate(self, last_score):
        return last_score > self.max_score


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    def terminate(self, last_score):
        return not np.isfinite(last_score)


# --------------------------------------------------------------------- savers
class EarlyStoppingModelSaver:
    def save_best_model(self, net, score):
        raise NotImplementedError

    def save_latest_model(self, net, score):
        pass

    def get_best_model(self):
        raise NotImplementedError


class InMemoryModelSaver(EarlyStoppingModelSaver):
    """Reference ``saver/InMemoryModelSaver.java`` — deep-copies the model."""

    def __init__(self):
        self.best = None

    def save_best_model(self, net, score):
        self.best = net.clone() if hasattr(net, "clone") else copy.deepcopy(net)

    def get_best_model(self):
        return self.best


class LocalFileModelSaver(EarlyStoppingModelSaver):
    """Reference ``saver/LocalFileModelSaver.java`` — ModelSerializer zips."""

    def __init__(self, directory: str):
        import os
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._is_graph = None

    def _path(self, name):
        import os
        return os.path.join(self.directory, name)

    def save_best_model(self, net, score):
        from ..utils.model_serializer import ModelSerializer
        from ..nn.multilayer import MultiLayerNetwork
        self._is_graph = not isinstance(net, MultiLayerNetwork)
        ModelSerializer.write_model(net, self._path("bestModel.bin"))

    def save_latest_model(self, net, score):
        from ..utils.model_serializer import ModelSerializer
        ModelSerializer.write_model(net, self._path("latestModel.bin"))

    def get_best_model(self):
        from ..utils.model_serializer import ModelSerializer
        return ModelSerializer.restore_model(self._path("bestModel.bin"))


# --------------------------------------------------------------------- config
@dataclass
class EarlyStoppingConfiguration:
    """Reference ``EarlyStoppingConfiguration`` + Builder."""
    score_calculator: Optional[ScoreCalculator] = None
    epoch_termination_conditions: List[EpochTerminationCondition] = field(
        default_factory=list)
    iteration_termination_conditions: List[IterationTerminationCondition] = field(
        default_factory=list)
    model_saver: EarlyStoppingModelSaver = field(default_factory=InMemoryModelSaver)
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False

    class Builder:
        def __init__(self):
            self._c = EarlyStoppingConfiguration()

        def score_calculator(self, sc):
            self._c.score_calculator = sc
            return self

        scoreCalculator = score_calculator

        def epoch_termination_conditions(self, *conds):
            self._c.epoch_termination_conditions.extend(conds)
            return self

        epochTerminationConditions = epoch_termination_conditions

        def iteration_termination_conditions(self, *conds):
            self._c.iteration_termination_conditions.extend(conds)
            return self

        iterationTerminationConditions = iteration_termination_conditions

        def model_saver(self, saver):
            self._c.model_saver = saver
            return self

        modelSaver = model_saver

        def evaluate_every_n_epochs(self, n):
            self._c.evaluate_every_n_epochs = int(n)
            return self

        evaluateEveryNEpochs = evaluate_every_n_epochs

        def save_last_model(self, flag=True):
            self._c.save_last_model = bool(flag)
            return self

        saveLastModel = save_last_model

        def build(self):
            return self._c

    @staticmethod
    def builder() -> "EarlyStoppingConfiguration.Builder":
        return EarlyStoppingConfiguration.Builder()


# --------------------------------------------------------------------- result
class TerminationReason:
    EpochTerminationCondition = "EpochTerminationCondition"
    IterationTerminationCondition = "IterationTerminationCondition"
    Error = "Error"


@dataclass
class EarlyStoppingResult:
    termination_reason: str
    termination_details: str
    score_vs_epoch: Dict[int, float]
    best_model_epoch: int
    best_model_score: float
    total_epochs: int
    best_model: Any


# -------------------------------------------------------------------- trainer
class EarlyStoppingTrainer:
    """Reference ``trainer/BaseEarlyStoppingTrainer.java:76`` fit loop; works
    for both ``MultiLayerNetwork`` and ``ComputationGraph``."""

    def __init__(self, config: EarlyStoppingConfiguration, net, train_iterator):
        self.config = config
        self.net = net
        self.iterator = train_iterator

    def _train_one_epoch(self, c, reason, details):
        """One epoch of training with per-iteration termination checks.
        Overridden by the distributed trainer (epoch-granular master fit,
        reference ``spark/earlystopping/BaseSparkEarlyStoppingTrainer.java``).
        Returns (terminated, reason, details)."""
        for ds in self.iterator:
            self.net._fit_batch(ds)
            last = float(self.net.score_)
            for cond in c.iteration_termination_conditions:
                if cond.terminate(last):
                    reason = TerminationReason.IterationTerminationCondition
                    details = f"{type(cond).__name__} at score {last}"
                    return True, reason, details
        return False, reason, details

    def fit(self) -> EarlyStoppingResult:
        c = self.config
        for cond in c.epoch_termination_conditions:
            cond.initialize()
        for cond in c.iteration_termination_conditions:
            cond.initialize()
        minimize = (c.score_calculator.minimize_score()
                    if c.score_calculator else True)
        for cond in c.epoch_termination_conditions:
            if hasattr(cond, "minimize"):
                cond.minimize = minimize
        score_vs_epoch: Dict[int, float] = {}
        best_score = np.inf if minimize else -np.inf
        best_epoch = -1
        epoch = 0
        reason, details = None, ""
        while True:
            iter_terminated, reason, details = self._train_one_epoch(
                c, reason, details)
            if iter_terminated:
                break
            self.net.epoch_count += 1
            evaluated = (c.score_calculator is not None
                         and epoch % c.evaluate_every_n_epochs == 0)
            if evaluated:
                score = float(c.score_calculator.calculate_score(self.net))
                score_vs_epoch[epoch] = score
                improved = score < best_score if minimize else score > best_score
                if improved:
                    best_score = score
                    best_epoch = epoch
                    c.model_saver.save_best_model(self.net, score)
                if c.save_last_model:
                    c.model_saver.save_latest_model(self.net, score)
            else:
                score = float(self.net.score_)
            # score-based epoch conditions only fire on epochs with a fresh
            # validation score (reference BaseEarlyStoppingTrainer gates the
            # check inside the evaluate-every-N block); epoch-count conditions
            # (MaxEpochs) are always checked so they fire between evaluations.
            score_valid = evaluated or c.score_calculator is None
            for cond in c.epoch_termination_conditions:
                if (not score_valid
                        and not isinstance(cond, MaxEpochsTerminationCondition)):
                    continue
                if cond.terminate(epoch, score):
                    reason = TerminationReason.EpochTerminationCondition
                    details = f"{type(cond).__name__} at epoch {epoch}"
                    break
            if reason == TerminationReason.EpochTerminationCondition:
                break
            epoch += 1
        best = c.model_saver.get_best_model()
        if best is None:
            best = self.net
            best_epoch = epoch
            best_score = float(self.net.score_)
        return EarlyStoppingResult(
            termination_reason=reason or TerminationReason.Error,
            termination_details=details,
            score_vs_epoch=score_vs_epoch,
            best_model_epoch=best_epoch,
            best_model_score=best_score,
            total_epochs=epoch + 1,
            best_model=best)


EarlyStoppingGraphTrainer = EarlyStoppingTrainer
