"""Training stats collection + storage.

TPU-native equivalent of reference ``deeplearning4j-ui-model`` (SURVEY.md §2.7):
``StatsListener`` (``BaseStatsListener.java:44``, ``iterationDone`` :286-307 —
score, param/gradient/update histograms & norms, memory, timing per iteration),
the ``StatsStorage`` SPI (``deeplearning4j-core/.../api/storage/``) and the
in-memory / file / sqlite backends (``ui/storage/``). The reference's SBE
binary codecs are replaced by JSON records — the wire format matters only to
its Java frontend; the information content is preserved.
"""
from __future__ import annotations

import json
import logging
import math
import os
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..optimize.listeners import TrainingListener

log = logging.getLogger(__name__)


# ---------------------------------------------------------------- stat record
def _array_stats(arr: np.ndarray, bins: int = 20) -> Dict[str, Any]:
    a = np.asarray(arr, np.float64).ravel()
    if a.size == 0:
        return {}
    hist, edges = np.histogram(a, bins=bins)
    return {"mean": float(a.mean()), "stdev": float(a.std()),
            "min": float(a.min()), "max": float(a.max()),
            "norm2": float(np.linalg.norm(a)),
            "mean_magnitude": float(np.abs(a).mean()),
            "histogram": hist.tolist(),
            "histogram_edges": [float(edges[0]), float(edges[-1])]}


def _system_stats() -> Dict[str, Any]:
    """Per-iteration system/memory stats (reference
    ``BaseStatsListener.java:286-307``: JVM current/max memory, off-heap, GC
    count+time per collector). Here: host RSS + peak, device HBM in-use/limit
    (when the backend reports ``memory_stats``), and Python GC collection
    counts standing in for the JVM GC counters."""
    import gc
    import resource

    out: Dict[str, Any] = {}
    ru = resource.getrusage(resource.RUSAGE_SELF)
    # ru_maxrss is KiB on Linux
    out["host_peak_rss_bytes"] = int(ru.ru_maxrss) * 1024
    try:
        with open("/proc/self/statm") as fh:
            pages = int(fh.read().split()[1])
        out["host_rss_bytes"] = pages * 4096
    except OSError:
        out["host_rss_bytes"] = out["host_peak_rss_bytes"]
    try:
        import jax
        ms = jax.devices()[0].memory_stats()
        if ms:
            out["device_bytes_in_use"] = int(ms.get("bytes_in_use", 0))
            out["device_bytes_limit"] = int(ms.get("bytes_limit", 0))
            out["device_peak_bytes_in_use"] = int(
                ms.get("peak_bytes_in_use", 0))
    except Exception:
        # CPU backends may not report memory stats — non-fatal, but leave
        # a trace so a broken TPU memory_stats surface doesn't hide forever
        log.debug("device memory stats unavailable", exc_info=True)
    out["gc_collections"] = [s.get("collections", 0) for s in gc.get_stats()]
    out["gc_collected"] = [s.get("collected", 0) for s in gc.get_stats()]
    return out


class StatsReport:
    """One iteration's stats (reference ``StatsReport``/SBE payload)."""

    def __init__(self, session_id: str, worker_id: str, iteration: int,
                 timestamp: float, score: float,
                 param_stats: Dict[str, Dict], update_stats: Dict[str, Dict],
                 duration_ms: float, memory_bytes: Optional[int] = None,
                 system: Optional[Dict[str, Any]] = None,
                 activations: Optional[Dict[str, Any]] = None):
        self.session_id = session_id
        self.worker_id = worker_id
        self.iteration = iteration
        self.timestamp = timestamp
        self.score = score
        self.param_stats = param_stats
        self.update_stats = update_stats
        self.duration_ms = duration_ms
        self.memory_bytes = memory_bytes
        self.system = system
        self.activations = activations

    def to_json(self) -> str:
        return json.dumps(self.__dict__)

    @staticmethod
    def from_json(s: str) -> "StatsReport":
        d = json.loads(s)
        return StatsReport(**d)


# -------------------------------------------------------------------- storage
class StatsStorage:
    """SPI (reference ``api/storage/StatsStorage.java``)."""

    def put_update(self, report: StatsReport):
        raise NotImplementedError

    putUpdate = put_update

    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    listSessionIDs = list_session_ids

    def get_all_updates(self, session_id: str) -> List[StatsReport]:
        raise NotImplementedError

    getAllUpdates = get_all_updates

    def get_latest_update(self, session_id: str) -> Optional[StatsReport]:
        ups = self.get_all_updates(session_id)
        return ups[-1] if ups else None

    getLatestUpdate = get_latest_update

    def close(self):
        pass


class InMemoryStatsStorage(StatsStorage):
    """Reference ``ui/storage/InMemoryStatsStorage``."""

    def __init__(self):
        from ..monitor.lockwatch import make_lock
        self._updates: Dict[str, List[StatsReport]] = {}
        self._lock = make_lock("InMemoryStatsStorage._lock")

    def put_update(self, report: StatsReport):
        with self._lock:
            self._updates.setdefault(report.session_id, []).append(report)

    putUpdate = put_update

    def list_session_ids(self):
        return list(self._updates)

    listSessionIDs = list_session_ids

    def get_all_updates(self, session_id):
        return list(self._updates.get(session_id, []))

    getAllUpdates = get_all_updates


class FileStatsStorage(StatsStorage):
    """JSON-lines file storage (reference ``FileStatsStorage`` is MapDB; same
    durability contract: every update is persisted and reloadable)."""

    def __init__(self, path: str):
        from ..monitor.lockwatch import make_lock
        self.path = path
        self._lock = make_lock("FileStatsStorage._lock")
        self._fh = open(path, "a", encoding="utf-8")

    def put_update(self, report: StatsReport):
        with self._lock:
            self._fh.write(report.to_json() + "\n")
            self._fh.flush()

    putUpdate = put_update

    def _read_all(self) -> List[StatsReport]:
        with self._lock:
            self._fh.flush()
        out = []
        with open(self.path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(StatsReport.from_json(line))
        return out

    def list_session_ids(self):
        return sorted({r.session_id for r in self._read_all()})

    listSessionIDs = list_session_ids

    def get_all_updates(self, session_id):
        return [r for r in self._read_all() if r.session_id == session_id]

    getAllUpdates = get_all_updates

    def close(self):
        self._fh.close()


class SqliteStatsStorage(StatsStorage):
    """Reference ``ui/storage/sqlite/J7FileStatsStorage`` counterpart."""

    def __init__(self, path: str):
        from ..monitor.lockwatch import make_lock
        self.path = path
        self._lock = make_lock("SqliteStatsStorage._lock")
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS updates (session_id TEXT, "
            "iteration INTEGER, payload TEXT)")
        self._conn.commit()

    def put_update(self, report: StatsReport):
        with self._lock:
            self._conn.execute("INSERT INTO updates VALUES (?, ?, ?)",
                               (report.session_id, report.iteration,
                                report.to_json()))
            self._conn.commit()

    putUpdate = put_update

    def list_session_ids(self):
        cur = self._conn.execute("SELECT DISTINCT session_id FROM updates")
        return [r[0] for r in cur.fetchall()]

    listSessionIDs = list_session_ids

    def get_all_updates(self, session_id):
        cur = self._conn.execute(
            "SELECT payload FROM updates WHERE session_id=? ORDER BY iteration",
            (session_id,))
        return [StatsReport.from_json(r[0]) for r in cur.fetchall()]

    getAllUpdates = get_all_updates

    def close(self):
        self._conn.close()


# ------------------------------------------------------------------- listener
class StatsListener(TrainingListener):
    """Reference ``BaseStatsListener.java:286`` iterationDone: collect score +
    per-param statistics into a StatsStorage every ``frequency`` iterations."""

    def __init__(self, storage: StatsStorage, frequency: int = 1,
                 session_id: Optional[str] = None, worker_id: str = "worker0",
                 collect_histograms: bool = True,
                 collect_system: bool = True,
                 activation_probe=None, activation_frequency: int = 10,
                 activation_max_channels: int = 16):
        """``collect_system``: per-iteration memory/GC stats (reference
        ``BaseStatsListener.java:286-307`` system tab data).
        ``activation_probe``: optional features batch; every
        ``activation_frequency`` reports, the model runs it forward and the
        first convolutional activation map of example 0 is stored
        (downsampled to ``activation_max_channels`` channels) — the
        reference train-UI's convolutional-activations view
        (``module/train/TrainModule.java``)."""
        self.storage = storage
        self.frequency = max(1, frequency)
        self.session_id = session_id or f"session_{int(time.time() * 1e3)}"
        self.worker_id = worker_id
        self.collect_histograms = collect_histograms
        self.collect_system = collect_system
        self.activation_probe = activation_probe
        self.activation_frequency = max(1, activation_frequency)
        self.activation_max_channels = activation_max_channels
        self._last_time = None
        self._prev_params: Optional[Dict[str, np.ndarray]] = None
        self._reports = 0

    def _conv_activations(self, model):
        """First rank-4 (conv) activation of example 0 on the probe batch →
        {"layer", "grids": [[rows]...]} per channel."""
        probe = np.asarray(self.activation_probe)
        acts = model.feed_forward(probe)
        if isinstance(acts, dict):
            # CG: skip the graph inputs, keep layer/vertex activations
            inputs = set(getattr(model.conf, "network_inputs", ()))
            items = ((k, v) for k, v in acts.items() if k not in inputs)
        else:
            # MLN list starts with the input itself — skip it
            items = ((str(i), a) for i, a in enumerate(acts[1:]))
        for name, a in items:
            a = np.asarray(a)
            if a.ndim == 4:  # NHWC
                grids = [a[0, :, :, c].tolist()
                         for c in range(min(a.shape[-1],
                                            self.activation_max_channels))]
                return {"layer": name, "grids": grids}
        return None

    def iteration_done(self, model, iteration, score):
        if iteration % self.frequency != 0:
            return
        now = time.perf_counter()
        duration = 0.0 if self._last_time is None else (now - self._last_time) * 1e3
        self._last_time = now
        params = {}
        updates = {}
        table = model.param_table()
        for name, arr in table.items():
            a = np.asarray(arr)
            params[name] = _array_stats(a) if self.collect_histograms else {
                "norm2": float(np.linalg.norm(a))}
            if self._prev_params is not None and name in self._prev_params:
                delta = a - self._prev_params[name]
                updates[name] = (_array_stats(delta) if self.collect_histograms
                                 else {"norm2": float(np.linalg.norm(delta))})
        self._prev_params = {k: np.asarray(v).copy() for k, v in table.items()}
        system = _system_stats() if self.collect_system else None
        activations = None
        if (self.activation_probe is not None
                and self._reports % self.activation_frequency == 0):
            activations = self._conv_activations(model)
        self._reports += 1
        report = StatsReport(self.session_id, self.worker_id, int(iteration),
                             time.time(), float(score), params, updates,
                             duration, system=system, activations=activations)
        self.storage.put_update(report)
