"""Training UI server.

TPU-native equivalent of reference ``deeplearning4j-play``
(``PlayUIServer.java:51``, train module overview/model tabs, remote receiver
``RemoteReceiverModule``): a stdlib ``http.server`` serving
 - ``/``                     — overview page (score chart, throughput, params)
 - ``/train/sessions``       — JSON session list
 - ``/train/overview?sid=``  — JSON score/updates series for charts
 - ``/train/model?sid=``     — JSON per-parameter stats (histograms, norms)
 - ``/metrics``              — Prometheus text exposition of the process's
   :class:`~deeplearning4j_tpu.monitor.MetricsRegistry` (scrape target)
 - ``/healthz``              — JSON liveness (last-iteration age, NaN flag,
   PS connectivity; HTTP 503 when unhealthy)
 - ``/trace``                — Chrome trace-event JSON from the monitor's
   span :class:`~deeplearning4j_tpu.monitor.Tracer` (open in Perfetto)
 - ``/profile``              — step-anatomy report: per-fn jit compile
   counts/times/flops, device-memory gauges, step/ETL timing split, and
   a ``trends`` block (now vs 1m/5m once the history sampler runs;
   ``?format=text`` for the terminal rendering)
 - ``/alerts``               — alert-rule states (OK/PENDING/FIRING) from
   the :mod:`~deeplearning4j_tpu.monitor.alerts` engine, evaluated at
   request time; always HTTP 200
 - ``/history``              — the metric-history ring: meta by default,
   ``?metric=<name>[&seconds=N]`` for one series
 - ``/fleet``                — merged per-worker metrics (Prometheus text,
   ``worker`` label; ``?format=json`` for the liveness table, which
   carries a per-shard rollup — staleness + wire bytes by shard — when
   workers run the sharded paramserver client) aggregated from
   ``OP_TELEMETRY`` reports on a paramserver-server process
 - ``/fleet/trace``          — whole-fleet Chrome trace, one ``pid`` row
   per process, propagated trace IDs intact
 - ``/events``               — the crash flight recorder's structured
   event log (worker join/leave, peer failures, health transitions)
 - ``/telemetry``            — one-round-trip scrape bundle for the fleet
   collector (registry dump + trace tail + seq-cursored flight events +
   health + exemplars; ``?since_seq=N`` for only-newer events)
 - ``/incidents``            — the incident recorder's bounded table
   (one summary row per merged incident); ``/incidents/<id>`` for one
   incident's full evidence bundle (404 on unknown ids)
 - POST ``/remote``          — remote StatsReport receiver (the reference's
   remote listener posting seam)

No Play/SBE/webjars: the data API is plain JSON and the page is a single
self-contained HTML document with inline SVG charts. See
docs/OBSERVABILITY.md for the monitor endpoints.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from ..monitor import (get_fleet, get_flight_recorder, get_health,
                       get_registry, get_tracer, profile_report,
                       render_profile_text, sample_device_memory)
from .stats import StatsStorage, StatsReport, InMemoryStatsStorage

#: POST bodies larger than this are refused with 413 (a remote stats report
#: is a few KB; anything megabytes-deep is a bug or abuse, and reading it
#: would buffer it all in RAM)
MAX_POST_BYTES = 8 << 20

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j-tpu training</title>
<style>body{font-family:sans-serif;margin:2em}h1{font-size:1.3em}
.chart{border:1px solid #ccc;margin:1em 0}td,th{padding:2px 8px;text-align:right}
th{background:#eee}</style></head>
<body><h1>Training overview</h1>
<div id="meta"></div>
<svg id="score" class="chart" width="800" height="240"></svg>
<table id="params"></table>
<h1>System</h1>
<div id="sysmeta"></div>
<svg id="system" class="chart" width="800" height="160"></svg>
<h1>t-SNE</h1>
<svg id="tsne" class="chart" width="400" height="400"></svg>
<h1>Convolutional activations</h1>
<div id="actmeta"></div>
<div id="acts"></div>
<script>
function polyline(svg, xs, ys, w, h, color){
  if(ys.length<2){return;}
  const xmin=Math.min(...xs), xmax=Math.max(...xs);
  const ymin=Math.min(...ys), ymax=Math.max(...ys);
  const pts=xs.map((x,i)=>((x-xmin)/(xmax-xmin||1)*(w-20)+10)+','+
    (h-10-(ys[i]-ymin)/(ymax-ymin||1)*(h-20))).join(' ');
  svg.innerHTML+='<polyline fill="none" stroke="'+color+'" points="'+pts+'"/>';
}
async function refresh(){
  const sessions = await (await fetch('/train/sessions')).json();
  if(!sessions.length){setTimeout(refresh,2000);return;}
  const sid = sessions[sessions.length-1];
  const ov = await (await fetch('/train/overview?sid='+sid)).json();
  document.getElementById('meta').textContent =
    'session '+sid+' — '+ov.iterations.length+' iterations, last score '+
    (ov.scores.length?ov.scores[ov.scores.length-1].toFixed(5):'n/a');
  const svg = document.getElementById('score');
  svg.innerHTML='';
  polyline(svg, ov.iterations, ov.scores, 800, 240, '#07c');
  const model = await (await fetch('/train/model?sid='+sid)).json();
  function hist(st){
    if(!st.histogram||!st.histogram.length){return '';}
    const h=st.histogram, hmax=Math.max(...h)||1;
    return '<svg width="'+(h.length*4)+'" height="24">'+h.map((v,i)=>
      '<rect x="'+i*4+'" y="'+(24-22*v/hmax)+'" width="3" height="'+(22*v/hmax)+
      '" fill="#07c"/>').join('')+'</svg>';
  }
  let html='<tr><th>param</th><th>norm2</th><th>mean</th><th>stdev</th>'+
    '<th>histogram</th><th>update hist</th></tr>';
  for(const [name,st] of Object.entries(model.params||{})){
    const up=(model.updates||{})[name]||{};
    html+='<tr><td style="text-align:left">'+name+'</td><td>'+
      (st.norm2||0).toFixed(4)+'</td><td>'+(st.mean!==undefined?st.mean.toFixed(5):'')+
      '</td><td>'+(st.stdev!==undefined?st.stdev.toFixed(5):'')+'</td><td>'+
      hist(st)+'</td><td>'+hist(up)+'</td></tr>';
  }
  document.getElementById('params').innerHTML=html;
  const sys = await (await fetch('/train/system?sid='+sid)).json();
  const ssvg = document.getElementById('system');
  ssvg.innerHTML='';
  const rss = (sys.host_rss_bytes||[]).filter(v=>v!=null);
  if(rss.length){
    document.getElementById('sysmeta').textContent =
      'host RSS '+(rss[rss.length-1]/1048576).toFixed(0)+' MB';
    polyline(ssvg, sys.iterations, rss, 800, 160, '#c70');
  }
  const dev=(sys.device_bytes_in_use||[]).filter(v=>v!=null);
  if(dev.length){polyline(ssvg, sys.iterations.slice(-dev.length), dev, 800, 160, '#0a5');}
  const ts = await (await fetch('/tsne/coords')).json();
  const tsvg = document.getElementById('tsne');
  tsvg.innerHTML='';
  if(ts.coords && ts.coords.length){
    const xs=ts.coords.map(c=>c[0]), ys=ts.coords.map(c=>c[1]);
    const xmin=Math.min(...xs),xmax=Math.max(...xs);
    const ymin=Math.min(...ys),ymax=Math.max(...ys);
    tsvg.innerHTML=ts.coords.map((c,i)=>'<circle r="2" fill="#07c" cx="'+
      ((c[0]-xmin)/(xmax-xmin||1)*380+10)+'" cy="'+
      ((c[1]-ymin)/(ymax-ymin||1)*380+10)+'"/>').join('');
  }
  const act = await (await fetch('/train/activations?sid='+sid)).json();
  if(act.grids && act.grids.length){
    document.getElementById('actmeta').textContent =
      'layer '+act.layer+' @ iteration '+act.iteration;
    document.getElementById('acts').innerHTML = act.grids.map(g=>{
      const h=g.length,w=g[0].length;
      let lo=Infinity,hi=-Infinity;
      g.forEach(r=>r.forEach(v=>{lo=Math.min(lo,v);hi=Math.max(hi,v);}));
      const cells=g.map((row,y)=>row.map((v,x)=>{
        const s=Math.round((v-lo)/(hi-lo||1)*255);
        return '<rect x="'+x*4+'" y="'+y*4+'" width="4" height="4" fill="rgb('+
          s+','+s+','+s+')"/>';}).join('')).join('');
      return '<svg class="chart" width="'+w*4+'" height="'+h*4+'">'+cells+'</svg>';
    }).join(' ');
  }
  setTimeout(refresh,2000);
}
refresh();
</script></body></html>"""


class JsonRequestHandler(BaseHTTPRequestHandler):
    """Shared JSON-over-HTTP handler plumbing: quiet logging, ``_json``
    responses with correct Content-Length, extra response headers, and
    bounded POST-body reads (the ``MAX_POST_BYTES`` 413 cap — refuse
    BEFORE reading, so an abusive body never enters memory). The training
    UI handler below and the serving tier's front door
    (``serving/server.py``) both build on this, so the two servers cannot
    drift on framing or limits."""

    def log_message(self, fmt, *args):  # quiet
        pass

    def _json(self, obj, code=200, default=None, headers=None):
        payload = json.dumps(obj, default=default).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    def _post_body(self, max_bytes: int = None):
        """Read and decode the POST body, or send the matching 400/413
        error and return None — callers just bail on None."""
        limit = MAX_POST_BYTES if max_bytes is None else max_bytes
        try:
            length = int(self.headers.get("Content-Length", 0))
        except (TypeError, ValueError):
            self._json({"error": "bad Content-Length"}, 400)
            return None
        if length < 0:
            # rfile.read(-1) would block until the client closes the socket
            self._json({"error": "bad Content-Length"}, 400)
            return None
        if length > limit:
            # refuse before reading: the body never enters memory
            self._json({"error": f"body of {length} bytes exceeds the "
                        f"{limit}-byte limit"}, 413)
            return None
        return self.rfile.read(length).decode("utf-8")

    def _text(self, text: str, content_type: str, code: int = 200):
        payload = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _monitor_get(self, url, q) -> bool:
        """Serve the process-monitor endpoints every server shares —
        ``/metrics``, ``/healthz``, ``/profile``, ``/alerts``,
        ``/history``, ``/control``, ``/probes``, ``/incidents``,
        ``/incidents/<id>``, ``/trace``, ``/events``, ``/fleet``,
        ``/fleet/trace``, ``/telemetry`` — so the training UI and the
        serving front door cannot drift on routing, status-code mapping,
        or framing. Returns True when the path was handled."""
        if url.path == "/metrics":
            # Prometheus scrape of the process-global monitor registry.
            # Device-memory gauges are sampled scrape-time (pull-model
            # freshness; a no-op on backends without memory stats)
            sample_device_memory()
            self._text(get_registry().render_prometheus(),
                       "text/plain; version=0.0.4; charset=utf-8")
            return True
        if url.path == "/healthz":
            snap = get_health().snapshot()
            self._json(snap, 200 if snap["healthy"] else 503)
            return True
        if url.path == "/profile":
            # step-anatomy report (docs/OBSERVABILITY.md "Compilation &
            # memory"): per-fn jit compile/call/cost table + device-memory
            # gauges + step/ETL split + the serving block, one view
            rep = profile_report()
            if q.get("format", [""])[0] == "text":
                self._text(render_profile_text(rep),
                           "text/plain; charset=utf-8")
            else:
                self._json(rep)
            return True
        if url.path == "/alerts":
            # alert-rule states (monitor/alerts.py): evaluated at request
            # time so the snapshot is never staler than the scrape, and
            # ALWAYS HTTP 200 — an alerting endpoint that 503s while
            # alerting would blind the prober exactly when it matters
            from ..monitor.alerts import get_alert_engine
            engine = get_alert_engine()
            engine.evaluate(strict=False)
            self._json(engine.snapshot())
            return True
        if url.path == "/control":
            # control-plane state (control/plane.py): policy state
            # machines, active cooldowns, recent actuator invocations.
            # ALWAYS HTTP 200 for the /alerts reason — the loop's
            # surface must stay readable exactly while it is acting
            from ..control.plane import get_control_plane
            self._json(get_control_plane().snapshot())
            return True
        if url.path == "/probes":
            # probe-plane state (monitor/probes.py): targets, golden-set
            # versions, last outcomes, deadman ages. ALWAYS HTTP 200 —
            # the black-box plane's own surface must stay readable
            # exactly while its targets are failing
            from ..monitor.probes import get_prober
            self._json(get_prober().snapshot())
            return True
        if url.path == "/history":
            # metric-history ring (monitor/history.py): ring meta by
            # default; ?metric=<name>[&seconds=N] for one time series
            from ..monitor.history import get_history
            hist = get_history()
            metric = q.get("metric", [None])[0]
            if metric:
                seconds = q.get("seconds", [None])[0]
                try:
                    seconds = float(seconds) if seconds else None
                except ValueError:
                    self._json({"error": "seconds must be a number"}, 400)
                    return True
                self._json(hist.series(metric, seconds=seconds))
            else:
                self._json(hist.describe())
            return True
        if url.path == "/trace":
            self._json(get_tracer().export())
            return True
        if url.path == "/fleet":
            # merged per-worker registry view (OP_TELEMETRY reports and
            # collector scrapes landed in the process-global FleetState):
            # Prometheus text with a worker label, or the liveness table
            # as JSON (?format=json — includes the per-shard
            # staleness/wire-bytes block when the fleet runs the sharded
            # paramserver client)
            fleet = get_fleet()
            if q.get("format", [""])[0] == "json":
                self._json(fleet.liveness())
                return True
            self._text(fleet.render_prometheus(),
                       "text/plain; version=0.0.4; charset=utf-8")
            return True
        if url.path == "/fleet/trace":
            # whole-fleet Chrome trace: every worker's shipped spans plus
            # this process's own, one pid row each (open in Perfetto)
            self._json(get_fleet().merged_trace())
            return True
        if url.path == "/events":
            rec = get_flight_recorder()
            # default=repr: event fields may be non-serializable by the
            # recorder's contract — they degrade here exactly as in dumps
            self._json({"events": rec.events(), "dropped": rec.dropped,
                        "last_dump_path": rec.last_dump_path},
                       default=repr)
            return True
        if url.path == "/telemetry":
            # one-round-trip scrape for the fleet collector
            # (monitor/collector.py): registry dump + trace tail +
            # seq-cursored flight events + health + latched exemplars.
            # No since_seq → prime reply (last_seq only, NO events — a
            # collector joining late must not replay history as fresh
            # incidents); ?since_seq=N → events with seq > N
            from ..monitor.collector import telemetry_snapshot
            since = q.get("since_seq", [None])[0]
            if since is not None:
                try:
                    since = int(since)
                except ValueError:
                    self._json({"error": "since_seq must be an int"}, 400)
                    return True
            self._json(telemetry_snapshot(since_seq=since), default=repr)
            return True
        if url.path == "/incidents":
            # incident-plane state (monitor/incidents.py): the bounded
            # incident table — one summary row per (merged) incident.
            # ALWAYS HTTP 200 — the postmortem surface must stay
            # readable exactly while an incident is open
            from ..monitor.incidents import get_incident_recorder
            self._json(get_incident_recorder().snapshot())
            return True
        if url.path.startswith("/incidents/"):
            # one incident's full bundle (the persisted schema for
            # closed incidents, a provisional one for the open one)
            from ..monitor.incidents import get_incident_recorder
            incident_id = url.path[len("/incidents/"):]
            bundle = get_incident_recorder().bundle(incident_id)
            if bundle is None:
                self._json({"error": f"unknown incident "
                                     f"{incident_id!r}"}, 404)
                return True
            self._json(bundle, default=repr)
            return True
        return False


class _Handler(JsonRequestHandler):
    storage: StatsStorage = None  # set by server factory
    tsne_data = None              # latest uploaded t-SNE coords/labels

    def do_GET(self):
        url = urlparse(self.path)
        q = parse_qs(url.query)
        if self._monitor_get(url, q):    # /metrics /healthz /telemetry ...
            return
        if url.path in ("/", "/train", "/train/overview.html"):
            payload = _PAGE.encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
            return
        if url.path == "/train/sessions":
            self._json(self.storage.list_session_ids())
            return
        if url.path == "/train/overview":
            sid = q.get("sid", [None])[0] or self._latest_session()
            ups = self.storage.get_all_updates(sid) if sid else []
            self._json({"iterations": [u.iteration for u in ups],
                        "scores": [u.score for u in ups],
                        "durations_ms": [u.duration_ms for u in ups]})
            return
        if url.path == "/train/model":
            sid = q.get("sid", [None])[0] or self._latest_session()
            latest = self.storage.get_latest_update(sid) if sid else None
            self._json({"params": latest.param_stats if latest else {},
                        "updates": latest.update_stats if latest else {}})
            return
        if url.path == "/train/system":
            # per-iteration memory/GC series (reference train-UI system tab,
            # data from BaseStatsListener.java:286-307)
            sid = q.get("sid", [None])[0] or self._latest_session()
            ups = self.storage.get_all_updates(sid) if sid else []
            sys_ups = [u for u in ups if getattr(u, "system", None)]
            self._json({
                "iterations": [u.iteration for u in sys_ups],
                "host_rss_bytes": [u.system.get("host_rss_bytes")
                                   for u in sys_ups],
                "host_peak_rss_bytes": [u.system.get("host_peak_rss_bytes")
                                        for u in sys_ups],
                "device_bytes_in_use": [u.system.get("device_bytes_in_use")
                                        for u in sys_ups],
                "gc_collections": [u.system.get("gc_collections")
                                   for u in sys_ups],
            })
            return
        if url.path == "/train/activations":
            # latest conv-activation grid (reference TrainModule's
            # convolutional activations view)
            sid = q.get("sid", [None])[0] or self._latest_session()
            ups = self.storage.get_all_updates(sid) if sid else []
            for u in reversed(ups):
                if getattr(u, "activations", None):
                    self._json({"iteration": u.iteration,
                                **u.activations})
                    return
            self._json({"iteration": None, "layer": None, "grids": []})
            return
        if url.path == "/tsne/coords":
            self._json(type(self).tsne_data or {"coords": [], "labels": []})
            return
        self._json({"error": "not found"}, 404)

    def do_POST(self):
        path = urlparse(self.path).path
        body = self._post_body()
        if body is None:
            return
        if path == "/remote":
            try:
                self.storage.put_update(StatsReport.from_json(body))
                self._json({"status": "ok"})
            except Exception as e:  # malformed report
                self._json({"error": str(e)}, 400)
            return
        if path == "/tsne/upload":
            # t-SNE tab data (reference tsne UI module): {"coords": [[x,y]..],
            # "labels": [...]} — typically produced by clustering.tsne
            try:
                data = json.loads(body)
                type(self).tsne_data = {"coords": data.get("coords", []),
                                        "labels": data.get("labels", [])}
                self._json({"status": "ok"})
            except Exception as e:
                self._json({"error": str(e)}, 400)
            return
        self._json({"error": "not found"}, 404)

    def _latest_session(self):
        ids = self.storage.list_session_ids()
        return ids[-1] if ids else None


class UIServer:
    """Reference ``UIServer.getInstance()`` / ``attach(statsStorage)``."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000, host: str = "127.0.0.1"):
        self.port = port
        self.host = host
        self.storage: StatsStorage = InMemoryStatsStorage()
        self._httpd = None
        self._thread = None

    @classmethod
    def get_instance(cls) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer()
        return cls._instance

    getInstance = get_instance

    def attach(self, storage: StatsStorage):
        self.storage = storage
        if self._httpd is not None:
            self._httpd.RequestHandlerClass.storage = storage
        return self

    def start(self, port: Optional[int] = None,
              host: Optional[str] = None) -> int:
        """Start serving; returns the bound port (0 → ephemeral).

        ``host`` defaults to the constructor's (loopback): pass
        ``"0.0.0.0"`` to make ``/metrics`` scrapeable from another machine
        — the endpoints are unauthenticated, so only widen the bind on a
        trusted network."""
        if self._httpd is not None:
            return self.port
        if port is not None:
            self.port = port
        if host is not None:
            self.host = host
        handler = type("BoundHandler", (_Handler,), {"storage": self.storage})
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def upload_tsne(self, coords, labels=None):
        """Publish t-SNE coordinates to the UI's t-SNE tab (reference tsne
        UI module; typically fed from ``clustering.tsne.BarnesHutTsne``)."""
        import numpy as np
        data = {"coords": np.asarray(coords).tolist(),
                "labels": list(labels) if labels is not None else []}
        if self._httpd is not None:
            self._httpd.RequestHandlerClass.tsne_data = data
        else:
            _Handler.tsne_data = data
        return self

    uploadTsne = upload_tsne

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    detach = stop


class RemoteUIStatsStorageRouter:
    """Client for POSTing reports to a remote UI server (reference
    ``RemoteUIStatsStorageRouter`` + ``RemoteReceiverModule``)."""

    def __init__(self, address: str):
        self.address = address.rstrip("/")

    def put_update(self, report: StatsReport):
        import urllib.request
        req = urllib.request.Request(
            self.address + "/remote", data=report.to_json().encode("utf-8"),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read().decode("utf-8"))

    putUpdate = put_update
