"""Training UI server.

TPU-native equivalent of reference ``deeplearning4j-play``
(``PlayUIServer.java:51``, train module overview/model tabs, remote receiver
``RemoteReceiverModule``): a stdlib ``http.server`` serving
 - ``/``                     — overview page (score chart, throughput, params)
 - ``/train/sessions``       — JSON session list
 - ``/train/overview?sid=``  — JSON score/updates series for charts
 - ``/train/model?sid=``     — JSON per-parameter stats (histograms, norms)
 - POST ``/remote``          — remote StatsReport receiver (the reference's
   remote listener posting seam)

No Play/SBE/webjars: the data API is plain JSON and the page is a single
self-contained HTML document with inline SVG charts.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from .stats import StatsStorage, StatsReport, InMemoryStatsStorage

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j-tpu training</title>
<style>body{font-family:sans-serif;margin:2em}h1{font-size:1.3em}
.chart{border:1px solid #ccc;margin:1em 0}td,th{padding:2px 8px;text-align:right}
th{background:#eee}</style></head>
<body><h1>Training overview</h1>
<div id="meta"></div>
<svg id="score" class="chart" width="800" height="240"></svg>
<table id="params"></table>
<script>
async function refresh(){
  const sessions = await (await fetch('/train/sessions')).json();
  if(!sessions.length){setTimeout(refresh,2000);return;}
  const sid = sessions[sessions.length-1];
  const ov = await (await fetch('/train/overview?sid='+sid)).json();
  document.getElementById('meta').textContent =
    'session '+sid+' — '+ov.iterations.length+' iterations, last score '+
    (ov.scores.length?ov.scores[ov.scores.length-1].toFixed(5):'n/a');
  const svg = document.getElementById('score');
  svg.innerHTML='';
  if(ov.scores.length>1){
    const xs=ov.iterations, ys=ov.scores;
    const xmin=Math.min(...xs), xmax=Math.max(...xs);
    const ymin=Math.min(...ys), ymax=Math.max(...ys);
    const pts=xs.map((x,i)=>((x-xmin)/(xmax-xmin||1)*780+10)+','+
      (230-(ys[i]-ymin)/(ymax-ymin||1)*220)).join(' ');
    svg.innerHTML='<polyline fill="none" stroke="#07c" points="'+pts+'"/>';
  }
  const model = await (await fetch('/train/model?sid='+sid)).json();
  let html='<tr><th>param</th><th>norm2</th><th>mean</th><th>stdev</th></tr>';
  for(const [name,st] of Object.entries(model.params||{})){
    html+='<tr><td style="text-align:left">'+name+'</td><td>'+
      (st.norm2||0).toFixed(4)+'</td><td>'+(st.mean!==undefined?st.mean.toFixed(5):'')+
      '</td><td>'+(st.stdev!==undefined?st.stdev.toFixed(5):'')+'</td></tr>';
  }
  document.getElementById('params').innerHTML=html;
  setTimeout(refresh,2000);
}
refresh();
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    storage: StatsStorage = None  # set by server factory

    def log_message(self, fmt, *args):  # quiet
        pass

    def _json(self, obj, code=200):
        payload = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):
        url = urlparse(self.path)
        q = parse_qs(url.query)
        if url.path in ("/", "/train", "/train/overview.html"):
            payload = _PAGE.encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
            return
        if url.path == "/train/sessions":
            self._json(self.storage.list_session_ids())
            return
        if url.path == "/train/overview":
            sid = q.get("sid", [None])[0] or self._latest_session()
            ups = self.storage.get_all_updates(sid) if sid else []
            self._json({"iterations": [u.iteration for u in ups],
                        "scores": [u.score for u in ups],
                        "durations_ms": [u.duration_ms for u in ups]})
            return
        if url.path == "/train/model":
            sid = q.get("sid", [None])[0] or self._latest_session()
            latest = self.storage.get_latest_update(sid) if sid else None
            self._json({"params": latest.param_stats if latest else {},
                        "updates": latest.update_stats if latest else {}})
            return
        self._json({"error": "not found"}, 404)

    def do_POST(self):
        if urlparse(self.path).path != "/remote":
            self._json({"error": "not found"}, 404)
            return
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length).decode("utf-8")
        try:
            self.storage.put_update(StatsReport.from_json(body))
            self._json({"status": "ok"})
        except Exception as e:  # malformed report
            self._json({"error": str(e)}, 400)

    def _latest_session(self):
        ids = self.storage.list_session_ids()
        return ids[-1] if ids else None


class UIServer:
    """Reference ``UIServer.getInstance()`` / ``attach(statsStorage)``."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000):
        self.port = port
        self.storage: StatsStorage = InMemoryStatsStorage()
        self._httpd = None
        self._thread = None

    @classmethod
    def get_instance(cls) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer()
        return cls._instance

    getInstance = get_instance

    def attach(self, storage: StatsStorage):
        self.storage = storage
        if self._httpd is not None:
            self._httpd.RequestHandlerClass.storage = storage
        return self

    def start(self, port: Optional[int] = None) -> int:
        """Start serving; returns the bound port (0 → ephemeral)."""
        if self._httpd is not None:
            return self.port
        if port is not None:
            self.port = port
        handler = type("BoundHandler", (_Handler,), {"storage": self.storage})
        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    detach = stop


class RemoteUIStatsStorageRouter:
    """Client for POSTing reports to a remote UI server (reference
    ``RemoteUIStatsStorageRouter`` + ``RemoteReceiverModule``)."""

    def __init__(self, address: str):
        self.address = address.rstrip("/")

    def put_update(self, report: StatsReport):
        import urllib.request
        req = urllib.request.Request(
            self.address + "/remote", data=report.to_json().encode("utf-8"),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read().decode("utf-8"))

    putUpdate = put_update
