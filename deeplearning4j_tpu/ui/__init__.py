"""Observability/UI (reference ``deeplearning4j-ui-parent`` — SURVEY.md §2.7):
StatsListener, StatsStorage backends, training UI web server, remote router."""
from .stats import (StatsListener, StatsReport, StatsStorage,
                    InMemoryStatsStorage, FileStatsStorage, SqliteStatsStorage)
from .server import UIServer, RemoteUIStatsStorageRouter

__all__ = ["StatsListener", "StatsReport", "StatsStorage",
           "InMemoryStatsStorage", "FileStatsStorage", "SqliteStatsStorage",
           "UIServer", "RemoteUIStatsStorageRouter"]
