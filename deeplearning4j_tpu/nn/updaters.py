"""Gradient updaters (optimizers) and learning-rate schedules.

TPU-native equivalent of ND4J's ``IUpdater``/``GradientUpdater`` hierarchy that the
reference's updater machinery delegates to (reference
``deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/updater/UpdaterBlock.java:104``,
``BaseMultiLayerUpdater.java``; SURVEY.md §2.1 "Updaters").

Key design shift: the reference keeps ONE flat updater-state buffer with per-block
views updated in place over JNI. Here updater state is a pytree mirroring the param
pytree, and ``apply`` is a pure function ``(state, grads, iteration) ->
(updates, new_state)`` executed inside the jitted training step with buffer
donation — XLA gives us the in-place semantics the reference hand-engineered,
plus the whole update fuses into the step executable.

An updater returns the *update* to be subtracted from params (matching the
reference's ``GradientUpdater.applyUpdater`` then ``stepFunction.step(params,
update)`` split, ``StochasticGradientDescent.java:79``).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "IUpdater", "Sgd", "Adam", "AdaMax", "Nadam", "Nesterovs", "RmsProp",
    "AdaGrad", "AdaDelta", "NoOp", "AMSGrad",
    "ISchedule", "FixedSchedule", "ExponentialSchedule", "InverseSchedule",
    "PolySchedule", "SigmoidSchedule", "StepSchedule", "MapSchedule",
    "WarmupCosineSchedule", "updater_from_dict", "schedule_from_dict",
]

_tm = jax.tree_util.tree_map


# ---------------------------------------------------------------------------
# Learning-rate schedules (reference: org.nd4j.linalg.schedule.ISchedule; the
# 0.9.x LearningRatePolicy enum maps onto these)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ISchedule:
    def value(self, iteration, epoch=0):  # pragma: no cover - abstract
        raise NotImplementedError

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["@sched"] = type(self).__name__
        return d


@dataclasses.dataclass
class FixedSchedule(ISchedule):
    value_: float = 1e-3

    def value(self, iteration, epoch=0):
        return self.value_


@dataclasses.dataclass
class ExponentialSchedule(ISchedule):
    initial_value: float = 1e-3
    gamma: float = 0.99

    def value(self, iteration, epoch=0):
        return self.initial_value * jnp.power(self.gamma, iteration)


@dataclasses.dataclass
class InverseSchedule(ISchedule):
    initial_value: float = 1e-3
    gamma: float = 0.99
    power: float = 1.0

    def value(self, iteration, epoch=0):
        return self.initial_value / jnp.power(1.0 + self.gamma * iteration, self.power)


@dataclasses.dataclass
class PolySchedule(ISchedule):
    initial_value: float = 1e-3
    power: float = 1.0
    max_iter: int = 10000

    def value(self, iteration, epoch=0):
        frac = jnp.minimum(iteration / float(self.max_iter), 1.0)
        return self.initial_value * jnp.power(1.0 - frac, self.power)


@dataclasses.dataclass
class SigmoidSchedule(ISchedule):
    initial_value: float = 1e-3
    gamma: float = 0.99
    step_size: int = 100

    def value(self, iteration, epoch=0):
        return self.initial_value / (1.0 + jnp.exp(self.gamma * (iteration - self.step_size)))


@dataclasses.dataclass
class StepSchedule(ISchedule):
    initial_value: float = 1e-3
    decay_rate: float = 0.1
    step_size: int = 1000

    def value(self, iteration, epoch=0):
        return self.initial_value * jnp.power(self.decay_rate,
                                              jnp.floor(iteration / float(self.step_size)))


@dataclasses.dataclass
class MapSchedule(ISchedule):
    """Piecewise-constant schedule keyed by iteration (jit-compatible)."""
    values: Any = None  # dict {iteration: lr}

    def value(self, iteration, epoch=0):
        # JSON round-trips stringify int keys; normalize before lookup
        values = {int(k): float(v) for k, v in self.values.items()}
        keys = sorted(values)
        lr = jnp.asarray(values[keys[0]])
        for k in keys[1:]:
            lr = jnp.where(iteration >= k, values[k], lr)
        return lr


@dataclasses.dataclass
class WarmupCosineSchedule(ISchedule):
    """Linear warmup then cosine decay — net-new (no reference equivalent),
    standard for large-batch TPU training."""
    peak_value: float = 1e-3
    warmup_steps: int = 1000
    total_steps: int = 100000
    end_value: float = 0.0

    def value(self, iteration, epoch=0):
        warm = self.peak_value * (iteration / jnp.maximum(self.warmup_steps, 1))
        frac = jnp.clip((iteration - self.warmup_steps)
                        / jnp.maximum(self.total_steps - self.warmup_steps, 1), 0.0, 1.0)
        cos = self.end_value + 0.5 * (self.peak_value - self.end_value) * (1 + jnp.cos(jnp.pi * frac))
        return jnp.where(iteration < self.warmup_steps, warm, cos)


def schedule_from_dict(d):
    d = dict(d)
    kind = d.pop("@sched")
    cls = {c.__name__: c for c in (FixedSchedule, ExponentialSchedule, InverseSchedule,
                                   PolySchedule, SigmoidSchedule, StepSchedule,
                                   MapSchedule, WarmupCosineSchedule)}[kind]
    return cls(**d)


def _lr_at(updater, iteration):
    if updater.lr_schedule is not None:
        return updater.lr_schedule.value(iteration)
    return updater.learning_rate


# ---------------------------------------------------------------------------
# Updaters
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class IUpdater:
    """Base updater. Subclasses implement ``init_one``/``apply_one`` on a single
    array; pytree mapping is handled here."""
    learning_rate: float = 1e-3
    lr_schedule: Optional[ISchedule] = None

    # -- single-leaf ops ---------------------------------------------------
    def init_one(self, p):
        return ()

    def apply_one(self, state, g, lr, t):  # pragma: no cover - abstract
        raise NotImplementedError

    # -- pytree ops --------------------------------------------------------
    def init_state(self, params):
        return _tm(self.init_one, params)

    def apply(self, state, grads, iteration):
        lr = _lr_at(self, iteration)
        t = iteration + 1  # bias-correction step count (1-based)
        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_s = treedef.flatten_up_to(state)
        out = [self.apply_one(s, g, lr, t) for s, g in zip(flat_s, flat_g)]
        updates = treedef.unflatten([u for u, _ in out])
        new_state = treedef.unflatten([s for _, s in out])
        return updates, new_state

    # -- serde -------------------------------------------------------------
    def to_dict(self):
        d = {k: v for k, v in dataclasses.asdict(self).items() if k != "lr_schedule"}
        d["@updater"] = type(self).__name__
        if self.lr_schedule is not None:
            d["lr_schedule"] = self.lr_schedule.to_dict()
        return d


def _init_zeros(p):
    """Host-backed zeros for EAGER updater-state init: avoids one tiny XLA
    compile per distinct param shape (GoogLeNet has dozens — init-time cost
    only). Inside a trace it stays a jnp zeros with no compile of its own."""
    if isinstance(p, jax.core.Tracer):
        return jnp.zeros_like(p)
    from .weights import host_full
    return host_full(np.shape(p), 0, p.dtype)


@dataclasses.dataclass
class NoOp(IUpdater):
    def apply_one(self, state, g, lr, t):
        return jnp.zeros_like(g), state


@dataclasses.dataclass
class Sgd(IUpdater):
    def apply_one(self, state, g, lr, t):
        return lr * g, state


@dataclasses.dataclass
class Nesterovs(IUpdater):
    learning_rate: float = 0.1
    momentum: float = 0.9

    def init_one(self, p):
        return _init_zeros(p)

    def apply_one(self, v, g, lr, t):
        # Matches ND4J NesterovsUpdater: vNew = mu*v - lr*g;
        # update = -(mu*vNew - (1+mu)... ) — ND4J uses
        # update = mu*vPrev + (1+mu)*(-vNew)? Implemented as the standard
        # "lookahead" form: update = -(mu * vNew - lr * g) ... simplified:
        v_new = self.momentum * v - lr * g
        update = -(self.momentum * v_new - lr * g)  # = lr*g*(1+mu) - mu^2*v ... lookahead step
        return update, v_new


@dataclasses.dataclass
class Adam(IUpdater):
    learning_rate: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_one(self, p):
        return (_init_zeros(p), _init_zeros(p))

    def apply_one(self, state, g, lr, t):
        m, v = state
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * (g * g)
        t = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
        mhat = m / (1 - jnp.power(self.beta1, t))
        vhat = v / (1 - jnp.power(self.beta2, t))
        return lr * mhat / (jnp.sqrt(vhat) + self.epsilon), (m, v)


@dataclasses.dataclass
class AMSGrad(IUpdater):
    learning_rate: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_one(self, p):
        return (_init_zeros(p), _init_zeros(p), _init_zeros(p))

    def apply_one(self, state, g, lr, t):
        m, v, vmax = state
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * (g * g)
        vmax = jnp.maximum(vmax, v)
        t = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
        mhat = m / (1 - jnp.power(self.beta1, t))
        return lr * mhat / (jnp.sqrt(vmax) + self.epsilon), (m, v, vmax)


@dataclasses.dataclass
class AdaMax(IUpdater):
    learning_rate: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_one(self, p):
        return (_init_zeros(p), _init_zeros(p))

    def apply_one(self, state, g, lr, t):
        m, u = state
        m = self.beta1 * m + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * u, jnp.abs(g))
        t = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
        mhat = m / (1 - jnp.power(self.beta1, t))
        return lr * mhat / (u + self.epsilon), (m, u)


@dataclasses.dataclass
class Nadam(IUpdater):
    learning_rate: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_one(self, p):
        return (_init_zeros(p), _init_zeros(p))

    def apply_one(self, state, g, lr, t):
        m, v = state
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * (g * g)
        t = t.astype(jnp.float32) if hasattr(t, "astype") else float(t)
        mhat = m / (1 - jnp.power(self.beta1, t))
        vhat = v / (1 - jnp.power(self.beta2, t))
        nad = self.beta1 * mhat + (1 - self.beta1) * g / (1 - jnp.power(self.beta1, t))
        return lr * nad / (jnp.sqrt(vhat) + self.epsilon), (m, v)


@dataclasses.dataclass
class RmsProp(IUpdater):
    learning_rate: float = 1e-1
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def init_one(self, p):
        return _init_zeros(p)

    def apply_one(self, cache, g, lr, t):
        cache = self.rms_decay * cache + (1 - self.rms_decay) * (g * g)
        return lr * g / (jnp.sqrt(cache) + self.epsilon), cache


@dataclasses.dataclass
class AdaGrad(IUpdater):
    learning_rate: float = 1e-1
    epsilon: float = 1e-6

    def init_one(self, p):
        return _init_zeros(p)

    def apply_one(self, hist, g, lr, t):
        hist = hist + g * g
        return lr * g / (jnp.sqrt(hist) + self.epsilon), hist


@dataclasses.dataclass
class AdaDelta(IUpdater):
    rho: float = 0.95
    epsilon: float = 1e-6

    def init_one(self, p):
        return (_init_zeros(p), _init_zeros(p))

    def apply_one(self, state, g, lr, t):
        msg, msdx = state
        msg = self.rho * msg + (1 - self.rho) * (g * g)
        dx = jnp.sqrt(msdx + self.epsilon) / jnp.sqrt(msg + self.epsilon) * g
        msdx = self.rho * msdx + (1 - self.rho) * (dx * dx)
        return dx, (msg, msdx)


_UPDATERS = {c.__name__: c for c in (Sgd, Adam, AdaMax, Nadam, Nesterovs, RmsProp,
                                     AdaGrad, AdaDelta, NoOp, AMSGrad)}


def updater_from_dict(d):
    d = dict(d)
    kind = d.pop("@updater")
    sched = d.pop("lr_schedule", None)
    u = _UPDATERS[kind](**d)
    if sched is not None:
        u.lr_schedule = schedule_from_dict(sched)
    return u
