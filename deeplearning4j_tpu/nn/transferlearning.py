"""Transfer learning: fine-tune, freeze, and surgically edit trained networks.

TPU-native equivalent of reference ``nn/transferlearning/`` (3 files;
``TransferLearning.Builder``: ``fineTuneConfiguration`` :73,
``setFeatureExtractor`` :84, ``nOutReplace`` :98, add/remove layers;
``TransferLearningHelper`` featurization). Params of retained layers are carried
over; edited layers are re-initialized; frozen layers are wrapped in
``FrozenLayer`` (gradient stop), exactly the reference's freezing mechanism
translated to ``jax.lax.stop_gradient``.
"""
from __future__ import annotations

import copy
import dataclasses
from typing import Any, Dict, List, Optional

import jax

from .conf import GlobalConfig, MultiLayerConfiguration
from .conf.layers import FeedForwardLayer, FrozenLayer, Layer
from .multilayer import MultiLayerNetwork
from ..datasets.dataset import DataSet

_tm = jax.tree_util.tree_map


@dataclasses.dataclass
class FineTuneConfiguration:
    """Global-config overrides applied during transfer (reference
    ``FineTuneConfiguration.java``). Only non-None fields are applied."""
    seed: Optional[int] = None
    updater: Optional[Any] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: Optional[float] = None

    class Builder:
        def __init__(self):
            self._c = FineTuneConfiguration()

        def __getattr__(self, name):
            if name.startswith("_"):
                raise AttributeError(name)

            def setter(v):
                if not hasattr(self._c, name):
                    raise AttributeError(f"FineTuneConfiguration has no field "
                                         f"'{name}'")
                setattr(self._c, name, v)
                return self
            return setter

        def build(self):
            return self._c

    @staticmethod
    def builder():
        return FineTuneConfiguration.Builder()

    def apply_to(self, gc: GlobalConfig) -> GlobalConfig:
        gc = copy.deepcopy(gc)
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v is not None:
                setattr(gc, f.name, v)
        return gc


class TransferLearning:
    """Namespace mirroring the reference's ``TransferLearning.Builder``."""

    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            self._net = net
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._frozen_till = -1
            self._n_out_replace: Dict[int, tuple] = {}
            self._remove_from: Optional[int] = None
            self._added: List[Layer] = []
            self._input_type = net.conf.input_type

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        fineTuneConfiguration = fine_tune_configuration

        def set_feature_extractor(self, layer_idx: int):
            """Freeze layers [0..layer_idx] (reference ``setFeatureExtractor``)."""
            self._frozen_till = int(layer_idx)
            return self

        setFeatureExtractor = set_feature_extractor

        def n_out_replace(self, layer_idx: int, n_out: int,
                          weight_init: Optional[str] = None):
            self._n_out_replace[int(layer_idx)] = (int(n_out), weight_init)
            return self

        nOutReplace = n_out_replace

        def remove_output_layer(self):
            return self.remove_layers_from_output(1)

        removeOutputLayer = remove_output_layer

        def remove_layers_from_output(self, n: int):
            total = len(self._net.conf.layers)
            self._remove_from = total - int(n)
            return self

        removeLayersFromOutput = remove_layers_from_output

        def add_layer(self, layer: Layer):
            self._added.append(layer)
            return self

        addLayer = add_layer

        def set_input_type(self, it):
            self._input_type = it
            return self

        setInputType = set_input_type

        # --------------------------------------------------------------
        def build(self) -> MultiLayerNetwork:
            old_conf = self._net.conf
            gc = old_conf.global_conf
            if self._fine_tune is not None:
                gc = self._fine_tune.apply_to(gc)

            layers = [copy.deepcopy(l) for l in old_conf.layers]
            keep = len(layers) if self._remove_from is None else self._remove_from
            layers = layers[:keep]
            reinit = set()  # indices whose params must be re-initialized

            for idx, (n_out, w_init) in sorted(self._n_out_replace.items()):
                lc = layers[idx]
                inner = getattr(lc, "inner", None) or lc
                if not isinstance(inner, FeedForwardLayer):
                    raise ValueError(f"nOutReplace on layer {idx} "
                                     f"({type(inner).__name__}): not a "
                                     f"FeedForwardLayer")
                inner.n_out = n_out
                if w_init is not None:
                    inner.weight_init = w_init
                reinit.add(idx)
                # next layer's nIn changes → must also re-init (reference
                # nOutReplace cascades to the following layer)
                if idx + 1 < len(layers):
                    nxt = getattr(layers[idx + 1], "inner", None) or layers[idx + 1]
                    if isinstance(nxt, FeedForwardLayer):
                        nxt.n_in = n_out
                        reinit.add(idx + 1)

            n_old = len(layers)
            layers.extend(copy.deepcopy(l) for l in self._added)
            reinit.update(range(n_old, len(layers)))

            # freeze [0..frozen_till]
            if self._frozen_till >= 0:
                for i in range(min(self._frozen_till + 1, len(layers))):
                    if not isinstance(layers[i], FrozenLayer):
                        layers[i] = FrozenLayer(inner=layers[i])

            preprocs = {k: v for k, v in old_conf.input_preprocessors.items()
                        if int(k) < len(layers)}
            new_conf = MultiLayerConfiguration(
                global_conf=gc, layers=layers,
                input_preprocessors=preprocs,
                input_type=self._input_type,
                backprop=old_conf.backprop, pretrain=False,
                backprop_type=old_conf.backprop_type,
                tbptt_fwd_length=old_conf.tbptt_fwd_length,
                tbptt_back_length=old_conf.tbptt_back_length)
            # re-run shape inference for appended layers
            if self._input_type is not None:
                it = self._input_type
                for i, lc in enumerate(layers):
                    pre = new_conf.preprocessor(i)
                    if pre is None:
                        p = lc.preprocessor_for(it)
                        if p is not None:
                            new_conf.input_preprocessors[str(i)] = p
                            pre = p
                    if pre is not None:
                        it = pre.get_output_type(it)
                    lc.set_n_in(it, override=False)
                    it = lc.get_output_type(i, it)

            new_net = MultiLayerNetwork(new_conf).init()
            # carry over params of retained, unedited layers
            for i in range(len(layers)):
                if i < len(old_conf.layers) and i not in reinit:
                    old_p = self._net.params.get(str(i))
                    if old_p:
                        new_net.params[str(i)] = _tm(lambda x: x, old_p)
                    old_s = self._net.states.get(str(i))
                    if old_s:
                        new_net.states[str(i)] = _tm(lambda x: x, old_s)
            new_net.updater_state = new_net.updater.init_state(new_net.params)
            return new_net

    GraphBuilder = None  # ComputationGraph transfer: see graph_transfer below


class TransferLearningHelper:
    """Featurize once through the frozen block, then train only the unfrozen
    tail (reference ``TransferLearningHelper.java``)."""

    def __init__(self, net: MultiLayerNetwork, frozen_till: int):
        self.orig = net
        self.frozen_till = int(frozen_till)
        # build the unfrozen tail as its own network
        conf = net.conf
        tail_layers = [copy.deepcopy(l) for l in conf.layers[frozen_till + 1:]]
        preprocs = {}
        for k, v in conf.input_preprocessors.items():
            idx = int(k) - (frozen_till + 1)
            if idx >= 0:
                preprocs[str(idx)] = v
        tail_conf = MultiLayerConfiguration(
            global_conf=conf.global_conf, layers=tail_layers,
            input_preprocessors=preprocs, input_type=None,
            backprop=conf.backprop, pretrain=False,
            backprop_type=conf.backprop_type,
            tbptt_fwd_length=conf.tbptt_fwd_length,
            tbptt_back_length=conf.tbptt_back_length)
        self.tail = MultiLayerNetwork(tail_conf).init()
        for i in range(len(tail_layers)):
            src = str(i + frozen_till + 1)
            if net.params.get(src):
                self.tail.params[str(i)] = _tm(lambda x: x, net.params[src])
            if net.states.get(src):
                self.tail.states[str(i)] = _tm(lambda x: x, net.states[src])
        self.tail.updater_state = self.tail.updater.init_state(self.tail.params)

    def featurize(self, ds: DataSet) -> DataSet:
        import numpy as np
        acts = self.orig.feed_forward_to_layer(self.frozen_till, ds.features)
        return DataSet(np.asarray(acts), ds.labels,
                       features_mask=ds.features_mask, labels_mask=ds.labels_mask)

    def fit_featurized(self, ds: DataSet):
        self.tail.fit(ds)
        return self

    fitFeaturized = fit_featurized

    def output_from_featurized(self, features):
        return self.tail.output(features)

    outputFromFeaturized = output_from_featurized

    def unfrozen_mln(self) -> MultiLayerNetwork:
        return self.tail

    unfrozenMLN = unfrozen_mln
