"""Transfer learning: fine-tune, freeze, and surgically edit trained networks.

TPU-native equivalent of reference ``nn/transferlearning/`` (3 files;
``TransferLearning.Builder``: ``fineTuneConfiguration`` :73,
``setFeatureExtractor`` :84, ``nOutReplace`` :98, add/remove layers;
``TransferLearningHelper`` featurization). Params of retained layers are carried
over; edited layers are re-initialized; frozen layers are wrapped in
``FrozenLayer`` (gradient stop), exactly the reference's freezing mechanism
translated to ``jax.lax.stop_gradient``.
"""
from __future__ import annotations

import copy
import dataclasses
from typing import Any, Dict, List, Optional

import jax

from .conf import GlobalConfig, MultiLayerConfiguration
from .conf.layers import FeedForwardLayer, FrozenLayer, Layer
from .multilayer import MultiLayerNetwork
from ..datasets.dataset import DataSet

_tm = jax.tree_util.tree_map


@dataclasses.dataclass
class FineTuneConfiguration:
    """Global-config overrides applied during transfer (reference
    ``FineTuneConfiguration.java``). Only non-None fields are applied."""
    seed: Optional[int] = None
    updater: Optional[Any] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: Optional[float] = None

    class Builder:
        def __init__(self):
            self._c = FineTuneConfiguration()

        def __getattr__(self, name):
            if name.startswith("_"):
                raise AttributeError(name)

            def setter(v):
                if not hasattr(self._c, name):
                    raise AttributeError(f"FineTuneConfiguration has no field "
                                         f"'{name}'")
                setattr(self._c, name, v)
                return self
            return setter

        def build(self):
            return self._c

    @staticmethod
    def builder():
        return FineTuneConfiguration.Builder()

    def apply_to(self, gc: GlobalConfig) -> GlobalConfig:
        gc = copy.deepcopy(gc)
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v is not None:
                setattr(gc, f.name, v)
        return gc


class TransferLearning:
    """Namespace mirroring the reference's ``TransferLearning.Builder``."""

    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            self._net = net
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._frozen_till = -1
            self._n_out_replace: Dict[int, tuple] = {}
            self._remove_from: Optional[int] = None
            self._added: List[Layer] = []
            self._input_type = net.conf.input_type

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        fineTuneConfiguration = fine_tune_configuration

        def set_feature_extractor(self, layer_idx: int):
            """Freeze layers [0..layer_idx] (reference ``setFeatureExtractor``)."""
            self._frozen_till = int(layer_idx)
            return self

        setFeatureExtractor = set_feature_extractor

        def n_out_replace(self, layer_idx: int, n_out: int,
                          weight_init: Optional[str] = None):
            self._n_out_replace[int(layer_idx)] = (int(n_out), weight_init)
            return self

        nOutReplace = n_out_replace

        def remove_output_layer(self):
            return self.remove_layers_from_output(1)

        removeOutputLayer = remove_output_layer

        def remove_layers_from_output(self, n: int):
            total = len(self._net.conf.layers)
            self._remove_from = total - int(n)
            return self

        removeLayersFromOutput = remove_layers_from_output

        def add_layer(self, layer: Layer):
            self._added.append(layer)
            return self

        addLayer = add_layer

        def set_input_type(self, it):
            self._input_type = it
            return self

        setInputType = set_input_type

        # --------------------------------------------------------------
        def build(self) -> MultiLayerNetwork:
            old_conf = self._net.conf
            gc = old_conf.global_conf
            if self._fine_tune is not None:
                gc = self._fine_tune.apply_to(gc)

            layers = [copy.deepcopy(l) for l in old_conf.layers]
            keep = len(layers) if self._remove_from is None else self._remove_from
            layers = layers[:keep]
            reinit = set()  # indices whose params must be re-initialized

            for idx, (n_out, w_init) in sorted(self._n_out_replace.items()):
                lc = layers[idx]
                inner = getattr(lc, "inner", None) or lc
                if not isinstance(inner, FeedForwardLayer):
                    raise ValueError(f"nOutReplace on layer {idx} "
                                     f"({type(inner).__name__}): not a "
                                     f"FeedForwardLayer")
                inner.n_out = n_out
                if w_init is not None:
                    inner.weight_init = w_init
                reinit.add(idx)
                # next layer's nIn changes → must also re-init (reference
                # nOutReplace cascades to the following layer)
                if idx + 1 < len(layers):
                    nxt = getattr(layers[idx + 1], "inner", None) or layers[idx + 1]
                    if isinstance(nxt, FeedForwardLayer):
                        nxt.n_in = n_out
                        reinit.add(idx + 1)

            n_old = len(layers)
            layers.extend(copy.deepcopy(l) for l in self._added)
            reinit.update(range(n_old, len(layers)))

            # freeze [0..frozen_till]
            if self._frozen_till >= 0:
                for i in range(min(self._frozen_till + 1, len(layers))):
                    if not isinstance(layers[i], FrozenLayer):
                        layers[i] = FrozenLayer(inner=layers[i])

            preprocs = {k: v for k, v in old_conf.input_preprocessors.items()
                        if int(k) < len(layers)}
            new_conf = MultiLayerConfiguration(
                global_conf=gc, layers=layers,
                input_preprocessors=preprocs,
                input_type=self._input_type,
                backprop=old_conf.backprop, pretrain=False,
                backprop_type=old_conf.backprop_type,
                tbptt_fwd_length=old_conf.tbptt_fwd_length,
                tbptt_back_length=old_conf.tbptt_back_length)
            # re-run shape inference for appended layers
            if self._input_type is not None:
                it = self._input_type
                for i, lc in enumerate(layers):
                    pre = new_conf.preprocessor(i)
                    if pre is None:
                        p = lc.preprocessor_for(it)
                        if p is not None:
                            new_conf.input_preprocessors[str(i)] = p
                            pre = p
                    if pre is not None:
                        it = pre.get_output_type(it)
                    lc.set_n_in(it, override=False)
                    it = lc.get_output_type(i, it)

            new_net = MultiLayerNetwork(new_conf).init()
            # carry over params of retained, unedited layers
            for i in range(len(layers)):
                if i < len(old_conf.layers) and i not in reinit:
                    old_p = self._net.params.get(str(i))
                    if old_p:
                        new_net.params[str(i)] = _tm(lambda x: x, old_p)
                    old_s = self._net.states.get(str(i))
                    if old_s:
                        new_net.states[str(i)] = _tm(lambda x: x, old_s)
            new_net.updater_state = new_net.updater.init_state(new_net.params)
            return new_net

    class GraphBuilder:
        """ComputationGraph transfer surgery (reference
        ``TransferLearning.GraphBuilder`` in ``TransferLearning.java``):
        freeze a feature-extractor subgraph, replace layer widths, remove and
        append vertices — carrying over retained parameters."""

        def __init__(self, net):
            self._net = net
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._frozen_at: List[str] = []
            self._n_out_replace: Dict[str, tuple] = {}
            self._removed: List[str] = []
            self._added: List[tuple] = []  # (name, layer_or_vertex, inputs)
            self._outputs: Optional[List[str]] = None

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        fineTuneConfiguration = fine_tune_configuration

        def set_feature_extractor(self, *vertex_names):
            """Freeze the named vertices AND everything feeding them
            (reference semantics: the frozen boundary is inclusive)."""
            self._frozen_at = list(vertex_names)
            return self

        setFeatureExtractor = set_feature_extractor

        def n_out_replace(self, layer_name: str, n_out: int,
                          weight_init: Optional[str] = None):
            self._n_out_replace[layer_name] = (int(n_out), weight_init)
            return self

        nOutReplace = n_out_replace

        def remove_vertex_and_connections(self, name: str):
            self._removed.append(name)
            return self

        removeVertexAndConnections = remove_vertex_and_connections

        def add_layer(self, name: str, layer: Layer, *inputs):
            self._added.append((name, layer, list(inputs)))
            return self

        addLayer = add_layer

        def add_vertex(self, name: str, vertex, *inputs):
            self._added.append((name, vertex, list(inputs)))
            return self

        addVertex = add_vertex

        def set_outputs(self, *names):
            self._outputs = list(names)
            return self

        setOutputs = set_outputs

        def build(self):
            from .graph import ComputationGraph
            from .conf.graph import (ComputationGraphConfiguration,
                                     MergeVertex)

            old_conf = self._net.conf
            gc = old_conf.global_conf
            if self._fine_tune is not None:
                gc = self._fine_tune.apply_to(gc)

            vertices = {k: copy.deepcopy(v)
                        for k, v in old_conf.vertices.items()}
            vertex_inputs = {k: list(v)
                             for k, v in old_conf.vertex_inputs.items()}
            outputs = list(self._outputs if self._outputs is not None
                           else old_conf.network_outputs)

            for name in self._removed:
                vertices.pop(name, None)
                vertex_inputs.pop(name, None)
                if name in outputs:
                    outputs.remove(name)

            reinit = set()
            for name, layer, inputs in self._added:
                ins = list(inputs)
                if len(ins) > 1 and isinstance(layer, Layer):
                    merge = f"{name}-merge"
                    vertices[merge] = MergeVertex()
                    vertex_inputs[merge] = ins
                    ins = [merge]
                vertices[name] = copy.deepcopy(layer)
                vertex_inputs[name] = ins
                reinit.add(name)

            consumers: Dict[str, List[str]] = {}
            for v, ins in vertex_inputs.items():
                for i in ins:
                    consumers.setdefault(i, []).append(v)

            for name, (n_out, w_init) in self._n_out_replace.items():
                lc = vertices.get(name)
                inner = getattr(lc, "inner", None) or lc
                if not isinstance(inner, FeedForwardLayer):
                    raise ValueError(f"nOutReplace on '{name}' "
                                     f"({type(inner).__name__}): not a "
                                     f"FeedForwardLayer")
                inner.n_out = n_out
                if w_init is not None:
                    inner.weight_init = w_init
                reinit.add(name)
                # cascade: direct consumers (and through merge vertices) get
                # their nIn re-derived by infer_shapes
                stack = list(consumers.get(name, []))
                while stack:
                    c = stack.pop()
                    cv = vertices.get(c)
                    ci = getattr(cv, "inner", None) or cv
                    if isinstance(ci, FeedForwardLayer):
                        ci.n_in = None  # re-filled by infer_shapes
                        reinit.add(c)
                    elif not isinstance(cv, Layer):
                        stack.extend(consumers.get(c, []))  # e.g. MergeVertex

            # freeze the named boundary + its ancestor closure
            if self._frozen_at:
                frozen = set()
                stack = list(self._frozen_at)
                while stack:
                    n = stack.pop()
                    if n in frozen or n not in vertices:
                        continue
                    frozen.add(n)
                    stack.extend(i for i in vertex_inputs.get(n, [])
                                 if i in vertices)
                for n in frozen:
                    if isinstance(vertices[n], Layer) and not isinstance(
                            vertices[n], FrozenLayer):
                        vertices[n] = FrozenLayer(inner=vertices[n])

            new_conf = ComputationGraphConfiguration(
                global_conf=gc,
                network_inputs=list(old_conf.network_inputs),
                network_outputs=outputs,
                vertices=vertices,
                vertex_inputs=vertex_inputs,
                input_preprocessors={
                    k: v for k, v in old_conf.input_preprocessors.items()
                    if k in vertices},
                input_types=old_conf.input_types,
                backprop_type=old_conf.backprop_type,
                tbptt_fwd_length=old_conf.tbptt_fwd_length,
                tbptt_back_length=old_conf.tbptt_back_length)
            new_conf.infer_shapes()

            new_net = ComputationGraph(new_conf).init()
            for name in vertices:
                if name in old_conf.vertices and name not in reinit:
                    if self._net.params.get(name):
                        new_net.params[name] = _tm(lambda x: x,
                                                   self._net.params[name])
                    if self._net.states.get(name):
                        new_net.states[name] = _tm(lambda x: x,
                                                   self._net.states[name])
            new_net.updater_state = new_net.updater.init_state(new_net.params)
            return new_net


class GraphTransferLearningHelper:
    """ComputationGraph variant of the featurization helper (reference
    ``TransferLearningHelper(ComputationGraph, String... frozenOutputAt)``):
    the frozen subgraph is everything feeding the named boundary vertices;
    ``featurize`` runs it once, and the unfrozen tail trains as its own graph
    whose network inputs are the boundary activations."""

    def __init__(self, net, *frozen_output_at: str):
        from .graph import ComputationGraph
        from .conf.graph import ComputationGraphConfiguration
        if not frozen_output_at:
            raise ValueError("Name at least one frozen boundary vertex")
        self.orig = net
        conf = net.conf
        frozen = set()
        stack = list(frozen_output_at)
        while stack:
            n = stack.pop()
            if n in frozen or n not in conf.vertices:
                continue
            frozen.add(n)
            stack.extend(i for i in conf.vertex_inputs.get(n, [])
                         if i in conf.vertices)
        self.frozen = frozen
        for out in conf.network_outputs:
            if out in frozen:
                raise ValueError(f"Output '{out}' is inside the frozen "
                                 f"subgraph")

        tail_vertices = {n: copy.deepcopy(v)
                         for n, v in conf.vertices.items() if n not in frozen}
        tail_inputs: List[str] = []
        tail_vertex_inputs: Dict[str, List[str]] = {}
        for n in tail_vertices:
            ins = []
            for i in conf.vertex_inputs[n]:
                if i in frozen or i in conf.network_inputs:
                    if i not in tail_inputs:
                        tail_inputs.append(i)
                ins.append(i)
            tail_vertex_inputs[n] = ins
        self.boundary = tail_inputs  # featurize() emits these, in order

        tail_conf = ComputationGraphConfiguration(
            global_conf=conf.global_conf,
            network_inputs=tail_inputs,
            network_outputs=list(conf.network_outputs),
            vertices=tail_vertices,
            vertex_inputs=tail_vertex_inputs,
            input_preprocessors={k: v
                                 for k, v in conf.input_preprocessors.items()
                                 if k in tail_vertices},
            input_types=None,
            backprop_type=conf.backprop_type,
            tbptt_fwd_length=conf.tbptt_fwd_length,
            tbptt_back_length=conf.tbptt_back_length)
        self.tail = ComputationGraph(tail_conf).init()
        for n in tail_vertices:
            if net.params.get(n):
                self.tail.params[n] = _tm(lambda x: x, net.params[n])
            if net.states.get(n):
                self.tail.states[n] = _tm(lambda x: x, net.states[n])
        self.tail.updater_state = self.tail.updater.init_state(self.tail.params)

    def featurize(self, ds):
        """Run the frozen subgraph once; returns a MultiDataSet whose features
        are the boundary activations in tail-input order."""
        import numpy as np
        from ..datasets.dataset import MultiDataSet
        mds = self.orig._as_multi(ds)
        acts = self.orig.feed_forward(*mds.features, train=False)
        feats = []
        for name in self.boundary:
            if name in self.orig.conf.network_inputs:
                idx = self.orig.conf.network_inputs.index(name)
                feats.append(np.asarray(mds.features[idx]))
            else:
                feats.append(np.asarray(acts[name]))
        return MultiDataSet(feats, list(mds.labels),
                            mds.features_masks, mds.labels_masks)

    def fit_featurized(self, mds):
        self.tail.fit(mds)
        return self

    fitFeaturized = fit_featurized

    def output_from_featurized(self, *features):
        return self.tail.output(*features)

    outputFromFeaturized = output_from_featurized

    def unfrozen_graph(self):
        return self.tail

    unfrozenGraph = unfrozen_graph


class TransferLearningHelper:
    """Featurize once through the frozen block, then train only the unfrozen
    tail (reference ``TransferLearningHelper.java``). For a
    ComputationGraph, pass boundary vertex names — dispatches to
    :class:`GraphTransferLearningHelper`."""

    def __new__(cls, net, frozen_till, *more):
        if not isinstance(net, MultiLayerNetwork):
            return GraphTransferLearningHelper(net, frozen_till, *more)
        return super().__new__(cls)

    def __init__(self, net: MultiLayerNetwork, frozen_till: int):
        self.orig = net
        self.frozen_till = int(frozen_till)
        # build the unfrozen tail as its own network
        conf = net.conf
        tail_layers = [copy.deepcopy(l) for l in conf.layers[frozen_till + 1:]]
        preprocs = {}
        for k, v in conf.input_preprocessors.items():
            idx = int(k) - (frozen_till + 1)
            if idx >= 0:
                preprocs[str(idx)] = v
        tail_conf = MultiLayerConfiguration(
            global_conf=conf.global_conf, layers=tail_layers,
            input_preprocessors=preprocs, input_type=None,
            backprop=conf.backprop, pretrain=False,
            backprop_type=conf.backprop_type,
            tbptt_fwd_length=conf.tbptt_fwd_length,
            tbptt_back_length=conf.tbptt_back_length)
        self.tail = MultiLayerNetwork(tail_conf).init()
        for i in range(len(tail_layers)):
            src = str(i + frozen_till + 1)
            if net.params.get(src):
                self.tail.params[str(i)] = _tm(lambda x: x, net.params[src])
            if net.states.get(src):
                self.tail.states[str(i)] = _tm(lambda x: x, net.states[src])
        self.tail.updater_state = self.tail.updater.init_state(self.tail.params)

    def featurize(self, ds: DataSet) -> DataSet:
        import numpy as np
        acts = self.orig.feed_forward_to_layer(self.frozen_till, ds.features)
        return DataSet(np.asarray(acts), ds.labels,
                       features_mask=ds.features_mask, labels_mask=ds.labels_mask)

    def fit_featurized(self, ds: DataSet):
        self.tail.fit(ds)
        return self

    fitFeaturized = fit_featurized

    def output_from_featurized(self, features):
        return self.tail.output(features)

    outputFromFeaturized = output_from_featurized

    def unfrozen_mln(self) -> MultiLayerNetwork:
        return self.tail

    unfrozenMLN = unfrozen_mln
