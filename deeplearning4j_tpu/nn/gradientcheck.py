"""Numerical gradient checking — the test backbone (SURVEY.md §4 item 1).

TPU-native equivalent of reference ``gradientcheck/GradientCheckUtil.java``
(:112 MLN entry, :268 CG variant): central-difference
``(f(x+eps) - f(x-eps)) / 2eps`` per parameter element vs the analytic gradient.

The reference hard-requires double precision (:122-127); TPU f64 is impractical,
so the rule maps to: run checks on the CPU backend under x64 (conftest pins
JAX_PLATFORMS=cpu; wrap network construction AND the check in
:func:`double_precision`, and build the net with ``dtype="float64"``,
``compute_dtype="float64"``). The reference's "SGD lr=1.0" requirement (:135-142)
does not apply — we differentiate the loss directly rather than inferring the
gradient from a parameter step.
"""
from __future__ import annotations

import contextlib
import logging
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..compat import enable_x64 as _enable_x64
from ..monitor.jitwatch import monitored_jit

log = logging.getLogger(__name__)


@contextlib.contextmanager
def double_precision():
    """Enable f64 for network construction + checking (reference double rule)."""
    with _enable_x64(True):
        yield


def _loss_at(net, params, ds):
    """Full training loss (incl. regularization) at ``params`` for either
    container type; train=True but rng=None so dropout/noise are inactive —
    gradient checks require deterministic nets, as in the reference."""
    from .multilayer import MultiLayerNetwork
    if isinstance(net, MultiLayerNetwork):
        f = net._adapt_input(jnp.asarray(ds.features))
        l = jnp.asarray(ds.labels)
        fm = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
        lm = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
        loss, _ = net._loss_fn(params, net.states, f, l, fm, lm, True, None)
        return loss
    mds = net._as_multi(ds)
    inputs = net._adapt_inputs([jnp.asarray(x) for x in mds.features])
    labels = [jnp.asarray(x) for x in mds.labels]
    fms = (None if mds.features_masks is None
           else [None if m is None else jnp.asarray(m) for m in mds.features_masks])
    lms = (None if mds.labels_masks is None
           else [None if m is None else jnp.asarray(m) for m in mds.labels_masks])
    loss, _ = net._loss_fn(params, net.states, inputs, labels, fms, lms, True, None)
    return loss


def check_function_gradients(loss_fn, params, epsilon: float = 1e-6,
                             max_rel_error: float = 1e-3,
                             min_abs_error: float = 1e-8,
                             max_per_param: Optional[int] = None,
                             seed: int = 12345,
                             expect_zero: Optional[set] = None) -> bool:
    """Central-difference check of an arbitrary scalar ``loss_fn(params)``
    against its AD gradient — used for pretrain losses (VAE/AutoEncoder,
    reference ``VaeGradientCheckTests``) and any custom objective.

    ``expect_zero``: leaf-path substrings whose analytic gradient must be
    exactly zero (frozen layers) — those leaves skip the numeric comparison
    and instead assert the zero."""
    loss_fn = monitored_jit(loss_fn, name="gradientcheck/loss")
    analytic = jax.grad(loss_fn)(params)
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    analytic_map = {_key_str(kp): np.asarray(v) for kp, v in
                    jax.tree_util.tree_flatten_with_path(analytic)[0]}
    rng = np.random.default_rng(seed)
    failed = 0
    for keypath, leaf in leaves:
        name = _key_str(keypath)
        grad = analytic_map[name]
        if expect_zero and any(z in name for z in expect_zero):
            if float(np.abs(grad).max(initial=0.0)) != 0.0:
                log.warning("Expected zero gradient for %s, got max %g", name,
                            np.abs(grad).max())
                failed += 1
            continue
        base = np.asarray(leaf, dtype=np.float64)
        flat_idx = np.arange(base.size)
        if max_per_param is not None and base.size > max_per_param:
            flat_idx = rng.choice(base.size, size=max_per_param, replace=False)
        for i in flat_idx:
            plus = base.copy().ravel()
            plus[i] += epsilon
            minus = base.copy().ravel()
            minus[i] -= epsilon
            p_plus = _with_leaf(params, keypath, plus.reshape(base.shape))
            p_minus = _with_leaf(params, keypath, minus.reshape(base.shape))
            num = (float(loss_fn(p_plus)) - float(loss_fn(p_minus))) / (2 * epsilon)
            ana = float(grad.ravel()[i])
            denom = max(abs(num), abs(ana))
            rel = 0.0 if denom == 0 else abs(num - ana) / denom
            if not (rel <= max_rel_error or (abs(num) < min_abs_error
                                             and abs(ana) < min_abs_error)):
                log.warning("Gradient check FAILED %s[%d]: numeric=%.8e "
                            "analytic=%.8e relError=%.4e", name, i, num, ana,
                            rel)
                failed += 1
    return failed == 0


class GradientCheckUtil:
    @staticmethod
    def check_gradients(net, ds, epsilon: float = 1e-6,
                        max_rel_error: float = 1e-3,
                        min_abs_error: float = 1e-8,
                        print_results: bool = False,
                        exit_on_first_error: bool = False,
                        max_per_param: Optional[int] = None,
                        seed: int = 12345,
                        exclude: Optional[set] = None) -> bool:
        """Return True when every checked element's analytic gradient matches the
        central difference within ``max_rel_error`` (elements where both are
        below ``min_abs_error`` pass unconditionally, reference semantics).
        ``max_per_param`` subsamples elements per parameter tensor for large nets.
        """
        leaves = jax.tree_util.tree_flatten_with_path(net.params)[0]
        dtypes = {np.asarray(v).dtype for _, v in leaves}
        if any(d != np.float64 for d in dtypes):
            raise ValueError(
                f"Gradient checks require float64 params (got {dtypes}); build "
                f"the net with dtype='float64', compute_dtype='float64' inside "
                f"gradientcheck.double_precision() (reference "
                f"GradientCheckUtil.java:122-127 double-precision rule)")

        loss_fn = monitored_jit(lambda p: _loss_at(net, p, ds),
                                name="gradientcheck/loss_at")
        analytic = jax.grad(loss_fn)(net.params)
        analytic_leaves = {}
        for keypath, leaf in jax.tree_util.tree_flatten_with_path(analytic)[0]:
            analytic_leaves[_key_str(keypath)] = np.asarray(leaf)

        rng = np.random.default_rng(seed)
        total_checked = 0
        total_failed = 0
        max_err_seen = 0.0
        for keypath, leaf in leaves:
            name = _key_str(keypath)
            if exclude and any(x in name for x in exclude):
                continue  # e.g. frozen layers (AD-zero but numerically active)
            base = np.asarray(leaf, dtype=np.float64)
            grad = analytic_leaves[name]
            flat_idx = np.arange(base.size)
            if max_per_param is not None and base.size > max_per_param:
                flat_idx = rng.choice(base.size, size=max_per_param, replace=False)
            for i in flat_idx:
                plus = base.copy().ravel()
                plus[i] += epsilon
                minus = base.copy().ravel()
                minus[i] -= epsilon
                p_plus = _with_leaf(net.params, keypath, plus.reshape(base.shape))
                p_minus = _with_leaf(net.params, keypath, minus.reshape(base.shape))
                num = (float(loss_fn(p_plus)) - float(loss_fn(p_minus))) / (2 * epsilon)
                ana = float(grad.ravel()[i])
                denom = max(abs(num), abs(ana))
                rel = 0.0 if denom == 0 else abs(num - ana) / denom
                ok = rel <= max_rel_error or (abs(num) < min_abs_error
                                              and abs(ana) < min_abs_error)
                total_checked += 1
                max_err_seen = max(max_err_seen, rel)
                if not ok:
                    total_failed += 1
                    msg = (f"Gradient check FAILED {name}[{i}]: numeric={num:.8e} "
                           f"analytic={ana:.8e} relError={rel:.4e}")
                    if print_results:
                        log.warning(msg)
                    if exit_on_first_error:
                        raise AssertionError(msg)
        if print_results:
            log.info("Gradient check: %d/%d passed (max relError %.3e)",
                     total_checked - total_failed, total_checked, max_err_seen)
        return total_failed == 0

    checkGradients = check_gradients


def _key_str(keypath):
    parts = []
    for k in keypath:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def _with_leaf(tree, keypath, value):
    """Copy of ``tree`` with the leaf at ``keypath`` replaced by ``value``."""
    target = _key_str(keypath)

    def repl(kp, leaf):
        return jnp.asarray(value) if _key_str(kp) == target else leaf

    return jax.tree_util.tree_map_with_path(repl, tree)


check_gradients = GradientCheckUtil.check_gradients
