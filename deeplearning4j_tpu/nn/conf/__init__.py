"""Configuration DSL: fluent builder → serializable network configurations.

TPU-native equivalent of reference ``nn/conf/NeuralNetConfiguration.java`` (Builder
:604, ListBuilder :215-324), ``MultiLayerConfiguration.java`` and
``ComputationGraphConfiguration.java`` (SURVEY.md §2.1 "Config DSL").

The reference attaches a full ``NeuralNetConfiguration`` (global + layer fields) to
every layer; here global training settings live once in :class:`GlobalConfig` and
per-layer configs override selectively — resolved at network init. JSON round-trip
via :mod:`.serde` replaces Jackson.

TPU-specific additions with no reference counterpart: ``dtype``/``compute_dtype``
(bfloat16 MXU policy), and mesh/sharding hints consumed by
``deeplearning4j_tpu.parallel``. The reference's ``WorkspaceMode``/``CacheMode``
(manual memory reuse, SURVEY.md §2.8 item 3) are accepted for API parity but map
to XLA buffer donation, which the jitted step does unconditionally.
"""
from __future__ import annotations

import copy
import dataclasses
from typing import Any, Dict, List, Optional

from . import serde
from .serde import register, to_json, from_json
from .inputs import InputType, InputTypeConvolutional, InputTypeConvolutionalFlat
from .layers import Layer, BaseLayer, FeedForwardLayer
from .preprocessors import InputPreProcessor
from .reconstruction import (ReconstructionDistribution,
                             GaussianReconstructionDistribution,
                             BernoulliReconstructionDistribution,
                             ExponentialReconstructionDistribution,
                             CompositeReconstructionDistribution,
                             LossFunctionWrapper)
from ..updaters import (IUpdater, Sgd, Adam, AdaMax, Nadam, Nesterovs, RmsProp,
                        AdaGrad, AdaDelta, NoOp, AMSGrad, FixedSchedule,
                        ExponentialSchedule, InverseSchedule, PolySchedule,
                        SigmoidSchedule, StepSchedule, MapSchedule,
                        WarmupCosineSchedule)
from ..weights import (WeightInit, NormalDistribution, GaussianDistribution,
                       UniformDistribution, ConstantDistribution,
                       BinomialDistribution)

# Register non-layer config dataclasses for serde round-trips.
for _cls in (Sgd, Adam, AdaMax, Nadam, Nesterovs, RmsProp, AdaGrad, AdaDelta, NoOp,
             AMSGrad, FixedSchedule, ExponentialSchedule, InverseSchedule,
             PolySchedule, SigmoidSchedule, StepSchedule, MapSchedule,
             WarmupCosineSchedule, NormalDistribution, GaussianDistribution,
             UniformDistribution, ConstantDistribution, BinomialDistribution):
    register(_cls)


class OptimizationAlgorithm:
    """Reference ``nn/api/OptimizationAlgorithm.java``."""
    STOCHASTIC_GRADIENT_DESCENT = "sgd"
    LINE_GRADIENT_DESCENT = "line_gd"
    CONJUGATE_GRADIENT = "cg"
    LBFGS = "lbfgs"


class GradientNormalization:
    """Reference ``nn/conf/GradientNormalization.java``."""
    None_ = "none"
    RenormalizeL2PerLayer = "renormalize_l2_per_layer"
    RenormalizeL2PerParamType = "renormalize_l2_per_param_type"
    ClipElementWiseAbsoluteValue = "clip_elementwise_absolute_value"
    ClipL2PerLayer = "clip_l2_per_layer"
    ClipL2PerParamType = "clip_l2_per_param_type"


class BackpropType:
    Standard = "standard"
    TruncatedBPTT = "tbptt"


class WorkspaceMode:
    """Accepted for parity (reference ``nn/conf/WorkspaceMode.java``); the jitted
    step always uses XLA buffer donation, so these are hints only."""
    NONE = "none"
    SINGLE = "single"
    SEPARATE = "separate"
    ENABLED = "enabled"


class CacheMode:
    NONE = "none"
    DEVICE = "device"
    HOST = "host"


@register
@dataclasses.dataclass
class GlobalConfig:
    """Defaults applied to every layer unless overridden per-layer."""
    seed: int = 12345
    updater: Any = None                     # IUpdater; default Sgd(1e-1) at init
    weight_init: str = WeightInit.XAVIER
    dist: Any = None
    activation: str = "sigmoid"
    bias_init: float = 0.0
    l1: float = 0.0
    l2: float = 0.0
    l1_bias: float = 0.0
    l2_bias: float = 0.0
    dropout: Optional[float] = None          # retain prob, reference semantics
    optimization_algo: str = OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT
    minimize: bool = True
    max_num_line_search_iterations: int = 5
    gradient_normalization: str = GradientNormalization.None_
    gradient_normalization_threshold: float = 1.0
    mini_batch: bool = True
    # TPU-native dtype policy: params kept in `dtype` (f32 master copies),
    # matmul/conv compute and inter-layer activations in `compute_dtype`
    # (bfloat16 targets the MXU; reductions/statistics accumulate in f32).
    dtype: str = "float32"
    compute_dtype: str = "float32"
    # Rematerialization policy for the jitted train step: "on" applies
    # jax.checkpoint with a named-saveable policy (store conv/gemm/pool and
    # junction-vertex outputs + BN statistics, recompute elementwise layers
    # in the backward pass) — the TPU equivalent of the reference's
    # workspace/CacheMode memory management. "auto" enables it only for
    # convolutional non-recurrent nets. Default "off": measured on
    # ResNet50/v5e, XLA's own fusion already avoids materializing elementwise
    # chains, and forced remat *adds* HBM traffic (see PERF.md); turn it on
    # when activation memory, not bandwidth, is the binding constraint
    # (very large batch/images).
    remat: str = "off"
    # Reference 0.9.x ``Builder.iterations(n)``: n optimizer iterations per
    # minibatch. TPU-native realization: the n steps compile into ONE XLA
    # program (lax.scan over the step core), so small-model training pays
    # the host→device dispatch latency once per n steps instead of per step.
    iterations: int = 1
    # parity-only knobs
    training_workspace_mode: str = WorkspaceMode.ENABLED
    inference_workspace_mode: str = WorkspaceMode.ENABLED
    cache_mode: str = CacheMode.NONE


@register
@dataclasses.dataclass
class MultiLayerConfiguration:
    """Reference ``nn/conf/MultiLayerConfiguration.java``."""
    global_conf: GlobalConfig = None
    layers: List[Any] = dataclasses.field(default_factory=list)
    input_preprocessors: Dict[str, Any] = dataclasses.field(default_factory=dict)
    input_type: Any = None
    backprop: bool = True
    pretrain: bool = False
    backprop_type: str = BackpropType.Standard
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20

    # ------------------------------------------------------------------
    def preprocessor(self, idx) -> Optional[InputPreProcessor]:
        return self.input_preprocessors.get(str(idx))

    def to_json(self) -> str:
        return to_json(self)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        obj = from_json(s)
        if not isinstance(obj, MultiLayerConfiguration):
            raise ValueError("JSON does not describe a MultiLayerConfiguration")
        return obj

    def clone(self):
        return copy.deepcopy(self)


class ListBuilder:
    """Reference ``NeuralNetConfiguration$ListBuilder`` (:215-324): collects layers,
    then ``setInputType`` runs shape inference (nIn filling + preprocessor
    insertion) and ``build`` emits a :class:`MultiLayerConfiguration`."""

    def __init__(self, global_conf: GlobalConfig):
        self._global = global_conf
        self._layers: List[Layer] = []
        self._preprocessors: Dict[int, InputPreProcessor] = {}
        self._input_type = None
        self._backprop = True
        self._pretrain = False
        self._backprop_type = BackpropType.Standard
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def layer(self, idx_or_layer, layer=None) -> "ListBuilder":
        if layer is None:
            self._layers.append(idx_or_layer)
        else:
            idx = int(idx_or_layer)
            while len(self._layers) <= idx:
                self._layers.append(None)
            self._layers[idx] = layer
        return self

    def input_preprocessor(self, idx, preproc) -> "ListBuilder":
        self._preprocessors[int(idx)] = preproc
        return self

    inputPreProcessor = input_preprocessor

    def set_input_type(self, input_type) -> "ListBuilder":
        self._input_type = input_type
        return self

    setInputType = set_input_type

    def backprop(self, flag: bool) -> "ListBuilder":
        self._backprop = bool(flag)
        return self

    def pretrain(self, flag: bool) -> "ListBuilder":
        self._pretrain = bool(flag)
        return self

    def backprop_type(self, t) -> "ListBuilder":
        self._backprop_type = t
        return self

    backpropType = backprop_type

    def t_bptt_forward_length(self, n) -> "ListBuilder":
        self._tbptt_fwd = int(n)
        return self

    tBPTTForwardLength = t_bptt_forward_length

    def t_bptt_backward_length(self, n) -> "ListBuilder":
        self._tbptt_back = int(n)
        return self

    tBPTTBackwardLength = t_bptt_backward_length

    # ------------------------------------------------------------------
    def build(self) -> MultiLayerConfiguration:
        layers = [l for l in self._layers]
        if any(l is None for l in layers):
            raise ValueError("Gaps in layer list (indexed .layer(i, ...) left holes)")
        preprocs = dict(self._preprocessors)
        if self._input_type is not None:
            # Shape inference pass, mirroring the reference's
            # MultiLayerConfiguration.Builder#build setInputType handling.
            it = self._input_type
            if isinstance(it, InputTypeConvolutionalFlat):
                # reference inserts FF->CNN preprocessor at layer 0 when needed
                pass
            for i, layer in enumerate(layers):
                if i not in preprocs:
                    p = layer.preprocessor_for(it)
                    if p is not None:
                        preprocs[i] = p
                if i in preprocs:
                    it = preprocs[i].get_output_type(it)
                layer.set_n_in(it, override=False)
                it = layer.get_output_type(i, it)
        return MultiLayerConfiguration(
            global_conf=self._global,
            layers=layers,
            input_preprocessors={str(k): v for k, v in preprocs.items()},
            input_type=self._input_type,
            backprop=self._backprop,
            pretrain=self._pretrain,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
        )


class Builder:
    """Fluent global-config builder (reference ``NeuralNetConfiguration.Builder``,
    ``NeuralNetConfiguration.java:604``). Both snake_case and reference-style
    camelCase spellings are provided."""

    def __init__(self):
        self._conf = GlobalConfig()

    # each setter returns self ------------------------------------------------
    def seed(self, s):
        self._conf.seed = int(s)
        return self

    def iterations(self, n):
        """n optimizer iterations per minibatch (reference 0.9.x
        ``Builder.iterations``); compiled as one scanned XLA program."""
        self._conf.iterations = int(n)
        return self

    def updater(self, u):
        self._conf.updater = u
        return self

    def weight_init(self, w):
        self._conf.weight_init = w
        return self

    weightInit = weight_init

    def dist(self, d):
        self._conf.dist = d
        if self._conf.weight_init != WeightInit.DISTRIBUTION:
            self._conf.weight_init = WeightInit.DISTRIBUTION
        return self

    def activation(self, a):
        self._conf.activation = a
        return self

    def bias_init(self, b):
        self._conf.bias_init = float(b)
        return self

    biasInit = bias_init

    def l1(self, v):
        self._conf.l1 = float(v)
        return self

    def l2(self, v):
        self._conf.l2 = float(v)
        return self

    def l1_bias(self, v):
        self._conf.l1_bias = float(v)
        return self

    def l2_bias(self, v):
        self._conf.l2_bias = float(v)
        return self

    def drop_out(self, p):
        self._conf.dropout = float(p)
        return self

    dropOut = drop_out
    dropout = drop_out

    def optimization_algo(self, o):
        self._conf.optimization_algo = o
        return self

    optimizationAlgo = optimization_algo

    def minimize(self, flag=True):
        self._conf.minimize = bool(flag)
        return self

    def max_num_line_search_iterations(self, n):
        self._conf.max_num_line_search_iterations = int(n)
        return self

    maxNumLineSearchIterations = max_num_line_search_iterations

    def gradient_normalization(self, g):
        self._conf.gradient_normalization = g
        return self

    gradientNormalization = gradient_normalization

    def gradient_normalization_threshold(self, t):
        self._conf.gradient_normalization_threshold = float(t)
        return self

    gradientNormalizationThreshold = gradient_normalization_threshold

    def mini_batch(self, flag):
        self._conf.mini_batch = bool(flag)
        return self

    miniBatch = mini_batch

    def dtype(self, d):
        self._conf.dtype = str(d)
        return self

    def compute_dtype(self, d):
        self._conf.compute_dtype = str(d)
        return self

    def remat(self, mode):
        """Activation rematerialization policy: "auto" | "on" | "off"."""
        self._conf.remat = str(mode)
        return self

    def training_workspace_mode(self, m):
        self._conf.training_workspace_mode = m
        return self

    trainingWorkspaceMode = training_workspace_mode

    def inference_workspace_mode(self, m):
        self._conf.inference_workspace_mode = m
        return self

    inferenceWorkspaceMode = inference_workspace_mode

    def cache_mode(self, m):
        self._conf.cache_mode = m
        return self

    cacheMode = cache_mode

    # terminals ---------------------------------------------------------------
    def list(self) -> ListBuilder:
        if self._conf.updater is None:
            self._conf.updater = Sgd(learning_rate=1e-1)
        return ListBuilder(copy.deepcopy(self._conf))

    def graph_builder(self):
        if self._conf.updater is None:
            self._conf.updater = Sgd(learning_rate=1e-1)
        from .graph import GraphBuilder
        return GraphBuilder(copy.deepcopy(self._conf))

    graphBuilder = graph_builder

    def build(self) -> GlobalConfig:
        if self._conf.updater is None:
            self._conf.updater = Sgd(learning_rate=1e-1)
        return copy.deepcopy(self._conf)


class NeuralNetConfiguration:
    """Entry point: ``NeuralNetConfiguration.builder()`` (reference class of the
    same name)."""

    Builder = Builder

    @staticmethod
    def builder() -> Builder:
        return Builder()
