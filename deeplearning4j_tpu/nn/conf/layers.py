"""Layer configuration classes.

TPU-native equivalent of reference ``nn/conf/layers/`` (41 config classes,
SURVEY.md §2.1 "Layer configs"): one dataclass per layer type, JSON-serializable
via :mod:`..conf.serde`, with shape-inference hooks (``get_output_type``,
``set_n_in``, ``preprocessor_for``) mirroring the reference's
``Layer.getOutputType/setNIn/getPreProcessorForInputType`` used by
``ListBuilder.setInputType`` (reference ``NeuralNetConfiguration.java:215-324``).

Layer *implementations* (init/forward as pure JAX functions) live in
``deeplearning4j_tpu.nn.layers`` and are looked up by config class name.

Note on dropout: following the reference's 0.9.x semantics, ``dropout`` is the
**retain probability** (1.0 = keep everything / disabled).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, List, Optional, Tuple

from .serde import register
from .inputs import (InputTypeConvolutional, InputTypeConvolutionalFlat,
                     InputTypeFeedForward, InputTypeRecurrent)

__all__ = [
    "Layer", "BaseLayer", "FeedForwardLayer", "DenseLayer", "ConvolutionLayer",
    "Convolution1DLayer", "SeparableConvolution2D", "Deconvolution2D",
    "SubsamplingLayer", "Subsampling1DLayer", "PoolingType",
    "Upsampling1D", "Upsampling2D", "ZeroPaddingLayer", "ZeroPadding1DLayer",
    "Cropping2D", "SpaceToDepthLayer", "DepthwiseConvolution2D",
    "BatchNormalization", "LocalResponseNormalization", "ActivationLayer",
    "DropoutLayer", "EmbeddingLayer", "EmbeddingSequenceLayer", "LSTM", "GravesLSTM",
    "GravesBidirectionalLSTM", "SimpleRnn", "Bidirectional", "LastTimeStep",
    "OutputLayer", "RnnOutputLayer", "LossLayer", "CenterLossOutputLayer",
    "AutoEncoder", "VariationalAutoencoder", "GlobalPoolingLayer",
    "Yolo2OutputLayer", "FrozenLayer", "ConvolutionMode", "SelfAttentionLayer",
    "MoEDenseLayer",
]


class ConvolutionMode:
    """Reference ``nn/conf/ConvolutionMode.java``: Strict/Truncate/Same."""
    Strict = "strict"
    Truncate = "truncate"
    Same = "same"


class PoolingType:
    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    PNORM = "pnorm"


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        if len(v) == 1:
            return (int(v[0]), int(v[0]))
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def conv_out_size(in_size, k, s, p, d, mode):
    """Output spatial size (reference ``util/ConvolutionUtils.getOutputSize``)."""
    eff_k = (k - 1) * d + 1
    if mode == ConvolutionMode.Same:
        return int(math.ceil(in_size / s))
    return (in_size - eff_k + 2 * p) // s + 1


@register
@dataclasses.dataclass
class Layer:
    """Base config: fields shared by every layer (reference ``nn/conf/layers/Layer.java``)."""
    name: Optional[str] = None
    dropout: Optional[float] = None  # retain probability, reference semantics

    # shape inference hooks -------------------------------------------------
    def get_output_type(self, index, input_type):
        return input_type

    def set_n_in(self, input_type, override=False):
        pass

    def preprocessor_for(self, input_type):
        return None

    def is_pretrain_layer(self):
        return False

    def initializer_keys(self):
        return []


@register
@dataclasses.dataclass
class BaseLayer(Layer):
    """Layers with weights: activation/init/regularization/updater overrides
    (reference ``nn/conf/layers/BaseLayer.java``)."""
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    dist: Optional[Any] = None
    bias_init: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    l1_bias: Optional[float] = None
    l2_bias: Optional[float] = None
    updater: Optional[Any] = None  # per-layer IUpdater override
    weight_noise: Optional[Any] = None
    constraints: Optional[List[Any]] = None


@register
@dataclasses.dataclass
class FeedForwardLayer(BaseLayer):
    """Reference ``nn/conf/layers/FeedForwardLayer.java``: has nIn/nOut."""
    n_in: Optional[int] = None
    n_out: Optional[int] = None

    def get_output_type(self, index, input_type):
        return InputTypeFeedForward(self.n_out)

    def set_n_in(self, input_type, override=False):
        if self.n_in is None or override:
            self.n_in = input_type.arity()

    def preprocessor_for(self, input_type):
        from .preprocessors import (CnnToFeedForwardPreProcessor,
                                    RnnToFeedForwardPreProcessor)
        if isinstance(input_type, (InputTypeConvolutional, InputTypeConvolutionalFlat)):
            return CnnToFeedForwardPreProcessor(input_type.height, input_type.width,
                                                input_type.channels)
        if isinstance(input_type, InputTypeRecurrent):
            return RnnToFeedForwardPreProcessor()
        return None


@register
@dataclasses.dataclass
class DenseLayer(FeedForwardLayer):
    """Fully connected layer (reference ``nn/conf/layers/DenseLayer.java``)."""
    has_bias: bool = True


@register
@dataclasses.dataclass
class MoEDenseLayer(FeedForwardLayer):
    """Mixture-of-experts dense layer — net-new vs the 0.9.x reference
    (like :class:`SelfAttentionLayer`), included because expert parallelism
    is a first-class mesh axis in the TPU build: the expert dim of the
    parameters shards over the ``expert`` mesh axis
    (``parallel/expert.py``), XLA partitioning the per-expert einsums.

    Dense (Shazeer-style) top-k routing: every token's input reaches each
    local expert shard, gate weights zero the non-selected experts, and the
    expert-dim reduction becomes a psum over the axis. ``aux_loss_weight``
    scales the Switch-Transformer load-balancing loss, accumulated through
    the forward ``ctx`` into the training objective."""
    num_experts: int = 4
    top_k: int = 2
    aux_loss_weight: float = 1e-2
    has_bias: bool = True
    #: > 0 enables SPARSE capacity-factor dispatch IN THE TRAIN STEP: each
    #: expert processes at most ``ceil(top_k * tokens * capacity_factor /
    #: num_experts)`` tokens (lane-aligned), so per-step FLOPs scale with
    #: ``top_k/num_experts`` instead of paying every expert for every
    #: token; over-capacity (token, expert) assignments are dropped,
    #: Switch-Transformer style — raise the factor if exact parity with
    #: dense routing matters more than FLOPs. Inference (train=False)
    #: always routes exactly via the dense combine, so output/score/
    #: streaming agree regardless of batch shape. 0 keeps the dense einsum
    #: path everywhere (the correctness oracle).
    capacity_factor: float = 0.0
    #: token-group size for the sparse dispatch (GShard "group" dim):
    #: capacity is enforced PER GROUP of this many tokens, so the one-hot
    #: dispatch tensor is [groups, G, E, C_g] with C_g ∝ G — memory linear
    #: in token count instead of quadratic ([n, E, C] with C ∝ n). Smaller
    #: groups = less dispatch memory but more capacity fragmentation
    #: (drops decided within each group). Token counts that don't divide
    #: evenly are zero-gate padded to a group multiple.
    group_size: int = 1024


@register
@dataclasses.dataclass
class ConvolutionLayer(FeedForwardLayer):
    """2-D convolution (reference ``nn/conf/layers/ConvolutionLayer.java``).

    ``n_in`` = input channels, ``n_out`` = output channels. The reference's
    cuDNN algo-mode knobs (``cudnnAlgoMode`` etc.) have no TPU meaning; XLA
    picks conv algorithms. Kernel layout is HWIO internally (MXU-friendly).
    """
    kernel_size: Tuple[int, int] = (5, 5)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: str = ConvolutionMode.Truncate
    has_bias: bool = True

    def get_output_type(self, index, input_type):
        if not isinstance(input_type, InputTypeConvolutional):
            raise ValueError(f"ConvolutionLayer '{self.name}' needs convolutional "
                             f"input, got {input_type}")
        k, s, p, d = _pair(self.kernel_size), _pair(self.stride), _pair(self.padding), _pair(self.dilation)
        h = conv_out_size(input_type.height, k[0], s[0], p[0], d[0], self.convolution_mode)
        w = conv_out_size(input_type.width, k[1], s[1], p[1], d[1], self.convolution_mode)
        return InputTypeConvolutional(h, w, self.n_out)

    def set_n_in(self, input_type, override=False):
        if self.n_in is None or override:
            self.n_in = input_type.channels

    def preprocessor_for(self, input_type):
        from .preprocessors import FeedForwardToCnnPreProcessor, RnnToCnnPreProcessor
        if isinstance(input_type, InputTypeConvolutionalFlat):
            return FeedForwardToCnnPreProcessor(input_type.height, input_type.width,
                                                input_type.channels)
        return None


@register
@dataclasses.dataclass
class Convolution1DLayer(ConvolutionLayer):
    """1-D convolution over [batch, channels, length] (reference
    ``nn/conf/layers/Convolution1DLayer.java``)."""

    def get_output_type(self, index, input_type):
        if not isinstance(input_type, InputTypeRecurrent):
            raise ValueError("Convolution1DLayer needs recurrent input")
        k, s, p, d = _pair(self.kernel_size)[0], _pair(self.stride)[0], _pair(self.padding)[0], _pair(self.dilation)[0]
        t = input_type.timeseries_length
        t_out = None if t is None else conv_out_size(t, k, s, p, d, self.convolution_mode)
        return InputTypeRecurrent(self.n_out, t_out)

    def set_n_in(self, input_type, override=False):
        if self.n_in is None or override:
            self.n_in = input_type.size

    def preprocessor_for(self, input_type):
        return None


@register
@dataclasses.dataclass
class DepthwiseConvolution2D(ConvolutionLayer):
    depth_multiplier: int = 1

    def set_n_in(self, input_type, override=False):
        super().set_n_in(input_type, override)
        # depthwise output channels are determined: n_in × depth_multiplier
        if self.n_out is None and self.n_in is not None:
            self.n_out = self.n_in * int(self.depth_multiplier)


@register
@dataclasses.dataclass
class SeparableConvolution2D(ConvolutionLayer):
    depth_multiplier: int = 1


@register
@dataclasses.dataclass
class Deconvolution2D(ConvolutionLayer):
    """Transposed convolution."""

    def get_output_type(self, index, input_type):
        k, s, p, d = _pair(self.kernel_size), _pair(self.stride), _pair(self.padding), _pair(self.dilation)
        if self.convolution_mode == ConvolutionMode.Same:
            h = input_type.height * s[0]
            w = input_type.width * s[1]
        else:
            h = s[0] * (input_type.height - 1) + (k[0] - 1) * d[0] + 1 - 2 * p[0]
            w = s[1] * (input_type.width - 1) + (k[1] - 1) * d[1] + 1 - 2 * p[1]
        return InputTypeConvolutional(h, w, self.n_out)


@register
@dataclasses.dataclass
class SubsamplingLayer(Layer):
    """Spatial pooling (reference ``nn/conf/layers/SubsamplingLayer.java``)."""
    pooling_type: str = PoolingType.MAX
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: str = ConvolutionMode.Truncate
    pnorm: Optional[int] = None
    eps: float = 1e-8

    def get_output_type(self, index, input_type):
        if not isinstance(input_type, InputTypeConvolutional):
            raise ValueError("SubsamplingLayer needs convolutional input")
        k, s, p, d = _pair(self.kernel_size), _pair(self.stride), _pair(self.padding), _pair(self.dilation)
        h = conv_out_size(input_type.height, k[0], s[0], p[0], d[0], self.convolution_mode)
        w = conv_out_size(input_type.width, k[1], s[1], p[1], d[1], self.convolution_mode)
        return InputTypeConvolutional(h, w, input_type.channels)


@register
@dataclasses.dataclass
class Subsampling1DLayer(SubsamplingLayer):
    def get_output_type(self, index, input_type):
        if not isinstance(input_type, InputTypeRecurrent):
            raise ValueError("Subsampling1DLayer needs recurrent input")
        k, s, p, d = _pair(self.kernel_size)[0], _pair(self.stride)[0], _pair(self.padding)[0], _pair(self.dilation)[0]
        t = input_type.timeseries_length
        t_out = None if t is None else conv_out_size(t, k, s, p, d, self.convolution_mode)
        return InputTypeRecurrent(input_type.size, t_out)


@register
@dataclasses.dataclass
class Upsampling2D(Layer):
    size: Tuple[int, int] = (2, 2)

    def get_output_type(self, index, input_type):
        s = _pair(self.size)
        return InputTypeConvolutional(input_type.height * s[0], input_type.width * s[1],
                                      input_type.channels)


@register
@dataclasses.dataclass
class Upsampling1D(Layer):
    size: int = 2

    def get_output_type(self, index, input_type):
        t = input_type.timeseries_length
        return InputTypeRecurrent(input_type.size, None if t is None else t * int(self.size))


@register
@dataclasses.dataclass
class ZeroPaddingLayer(Layer):
    """[top, bottom, left, right] padding (reference ``ZeroPaddingLayer.java``)."""
    padding: Tuple[int, int, int, int] = (0, 0, 0, 0)

    def _pads(self):
        p = list(self.padding)
        if len(p) == 2:
            p = [p[0], p[0], p[1], p[1]]
        return p

    def get_output_type(self, index, input_type):
        p = self._pads()
        return InputTypeConvolutional(input_type.height + p[0] + p[1],
                                      input_type.width + p[2] + p[3],
                                      input_type.channels)


@register
@dataclasses.dataclass
class ZeroPadding1DLayer(Layer):
    padding: Tuple[int, int] = (0, 0)

    def get_output_type(self, index, input_type):
        p = _pair(self.padding)
        t = input_type.timeseries_length
        return InputTypeRecurrent(input_type.size, None if t is None else t + p[0] + p[1])


@register
@dataclasses.dataclass
class Cropping2D(Layer):
    cropping: Tuple[int, int, int, int] = (0, 0, 0, 0)

    def _crops(self):
        c = list(self.cropping)
        if len(c) == 2:
            c = [c[0], c[0], c[1], c[1]]
        return c

    def get_output_type(self, index, input_type):
        c = self._crops()
        return InputTypeConvolutional(input_type.height - c[0] - c[1],
                                      input_type.width - c[2] - c[3],
                                      input_type.channels)


@register
@dataclasses.dataclass
class SpaceToDepthLayer(Layer):
    block_size: int = 2

    def get_output_type(self, index, input_type):
        b = int(self.block_size)
        return InputTypeConvolutional(input_type.height // b, input_type.width // b,
                                      input_type.channels * b * b)


@register
@dataclasses.dataclass
class BatchNormalization(FeedForwardLayer):
    """Reference ``nn/conf/layers/BatchNormalization.java``. ``decay`` is the
    running-stats momentum; gamma/beta trainable unless ``lock_gamma_beta``."""
    decay: float = 0.9
    eps: float = 1e-5
    gamma: float = 1.0
    beta: float = 0.0
    lock_gamma_beta: bool = False

    def get_output_type(self, index, input_type):
        return input_type

    def set_n_in(self, input_type, override=False):
        if self.n_in is None or override:
            self.n_in = (input_type.channels if isinstance(input_type, InputTypeConvolutional)
                         else input_type.arity())
        self.n_out = self.n_in

    def preprocessor_for(self, input_type):
        return None


@register
@dataclasses.dataclass
class LayerNormalization(FeedForwardLayer):
    """Per-token normalization over the FEATURE dim with learned gain/bias —
    net-new vs the 0.9.x reference (which predates transformers; its only
    norms are Batch/LRN, ``nn/conf/layers/BatchNormalization.java``).
    Included because the transformer family (SelfAttentionLayer, MoEDense,
    TransformerLM) is first-class in the TPU build: LN is stateless (no
    running stats ⇒ no cross-replica/shard state to reconcile), normalizes
    each position independently (works for [b, F] and [b, T, F], and the
    time dim may be sharded — sp-safe by construction), and XLA fuses the
    two-moment pass into neighbouring elementwise work."""
    eps: float = 1e-5

    def get_output_type(self, index, input_type):
        return input_type

    def set_n_in(self, input_type, override=False):
        if self.n_in is None or override:
            self.n_in = input_type.arity()
        self.n_out = self.n_in

    def preprocessor_for(self, input_type):
        return None


@register
@dataclasses.dataclass
class LocalResponseNormalization(Layer):
    """Reference ``nn/conf/layers/LocalResponseNormalization.java``."""
    k: float = 2.0
    n: float = 5.0
    alpha: float = 1e-4
    beta: float = 0.75


@register
@dataclasses.dataclass
class ActivationLayer(BaseLayer):
    pass


@register
@dataclasses.dataclass
class DropoutLayer(FeedForwardLayer):
    def get_output_type(self, index, input_type):
        return input_type

    def set_n_in(self, input_type, override=False):
        pass

    def preprocessor_for(self, input_type):
        return None


@register
@dataclasses.dataclass
class EmbeddingLayer(FeedForwardLayer):
    """Index → vector lookup, one index per example
    (reference ``nn/conf/layers/EmbeddingLayer.java``)."""
    has_bias: bool = True


@register
@dataclasses.dataclass
class EmbeddingSequenceLayer(FeedForwardLayer):
    """Index sequence → vector sequence (added post-0.9 in the reference line;
    included for NLP-model parity)."""
    has_bias: bool = False

    def get_output_type(self, index, input_type):
        t = input_type.timeseries_length if isinstance(input_type, InputTypeRecurrent) else None
        return InputTypeRecurrent(self.n_out, t)

    def preprocessor_for(self, input_type):
        # consumes [b, T] token ids directly — a recurrent input type
        # describes the SEQUENCE (vocab arity), never a tensor to flatten
        return None


@register
@dataclasses.dataclass
class BaseRecurrentLayer(FeedForwardLayer):
    def get_output_type(self, index, input_type):
        t = input_type.timeseries_length if isinstance(input_type, InputTypeRecurrent) else None
        return InputTypeRecurrent(self.n_out, t)

    def set_n_in(self, input_type, override=False):
        if self.n_in is None or override:
            self.n_in = input_type.size

    def preprocessor_for(self, input_type):
        from .preprocessors import (FeedForwardToRnnPreProcessor, CnnToRnnPreProcessor)
        if isinstance(input_type, InputTypeFeedForward):
            return FeedForwardToRnnPreProcessor()
        if isinstance(input_type, InputTypeConvolutional):
            return CnnToRnnPreProcessor(input_type.height, input_type.width,
                                        input_type.channels)
        return None


@register
@dataclasses.dataclass
class LSTM(BaseRecurrentLayer):
    """Standard LSTM, no peepholes (reference ``nn/conf/layers/LSTM.java``);
    compiled as a fused-gate ``lax.scan`` on TPU (one [4H] gemm per step)."""
    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"


@register
@dataclasses.dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections (reference ``GravesLSTM.java``,
    ``LSTMHelpers.java:68``)."""
    pass


@register
@dataclasses.dataclass
class GravesBidirectionalLSTM(GravesLSTM):
    """Two independent GravesLSTMs run forward and backward over time, with
    per-direction parameter sets; direction outputs are summed so the layer
    output stays nOut-sized (reference ``GravesBidirectionalLSTM.java``)."""

    def get_output_type(self, index, input_type):
        t = input_type.timeseries_length if isinstance(input_type, InputTypeRecurrent) else None
        return InputTypeRecurrent(self.n_out, t)


@register
@dataclasses.dataclass
class SimpleRnn(BaseRecurrentLayer):
    pass


@register
@dataclasses.dataclass
class Bidirectional(Layer):
    """Wrapper running an inner recurrent layer in both directions.
    ``mode``: concat | add | mul | ave (reference 1.0 line ``Bidirectional.java``)."""
    inner: Optional[Any] = None
    mode: str = "concat"

    def get_output_type(self, index, input_type):
        out = self.inner.get_output_type(index, input_type)
        if self.mode == "concat":
            out = InputTypeRecurrent(out.size * 2, out.timeseries_length)
        return out

    def set_n_in(self, input_type, override=False):
        self.inner.set_n_in(input_type, override)

    def preprocessor_for(self, input_type):
        return self.inner.preprocessor_for(input_type)


@register
@dataclasses.dataclass
class LastTimeStep(Layer):
    """Wrapper extracting the last (mask-aware) timestep of an inner RNN layer."""
    inner: Optional[Any] = None

    def get_output_type(self, index, input_type):
        out = self.inner.get_output_type(index, input_type)
        return InputTypeFeedForward(out.size)

    def set_n_in(self, input_type, override=False):
        self.inner.set_n_in(input_type, override)

    def preprocessor_for(self, input_type):
        return self.inner.preprocessor_for(input_type)


@register
@dataclasses.dataclass
class SelfAttentionLayer(BaseRecurrentLayer):
    """Multi-head self-attention over a sequence — net-new vs the 0.9.x reference
    (which has no attention, SURVEY.md §5 "Long-context"); included because
    long-context/sequence-parallel support is first-class in the TPU build.
    Supports ring-attention sequence parallelism (see ``parallel/sequence.py``)."""
    num_heads: int = 4
    head_dim: Optional[int] = None
    causal: bool = True
    dropout_rate: float = 0.0
    #: KV-cache capacity for streaming inference (``rnn_time_step``) and
    #: cross-segment TBPTT attention; static so the cached step keeps one
    #: compiled shape. Streams beyond this length roll over the tail.
    stream_max_length: int = 512


@register
@dataclasses.dataclass
class OutputLayer(FeedForwardLayer):
    """Dense + loss (reference ``nn/conf/layers/OutputLayer.java``)."""
    loss: str = "mcxent"
    has_bias: bool = True


@register
@dataclasses.dataclass
class RnnOutputLayer(OutputLayer):
    def get_output_type(self, index, input_type):
        t = input_type.timeseries_length if isinstance(input_type, InputTypeRecurrent) else None
        return InputTypeRecurrent(self.n_out, t)

    def set_n_in(self, input_type, override=False):
        if self.n_in is None or override:
            self.n_in = input_type.size

    def preprocessor_for(self, input_type):
        from .preprocessors import FeedForwardToRnnPreProcessor
        if isinstance(input_type, InputTypeFeedForward):
            return FeedForwardToRnnPreProcessor()
        return None


@register
@dataclasses.dataclass
class LossLayer(FeedForwardLayer):
    """Loss without weights (reference ``nn/conf/layers/LossLayer.java``)."""
    loss: str = "mcxent"

    def get_output_type(self, index, input_type):
        return input_type

    def set_n_in(self, input_type, override=False):
        pass


@register
@dataclasses.dataclass
class CenterLossOutputLayer(OutputLayer):
    """Reference ``nn/conf/layers/CenterLossOutputLayer.java``: softmax loss +
    center loss with per-class feature centers updated by EMA."""
    alpha: float = 0.05
    lambda_: float = 2e-4
    gradient_check: bool = False


@register
@dataclasses.dataclass
class AutoEncoder(FeedForwardLayer):
    """Denoising autoencoder pretrain layer (reference ``nn/conf/layers/AutoEncoder.java``)."""
    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss: str = "mse"

    def is_pretrain_layer(self):
        return True


@register
@dataclasses.dataclass
class RBM(FeedForwardLayer):
    """Restricted Boltzmann Machine (reference ``nn/conf/layers/RBM.java:62``
    config + ``nn/layers/feedforward/rbm/RBM.java:1`` CD-k impl — deprecated
    there in favor of the VAE, ported for §2.1 layer-inventory completeness).

    Supervised forward = ``propUp`` (hidden mean activation). Unsupervised
    pretraining = CD-k contrastive divergence behind the standard pretrain
    seam: ``pretrain_loss`` is the free-energy-difference surrogate
    ``mean(F(v0) - F(v_k))`` with the k-step Gibbs chain stop-gradiented,
    whose gradient IS the CD-k update ``⟨v0 h0⟩ - ⟨vk hk⟩`` (TPU-first: the
    whole chain jits; no hand-written update rule).

    ``hidden_unit``: binary | rectified | gaussian | identity;
    ``visible_unit``: binary | gaussian | linear | identity (reference
    enums; softmax units were never wired into the reference's gradient
    path and are rejected here rather than silently mis-trained)."""
    hidden_unit: str = "binary"
    visible_unit: str = "binary"
    k: int = 1
    sparsity: float = 0.0

    def is_pretrain_layer(self):
        return True


@register
@dataclasses.dataclass
class VariationalAutoencoder(FeedForwardLayer):
    """Reference ``nn/conf/layers/variational/VariationalAutoencoder.java`` /
    impl ``nn/layers/variational/VariationalAutoencoder.java`` (1163 LoC).

    ``n_out`` = latent size. Forward (supervised use) emits the mean of q(z|x).
    Pretraining maximizes the ELBO with ``num_samples`` MC samples.
    """
    encoder_layer_sizes: Tuple[int, ...] = (100,)
    decoder_layer_sizes: Tuple[int, ...] = (100,)
    pzx_activation: str = "identity"
    # a ReconstructionDistribution object (conf.reconstruction) or a legacy
    # string name: gaussian (learned variance) | bernoulli | exponential
    reconstruction_distribution: Any = "gaussian"
    num_samples: int = 1

    def is_pretrain_layer(self):
        return True


class PoolingDimension:
    pass


@register
@dataclasses.dataclass
class GlobalPoolingLayer(Layer):
    """Pool over spatial/time dims (reference ``nn/conf/layers/GlobalPoolingLayer.java``);
    mask-aware for RNN input."""
    pooling_type: str = PoolingType.MAX
    pooling_dimensions: Optional[Tuple[int, ...]] = None
    collapse_dimensions: bool = True
    pnorm: int = 2

    def get_output_type(self, index, input_type):
        if isinstance(input_type, InputTypeConvolutional):
            return InputTypeFeedForward(input_type.channels)
        if isinstance(input_type, InputTypeRecurrent):
            return InputTypeFeedForward(input_type.size)
        return input_type


@register
@dataclasses.dataclass
class Yolo2OutputLayer(Layer):
    """YOLOv2 detection loss (reference ``nn/conf/layers/objdetect/Yolo2OutputLayer.java``,
    impl ``nn/layers/objdetect/Yolo2OutputLayer.java`` 714 LoC).

    ``boxes``: [[h,w], ...] anchor box priors in grid units.
    Labels: [batch, 4 + C, gridH, gridW] as in the reference.
    """
    boxes: Optional[List[List[float]]] = None
    lambda_coord: float = 5.0
    lambda_no_obj: float = 0.5

    def get_output_type(self, index, input_type):
        return input_type


@register
@dataclasses.dataclass
class FrozenLayer(Layer):
    """Wrapper marking the inner layer non-trainable (reference
    ``nn/conf/layers/misc/FrozenLayer.java``); gradients are zeroed via
    ``jax.lax.stop_gradient`` on the inner params."""
    inner: Optional[Any] = None

    def get_output_type(self, index, input_type):
        return self.inner.get_output_type(index, input_type)

    def set_n_in(self, input_type, override=False):
        self.inner.set_n_in(input_type, override)

    def preprocessor_for(self, input_type):
        return self.inner.preprocessor_for(input_type)
