"""VAE reconstruction distributions p(x|z).

TPU-native equivalent of reference
``nn/conf/layers/variational/ReconstructionDistribution.java`` and its four
implementations (Gaussian with learned variance, Bernoulli, Exponential,
Composite) plus ``LossFunctionWrapper.java``. The reference interface needs
hand-written ``gradient()`` methods; here ``neg_log_prob`` is written once and
AD differentiates it inside the jitted pretrain step, so each distribution is
just the math:

- ``param_size(d)``  — decoder head width (``distributionInputSize``)
- ``neg_log_prob(x, pre_out)`` — per-example −log p(x|z), shape [b]
  (``exampleNegLogProbability``; sums/averages derive from it)
- ``sample(rng, pre_out)`` / ``mean(pre_out)`` — ``generateRandom`` /
  ``generateAtMean``

All are config dataclasses (serde-registered) so VAE models round-trip
through ModelSerializer JSON.
"""
from __future__ import annotations

import dataclasses
import math
from typing import List, Tuple

import jax
import jax.numpy as jnp

from .serde import register
from ..activations import get_activation

# plain-math constant: module import must NOT trigger XLA backend init
# (jax.distributed.initialize requires a pristine backend)
_HALF_LOG_2PI = 0.5 * math.log(2 * math.pi)


class ReconstructionDistribution:
    """Base contract (reference ``ReconstructionDistribution.java:24``)."""

    has_loss_function = False

    def param_size(self, data_size: int) -> int:
        raise NotImplementedError

    def neg_log_prob(self, x, pre_out):
        """Per-example −log p(x|z), shape [b]."""
        raise NotImplementedError

    def sample(self, rng, pre_out):
        raise NotImplementedError

    def mean(self, pre_out):
        raise NotImplementedError


@register
@dataclasses.dataclass
class GaussianReconstructionDistribution(ReconstructionDistribution):
    """Diagonal Gaussian with LEARNED variance (reference
    ``GaussianReconstructionDistribution.java``): the decoder head emits
    ``[mean, log(sigma^2)]`` (2 params per data value), activation applied to
    the whole pre-out as in the reference."""

    activation: str = "identity"

    def param_size(self, data_size):
        return 2 * data_size

    def _split(self, pre_out):
        out = get_activation(self.activation)(pre_out)
        mean, log_var = jnp.split(out, 2, axis=-1)
        return mean, log_var

    def neg_log_prob(self, x, pre_out):
        mean, log_var = self._split(pre_out)
        var = jnp.exp(log_var)
        per_elem = _HALF_LOG_2PI + 0.5 * log_var + (x - mean) ** 2 / (2 * var)
        return jnp.sum(per_elem, axis=-1)

    def sample(self, rng, pre_out):
        mean, log_var = self._split(pre_out)
        eps = jax.random.normal(rng, mean.shape, mean.dtype)
        return mean + jnp.exp(0.5 * log_var) * eps

    def mean(self, pre_out):
        return self._split(pre_out)[0]


@register
@dataclasses.dataclass
class BernoulliReconstructionDistribution(ReconstructionDistribution):
    """Bernoulli p(x|z) for binary/binarized data (reference
    ``BernoulliReconstructionDistribution.java``). With the default sigmoid
    activation the log-prob uses the numerically stable logits form."""

    activation: str = "sigmoid"

    def param_size(self, data_size):
        return data_size

    def neg_log_prob(self, x, pre_out):
        if self.activation == "sigmoid":
            # stable: max(l,0) - l*x + log(1+exp(-|l|))
            per_elem = (jnp.maximum(pre_out, 0) - pre_out * x
                        + jnp.log1p(jnp.exp(-jnp.abs(pre_out))))
        else:
            p = jnp.clip(get_activation(self.activation)(pre_out), 1e-7,
                         1 - 1e-7)
            per_elem = -(x * jnp.log(p) + (1 - x) * jnp.log1p(-p))
        return jnp.sum(per_elem, axis=-1)

    def _probs(self, pre_out):
        return get_activation(self.activation)(pre_out)

    def sample(self, rng, pre_out):
        p = self._probs(pre_out)
        return jax.random.bernoulli(rng, p).astype(pre_out.dtype)

    def mean(self, pre_out):
        return self._probs(pre_out)


@register
@dataclasses.dataclass
class ExponentialReconstructionDistribution(ReconstructionDistribution):
    """Exponential p(x|z) for non-negative data (reference
    ``ExponentialReconstructionDistribution.java``): the head models
    ``gamma = log(lambda)`` so any real-valued activation works;
    ``log p(x) = gamma - exp(gamma) * x``."""

    activation: str = "identity"

    def param_size(self, data_size):
        return data_size

    def _gamma(self, pre_out):
        return get_activation(self.activation)(pre_out)

    def neg_log_prob(self, x, pre_out):
        gamma = self._gamma(pre_out)
        return -jnp.sum(gamma - jnp.exp(gamma) * x, axis=-1)

    def sample(self, rng, pre_out):
        lam = jnp.exp(self._gamma(pre_out))
        return jax.random.exponential(rng, lam.shape, lam.dtype) / lam

    def mean(self, pre_out):
        return jnp.exp(-self._gamma(pre_out))  # E[x] = 1/lambda


@register
@dataclasses.dataclass
class CompositeReconstructionDistribution(ReconstructionDistribution):
    """Mixed data types: different distributions over column ranges of x
    (reference ``CompositeReconstructionDistribution.java``). Built from
    ``(size, distribution)`` pairs covering the data columns in order."""

    distribution_sizes: Tuple[int, ...] = ()
    distributions: Tuple[ReconstructionDistribution, ...] = ()

    @property
    def has_loss_function(self):
        # reference: true when ANY component wraps a loss function (then the
        # composite has no well-defined log-probability)
        return any(d.has_loss_function for d in self.distributions)

    class Builder:
        def __init__(self):
            self._sizes: List[int] = []
            self._dists: List[ReconstructionDistribution] = []

        def add_distribution(self, size, dist):
            self._sizes.append(int(size))
            self._dists.append(dist)
            return self

        addDistribution = add_distribution

        def build(self):
            return CompositeReconstructionDistribution(
                tuple(self._sizes), tuple(self._dists))

    @staticmethod
    def builder():
        return CompositeReconstructionDistribution.Builder()

    def param_size(self, data_size):
        if sum(self.distribution_sizes) != data_size:
            raise ValueError(
                f"Composite distribution sizes {self.distribution_sizes} do "
                f"not cover data size {data_size}")
        return sum(d.param_size(s) for s, d in
                   zip(self.distribution_sizes, self.distributions))

    def _splits(self, x, pre_out):
        xi, pi = 0, 0
        for s, d in zip(self.distribution_sizes, self.distributions):
            ps = d.param_size(s)
            yield d, x[..., xi:xi + s] if x is not None else None, \
                pre_out[..., pi:pi + ps]
            xi, pi = xi + s, pi + ps

    def neg_log_prob(self, x, pre_out):
        total = 0.0
        for d, xs, ps in self._splits(x, pre_out):
            total = total + d.neg_log_prob(xs, ps)
        return total

    def sample(self, rng, pre_out):
        keys = jax.random.split(rng, len(self.distributions))
        return jnp.concatenate(
            [d.sample(k, ps) for k, (d, _, ps) in
             zip(keys, self._splits(None, pre_out))], axis=-1)

    def mean(self, pre_out):
        return jnp.concatenate(
            [d.mean(ps) for d, _, ps in self._splits(None, pre_out)], axis=-1)


@register
@dataclasses.dataclass
class LossFunctionWrapper(ReconstructionDistribution):
    """Deterministic reconstruction via a standard loss function (reference
    ``LossFunctionWrapper.java``): no probabilistic p(x|z) — ``neg_log_prob``
    is the per-example loss, sampling returns the activated output."""

    loss: str = "mse"
    activation: str = "identity"

    has_loss_function = True

    def param_size(self, data_size):
        return data_size

    def neg_log_prob(self, x, pre_out):
        from ..losses import get_loss
        fn = get_loss(self.loss)
        # per-example via vmap of the (batch-averaged) scalar loss on b=1
        return jax.vmap(
            lambda xi, pi: fn(xi[None], pi[None], self.activation, None))(
                x, pre_out)

    def sample(self, rng, pre_out):
        return self.mean(pre_out)

    def mean(self, pre_out):
        return get_activation(self.activation)(pre_out)


def resolve_distribution(spec) -> ReconstructionDistribution:
    """Accept a distribution object or a legacy string name."""
    if isinstance(spec, ReconstructionDistribution):
        return spec
    name = str(spec).lower()
    if name == "gaussian":
        return GaussianReconstructionDistribution()
    if name == "bernoulli":
        return BernoulliReconstructionDistribution()
    if name == "exponential":
        return ExponentialReconstructionDistribution()
    raise ValueError(f"Unknown reconstruction distribution {spec!r}")
