"""Input types for shape inference.

TPU-native equivalent of reference ``nn/conf/inputs/InputType.java``: a small
algebra describing activations flowing between layers, used by the ListBuilder's
``setInputType`` pass to infer ``nIn`` and auto-insert preprocessors
(reference ``NeuralNetConfiguration.java:215-324``).

Convolutional activations are described by (height, width, channels) as in the
reference; the runtime lays them out NHWC internally (TPU-friendly) while the
user-facing tensors keep the reference's NCHW convention.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from .serde import register

__all__ = ["InputType", "InputTypeFeedForward", "InputTypeRecurrent",
           "InputTypeConvolutional", "InputTypeConvolutionalFlat"]


@register
@dataclasses.dataclass
class InputTypeFeedForward:
    size: int = 0

    def arity(self):
        return self.size


@register
@dataclasses.dataclass
class InputTypeRecurrent:
    size: int = 0
    timeseries_length: Optional[int] = None

    def arity(self):
        return self.size


@register
@dataclasses.dataclass
class InputTypeConvolutional:
    height: int = 0
    width: int = 0
    channels: int = 0

    def arity(self):
        return self.height * self.width * self.channels


@register
@dataclasses.dataclass
class InputTypeConvolutionalFlat:
    height: int = 0
    width: int = 0
    channels: int = 0

    def arity(self):
        return self.height * self.width * self.channels


class InputType:
    """Factory namespace matching the reference's static methods."""

    FeedForward = InputTypeFeedForward
    Recurrent = InputTypeRecurrent
    Convolutional = InputTypeConvolutional
    ConvolutionalFlat = InputTypeConvolutionalFlat

    @staticmethod
    def feed_forward(size):
        return InputTypeFeedForward(int(size))

    # reference-style camelCase aliases
    feedForward = feed_forward

    @staticmethod
    def recurrent(size, timeseries_length=None):
        return InputTypeRecurrent(int(size), timeseries_length)

    @staticmethod
    def convolutional(height, width, channels):
        return InputTypeConvolutional(int(height), int(width), int(channels))

    @staticmethod
    def convolutional_flat(height, width, channels):
        return InputTypeConvolutionalFlat(int(height), int(width), int(channels))

    convolutionalFlat = convolutional_flat
