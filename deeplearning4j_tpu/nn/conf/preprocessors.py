"""Input preprocessors: shape adapters between layer families.

TPU-native equivalent of reference ``nn/conf/preprocessor/`` (12 classes,
SURVEY.md §2.1 "Preprocessors"). Unlike the reference — which implements
``preProcess`` and a hand-written ``backprop`` per adapter — these are pure
reshape/transpose functions; AD provides the backward pass and XLA folds the
reshapes into adjacent ops (usually free on TPU).

Data conventions (TPU-native; differ from the reference's CUDA-era layouts):
 - feed-forward activations: ``[batch, size]``
 - recurrent activations:    ``[batch, time, size]``   (reference: [b, size, T])
 - convolutional activations:``[batch, h, w, c]`` NHWC (reference: NCHW)
Flattened orderings (e.g. CnnToFeedForward) keep the reference's channel-major
(c, h, w) element order so flattened dense weights stay interchangeable with
reference/Keras checkpoints.

Preprocessors receive a mutable runtime ``ctx`` dict carrying static-shape facts
(minibatch size, sequence length) that the reference stored as instance state.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from .serde import register
from .inputs import (InputTypeConvolutional,
                     InputTypeFeedForward, InputTypeRecurrent)

__all__ = ["InputPreProcessor", "CnnToFeedForwardPreProcessor",
           "FeedForwardToCnnPreProcessor", "RnnToFeedForwardPreProcessor",
           "FeedForwardToRnnPreProcessor", "CnnToRnnPreProcessor",
           "RnnToCnnPreProcessor", "ComposableInputPreProcessor"]


@dataclasses.dataclass
class InputPreProcessor:
    def __call__(self, x, ctx):  # pragma: no cover - abstract
        raise NotImplementedError

    def get_output_type(self, input_type):
        raise NotImplementedError


@register
@dataclasses.dataclass
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    """[b,h,w,c] → [b, c*h*w] in reference channel-major order
    (reference ``CnnToFeedForwardPreProcessor.java``)."""
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x, ctx):
        b = x.shape[0]
        return x.transpose(0, 3, 1, 2).reshape(b, -1)

    def get_output_type(self, input_type):
        return InputTypeFeedForward(input_type.arity())


@register
@dataclasses.dataclass
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    """[b, c*h*w] (channel-major) → [b,h,w,c] (reference ``FeedForwardToCnnPreProcessor.java``)."""
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x, ctx):
        b = x.shape[0]
        return x.reshape(b, self.channels, self.height, self.width).transpose(0, 2, 3, 1)

    def get_output_type(self, input_type):
        return InputTypeConvolutional(self.height, self.width, self.channels)


@register
@dataclasses.dataclass
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """[b,T,s] → [b*T, s] (reference ``RnnToFeedForwardPreProcessor.java``)."""

    def __call__(self, x, ctx):
        b, t, s = x.shape
        ctx["minibatch"] = b
        ctx["timesteps"] = t
        return x.reshape(b * t, s)

    def get_output_type(self, input_type):
        return InputTypeFeedForward(input_type.size)


@register
@dataclasses.dataclass
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    """[b*T, s] → [b,T,s] using ctx, or [b,s] → [b,1,s] when no sequence context
    (reference ``FeedForwardToRnnPreProcessor.java``)."""

    def __call__(self, x, ctx):
        n, s = x.shape
        b = ctx.get("minibatch")
        t = ctx.get("timesteps")
        if b is None or t is None or b * t != n:
            return x.reshape(n, 1, s)
        return x.reshape(b, t, s)

    def get_output_type(self, input_type):
        return InputTypeRecurrent(input_type.arity())


@register
@dataclasses.dataclass
class CnnToRnnPreProcessor(InputPreProcessor):
    """[b*T,h,w,c] → [b,T,c*h*w] (reference ``CnnToRnnPreProcessor.java``)."""
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x, ctx):
        n = x.shape[0]
        b = ctx.get("minibatch", n)
        t = max(n // max(b, 1), 1)
        flat = x.transpose(0, 3, 1, 2).reshape(n, -1)
        return flat.reshape(b, t, flat.shape[-1])

    def get_output_type(self, input_type):
        return InputTypeRecurrent(input_type.arity())


@register
@dataclasses.dataclass
class RnnToCnnPreProcessor(InputPreProcessor):
    """[b,T,c*h*w] → [b*T,h,w,c] (reference ``RnnToCnnPreProcessor.java``)."""
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x, ctx):
        b, t, s = x.shape
        ctx["minibatch"] = b
        ctx["timesteps"] = t
        y = x.reshape(b * t, self.channels, self.height, self.width)
        return y.transpose(0, 2, 3, 1)

    def get_output_type(self, input_type):
        return InputTypeConvolutional(self.height, self.width, self.channels)


@register
@dataclasses.dataclass
class ComposableInputPreProcessor(InputPreProcessor):
    processors: Optional[list] = None

    def __call__(self, x, ctx):
        for p in self.processors or []:
            x = p(x, ctx)
        return x

    def get_output_type(self, input_type):
        for p in self.processors or []:
            input_type = p.get_output_type(input_type)
        return input_type
