"""IDropout implementations + weight noise + parameter constraints.

TPU-native equivalents of reference ``nn/conf/dropout/`` (Dropout,
AlphaDropout, GaussianDropout, GaussianNoise), ``nn/conf/weightnoise/``
(DropConnect, WeightNoise) and ``nn/conf/constraint/`` (MaxNorm, MinMaxNorm,
NonNegative, UnitNorm) — SURVEY.md §2.1 "Regularization & noise".

Dropout objects transform ACTIVATIONS during training; weight-noise objects
transform WEIGHTS during the forward pass; constraints project PARAMS after
each update. All are pure functions applied inside the jitted train step.
Plain floats remain accepted wherever a Dropout is expected (retain
probability — reference 0.9.x semantics).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from .serde import register


# ------------------------------------------------------------------ dropout
@register
@dataclasses.dataclass
class Dropout:
    """Inverted dropout; ``p`` = retain probability (reference semantics)."""
    p: float = 0.5

    def apply(self, x, rng, train):
        if not train or rng is None or self.p >= 1.0:
            return x
        keep = jax.random.bernoulli(rng, self.p, x.shape)
        return jnp.where(keep, x / self.p, jnp.zeros_like(x))


@register
@dataclasses.dataclass
class AlphaDropout:
    """SELU-preserving dropout (reference ``AlphaDropout``): dropped units go
    to alpha' and the output is affinely corrected to keep self-normalizing
    statistics. ``p`` = retain probability."""
    p: float = 0.95

    ALPHA = 1.6732632423543772
    SCALE = 1.0507009873554805

    def apply(self, x, rng, train):
        if not train or rng is None or self.p >= 1.0:
            return x
        alpha_p = -self.ALPHA * self.SCALE
        keep = jax.random.bernoulli(rng, self.p, x.shape)
        a = (self.p + alpha_p ** 2 * self.p * (1 - self.p)) ** -0.5
        b = -a * alpha_p * (1 - self.p)
        return a * jnp.where(keep, x, alpha_p) + b


@register
@dataclasses.dataclass
class GaussianDropout:
    """Multiplicative 1+N(0, rate/(1-rate)) noise (reference
    ``GaussianDropout``)."""
    rate: float = 0.5

    def apply(self, x, rng, train):
        if not train or rng is None or self.rate <= 0:
            return x
        std = math.sqrt(self.rate / (1.0 - self.rate))
        return x * (1.0 + std * jax.random.normal(rng, x.shape, x.dtype))


@register
@dataclasses.dataclass
class GaussianNoise:
    """Additive N(0, stddev) noise (reference ``GaussianNoise``)."""
    stddev: float = 0.1

    def apply(self, x, rng, train):
        if not train or rng is None or self.stddev <= 0:
            return x
        return x + self.stddev * jax.random.normal(rng, x.shape, x.dtype)


def resolve_dropout(spec):
    """float (retain prob) → Dropout; IDropout objects pass through."""
    if spec is None:
        return None
    if isinstance(spec, (int, float)):
        return Dropout(p=float(spec)) if spec < 1.0 else None
    return spec


# -------------------------------------------------------------- weight noise
@register
@dataclasses.dataclass
class DropConnect:
    """Per-weight Bernoulli masking during forward (reference ``DropConnect``);
    ``p`` = retain probability."""
    p: float = 0.5
    apply_to_bias: bool = False

    def apply_to_weights(self, w, key, rng, train):
        if not train or rng is None:
            return w
        if key.startswith("b") and not self.apply_to_bias:
            return w
        keep = jax.random.bernoulli(rng, self.p, w.shape)
        return jnp.where(keep, w / self.p, jnp.zeros_like(w))


@register
@dataclasses.dataclass
class WeightNoise:
    """Additive/multiplicative gaussian weight noise (reference
    ``WeightNoise`` with a distribution)."""
    stddev: float = 0.01
    additive: bool = True
    apply_to_bias: bool = False

    def apply_to_weights(self, w, key, rng, train):
        if not train or rng is None:
            return w
        if key.startswith("b") and not self.apply_to_bias:
            return w
        noise = self.stddev * jax.random.normal(rng, w.shape, w.dtype)
        return w + noise if self.additive else w * (1.0 + noise)


# --------------------------------------------------------------- constraints
class BaseConstraint:
    """Projected onto params after each update (reference
    ``BaseConstraint.applyConstraint``); weights only unless
    ``apply_to_bias``."""
    apply_to_bias = False

    def applies_to(self, key: str) -> bool:
        is_bias = key == "b" or key.endswith("_b") or key == "beta"
        return self.apply_to_bias or not is_bias

    def project(self, w):
        raise NotImplementedError

    @staticmethod
    def _axes_for(w):
        # norm over input dims, per output unit (last axis)
        return tuple(range(w.ndim - 1)) if w.ndim > 1 else (0,)


@register
@dataclasses.dataclass
class MaxNormConstraint(BaseConstraint):
    """Clip per-unit L2 norm to ``max_norm`` (reference ``MaxNormConstraint``)."""
    max_norm: float = 2.0

    def project(self, w):
        axes = self._axes_for(w)
        norm = jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True))
        scale = jnp.minimum(1.0, self.max_norm / jnp.maximum(norm, 1e-8))
        return w * scale


@register
@dataclasses.dataclass
class MinMaxNormConstraint(BaseConstraint):
    """Force per-unit norms into [min, max] with strength ``rate``
    (reference ``MinMaxNormConstraint``)."""
    min_norm: float = 0.0
    max_norm: float = 2.0
    rate: float = 1.0

    def project(self, w):
        axes = self._axes_for(w)
        norm = jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True))
        clipped = jnp.clip(norm, self.min_norm, self.max_norm)
        target = self.rate * clipped + (1 - self.rate) * norm
        return w * target / jnp.maximum(norm, 1e-8)


@register
@dataclasses.dataclass
class NonNegativeConstraint(BaseConstraint):
    def project(self, w):
        return jnp.maximum(w, 0.0)


@register
@dataclasses.dataclass
class UnitNormConstraint(BaseConstraint):
    def project(self, w):
        axes = self._axes_for(w)
        norm = jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True))
        return w / jnp.maximum(norm, 1e-8)


def apply_constraints(constraints, layer_params):
    """Project one layer's params through its constraint list."""
    if not constraints:
        return layer_params
    out = dict(layer_params)
    for c in constraints:
        for k, v in out.items():
            if c.applies_to(k):
                out[k] = c.project(v)
    return out
