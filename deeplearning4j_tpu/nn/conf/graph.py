"""ComputationGraph configuration: DAG of layers and vertices.

TPU-native equivalent of reference ``nn/conf/ComputationGraphConfiguration.java``
(GraphBuilder) and the vertex config classes in ``nn/conf/graph/`` mirrored by
runtime vertices in ``nn/graph/vertex/impl/`` (SURVEY.md §2.1 "Graph vertices":
LayerVertex, MergeVertex, ElementWiseVertex, SubsetVertex, Stack/UnstackVertex,
Scale/ShiftVertex, L2NormalizeVertex, L2Vertex, PreprocessorVertex,
ReshapeVertex, PoolHelperVertex, rnn Last/DuplicateToTimeSeries vertices).

Design shift: the reference splits each vertex into a config class and a
runtime ``GraphVertex`` with hand-written ``doForward``/``doBackward``; here a
vertex is ONE serializable dataclass whose ``forward(inputs, ctx)`` is a pure
jnp function — the whole DAG is traced into a single jitted step and AD derives
the backward pass, so there is no doBackward to maintain.

Data layout conventions follow :mod:`.preprocessors` (NHWC conv, [b,T,s] rnn).
"""
from __future__ import annotations

import copy
import dataclasses
from typing import Any, Dict, List, Optional

import jax.numpy as jnp

from .serde import register, to_json, from_json
from .inputs import (InputTypeFeedForward, InputTypeRecurrent,
                     InputTypeConvolutional, InputTypeConvolutionalFlat)
from .layers import Layer

__all__ = ["GraphVertexConf", "MergeVertex", "ElementWiseVertex", "SubsetVertex",
           "StackVertex", "UnstackVertex", "ScaleVertex", "ShiftVertex",
           "L2NormalizeVertex", "L2Vertex", "PreprocessorVertex",
           "ReshapeVertex", "PoolHelperVertex", "LastTimeStepVertex",
           "DuplicateToTimeSeriesVertex", "ComputationGraphConfiguration",
           "GraphBuilder"]


@dataclasses.dataclass
class GraphVertexConf:
    """Base non-layer vertex: pure function of its input activations."""

    def n_inputs(self):  # expected input arity; None = any
        return None

    def forward(self, inputs: List, ctx: Dict) -> Any:
        raise NotImplementedError

    def propagate_mask(self, in_masks: List):
        """Feature mask of this vertex's output given its inputs' masks
        (reference ``GraphVertex.feedForwardMaskArrays``)."""
        return in_masks[0] if in_masks else None

    def get_output_type(self, input_types: List):
        return input_types[0]


@register
@dataclasses.dataclass
class MergeVertex(GraphVertexConf):
    """Concatenate along the feature/channel axis (reference ``MergeVertex``).
    FF/RNN: last axis; CNN (NHWC): channel axis = last axis too."""

    def forward(self, inputs, ctx):
        return jnp.concatenate(inputs, axis=-1)

    def propagate_mask(self, in_masks):
        for m in in_masks:
            if m is not None:
                return m
        return None

    def get_output_type(self, input_types):
        t0 = input_types[0]
        if t0 is None:
            return None
        if isinstance(t0, InputTypeFeedForward):
            return InputTypeFeedForward(sum(t.size for t in input_types))
        if isinstance(t0, InputTypeRecurrent):
            return InputTypeRecurrent(sum(t.size for t in input_types),
                                      t0.timeseries_length)
        if isinstance(t0, InputTypeConvolutional):
            return InputTypeConvolutional(t0.height, t0.width,
                                          sum(t.channels for t in input_types))
        if isinstance(t0, InputTypeConvolutionalFlat):
            return InputTypeFeedForward(sum(t.arity() for t in input_types))
        raise ValueError(f"MergeVertex: unsupported input type {type(t0).__name__}")


@register
@dataclasses.dataclass
class ElementWiseVertex(GraphVertexConf):
    """Elementwise Add/Subtract/Product/Average/Max (reference
    ``ElementWiseVertex.Op``)."""
    op: str = "add"

    def forward(self, inputs, ctx):
        op = self.op.lower()
        if op == "add":
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if op == "subtract":
            if len(inputs) != 2:
                raise ValueError("subtract needs exactly 2 inputs")
            return inputs[0] - inputs[1]
        if op == "product":
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if op == "average":
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out / len(inputs)
        if op == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        raise ValueError(f"Unknown ElementWiseVertex op '{self.op}'")


@register
@dataclasses.dataclass
class SubsetVertex(GraphVertexConf):
    """Feature-range subset [from, to] inclusive (reference ``SubsetVertex``)."""
    from_idx: int = 0
    to_idx: int = 0

    def n_inputs(self):
        return 1

    def forward(self, inputs, ctx):
        return inputs[0][..., self.from_idx:self.to_idx + 1]

    def get_output_type(self, input_types):
        t = input_types[0]
        n = self.to_idx - self.from_idx + 1
        if isinstance(t, InputTypeRecurrent):
            return InputTypeRecurrent(n, t.timeseries_length)
        if isinstance(t, InputTypeConvolutional):
            return InputTypeConvolutional(t.height, t.width, n)
        return InputTypeFeedForward(n)


@register
@dataclasses.dataclass
class StackVertex(GraphVertexConf):
    """Concatenate along the batch (minibatch) axis (reference ``StackVertex``)."""

    def forward(self, inputs, ctx):
        return jnp.concatenate(inputs, axis=0)

    def propagate_mask(self, in_masks):
        if all(m is None for m in in_masks):
            return None
        if any(m is None for m in in_masks):
            raise ValueError("StackVertex: either all or no inputs must have "
                             "feature masks")
        return jnp.concatenate(in_masks, axis=0)


@register
@dataclasses.dataclass
class UnstackVertex(GraphVertexConf):
    """Inverse of StackVertex: take slice ``from_idx`` of ``stack_size`` equal
    batch chunks (reference ``UnstackVertex``)."""
    from_idx: int = 0
    stack_size: int = 1

    def n_inputs(self):
        return 1

    def forward(self, inputs, ctx):
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_idx * step:(self.from_idx + 1) * step]

    def propagate_mask(self, in_masks):
        m = in_masks[0]
        if m is None:
            return None
        step = m.shape[0] // self.stack_size
        return m[self.from_idx * step:(self.from_idx + 1) * step]


@register
@dataclasses.dataclass
class ScaleVertex(GraphVertexConf):
    scale: float = 1.0

    def n_inputs(self):
        return 1

    def forward(self, inputs, ctx):
        return inputs[0] * self.scale


@register
@dataclasses.dataclass
class ShiftVertex(GraphVertexConf):
    shift: float = 0.0

    def n_inputs(self):
        return 1

    def forward(self, inputs, ctx):
        return inputs[0] + self.shift


@register
@dataclasses.dataclass
class L2NormalizeVertex(GraphVertexConf):
    """x / ||x||_2 over all non-batch dims (reference ``L2NormalizeVertex``)."""
    eps: float = 1e-8

    def n_inputs(self):
        return 1

    def forward(self, inputs, ctx):
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        norm = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True))
        return x / (norm + self.eps)


@register
@dataclasses.dataclass
class L2Vertex(GraphVertexConf):
    """Pairwise L2 distance between two activations → [b, 1] (reference
    ``L2Vertex``)."""
    eps: float = 1e-8

    def n_inputs(self):
        return 2

    def forward(self, inputs, ctx):
        a, b = inputs
        d = a - b
        axes = tuple(range(1, d.ndim))
        return jnp.sqrt(jnp.sum(d * d, axis=axes) + self.eps)[:, None]

    def get_output_type(self, input_types):
        return InputTypeFeedForward(1)


@register
@dataclasses.dataclass
class PreprocessorVertex(GraphVertexConf):
    """Wraps an InputPreProcessor as a standalone vertex (reference
    ``PreprocessorVertex``)."""
    preprocessor: Any = None

    def n_inputs(self):
        return 1

    def forward(self, inputs, ctx):
        return self.preprocessor(inputs[0], ctx)

    def get_output_type(self, input_types):
        return self.preprocessor.get_output_type(input_types[0])


@register
@dataclasses.dataclass
class ReshapeVertex(GraphVertexConf):
    """Reshape to ``shape`` (batch dim preserved when shape[0] == -1;
    reference ``ReshapeVertex``)."""
    shape: Any = None

    def n_inputs(self):
        return 1

    def forward(self, inputs, ctx):
        return jnp.reshape(inputs[0], tuple(self.shape))


@register
@dataclasses.dataclass
class PoolHelperVertex(GraphVertexConf):
    """Strips the first row/column of a CNN activation — compatibility shim the
    reference ships for badly-padded imported GoogLeNet models (reference
    ``PoolHelperVertex``). NHWC here."""

    def n_inputs(self):
        return 1

    def forward(self, inputs, ctx):
        return inputs[0][:, 1:, 1:, :]

    def get_output_type(self, input_types):
        t = input_types[0]
        return InputTypeConvolutional(t.height - 1, t.width - 1, t.channels)


@register
@dataclasses.dataclass
class LastTimeStepVertex(GraphVertexConf):
    """[b,T,s] → [b,s] taking the last *unmasked* step. ``mask_input`` names the
    network input whose mask applies (reference ``rnn/LastTimeStepVertex``)."""
    mask_input: Optional[str] = None

    def n_inputs(self):
        return 1

    def forward(self, inputs, ctx):
        x = inputs[0]
        mask = (ctx or {}).get("input_masks", {}).get(self.mask_input)
        if mask is None:
            return x[:, -1, :]
        last = jnp.maximum(jnp.sum(mask > 0, axis=1).astype(jnp.int32) - 1, 0)
        return jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0, :]

    def propagate_mask(self, in_masks):
        return None  # output is [b, s]: the time dimension is gone

    def get_output_type(self, input_types):
        t = input_types[0]
        return InputTypeFeedForward(t.size if isinstance(t, InputTypeRecurrent)
                                    else t.arity())


@register
@dataclasses.dataclass
class DuplicateToTimeSeriesVertex(GraphVertexConf):
    """[b,s] → [b,T,s], T taken from the named network input's time length
    (reference ``rnn/DuplicateToTimeSeriesVertex``)."""
    reference_input: Optional[str] = None

    def n_inputs(self):
        return 1

    def forward(self, inputs, ctx):
        x = inputs[0]
        ref = (ctx or {}).get("inputs", {}).get(self.reference_input)
        if ref is None:
            raise ValueError(f"DuplicateToTimeSeriesVertex: reference input "
                             f"'{self.reference_input}' not found")
        T = ref.shape[1]
        return jnp.broadcast_to(x[:, None, :], (x.shape[0], T, x.shape[1]))

    def get_output_type(self, input_types):
        t = input_types[0]
        return InputTypeRecurrent(t.arity())


# ---------------------------------------------------------------------------


@register
@dataclasses.dataclass
class ComputationGraphConfiguration:
    """Reference ``nn/conf/ComputationGraphConfiguration.java``. ``vertices``
    maps name → Layer or GraphVertexConf; ``vertex_inputs`` maps name → input
    names (network inputs or other vertices)."""
    global_conf: Any = None
    network_inputs: List[str] = dataclasses.field(default_factory=list)
    network_outputs: List[str] = dataclasses.field(default_factory=list)
    vertices: Dict[str, Any] = dataclasses.field(default_factory=dict)
    vertex_inputs: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    input_preprocessors: Dict[str, Any] = dataclasses.field(default_factory=dict)
    input_types: Optional[List[Any]] = None
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20

    def topological_order(self) -> List[str]:
        """Kahn topological sort of vertex names (reference caches this at init,
        ``ComputationGraph.java:394``/``topologicalSortOrder()`` :1190)."""
        indeg = {}
        children = {n: [] for n in self.vertices}
        for name, ins in self.vertex_inputs.items():
            indeg[name] = 0
            for i in ins:
                if i in self.vertices:
                    indeg[name] += 1
                    children[i].append(name)
                elif i not in self.network_inputs:
                    raise ValueError(f"Vertex '{name}' input '{i}' is neither a "
                                     f"vertex nor a network input")
        ready = sorted(n for n in self.vertices if indeg.get(n, 0) == 0)
        order = []
        while ready:
            n = ready.pop(0)
            order.append(n)
            for ch in children[n]:
                indeg[ch] -= 1
                if indeg[ch] == 0:
                    ready.append(ch)
        if len(order) != len(self.vertices):
            cyc = set(self.vertices) - set(order)
            raise ValueError(f"Cycle in computation graph involving {sorted(cyc)}")
        return order

    def infer_shapes(self) -> Dict[str, Any]:
        """Propagate input types over the DAG: validate vertex arity,
        auto-insert layer preprocessors (reference ``addPreProcessors``), fill
        ``nIn``. Returns {vertex name → resolved InputType (or None)}. Used by
        both ``GraphBuilder.build`` and ``ComputationGraph.init`` (from_json
        configs arrive without resolved shapes)."""
        types: Dict[str, Any] = {}
        if self.input_types is not None:
            if len(self.input_types) != len(self.network_inputs):
                raise ValueError(f"{len(self.network_inputs)} inputs but "
                                 f"{len(self.input_types)} input types")
            types.update(zip(self.network_inputs, self.input_types))
        for name in self.topological_order():
            v = self.vertices[name]
            in_types = [types.get(i) for i in self.vertex_inputs[name]]
            if isinstance(v, Layer):
                it = in_types[0] if in_types else None
                if it is None:
                    types[name] = None
                    continue
                if name not in self.input_preprocessors:
                    p = v.preprocessor_for(it)
                    if p is not None:
                        self.input_preprocessors[name] = p
                if name in self.input_preprocessors:
                    it = self.input_preprocessors[name].get_output_type(it)
                v.set_n_in(it, override=False)
                types[name] = v.get_output_type(0, it)
            else:
                exp = v.n_inputs()
                if exp is not None and len(self.vertex_inputs[name]) != exp:
                    raise ValueError(f"Vertex '{name}' expects {exp} inputs, "
                                     f"got {len(self.vertex_inputs[name])}")
                types[name] = (None if any(t is None for t in in_types)
                               else v.get_output_type(in_types))
        return types

    def to_json(self) -> str:
        return to_json(self)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        obj = from_json(s)
        if not isinstance(obj, ComputationGraphConfiguration):
            raise ValueError("JSON does not describe a ComputationGraphConfiguration")
        return obj

    def clone(self):
        return copy.deepcopy(self)


class GraphBuilder:
    """Reference ``ComputationGraphConfiguration$GraphBuilder``: addInputs /
    addLayer / addVertex / setOutputs / setInputTypes / build."""

    def __init__(self, global_conf):
        self._global = global_conf
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._vertices: Dict[str, Any] = {}
        self._vertex_inputs: Dict[str, List[str]] = {}
        self._preprocessors: Dict[str, Any] = {}
        self._input_types = None
        self._backprop_type = "standard"
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def add_inputs(self, *names) -> "GraphBuilder":
        for n in names:
            if n in self._inputs or n in self._vertices:
                raise ValueError(f"Duplicate input name '{n}'")
            self._inputs.append(n)
        return self

    addInputs = add_inputs

    def _check_name(self, name):
        if name in self._vertices:
            raise ValueError(f"Duplicate vertex name '{name}'")
        if name in self._inputs:
            raise ValueError(f"Vertex name '{name}' collides with a network input")

    def add_layer(self, name, layer, *inputs, preprocessor=None) -> "GraphBuilder":
        self._check_name(name)
        ins = list(inputs)
        if len(ins) > 1:
            # reference auto-inserts a MergeVertex when a layer has >1 input
            merge_name = f"{name}-merge"
            self._vertices[merge_name] = MergeVertex()
            self._vertex_inputs[merge_name] = ins
            ins = [merge_name]
        self._vertices[name] = layer
        self._vertex_inputs[name] = ins
        if preprocessor is not None:
            self._preprocessors[name] = preprocessor
        return self

    addLayer = add_layer

    def add_vertex(self, name, vertex, *inputs) -> "GraphBuilder":
        self._check_name(name)
        self._vertices[name] = vertex
        self._vertex_inputs[name] = list(inputs)
        return self

    addVertex = add_vertex

    def set_outputs(self, *names) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    setOutputs = set_outputs

    def set_input_types(self, *types) -> "GraphBuilder":
        self._input_types = list(types)
        return self

    setInputTypes = set_input_types

    def input_preprocessor(self, layer_name, preproc) -> "GraphBuilder":
        self._preprocessors[layer_name] = preproc
        return self

    inputPreProcessor = input_preprocessor

    def backprop_type(self, t) -> "GraphBuilder":
        self._backprop_type = t
        return self

    backpropType = backprop_type

    def t_bptt_forward_length(self, n) -> "GraphBuilder":
        self._tbptt_fwd = int(n)
        return self

    tBPTTForwardLength = t_bptt_forward_length

    def t_bptt_backward_length(self, n) -> "GraphBuilder":
        self._tbptt_back = int(n)
        return self

    tBPTTBackwardLength = t_bptt_backward_length

    # ------------------------------------------------------------------
    def build(self) -> ComputationGraphConfiguration:
        if not self._inputs:
            raise ValueError("GraphBuilder: no network inputs (addInputs)")
        if not self._outputs:
            raise ValueError("GraphBuilder: no network outputs (setOutputs)")
        for out in self._outputs:
            if out not in self._vertices:
                raise ValueError(f"Output '{out}' is not a vertex")
        conf = ComputationGraphConfiguration(
            global_conf=self._global,
            network_inputs=list(self._inputs),
            network_outputs=list(self._outputs),
            vertices=dict(self._vertices),
            vertex_inputs=dict(self._vertex_inputs),
            input_preprocessors=dict(self._preprocessors),
            input_types=self._input_types,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
        )
        conf.infer_shapes()
        return conf
