"""JSON serde for configuration objects.

TPU-native equivalent of the reference's Jackson-based config serialization
(reference ``deeplearning4j-nn/.../nn/conf/serde/``, ``toJson/fromJson`` on
``MultiLayerConfiguration``/``ComputationGraphConfiguration``). Every config
dataclass registers here; objects round-trip through plain JSON dicts tagged
with ``"@class"`` so saved models (``ModelSerializer``) are self-describing.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Type

_REGISTRY: Dict[str, Type] = {}


def register(cls):
    """Class decorator: make a config dataclass JSON round-trippable."""
    _REGISTRY[cls.__name__] = cls
    return cls


def registered(name):
    return _REGISTRY[name]


def encode(obj) -> Any:
    """Recursively encode dataclasses / containers into JSON-able structures."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {"@class": type(obj).__name__}
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            out[f.name] = encode(v)
        return out
    if isinstance(obj, (list, tuple)):
        return [encode(v) for v in obj]
    if isinstance(obj, dict):
        return {str(k): encode(v) for k, v in obj.items()}
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    if hasattr(obj, "item") and getattr(obj, "ndim", None) == 0:  # numpy scalar
        return obj.item()
    raise TypeError(f"Cannot encode {type(obj)} ({obj!r}) to config JSON")


def decode(data) -> Any:
    """Inverse of :func:`encode`."""
    if isinstance(data, dict):
        if "@class" in data:
            d = dict(data)
            name = d.pop("@class")
            if name not in _REGISTRY:
                raise ValueError(f"Unknown config class '{name}' in JSON "
                                 f"(known: {sorted(_REGISTRY)})")
            cls = _REGISTRY[name]
            kwargs = {k: decode(v) for k, v in d.items()}
            # tolerate forward-compat extra keys
            names = {f.name for f in dataclasses.fields(cls)}
            kwargs = {k: v for k, v in kwargs.items() if k in names}
            obj = cls(**kwargs)
            # restore tuple-ness where the field default or type hints suggest it
            return obj
        return {k: decode(v) for k, v in data.items()}
    if isinstance(data, list):
        return [decode(v) for v in data]
    return data


def to_json(obj, indent=2) -> str:
    return json.dumps(encode(obj), indent=indent)


def from_json(s: str):
    return decode(json.loads(s))
