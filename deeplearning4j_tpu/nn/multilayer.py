"""MultiLayerNetwork: sequential network container.

TPU-native equivalent of reference ``nn/multilayer/MultiLayerNetwork.java``
(3156 LoC; ``fit`` :1156, ``feedForwardToLayer`` :903, ``computeGradientAndScore``
:2206, ``backprop`` :1267, TBPTT :1219).

Architectural shift (SURVEY.md §7): the reference executes op-by-op over JNI with a
mutable flattened param buffer (``:110/:601/:615``) and hand-written backprop; here
the whole step — forward, loss, AD backward, gradient normalization, updater, and
parameter update — is ONE jitted XLA computation with params/updater-state/layer-state
donated (the functional realization of the reference's in-place
``stepFunction.step``, ``StochasticGradientDescent.java:79``). Workspaces/CacheMode
(§2.8 item 3) collapse into XLA buffer donation + executable caching, which jit
gives us for free.

Training state (BN running stats, RNN streaming state) is explicit: ``states``
pytree and the TBPTT carry, replacing the reference's mutable layer fields.
"""
from __future__ import annotations

import contextlib
import logging
import time
from functools import partial
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .conf import (MultiLayerConfiguration, BackpropType, CacheMode,
                   GradientNormalization)
from .conf.inputs import (InputTypeConvolutional, InputTypeConvolutionalFlat,
                          InputTypeRecurrent)
from jax.ad_checkpoint import checkpoint_name

from .layers import impl_for
from .layers.base import remat_enabled, remat_policy
from .layers.recurrent import _BaseLSTMImpl
from ..datasets.dataset import DataSet, DataSetIterator, ListDataSetIterator
from ..datasets.prefetch import wrap_for_training
from ..optimize.updater import NetworkUpdater, normalize_gradients
from .. import monitor as _mon
from ..monitor.jitwatch import monitored_jit

log = logging.getLogger(__name__)

_tm = jax.tree_util.tree_map


def _n_iterations(gc):
    """Configured optimizer iterations per minibatch/segment (0.9.x
    ``iterations`` config), with the legacy-config fallback in ONE place."""
    return int(getattr(gc, "iterations", 1) or 1)


def _scan_iterations(step, n_iter, with_rnn_state=False):
    """Wrap a train-step fn in a ``lax.scan`` running ``n_iter`` optimizer
    iterations on the SAME minibatch inside one compiled program — the
    TPU-native realization of the reference's 0.9.x ``iterations`` config
    (``NeuralNetConfiguration.Builder.iterations``): small-model training
    pays the dispatch latency once per n steps. Same signature as ``step``;
    the iteration counter advances per scanned step and the rng is split so
    dropout differs across iterations; returns the LAST loss (and, on the
    TBPTT variant, the last rnn state — every iteration of a segment starts
    from the same carried-in state, reference solver-per-segment
    semantics)."""
    def scanned(params, states, upd_state, iteration, rng, f, l, fm, lm,
                rnn_state_in=None):
        def body(carry, i):
            params, states, upd_state, rng = carry
            rng, key = jax.random.split(rng)
            out = step(params, states, upd_state, iteration + i, key, f, l,
                       fm, lm, rnn_state_in)
            params, states, upd_state, loss = out[:4]
            extra = out[4] if with_rnn_state else None
            return (params, states, upd_state, rng), (loss, extra)
        (params, states, upd_state, _), (losses, extras) = jax.lax.scan(
            body, (params, states, upd_state, rng),
            jnp.arange(n_iter, dtype=jnp.int32))
        if with_rnn_state:
            last_rnn = _tm(lambda x: x[-1], extras)
            return params, states, upd_state, losses[-1], last_rnn
        return params, states, upd_state, losses[-1]
    return scanned


def _build_tbptt_scan(step, n_iter):
    """Jit a with-rnn-state train step into ONE program running the whole
    TBPTT segment loop (``lax.scan`` over stacked segments, params/updater/
    RNN state carried, segments detached by the step itself). Shared by
    MultiLayerNetwork AND ComputationGraph so the two containers' fused
    TBPTT semantics cannot drift. Inputs are segment-stacked pytrees
    ``[S, ...]`` (tuples of streams for the graph container ride through
    untouched — scan maps over every leaf's leading dim)."""
    if n_iter > 1:
        step = _scan_iterations(step, n_iter, with_rnn_state=True)

    def scanned(params, states, upd, it0, rng, f_s, l_s, fm_s, lm_s, rnn0):
        def body(carry, xs):
            params, states, upd, rnn, s = carry
            f_c, l_c, fm_c, lm_c = xs
            params, states, upd, loss, rnn = step(
                params, states, upd, it0 + s * n_iter,
                jax.random.fold_in(rng, s), f_c, l_c, fm_c, lm_c, rnn)
            return (params, states, upd, rnn, s + 1), loss

        init = (params, states, upd, rnn0, jnp.asarray(0, jnp.int32))
        (params, states, upd, _, _), losses = jax.lax.scan(
            body, init, (f_s, l_s, fm_s, lm_s))
        return params, states, upd, losses[-1]

    return monitored_jit(scanned, name="nn/tbptt_scan",
                         donate_argnums=(0, 2))


def _map_streams(fn, x):
    """Apply ``fn`` to every stream array — bare arrays (MultiLayerNetwork),
    tuples of optional streams (ComputationGraph), None passthrough. Exactly
    ``tree_map`` semantics; the alias names the intent at the call sites."""
    return jax.tree_util.tree_map(fn, x)


def _run_tbptt(net, f, l, fm, lm, single_iteration):
    """The TBPTT dispatch loop shared by BOTH containers (reference
    ``doTruncatedBPTT`` in `MultiLayerNetwork.java:1219` and
    `ComputationGraph.java`): equal segments fuse into ONE scanned program
    (segment stacking [b, T, ...] → [S, b, L, ...], rank-2 labels/static
    streams broadcast over S); a ragged tail falls back to per-segment
    dispatch with the (h, c) carries threaded on the host. Stream-shape
    differences between the containers are confined to ``_map_streams``."""
    conf, gc = net.conf, net.gc
    first = f[0] if isinstance(f, tuple) else f
    T = int(first.shape[1])
    L = conf.tbptt_fwd_length
    n_applied = 1 if single_iteration else _n_iterations(gc)
    if T % L == 0:
        S, b = T // L, int(first.shape[0])

        def stack(x):
            return jnp.swapaxes(x.reshape(b, S, L, *x.shape[2:]), 0, 1)

        def stack_lbl(x):
            return (stack(x) if x.ndim == 3
                    else jnp.broadcast_to(x, (S,) + x.shape))

        scan_step = net._ensure_tbptt_scan_step(single_iteration)
        it0 = jnp.asarray(net.iteration_count, jnp.int32)
        (net.params, net.states, net.updater_state, loss) = scan_step(
            net.params, net.states, net.updater_state, it0, net._next_rng(),
            _map_streams(stack, f), _map_streams(stack_lbl, l),
            _map_streams(stack, fm), _map_streams(stack, lm),
            net._init_rnn_state(b))
        # one iteration per TBPTT segment × iterations(n) applied per
        # segment (reference increments iterationCount per applied update,
        # so Adam bias correction and lr schedules see each one)
        net.iteration_count += S * n_applied
    else:
        step = net._ensure_tbptt_step(single_iteration=single_iteration)
        rnn_state = net._init_rnn_state(int(first.shape[0]))
        for start in range(0, T, L):
            sl = slice(start, min(start + L, T))
            it = jnp.asarray(net.iteration_count, jnp.int32)
            (net.params, net.states, net.updater_state, loss,
             rnn_state) = step(
                net.params, net.states, net.updater_state, it,
                net._next_rng(),
                _map_streams(lambda x: x[:, sl], f),
                _map_streams(lambda x: x[:, sl] if x.ndim == 3 else x, l),
                _map_streams(lambda x: x[:, sl], fm),
                _map_streams(lambda x: x[:, sl], lm), rnn_state)
            net.iteration_count += n_applied
    net.score_ = loss
    if net.listeners or _mon.enabled():
        score = float(loss)  # device→host value fetch: completion barrier
        _mon.record_training_iteration(net, net.iteration_count - 1, score,
                                       batch_size=int(first.shape[0]))
        for lst in net.listeners:
            lst.iteration_done(net, net.iteration_count - 1, score)


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.gc = conf.global_conf
        self.impls = None
        self.params = None          # {"0": {"W": ..., "b": ...}, ...}
        self.states = None          # non-trainable layer state
        self.updater = None         # NetworkUpdater
        self.updater_state = None
        self.iteration_count = 0
        self.epoch_count = 0
        self.listeners: List = []
        self.score_ = float("nan")
        self.last_batch_size = 0
        self.last_etl_ms = 0.0
        self.halt_requested = False  # TrainingHealthListener "halt" action
        self._rng = None
        self._jit_step = None
        self._jit_tbptt_step = None
        self._jit_output = {}
        self._rnn_state = None      # streaming state for rnn_time_step

    # ------------------------------------------------------------------ init
    def init(self, params=None):
        """Build layer impls and initialize parameters (reference ``init()`` :541)."""
        layers = self.conf.layers
        # resolve per-layer input types (best effort; None when unknown)
        input_types = [None] * len(layers)
        it = self.conf.input_type
        if it is not None:
            for i, lc in enumerate(layers):
                pre = self.conf.preprocessor(i)
                if pre is not None:
                    it = pre.get_output_type(it)
                input_types[i] = it
                lc.set_n_in(it, override=False)
                it = lc.get_output_type(i, it)
        from .conf.layers import FeedForwardLayer, DropoutLayer, LossLayer
        for i, lc in enumerate(layers):
            inner = getattr(lc, "inner", None) or lc
            if isinstance(inner, (DropoutLayer, LossLayer)):
                continue  # nIn/nOut not required (pass-through layers)
            if isinstance(inner, FeedForwardLayer):
                if inner.n_out is None:
                    raise ValueError(f"Layer {i} ({type(inner).__name__}): n_out "
                                     f"is not set")
                if inner.n_in is None:
                    raise ValueError(
                        f"Layer {i} ({type(inner).__name__}): n_in is not set — "
                        f"set n_in explicitly or call set_input_type(...) on the "
                        f"ListBuilder so it can be inferred")
        self.impls = []
        for i, lc in enumerate(layers):
            impl = impl_for(lc, self.gc, input_types[i])
            impl.index = i
            self.impls.append(impl)
        key = jax.random.PRNGKey(self.gc.seed)
        self._rng, *layer_keys = jax.random.split(key, len(layers) + 1)
        if params is not None:
            self.params = params
            self.states = {str(i): impl.init(layer_keys[i])[1]
                           for i, impl in enumerate(self.impls)}
        else:
            self.params = {}
            self.states = {}
            for i, impl in enumerate(self.impls):
                p, s = impl.init(layer_keys[i])
                self.params[str(i)] = p
                self.states[str(i)] = s
        # one updater per layer: per-layer override or global default
        layer_updaters = {}
        for i, lc in enumerate(layers):
            u = getattr(lc, "updater", None) or self.gc.updater
            layer_updaters[str(i)] = u
        self.updater = NetworkUpdater(layer_updaters)
        self.updater_state = self.updater.init_state(self.params)
        return self

    # -------------------------------------------------------------- forward
    def _apply_layers(self, params, states, x, fmask, train, rng, upto=None,
                      rnn_state_in=None):
        """Run layers [0, upto). Returns (x, new_states, rnn_state_out)."""
        n = len(self.impls)
        end = n if upto is None else upto
        keys = (jax.random.split(rng, end) if rng is not None else [None] * end)
        ctx = {}
        if rnn_state_in is not None:
            ctx["rnn_state_in"] = rnn_state_in
        new_states = dict(states)
        i = 0
        while i < end:
            pre = self.conf.preprocessor(i)
            if pre is not None:
                x = pre(x, ctx)
            impl = self.impls[i]
            # fused two-layer persistent LSTM (ops/lstm_fused.py): two
            # consecutive eligible LSTM layers run as ONE kernel chain —
            # half the sequential grid steps, no inter-layer HBM round
            # trip. Eligibility is static per (shape, config); ineligible
            # pairs (masks, bidirectional, dropout between, VMEM budget)
            # take the per-layer path below unchanged.
            if (i + 1 < end and self.conf.preprocessor(i + 1) is None
                    and self._lstm_pair_fusable(i, x, fmask, train)):
                x = self._fused_lstm_forward(params, x, train, keys[i],
                                             ctx, i)
                i += 2
                continue
            p_i = impl.noised_params(params[str(i)], train, keys[i])
            x, ns = impl.forward(p_i, states[str(i)], x, train=train,
                                 rng=keys[i], mask=fmask, ctx=ctx)
            if impl.save_output:
                # tag for the remat policy (identity outside jax.checkpoint)
                x = checkpoint_name(x, "dl4j_act")
            new_states[str(i)] = ns
            i += 1
        return x, new_states, ctx

    def _lstm_pair_fusable(self, i, x, fmask, train):
        """Static eligibility for fusing layers (i, i+1) into
        ``ops/lstm_fused.lstm_scan2``: both plain (non-bidirectional) LSTM
        impls with matching peephole-ness and H, no step mask, no
        inter-layer dropout or weight noise in effect, each layer
        kernel-eligible, and the fused VMEM budget admits the shape."""
        from .layers.recurrent import (_BaseLSTMImpl,
                                       GravesBidirectionalLSTMImpl)
        from ..ops import lstm_cell as _lk
        from ..ops import lstm_fused as _lf

        if fmask is not None or getattr(x, "ndim", 0) != 3:
            return False
        a, b_ = self.impls[i], self.impls[i + 1]
        for im in (a, b_):
            if (not isinstance(im, _BaseLSTMImpl)
                    or isinstance(im, GravesBidirectionalLSTMImpl)):
                return False
            if train and im.weight_noise is not None:
                return False
        if a.peepholes != b_.peepholes:
            return False
        if train and b_.dropout_obj is not None:
            return False
        ca, cb = a.conf, b_.conf
        if not (ca.n_out == cb.n_in == cb.n_out):
            return False
        bsz, T = x.shape[0], x.shape[1]
        H = ca.n_out
        wb = jnp.dtype(a.compute_dtype).itemsize
        for im, c in ((a, ca), (b_, cb)):
            gate = str(getattr(c, "gate_activation", "sigmoid"))
            if not _lk.supported(bsz, T, H, im.activation_name, gate,
                                 weight_bytes=wb):
                return False
        return _lf.supported2(bsz, T, H, weight_bytes=wb)

    def _fused_lstm_forward(self, params, x, train, rng, ctx, i):
        """Run layers (i, i+1) through the fused kernel. Mirrors
        ``recurrent._BaseLSTMImpl._run``'s hoisted input projection and
        ctx-carried (h, c) state handling for BOTH layer indices."""
        from ..ops import lstm_fused as _lf
        from .layers.base import acc_dtype
        from .layers.recurrent import _match_vma

        a, b_ = self.impls[i], self.impls[i + 1]
        x = a.maybe_dropout(x, train, rng)
        pa, pb = params[str(i)], params[str(i + 1)]
        cd = a.compute_dtype
        ad = acc_dtype(cd)
        bsz, T, _ = x.shape
        H = a.conf.n_out
        xp1 = (x.reshape(bsz * T, -1).astype(cd)
               @ pa["W"].astype(cd)).astype(ad)
        xp1 = xp1.reshape(bsz, T, 4 * H) + pa["b"].astype(ad)
        zeros = lambda: jnp.zeros((bsz, H), ad)
        sin = (ctx or {}).get("rnn_state_in", {})
        h01, c01 = sin.get(i) or (zeros(), zeros())
        h02, c02 = sin.get(i + 1) or (zeros(), zeros())
        # same shard_map carry-typing fix as recurrent._run (fresh zero
        # states are not device-varying; xp1 is)
        h01, c01 = _match_vma(h01, xp1), _match_vma(c01, xp1)
        h02, c02 = _match_vma(h02, xp1), _match_vma(c02, xp1)
        peep1 = ((pa["pi"], pa["pf"], pa["po"]) if a.peepholes else None)
        peep2 = ((pb["pi"], pb["pf"], pb["po"]) if b_.peepholes else None)
        ys2, hc1, hc2 = _lf.lstm_scan2(
            xp1, pa["RW"].astype(cd), peep1, pb["W"].astype(cd),
            pb["b"], pb["RW"].astype(cd), peep2, h01, c01, h02, c02)
        if ctx is not None:
            out = ctx.setdefault("rnn_state_out", {})
            out[i] = hc1
            out[i + 1] = hc2
        y = ys2.astype(b_.out_dtype)
        if b_.save_output:
            y = checkpoint_name(y, "dl4j_act")
        return y

    def _adapt_input(self, f):
        """User-facing convolutional input is NCHW (reference convention);
        internally NHWC. Transpose once at the boundary."""
        it = self.conf.input_type
        if isinstance(it, InputTypeConvolutional) and f.ndim == 4:
            # accept NCHW when channel dim matches conf
            if f.shape[1] == it.channels and f.shape[2] == it.height:
                return jnp.transpose(f, (0, 2, 3, 1))
        return f

    def _loss_fn(self, params, states, f, l, fm, lm, train, rng, rnn_state_in=None):
        n = len(self.impls)
        x, new_states, ctx = self._apply_layers(params, states, f, fm, train,
                                                rng, upto=n - 1,
                                                rnn_state_in=rnn_state_in)
        out_impl = self.impls[-1]
        pre = self.conf.preprocessor(n - 1)
        if pre is not None:
            x = pre(x, ctx)
        mask = lm if lm is not None else (fm if x.ndim == 3 else None)
        if not hasattr(out_impl, "loss_on"):
            raise ValueError(f"Last layer {type(out_impl).__name__} is not an "
                             f"output layer")
        loss = out_impl.loss_on(params[str(n - 1)], states[str(n - 1)], x, l,
                                mask=mask, train=train, rng=rng)
        if hasattr(out_impl, "update_state"):
            # e.g. CenterLossOutputLayer EMA centers — updated outside AD
            xs = jax.lax.stop_gradient(x)
            new_states[str(n - 1)] = out_impl.update_state(states[str(n - 1)],
                                                           xs, l)
        reg = 0.0
        for i, impl in enumerate(self.impls):
            reg = reg + impl.regularization(params[str(i)])
        # activation-dependent auxiliary losses (e.g. MoE load balancing)
        # accumulate in ctx during the forward pass
        aux = ctx.get("aux_loss", 0.0)
        return loss + reg + aux, (new_states, ctx.get("rnn_state_out"))

    # ---------------------------------------------------------- train step
    def _raw_update_core(self, grads_reduce=None):
        """Shared step core: loss → AD grads → gradient normalization →
        updater transform. Returns ``(updates, new_states, new_upd, loss,
        rnn_out)`` WITHOUT applying the update, so both ``_raw_step`` (apply
        in-graph) and ``_raw_update_step`` (ship the update through the
        SHARED_GRADIENTS codec) stay in lock-step by construction.

        ``grads_reduce(grads, loss, new_states) -> (grads, loss,
        new_states)``: optional cross-device reduction hook applied right
        after AD, BEFORE the minimize flip / normalization / updater —
        the seam ``parallel.sequence.sequence_parallel_step`` uses to psum
        time-sliced gradients while inheriting this core's remat/adapt/aux
        behavior instead of duplicating it."""
        gn_mode = self.gc.gradient_normalization
        gn_thresh = self.gc.gradient_normalization_threshold
        minimize = self.gc.minimize

        use_remat = remat_enabled(self.gc, self.impls)

        def core(params, states, upd_state, iteration, rng, f, l, fm, lm,
                 rnn_state_in=None):
            f = self._adapt_input(f)

            def loss_fn(p):
                return self._loss_fn(p, states, f, l, fm, lm, True, rng,
                                     rnn_state_in)

            if use_remat:
                loss_fn = jax.checkpoint(loss_fn, policy=remat_policy())
            (loss, (new_states, rnn_out)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            if grads_reduce is not None:
                grads, loss, new_states = grads_reduce(grads, loss,
                                                       new_states)
            if not minimize:
                grads = _tm(lambda g: -g, grads)
            grads = normalize_gradients(grads, gn_mode, gn_thresh)
            updates, new_upd = self.updater.apply(upd_state, grads, iteration)
            return updates, new_states, new_upd, loss, rnn_out

        return core

    def _raw_step(self, with_rnn_state):
        """The pure (unjitted) train-step function. ``_build_step`` jits it for
        single-device training; ``deeplearning4j_tpu.parallel`` re-jits it with
        explicit ``NamedSharding``s over a device mesh (SPMD data parallelism —
        the reference's ParallelWrapper role, SURVEY.md §2.4/§7 Phase 3)."""
        core = self._raw_update_core()

        def step(params, states, upd_state, iteration, rng, f, l, fm, lm,
                 rnn_state_in=None):
            updates, new_states, new_upd, loss, rnn_out = core(
                params, states, upd_state, iteration, rng, f, l, fm, lm,
                rnn_state_in)
            new_params = _tm(lambda p, u: p - u.astype(p.dtype), params,
                             updates)
            new_params = self._apply_constraints(new_params)
            if with_rnn_state:
                rnn_out = _tm(jax.lax.stop_gradient, rnn_out) if rnn_out else rnn_out
                return new_params, new_states, new_upd, loss, rnn_out
            return new_params, new_states, new_upd, loss

        return step

    def _raw_update_step(self, with_rnn_state=False):
        """Updater-transformed update without application — the
        SHARED_GRADIENTS wire seam: the reference encodes post-updater updates
        for peer broadcast (``SymmetricTrainer`` via
        ``EncodingHandler.java:136``), so the codec must see the update, not
        the raw gradient. ``with_rnn_state``: thread the detached RNN/KV
        carry through (TBPTT segments under SHARED_GRADIENTS)."""
        core = self._raw_update_core()

        def step(params, states, upd_state, iteration, rng, f, l, fm, lm,
                 rnn_state_in=None):
            updates, new_states, new_upd, loss, rnn_out = core(
                params, states, upd_state, iteration, rng, f, l, fm, lm,
                rnn_state_in)
            if with_rnn_state:
                rnn_out = (_tm(jax.lax.stop_gradient, rnn_out)
                           if rnn_out else rnn_out)
                return updates, new_states, new_upd, loss, rnn_out
            return updates, new_states, new_upd, loss

        return step

    def _apply_constraints(self, params):
        """Per-layer parameter constraints after each update (reference
        ``BaseConstraint.applyConstraint`` timing)."""
        from .conf.dropout import apply_constraints
        out = dict(params)
        for i, lc in enumerate(self.conf.layers):
            cons = getattr(lc, "constraints", None) or \
                getattr(getattr(lc, "inner", None), "constraints", None)
            if cons:
                out[str(i)] = apply_constraints(cons, params[str(i)])
        return out

    def _build_step(self, with_rnn_state, single_iteration=False):
        step = self._raw_step(with_rnn_state)
        n_iter = 1 if single_iteration else _n_iterations(self.gc)
        if n_iter > 1:
            step = _scan_iterations(step, n_iter, with_rnn_state)
        return monitored_jit(step, name="mln/step",
                             donate_argnums=(0, 2))

    def _ensure_step(self, single_iteration=False):
        if single_iteration and _n_iterations(self.gc) > 1:
            if getattr(self, "_jit_step_single", None) is None:
                self._jit_step_single = self._build_step(
                    with_rnn_state=False, single_iteration=True)
            return self._jit_step_single
        if self._jit_step is None:
            self._jit_step = self._build_step(with_rnn_state=False)
        return self._jit_step

    def _ensure_tbptt_step(self, single_iteration=False):
        if single_iteration and _n_iterations(self.gc) > 1:
            if getattr(self, "_jit_tbptt_step_single", None) is None:
                self._jit_tbptt_step_single = self._build_step(
                    with_rnn_state=True, single_iteration=True)
            return self._jit_tbptt_step_single
        if self._jit_tbptt_step is None:
            self._jit_tbptt_step = self._build_step(with_rnn_state=True)
        return self._jit_tbptt_step

    def _build_tbptt_scan_step(self, single_iteration=False):
        """The WHOLE TBPTT loop as one jitted program: ``lax.scan`` over
        stacked segments, carrying params/updater/RNN state (detached between
        segments by the inner step). One device dispatch per minibatch
        instead of one per segment — on a tunneled TPU each dispatch costs
        ~5 ms, so a 200-char/50-TBPTT batch saves 3 of 4 round trips (the
        LSTM-throughput lever from the round-3 VERDICT; same move as the
        ``iterations(n)`` scan, applied to the segment dimension)."""
        n_iter = 1 if single_iteration else _n_iterations(self.gc)
        return _build_tbptt_scan(self._raw_step(True), n_iter)

    def _ensure_tbptt_scan_step(self, single_iteration=False):
        cache = getattr(self, "_jit_tbptt_scan", None)
        if cache is None:
            cache = self._jit_tbptt_scan = {}
        key = bool(single_iteration)
        if key not in cache:
            cache[key] = self._build_tbptt_scan_step(single_iteration)
        return cache[key]

    def _next_rng(self):
        self._rng, k = jax.random.split(self._rng)
        return k

    # ----------------------------------------------------------------- fit
    def fit(self, data, labels=None, epochs=1):
        """Train (reference ``fit(DataSetIterator)`` :1156). Accepts a DataSet,
        a DataSetIterator, or (features, labels) arrays.

        .. note:: Timing caution (remote/tunneled TPU backends): steps are
           dispatched asynchronously and ``jax.block_until_ready`` has been
           observed to return BEFORE the device program finishes on tunneled
           backends. To time training reliably, gate on a device→host VALUE
           fetch — e.g. ``float(net.score_)`` / ``np.asarray(loss)`` — or
           attach :class:`deeplearning4j_tpu.utils.profiling.StepTimerListener`,
           which does this for you (see PERF.md addendum 2)."""
        if labels is not None:
            data = DataSet(np.asarray(data), np.asarray(labels))
        if isinstance(data, DataSet):
            data = ListDataSetIterator([data])
        if self.conf.pretrain and not getattr(self, "_pretrained", False):
            self.pretrain(data)
            self._pretrained = True
        # multi-worker prefetch + device-put-ahead (datasets/prefetch.py):
        # batch k+1 is transferred while step k computes, so etl_ms
        # measures a queue pop. DL4J_TPU_PREFETCH_WORKERS=0 restores the
        # fully synchronous path.
        it, own_pipeline = wrap_for_training(
            data, cache_device=self.gc.cache_mode == CacheMode.DEVICE)
        # a new fit() supersedes a previous health halt — without this, one
        # halt would silently truncate every later fit to a single batch
        self.halt_requested = False
        _mon.get_health().clear_halt()
        try:
            for epoch in range(epochs):
                for lst in self.listeners:
                    lst.on_epoch_start(self, self.epoch_count)
                with _mon.get_tracer().span("epoch", cat="train",
                                            epoch=self.epoch_count):
                    t_etl = time.perf_counter()
                    for ds in it:
                        self.last_etl_ms = (time.perf_counter() - t_etl) * 1e3
                        self._fit_batch(ds)
                        if self.halt_requested:
                            break
                        t_etl = time.perf_counter()
                for lst in self.listeners:
                    lst.on_epoch_end(self, self.epoch_count)
                self.epoch_count += 1
                if self.halt_requested:
                    log.warning("fit halted at epoch %d (halt_requested; see "
                                "TrainingHealthListener)", self.epoch_count)
                    break
        except BaseException as e:
            # error seam: listeners holding process-global resources (an
            # active ProfilerListener trace window) must release them
            # before the exception unwinds out of fit
            from ..optimize.listeners import dispatch_training_error
            dispatch_training_error(self, self.listeners, e)
            raise
        finally:
            if own_pipeline:
                it.shutdown()   # no prefetch worker outlives its fit
        return self

    def _fit_batch(self, ds: DataSet, single_iteration=False):
        """One minibatch. ``single_iteration=True`` applies exactly ONE
        optimizer update even when ``iterations(n)`` scans are configured —
        the ParallelWrapper tail-batch fallback needs update-count parity
        with its sharded dispatches (masks and TBPTT routing preserved)."""
        if self.gc.cache_mode == CacheMode.DEVICE:
            f, l, fm, lm = ds.device_arrays()
        else:
            f = jnp.asarray(ds.features)
            l = jnp.asarray(ds.labels)
            fm = (None if ds.features_mask is None
                  else jnp.asarray(ds.features_mask))
            lm = (None if ds.labels_mask is None
                  else jnp.asarray(ds.labels_mask))
        self.last_batch_size = int(f.shape[0])
        if (self.conf.backprop_type == BackpropType.TruncatedBPTT and f.ndim == 3
                and f.shape[1] > self.conf.tbptt_fwd_length):
            self._fit_tbptt(f, l, fm, lm, single_iteration=single_iteration)
            return
        step = self._ensure_step(single_iteration=single_iteration)
        it = jnp.asarray(self.iteration_count, jnp.int32)
        observe = bool(self.listeners) or _mon.enabled()
        score = None
        t0 = time.perf_counter()
        # span only when observing: without the float(loss) barrier inside
        # it, a span would record dispatch time and be worse than no data
        with (_mon.step_span(self.iteration_count) if observe
              else contextlib.nullcontext()):
            self.params, self.states, self.updater_state, loss = step(
                self.params, self.states, self.updater_state, it,
                self._next_rng(), f, l, fm, lm)
            if observe:
                # device→host VALUE fetch: the completion barrier that makes
                # the span (and step_ms) measure the step, not its dispatch
                score = float(loss)
        self.score_ = loss
        self.iteration_count += (1 if single_iteration
                                 else _n_iterations(self.gc))
        if observe:
            _mon.record_training_iteration(
                self, self.iteration_count - 1, score,
                batch_size=self.last_batch_size,
                step_ms=(time.perf_counter() - t0) * 1e3,
                etl_ms=self.last_etl_ms)
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration_count - 1, score)

    def _fit_tbptt(self, f, l, fm, lm, single_iteration=False):
        """Truncated BPTT (reference ``doTruncatedBPTT``): split time into
        chunks of tbptt_fwd_length, carry RNN state (detached) across chunks.
        Like the reference's practical behavior, the backward truncation equals
        the forward chunk length; a differing ``tbptt_back_length`` is treated
        as ``tbptt_fwd_length`` (warned once)."""
        if (self.conf.tbptt_back_length != self.conf.tbptt_fwd_length
                and not getattr(self, "_warned_tbptt", False)):
            log.warning("tbptt_back_length=%d differs from tbptt_fwd_length=%d; "
                        "backprop truncation uses the forward chunk length",
                        self.conf.tbptt_back_length, self.conf.tbptt_fwd_length)
            self._warned_tbptt = True
        _run_tbptt(self, f, l, fm, lm, single_iteration)

    def _init_rnn_state(self, batch):
        state = {}
        for i, impl in enumerate(self.impls):
            if hasattr(impl, "init_stream_state"):
                state[i] = impl.init_stream_state(batch)
        return state

    # -------------------------------------------------------------- pretrain
    def pretrain(self, iterator, epochs=1):
        """Layerwise unsupervised pretraining (reference ``pretrain(iter)``
        :1172): for each pretrain-capable layer (AutoEncoder, VAE), optimize its
        ``pretrain_loss`` on that layer's input activations."""
        for i, lc in enumerate(self.conf.layers):
            if lc.is_pretrain_layer():
                self.pretrain_layer(i, iterator, epochs=epochs)
        return self

    def pretrain_layer(self, layer_idx, iterator, epochs=1):
        """Reference ``pretrainLayer(int, DataSetIterator)``."""
        impl = self.impls[layer_idx]
        if not hasattr(impl, "pretrain_loss"):
            raise ValueError(f"Layer {layer_idx} ({type(impl).__name__}) is not "
                             f"a pretrainable layer")
        key = str(layer_idx)
        updater = self.updater.layer_updaters[key]

        def step(layer_params, upd_state, feats, rng, it):
            def loss_fn(p):
                return impl.pretrain_loss(p, feats, rng)
            loss, grads = jax.value_and_grad(loss_fn)(layer_params)
            updates, new_upd = updater.apply(upd_state, grads, it)
            new_params = _tm(lambda p, u: p - u.astype(p.dtype), layer_params,
                             updates)
            return new_params, new_upd, loss

        jstep = monitored_jit(step, name="mln/pretrain_step",
                              donate_argnums=(0, 1))
        upd_state = updater.init_state(self.params[key])
        it_count = 0
        for _ in range(epochs):
            for ds in iterator:
                x = jnp.asarray(ds.features)
                x = self._adapt_input(x)
                if layer_idx > 0:
                    x = self.feed_forward_to_layer(layer_idx - 1, x)
                p, upd_state, loss = jstep(self.params[key], upd_state, x,
                                           self._next_rng(),
                                           jnp.asarray(it_count, jnp.int32))
                self.params[key] = p
                it_count += 1
        self.score_ = loss
        return self

    # ------------------------------------------------------------- inference
    def output(self, x, train=False, mask=None):
        """Forward to activations of the last layer (reference ``output``).
        ``mask`` is the features mask for sequence inputs — affects mask-aware
        layers (bidirectional RNNs, global pooling) exactly as in training."""
        x = jnp.asarray(x)
        mask = None if mask is None else jnp.asarray(mask)
        key = (bool(train), mask is not None)
        if key not in self._jit_output:
            def fwd(params, states, f, fm):
                f = self._adapt_input(f)
                y, _, _ = self._apply_layers(params, states, f, fm, train, None)
                return y
            # jax.jit itself specializes per shape/dtype; one callable per
            # (train, has_mask) keeps the python-side cache bounded
            self._jit_output[key] = monitored_jit(fwd,
                                                  name="mln/output")
        return self._jit_output[key](self.params, self.states, x, mask)

    def feed_forward(self, x, train=False):
        """All layer activations, eager (reference ``feedForward`` list)."""
        x = jnp.asarray(x)
        x = self._adapt_input(x)
        acts = [x]
        ctx = {}
        for i, impl in enumerate(self.impls):
            pre = self.conf.preprocessor(i)
            if pre is not None:
                x = pre(x, ctx)
            x, _ = impl.forward(self.params[str(i)], self.states[str(i)], x,
                                train=train, rng=None, mask=None, ctx=ctx)
            acts.append(x)
        return acts

    feedForward = feed_forward

    def feed_forward_to_layer(self, layer_idx, x, train=False):
        """Reference ``feedForwardToLayer`` :903 (activation materialization
        point — partial-graph execution)."""
        x = jnp.asarray(x)
        x = self._adapt_input(x)
        ctx = {}
        for i in range(layer_idx + 1):
            pre = self.conf.preprocessor(i)
            if pre is not None:
                x = pre(x, ctx)
            x, _ = self.impls[i].forward(self.params[str(i)], self.states[str(i)],
                                         x, train=train, rng=None, mask=None,
                                         ctx=ctx)
        return x

    feedForwardToLayer = feed_forward_to_layer

    def rnn_time_step(self, x):
        """Stateful streaming inference (reference ``rnnTimeStep``)."""
        x = jnp.asarray(x)
        single_step = x.ndim == 2
        if single_step:
            x = x[:, None, :]
        if self._rnn_state is None:
            self._rnn_state = self._init_rnn_state(int(x.shape[0]))
        if getattr(self, "_jit_rnn_step", None) is None:
            # cached on self: jit re-traces per input shape, but a fresh
            # closure per call would recompile every streaming step
            def fwd(params, states, f, rnn_state):
                y, _, ctx = self._apply_layers(params, states, f, None, False,
                                               None, rnn_state_in=rnn_state)
                return y, ctx.get("rnn_state_out")
            self._jit_rnn_step = monitored_jit(fwd,
                                               name="mln/rnn_step")
        y, self._rnn_state = self._jit_rnn_step(self.params, self.states, x,
                                                self._rnn_state)
        return y[:, -1, :] if single_step else y

    rnnTimeStep = rnn_time_step

    def rnn_clear_previous_state(self):
        self._rnn_state = None

    rnnClearPreviousState = rnn_clear_previous_state

    # ----------------------------------------------------------------- score
    def score(self, ds: Optional[DataSet] = None, training=False):
        """Loss (+reg) on a dataset (reference ``score(DataSet)``), or last
        training score when called without arguments."""
        if ds is None:
            return float(self.score_)
        f = jnp.asarray(ds.features)
        l = jnp.asarray(ds.labels)
        fm = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
        lm = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
        key = (bool(training), fm is not None, lm is not None)
        if not hasattr(self, "_jit_score"):
            self._jit_score = {}
        if key not in self._jit_score:
            # jitted: early stopping / evaluative listeners call this every
            # epoch over the full validation set — eager tracing per batch
            # would make evaluation the epoch bottleneck on TPU
            def score_fn(params, states, f, l, fm, lm):
                f2 = self._adapt_input(f)
                loss, _ = self._loss_fn(params, states, f2, l, fm, lm,
                                        training, None)
                return loss
            self._jit_score[key] = monitored_jit(score_fn,
                                                 name="mln/score")
        loss = self._jit_score[key](self.params, self.states, f, l, fm, lm)
        return float(loss)

    def compute_gradient_and_score(self, ds: DataSet):
        """Reference ``computeGradientAndScore`` :2206 — returns (grads, score)
        without updating params (used by gradient checks and external
        optimizers)."""
        f = self._adapt_input(jnp.asarray(ds.features))
        l = jnp.asarray(ds.labels)
        fm = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
        lm = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)

        def loss_fn(p):
            loss, _ = self._loss_fn(p, self.states, f, l, fm, lm, True, None)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(self.params)
        self.score_ = loss
        return grads, float(loss)

    # ------------------------------------------------------------ evaluation
    def evaluate(self, iterator):
        from ..eval.evaluation import Evaluation
        ev = Evaluation()
        for ds in iterator:
            out = self.output(ds.features, mask=ds.features_mask)
            ev.eval(ds.labels, np.asarray(out),
                    mask=ds.labels_mask if ds.labels_mask is not None
                    else ds.features_mask)
        return ev

    def evaluate_regression(self, iterator):
        from ..eval.regression import RegressionEvaluation
        ev = RegressionEvaluation()
        for ds in iterator:
            out = self.output(ds.features)
            ev.eval(ds.labels, np.asarray(out))
        return ev

    # ------------------------------------------------------------ parameters
    def param_table(self):
        """{"0_W": array, ...} (reference ``paramTable()`` naming)."""
        out = {}
        for i in sorted(self.params, key=int):
            for k, v in self.params[i].items():
                out[f"{i}_{k}"] = v
        return out

    paramTable = param_table

    def get_param(self, key):
        i, k = key.split("_", 1)
        return self.params[i][k]

    def num_params(self) -> int:
        return sum(int(v.size) for v in jax.tree_util.tree_leaves(self.params))

    numParams = num_params

    def params_flat(self) -> np.ndarray:
        """Single flattened param vector, layer-major (reference's flattened
        params buffer ``MultiLayerNetwork.java:110``)."""
        chunks = []
        for i in sorted(self.params, key=int):
            for k in self.params[i]:
                chunks.append(np.asarray(self.params[i][k]).ravel())
        if not chunks:
            return np.zeros((0,), np.float32)
        return np.concatenate(chunks)

    def set_params_flat(self, vec):
        vec = np.asarray(vec)
        pos = 0
        new = {}
        for i in sorted(self.params, key=int):
            new[i] = {}
            for k, v in self.params[i].items():
                n = int(np.prod(v.shape)) if v.shape else 1
                new[i][k] = jnp.asarray(vec[pos:pos + n].reshape(v.shape),
                                        dtype=v.dtype)
                pos += n
        if pos != vec.size:
            raise ValueError(f"Param vector length {vec.size} != model {pos}")
        self.params = new

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    setListeners = set_listeners

    def add_listeners(self, *listeners):
        self.listeners.extend(listeners)
        return self

    # ------------------------------------------------------------------ misc
    def clone(self):
        net = MultiLayerNetwork(self.conf.clone())
        net.init()
        net.params = _tm(lambda x: x, self.params)
        net.states = _tm(lambda x: x, self.states)
        net.updater_state = _tm(lambda x: x, self.updater_state)
        return net

    @property
    def n_layers(self):
        return len(self.conf.layers)

    def summary(self) -> str:
        lines = [f"{'idx':>3}  {'type':<28} {'params':>10}"]
        for i, impl in enumerate(self.impls):
            n = impl.num_params(self.params[str(i)])
            lines.append(f"{i:>3}  {type(self.conf.layers[i]).__name__:<28} {n:>10}")
        lines.append(f"Total params: {self.num_params()}")
        return "\n".join(lines)
