"""Pooling implementations: Subsampling (spatial) and GlobalPooling.

TPU-native equivalents of reference ``nn/layers/convolution/subsampling/`` and
``nn/layers/pooling/GlobalPoolingLayer.java``. Windowed pools compile to
``lax.reduce_window`` (VPU-friendly); global RNN pooling is mask-aware like the
reference's ``MaskedReductionUtil``.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from .base import NoParamLayerImpl, implements
from ..conf.layers import ConvolutionMode, PoolingType, _pair


def _pool2d(x, kind, k, s, pad, pnorm=None, eps=1e-8):
    dims = (1, k[0], k[1], 1)
    strides = (1, s[0], s[1], 1)
    if kind == PoolingType.MAX:
        init = -jnp.inf
        y = lax.reduce_window(x, init, lax.max, dims, strides, pad)
        return y
    if kind in (PoolingType.AVG, PoolingType.SUM):
        y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
        if kind == PoolingType.SUM:
            return y
        if pad == "VALID":
            return y / (k[0] * k[1])
        ones = jnp.ones_like(x)
        counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pad)
        return y / jnp.maximum(counts, 1.0)
    if kind == PoolingType.PNORM:
        p = float(pnorm or 2)
        y = lax.reduce_window(jnp.power(jnp.abs(x), p), 0.0, lax.add, dims, strides, pad)
        return jnp.power(y + eps, 1.0 / p)
    raise ValueError(f"Unknown pooling type {kind}")


@implements("SubsamplingLayer")
class SubsamplingImpl(NoParamLayerImpl):
    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        c = self.conf
        k, s, p = _pair(c.kernel_size), _pair(c.stride), _pair(c.padding)
        if c.convolution_mode == ConvolutionMode.Same:
            pad = "SAME"
        elif p == (0, 0):
            pad = "VALID"
        else:
            pad = ((0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0))
        y = _pool2d(x, c.pooling_type, k, s, pad, c.pnorm, c.eps)
        return y, state


@implements("Subsampling1DLayer")
class Subsampling1DImpl(NoParamLayerImpl):
    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        c = self.conf
        k = _pair(c.kernel_size)[0]
        s = _pair(c.stride)[0]
        p = _pair(c.padding)[0]
        if c.convolution_mode == ConvolutionMode.Same:
            pad = "SAME"
        elif p == 0:
            pad = "VALID"
        else:
            pad = ((0, 0), (p, p), (0, 0), (0, 0))
        x4 = x[:, :, None, :]  # [b, T, 1, c]
        y = _pool2d(x4, c.pooling_type, (k, 1), (s, 1), pad, c.pnorm, c.eps)
        return y[:, :, 0, :], state


@implements("GlobalPoolingLayer")
class GlobalPoolingImpl(NoParamLayerImpl):
    """Pool over time ([b,T,s] → [b,s]) or space ([b,h,w,c] → [b,c]); mask-aware
    over the time dimension (reference ``GlobalPoolingLayer.java`` +
    ``MaskedReductionUtil``)."""

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        c = self.conf
        kind = c.pooling_type
        if x.ndim == 3:  # [b, T, s], mask [b, T]
            axes = (1,)
            if mask is not None:
                m = mask.astype(x.dtype)[:, :, None]
                if kind == PoolingType.MAX:
                    big_neg = jnp.asarray(-1e30, x.dtype)
                    return jnp.max(jnp.where(m > 0, x, big_neg), axis=1), state
                if kind == PoolingType.SUM:
                    return jnp.sum(x * m, axis=1), state
                if kind == PoolingType.AVG:
                    denom = jnp.maximum(jnp.sum(m, axis=1), 1.0)
                    return jnp.sum(x * m, axis=1) / denom, state
                if kind == PoolingType.PNORM:
                    p = float(c.pnorm)
                    return jnp.power(jnp.sum(jnp.power(jnp.abs(x) * m, p), axis=1),
                                     1.0 / p), state
        elif x.ndim == 4:  # [b, h, w, c]
            axes = (1, 2)
        else:
            raise ValueError(f"GlobalPoolingLayer: unsupported rank {x.ndim}")

        if kind == PoolingType.MAX:
            return jnp.max(x, axis=axes), state
        if kind == PoolingType.AVG:
            return jnp.mean(x, axis=axes), state
        if kind == PoolingType.SUM:
            return jnp.sum(x, axis=axes), state
        if kind == PoolingType.PNORM:
            p = float(c.pnorm)
            return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axes), 1.0 / p), state
        raise ValueError(f"Unknown pooling type {kind}")
