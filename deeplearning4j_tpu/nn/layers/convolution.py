"""Convolution family implementations.

TPU-native equivalents of reference ``nn/layers/convolution/`` (ConvolutionLayer,
ZeroPadding, Upsampling; cuDNN hook at ``ConvolutionLayer.java:76``). Convs run as
``lax.conv_general_dilated`` in NHWC/HWIO — XLA tiles them onto the MXU; the
reference's cuDNN algo-selection knobs have no equivalent because XLA owns
algorithm choice. bfloat16 compute with f32 accumulation via
``preferred_element_type``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .base import LayerImpl, NoParamLayerImpl, implements, acc_dtype, pet_dtype
from ..conf.layers import ConvolutionMode, _pair

_DN2D = ("NHWC", "HWIO", "NHWC")


def conv_padding(mode, k, s, p, d):
    """Per-dim (lo, hi) padding. Same → SAME semantics; Truncate/Strict → symmetric
    explicit padding (reference ``ConvolutionUtils``)."""
    if mode == ConvolutionMode.Same:
        return "SAME"
    return [(pi, pi) for pi in p]


@implements("ConvolutionLayer")
class Conv2DImpl(LayerImpl):
    """z = conv(x, W) + b; W stored HWIO [kh, kw, cin, cout] (reference stores
    [cout, cin, kh, kw]; layout chosen for XLA/TPU)."""

    def init(self, rng):
        c = self.conf
        kh, kw = _pair(c.kernel_size)
        fan_in = c.n_in * kh * kw
        fan_out = c.n_out * kh * kw
        params = {"W": self._init_w(rng, (kh, kw, c.n_in, c.n_out), fan_in, fan_out)}
        if getattr(c, "has_bias", True):
            params["b"] = self._init_b((c.n_out,))
        return params, {}

    def _conv(self, x, w):
        c = self.conf
        k, s, p, d = (_pair(c.kernel_size), _pair(c.stride), _pair(c.padding),
                      _pair(c.dilation))
        return lax.conv_general_dilated(
            x.astype(self.compute_dtype), w.astype(self.compute_dtype),
            window_strides=s,
            padding=conv_padding(c.convolution_mode, k, s, p, d),
            rhs_dilation=d,
            dimension_numbers=_DN2D,
            preferred_element_type=pet_dtype(self.compute_dtype))

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        x = self.maybe_dropout(x, train, rng)
        z = self._conv(x, params["W"])
        if "b" in params:
            z = z + params["b"].astype(z.dtype)
        return self.activation(z).astype(self.out_dtype), state


@implements("Convolution1DLayer")
class Conv1DImpl(LayerImpl):
    """1-D conv over [b, T, c] (reference ``Convolution1DLayer.java`` operates on
    [b, c, T]; layout difference documented in conf.preprocessors)."""

    def init(self, rng):
        c = self.conf
        k = _pair(c.kernel_size)[0]
        fan_in = c.n_in * k
        fan_out = c.n_out * k
        params = {"W": self._init_w(rng, (k, c.n_in, c.n_out), fan_in, fan_out)}
        if getattr(c, "has_bias", True):
            params["b"] = self._init_b((c.n_out,))
        return params, {}

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        c = self.conf
        x = self.maybe_dropout(x, train, rng)
        k = _pair(c.kernel_size)[0]
        s = _pair(c.stride)[0]
        p = _pair(c.padding)[0]
        d = _pair(c.dilation)[0]
        pad = "SAME" if c.convolution_mode == ConvolutionMode.Same else [(p, p)]
        z = lax.conv_general_dilated(
            x.astype(self.compute_dtype), params["W"].astype(self.compute_dtype),
            window_strides=(s,), padding=pad, rhs_dilation=(d,),
            dimension_numbers=("NHC", "HIO", "NHC"),
            preferred_element_type=pet_dtype(self.compute_dtype))
        if "b" in params:
            z = z + params["b"].astype(z.dtype)
        return self.activation(z).astype(self.out_dtype), state


@implements("Deconvolution2D")
class Deconv2DImpl(Conv2DImpl):
    """Transposed conv (reference ``Deconvolution2D``); implemented with
    ``lax.conv_transpose``."""

    def init(self, rng):
        c = self.conf
        kh, kw = _pair(c.kernel_size)
        fan_in = c.n_in * kh * kw
        fan_out = c.n_out * kh * kw
        params = {"W": self._init_w(rng, (kh, kw, c.n_in, c.n_out), fan_in, fan_out)}
        if getattr(c, "has_bias", True):
            params["b"] = self._init_b((c.n_out,))
        return params, {}

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        c = self.conf
        x = self.maybe_dropout(x, train, rng)
        s = _pair(c.stride)
        p = _pair(c.padding)
        d = _pair(c.dilation)
        if c.convolution_mode == ConvolutionMode.Same:
            pad = "SAME"
        else:
            # conv_transpose explicit pads are raw pads on the lhs-dilated
            # input; deconv padding p means out = s(i-1) + (k-1)d + 1 - 2p,
            # which needs per-side raw pad (k-1)d - p.
            k = _pair(c.kernel_size)
            pad = [((k[i] - 1) * d[i] - p[i], (k[i] - 1) * d[i] - p[i])
                   for i in range(2)]
        z = lax.conv_transpose(
            x.astype(self.compute_dtype), params["W"].astype(self.compute_dtype),
            strides=s, padding=pad, rhs_dilation=d, dimension_numbers=_DN2D,
            preferred_element_type=pet_dtype(self.compute_dtype))
        if "b" in params:
            z = z + params["b"].astype(z.dtype)
        return self.activation(z).astype(self.out_dtype), state


@implements("DepthwiseConvolution2D")
class DepthwiseConv2DImpl(LayerImpl):
    def init(self, rng):
        c = self.conf
        kh, kw = _pair(c.kernel_size)
        m = getattr(c, "depth_multiplier", 1)
        fan_in = kh * kw
        params = {"W": self._init_w(rng, (kh, kw, 1, c.n_in * m), fan_in, fan_in * m)}
        if getattr(c, "has_bias", True):
            params["b"] = self._init_b((c.n_in * m,))
        return params, {}

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        c = self.conf
        x = self.maybe_dropout(x, train, rng)
        s, p, d = _pair(c.stride), _pair(c.padding), _pair(c.dilation)
        pad = ("SAME" if c.convolution_mode == ConvolutionMode.Same
               else [(pi, pi) for pi in p])
        z = lax.conv_general_dilated(
            x.astype(self.compute_dtype), params["W"].astype(self.compute_dtype),
            window_strides=s, padding=pad, rhs_dilation=d,
            dimension_numbers=_DN2D, feature_group_count=c.n_in,
            preferred_element_type=pet_dtype(self.compute_dtype))
        if "b" in params:
            z = z + params["b"].astype(z.dtype)
        return self.activation(z).astype(self.out_dtype), state


@implements("SeparableConvolution2D")
class SeparableConv2DImpl(LayerImpl):
    """Depthwise + pointwise (reference ``SeparableConvolution2D``)."""

    def init(self, rng):
        c = self.conf
        kh, kw = _pair(c.kernel_size)
        m = getattr(c, "depth_multiplier", 1)
        k1, k2 = jax.random.split(rng)
        params = {
            "dW": self._init_w(k1, (kh, kw, 1, c.n_in * m), kh * kw, kh * kw * m),
            "pW": self._init_w(k2, (1, 1, c.n_in * m, c.n_out), c.n_in * m, c.n_out),
        }
        if getattr(c, "has_bias", True):
            params["b"] = self._init_b((c.n_out,))
        return params, {}

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        c = self.conf
        x = self.maybe_dropout(x, train, rng)
        s, p, d = _pair(c.stride), _pair(c.padding), _pair(c.dilation)
        pad = ("SAME" if c.convolution_mode == ConvolutionMode.Same
               else [(pi, pi) for pi in p])
        z = lax.conv_general_dilated(
            x.astype(self.compute_dtype), params["dW"].astype(self.compute_dtype),
            window_strides=s, padding=pad, rhs_dilation=d,
            dimension_numbers=_DN2D, feature_group_count=c.n_in,
            preferred_element_type=pet_dtype(self.compute_dtype))
        z = lax.conv_general_dilated(
            z.astype(self.compute_dtype), params["pW"].astype(self.compute_dtype),
            window_strides=(1, 1), padding="VALID", dimension_numbers=_DN2D,
            preferred_element_type=pet_dtype(self.compute_dtype))
        if "b" in params:
            z = z + params["b"].astype(z.dtype)
        return self.activation(z).astype(self.out_dtype), state


@implements("ZeroPaddingLayer")
class ZeroPaddingImpl(NoParamLayerImpl):
    save_output = False

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        t, b, l, r = self.conf._pads()
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state


@implements("ZeroPadding1DLayer")
class ZeroPadding1DImpl(NoParamLayerImpl):
    save_output = False

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        l, r = _pair(self.conf.padding)
        return jnp.pad(x, ((0, 0), (l, r), (0, 0))), state


@implements("Cropping2D")
class Cropping2DImpl(NoParamLayerImpl):
    save_output = False

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        t, b, l, r = self.conf._crops()
        h, w = x.shape[1], x.shape[2]
        return x[:, t:h - b or None, l:w - r or None, :], state


@implements("SpaceToDepthLayer")
class SpaceToDepthImpl(NoParamLayerImpl):
    save_output = False

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        bsz = int(self.conf.block_size)
        b, h, w, c = x.shape
        x = x.reshape(b, h // bsz, bsz, w // bsz, bsz, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // bsz, w // bsz, bsz * bsz * c)
        return x, state


@implements("Upsampling2D")
class Upsampling2DImpl(NoParamLayerImpl):
    """Nearest-neighbor upsampling (reference ``Upsampling2D.java``)."""

    save_output = False

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        sh, sw = _pair(self.conf.size)
        return jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2), state


@implements("Upsampling1D")
class Upsampling1DImpl(NoParamLayerImpl):
    save_output = False

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        return jnp.repeat(x, int(self.conf.size), axis=1), state
