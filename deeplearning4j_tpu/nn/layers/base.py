"""Layer implementation protocol and registry.

TPU-native equivalent of the reference's ``Layer`` runtime interface
(reference ``nn/api/Layer.java:38``: ``activate``/``backpropGradient``/``preOutput``)
and the per-layer impl tree ``nn/layers/`` (SURVEY.md §2.1 "Layer impls").

Central idiom shift (SURVEY.md §7 Phase 0): the reference dispatches every op over
JNI and hand-writes ``backpropGradient`` per layer; here each layer is a *pure
function* ``forward(params, state, x) -> (y, state)`` traced once into the jitted
training step, and the backward pass is ``jax.grad`` of the whole step. There is no
per-layer backprop code to keep in sync with forward — the cuDNN-helper
pattern (``ConvolutionLayer.java:76`` reflective Cudnn*Helper loading) maps to XLA
fusing + optional Pallas kernels registered per layer type in ``ops/``.

Every impl exposes:
 - ``init(rng) -> (params, state)``: params = trainable pytree ({"W": ..., "b": ...},
   reference param-name parity), state = non-trainable (BN running stats, ...)
 - ``forward(params, state, x, train, rng, mask, ctx) -> (y, new_state)``
 - ``regularization(params) -> scalar`` (l1/l2 penalty contribution)
"""
from __future__ import annotations

from typing import Dict, Optional, Type

import jax
import jax.numpy as jnp

from ..activations import get_activation
from ..weights import init_weight, host_full, WeightInit
from ..conf.layers import BaseLayer

_IMPL_REGISTRY: Dict[str, Type["LayerImpl"]] = {}


def implements(*config_class_names):
    def deco(cls):
        for n in config_class_names:
            _IMPL_REGISTRY[n] = cls
        return cls
    return deco


def impl_for(conf, global_conf, input_type=None) -> "LayerImpl":
    name = type(conf).__name__
    if name not in _IMPL_REGISTRY:
        raise ValueError(f"No layer implementation registered for config '{name}'")
    return _IMPL_REGISTRY[name](conf, global_conf, input_type)


def _resolved(conf, gc, field, default=None):
    v = getattr(conf, field, None)
    if v is None:
        v = getattr(gc, field, None)
    if v is None:
        v = default
    return v


class LayerImpl:
    """Base implementation; resolves per-layer vs global config fields."""

    #: Whether this layer's output is worth storing for the backward pass.
    #: Under the train step's remat policy (``GlobalConfig.remat``), outputs of
    #: layers with ``save_output=True`` (convs, gemms, pooling — expensive to
    #: recompute) are checkpointed; cheap elementwise layers (BN normalize,
    #: activations, dropout, padding) are recomputed during the backward pass
    #: instead of being written to and re-read from HBM. This is the TPU
    #: answer to the reference's workspace memory management
    #: (``WorkspaceMode``, ``nn/conf/WorkspaceMode.java``): activation
    #: residency is a compiler-visible policy, not a buffer pool.
    save_output = True

    def __init__(self, conf, gc, input_type=None):
        self.conf = conf
        self.gc = gc
        self.input_type = input_type
        self.dtype = jnp.dtype(gc.dtype)
        self.compute_dtype = jnp.dtype(gc.compute_dtype)
        # Mixed-precision activation policy: params live in `dtype` (f32
        # master copies), activations flow between layers in the compute
        # dtype when it is sub-32-bit (bfloat16). Casting every layer output
        # back to f32 — the naive reading of the reference's single global
        # dtype — doubles HBM traffic on conv nets, and HBM bandwidth is the
        # TPU bottleneck (see PERF.md).
        self.out_dtype = (self.compute_dtype
                          if self.compute_dtype.itemsize < 4 else self.dtype)
        if isinstance(conf, BaseLayer):
            self.activation_name = _resolved(conf, gc, "activation", "identity")
            self.activation = get_activation(self.activation_name)
            self.weight_init = _resolved(conf, gc, "weight_init", WeightInit.XAVIER)
            self.dist = _resolved(conf, gc, "dist")
            self.bias_init = float(_resolved(conf, gc, "bias_init", 0.0))
            self.l1 = float(_resolved(conf, gc, "l1", 0.0))
            self.l2 = float(_resolved(conf, gc, "l2", 0.0))
            self.l1_bias = float(_resolved(conf, gc, "l1_bias", 0.0))
            self.l2_bias = float(_resolved(conf, gc, "l2_bias", 0.0))
        from ..conf.dropout import resolve_dropout
        # float (retain prob) or IDropout object → unified apply() object
        self.dropout_p = _resolved(conf, gc, "dropout")
        self.dropout_obj = resolve_dropout(self.dropout_p)
        self.weight_noise = getattr(conf, "weight_noise", None)

    # ------------------------------------------------------------------
    def init(self, rng):
        return {}, {}

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        raise NotImplementedError

    # ------------------------------------------------------------------
    def _init_w(self, rng, shape, fan_in, fan_out):
        return init_weight(rng, shape, fan_in, fan_out, self.weight_init, self.dist,
                           self.dtype)

    def _init_b(self, shape, value=None):
        v = self.bias_init if value is None else value
        return host_full(shape, v, self.dtype)

    def maybe_dropout(self, x, train, rng):
        """Input dropout/noise (reference ``BaseLayer.preOutput`` input
        dropout). Accepts the float retain-probability shorthand or any
        IDropout object (Dropout, AlphaDropout, GaussianDropout,
        GaussianNoise)."""
        if self.dropout_obj is None or not train or rng is None:
            return x
        return self.dropout_obj.apply(x, rng, train)

    def noised_params(self, params, train, rng):
        """Apply weight noise (DropConnect/WeightNoise) for this forward pass
        (reference ``weightnoise`` applied on param views per iteration)."""
        wn = self.weight_noise
        if wn is None or not train or rng is None or not params:
            return params
        out = {}
        for i, (k, v) in enumerate(params.items()):
            out[k] = wn.apply_to_weights(v, k, jax.random.fold_in(rng, i),
                                         train)
        return out

    def cast_in(self, *arrays):
        """Cast to compute dtype (bfloat16 policy targets the MXU)."""
        out = tuple(a.astype(self.compute_dtype) if a is not None else None
                    for a in arrays)
        return out if len(out) > 1 else out[0]

    def regularization(self, params):
        """L1/L2 penalty, matching reference ``BaseLayer.calcL1/calcL2``:
        applied to weight params ("W"-like) and biases separately."""
        if not params:
            return 0.0
        total = 0.0
        for k, v in params.items():
            if _is_bias_key(k):
                if self.l1_bias:
                    total = total + self.l1_bias * jnp.sum(jnp.abs(v))
                if self.l2_bias:
                    total = total + 0.5 * self.l2_bias * jnp.sum(v * v)
            else:
                if self.l1:
                    total = total + self.l1 * jnp.sum(jnp.abs(v))
                if self.l2:
                    total = total + 0.5 * self.l2 * jnp.sum(v * v)
        return total

    def num_params(self, params):
        return sum(int(v.size) for v in jax.tree_util.tree_leaves(params))


def remat_enabled(gc, impls) -> bool:
    """Whether the jitted train step should run under the named-saveable
    remat policy (``GlobalConfig.remat``). "auto" enables it for
    convolutional feed-forward nets — where activation HBM round-trips
    dominate the step — and leaves recurrent nets alone (scan residuals
    interact badly with whole-step remat)."""
    mode = getattr(gc, "remat", "off")
    if mode == "on":
        return True
    if mode != "auto":
        return False

    def unwrap(i):
        # wrapper impls (Frozen, Bidirectional, LastTimeStep) hide the inner
        # layer behind .inner — recurse so a wrapped LSTM still counts as
        # recurrent
        seen = []
        while i is not None:
            seen.append(i)
            i = getattr(i, "inner", None)
        return seen

    flat = [j for i in impls for j in unwrap(i)]
    has_conv = any(getattr(j.conf, "kernel_size", None) is not None
                   for j in flat)
    # scan-carrying layers (true RNNs) defeat the named-saveable policy;
    # attention has a stream state (KV cache) but its training forward is
    # scan-free, so it must not disable remat for conv+attention nets
    has_rnn = any(hasattr(j, "init_stream_state")
                  and not getattr(j, "scan_free_training", False)
                  for j in flat)
    return has_conv and not has_rnn


#: jax.checkpoint policy saving exactly the tensors the layer protocol tags:
#: layer outputs flagged ``save_output`` ("dl4j_act") and BN statistics
#: ("dl4j_stat"). Everything else is recomputed during the backward pass.
def remat_policy():
    return jax.checkpoint_policies.save_only_these_names("dl4j_act",
                                                         "dl4j_stat")


def acc_dtype(compute_dtype):
    """Accumulator/stats dtype: f32 when computing in a sub-32-bit dtype
    (bf16/f16), otherwise the compute dtype itself — forcing f32 under f64
    compute would silently truncate, breaking the f64 gradient-check path.
    Used for BN statistics, RNN carries and softmax accumulation."""
    cd = jnp.dtype(compute_dtype)
    return jnp.dtype(jnp.float32) if cd.itemsize < 4 else cd


def pet_dtype(compute_dtype):
    """``preferred_element_type`` for dots/convs. For sub-32-bit compute the
    answer is None: XLA's TPU MXU already accumulates bf16 operands in f32
    internally, and requesting an f32 *output* breaks the conv-transpose
    dtype rule under AD (cotangent f32 vs operand bf16). For f32/f64 compute
    the compute dtype itself keeps results exact."""
    cd = jnp.dtype(compute_dtype)
    return None if cd.itemsize < 4 else cd


def _is_bias_key(k: str) -> bool:
    return k == "b" or k.endswith("_b") or k in ("beta",)


class NoParamLayerImpl(LayerImpl):
    def init(self, rng):
        return {}, {}

    def regularization(self, params):
        return 0.0
