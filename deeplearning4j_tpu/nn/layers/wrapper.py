"""Wrapper layer implementations: FrozenLayer.

TPU-native equivalent of reference ``nn/layers/FrozenLayer.java``: the inner
layer runs normally but its params receive no gradient — implemented with
``jax.lax.stop_gradient`` instead of the reference's no-op updater trick.
"""
from __future__ import annotations

import jax

from .base import LayerImpl, implements, impl_for


@implements("FrozenLayer")
class FrozenImpl(LayerImpl):
    def __init__(self, conf, gc, input_type=None):
        super().__init__(conf, gc, input_type)
        self.inner = impl_for(conf.inner, gc, input_type)

    def init(self, rng):
        return self.inner.init(rng)

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        frozen = jax.tree_util.tree_map(jax.lax.stop_gradient, params)
        return self.inner.forward(frozen, state, x, train=train, rng=rng,
                                  mask=mask, ctx=ctx)

    def loss_on(self, params, state, x, labels, mask=None, train=True, rng=None):
        frozen = jax.tree_util.tree_map(jax.lax.stop_gradient, params)
        return self.inner.loss_on(frozen, state, x, labels, mask=mask, train=train,
                                  rng=rng)

    def regularization(self, params):
        return 0.0
