"""Mixture-of-experts dense layer (expert parallelism).

Net-new vs the 0.9.x reference (SURVEY.md §2.4: data parallelism only), the
``expert`` counterpart to the net-new tensor/sequence/pipeline axes. Dense
top-k dispatch in einsum form so the expert dimension is a *shardable array
axis*: with ``W: [E, n_in, n_out]`` sharded over the mesh ``expert`` axis
(``parallel/expert.py``), XLA partitions the per-expert einsum so each device
computes only its expert shard and the final expert-dim reduction lowers to a
psum over ICI — expert parallelism without a hand-written all-to-all.

The Switch-Transformer load-balancing auxiliary loss (num_experts × Σ_e
fraction_of_tokens_routed_to_e × mean_gate_prob_e) accumulates through the
forward ``ctx`` into the training objective (``nn/multilayer.py`` /
``nn/graph.py`` add ``ctx['aux_loss']`` to loss+reg).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import LayerImpl, implements, pet_dtype


@implements("MoEDenseLayer")
class MoEDenseImpl(LayerImpl):
    def init(self, rng):
        c = self.conf
        E = c.num_experts
        if E < 1 or not (1 <= c.top_k <= E):
            raise ValueError(f"MoEDenseLayer needs 1 <= top_k <= num_experts "
                             f"(got top_k={c.top_k}, num_experts={E})")
        if c.capacity_factor < 0:
            raise ValueError(f"capacity_factor must be >= 0 "
                             f"(got {c.capacity_factor})")
        kg, kw = jax.random.split(rng)
        params = {
            # router: small, always f32-precision-critical
            "Wg": self._init_w(kg, (c.n_in, E), c.n_in, E),
            # per-expert dense weights, expert dim leading (shardable)
            "W": self._init_w(kw, (E, c.n_in, c.n_out), c.n_in, c.n_out),
        }
        if c.has_bias:
            params["b"] = self._init_b((E, c.n_out))
        return params, {}

    def _router_dtype(self):
        """Router math runs at least f32 (precision-critical softmax), and
        full f64 under the gradient-check dtype policy."""
        return jnp.promote_types(jnp.float32, self.dtype)

    def _route(self, xr, Wg):
        """Top-k gates: softmax over experts, keep the k largest, renormalize.
        Returns gates [b, E] (zero outside the top-k) and the full probs."""
        c = self.conf
        logits = xr @ Wg.astype(xr.dtype)
        probs = jax.nn.softmax(logits, axis=-1)
        if c.top_k >= c.num_experts:
            return probs, probs
        # index-based mask: exactly top_k experts even on tied probs (a
        # threshold mask would gate ALL experts for an all-uniform row)
        _, idxs = jax.lax.top_k(probs, c.top_k)
        mask = jnp.sum(jax.nn.one_hot(idxs, c.num_experts, dtype=probs.dtype),
                       axis=-2)
        gates = probs * mask
        gates = gates / jnp.sum(gates, axis=-1, keepdims=True)
        return gates, probs

    def _dense_combine(self, params, flat, gates, cd):
        """Dense (Shazeer-style) path — every token through every expert,
        gate-masked. O(n·E·F·O) FLOPs; the correctness oracle for the sparse
        dispatch below."""
        h = jnp.einsum("nf,efo->neo", flat.astype(cd),
                       params["W"].astype(cd),
                       preferred_element_type=pet_dtype(cd))
        if "b" in params:
            h = h + params["b"].astype(h.dtype)
        # gate-weighted combine; reduction over E → psum when E is sharded
        return jnp.einsum("ne,neo->no", gates.astype(h.dtype), h,
                          preferred_element_type=pet_dtype(cd))

    def _capacity(self, n):
        c = self.conf
        k = min(c.top_k, c.num_experts)
        cap = -(-k * n * c.capacity_factor // c.num_experts)
        return int(min(max(8, -(-cap // 8) * 8), max(8, -(-n // 8) * 8)))

    def _sparse_combine(self, params, flat, gates, cd):
        """Capacity-factor token dispatch (GShard/Switch one-hot einsum form):
        each expert computes a fixed [C, F] buffer of its routed tokens, so
        expert FLOPs are E·C·F·O ≈ (top_k/E)·dense instead of n·E·F·O.

        Tokens are processed in GROUPS of ``conf.group_size`` (the GShard
        group dim): capacity is enforced per group, so the one-hot dispatch
        tensor is [g, G, E, C_g] with C_g ∝ G — memory LINEAR in token
        count (n·k·G·cf elements) instead of the groupless [n, E, C]
        (C ∝ n ⇒ quadratic: the T=8k flagship would need multi-GB dispatch
        intermediates). A short token run (n ≤ G) is a single group, so
        small-batch behavior is unchanged.

        Buffer positions are assigned slot-major within each group (all
        rank-0 assignments before rank-1), so when an expert overflows its
        per-group capacity the LOWER-gate assignments are the ones dropped.
        Dropped (token, expert) pairs simply contribute zero —
        Switch-Transformer semantics. The dispatch tensor stays
        one-hot/shardable: with ``W`` sharded over the mesh 'expert' axis
        the per-expert einsums partition and the combine reduction lowers
        to a psum, same as the dense path."""
        c = self.conf
        n, E = flat.shape[0], c.num_experts
        k = min(c.top_k, E)
        G = max(8, min(n, int(getattr(c, "group_size", 1024) or 1024)))
        g = -(-n // G)
        pad = g * G - n
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad, flat.shape[1]), flat.dtype)], axis=0)
            gates = jnp.concatenate(
                [gates, jnp.zeros((pad, E), gates.dtype)], axis=0)
        C = self._capacity(G)
        xg = flat.reshape(g, G, -1)
        gg = gates.reshape(g, G, E)
        _, idxs = jax.lax.top_k(gg, k)                       # [g, G, k]
        mask = jax.nn.one_hot(idxs, E, dtype=jnp.int32)      # [g, G, k, E]
        if pad:
            # top_k on a padding row's all-zero gates still one-hots experts
            # 0..k-1; zero those mask rows so pads claim no buffer slots
            # (they'd otherwise displace real low-gate assignments in the
            # tail group)
            valid = (jnp.arange(g * G) < n).astype(jnp.int32).reshape(g, G)
            mask = mask * valid[:, :, None, None]
        mk = mask.transpose(0, 2, 1, 3).reshape(g, k * G, E)  # slot-major
        pos = jnp.cumsum(mk, axis=1) - 1                     # per-expert fill
        pos_t = jnp.sum(pos * mk, axis=-1)                   # [g, k*G]
        keep = (pos_t < C) & (jnp.sum(mk, axis=-1) > 0)
        slot = (jax.nn.one_hot(pos_t, C, dtype=cd)
                * keep[..., None].astype(cd))                # [g, k*G, C]
        disp = (mk.astype(cd)[..., None] * slot[..., None, :])
        disp = disp.reshape(g, k, G, E, C).sum(axis=1)       # [g, G, E, C]
        combine = disp * gg.astype(cd)[..., None]
        expert_in = jnp.einsum("gnec,gnf->egcf", disp, xg.astype(cd),
                               preferred_element_type=pet_dtype(cd))
        h = jnp.einsum("egcf,efo->egco", expert_in, params["W"].astype(cd),
                       preferred_element_type=pet_dtype(cd))
        if "b" in params:
            h = h + params["b"].astype(h.dtype)[:, None, None, :]
        y = jnp.einsum("gnec,egco->gno", combine, h,
                       preferred_element_type=pet_dtype(cd))
        return y.reshape(g * G, -1)[:n]

    def forward(self, params, state, x, train=False, rng=None, mask=None,
                ctx=None):
        c = self.conf
        x = self.maybe_dropout(x, train, rng)
        flat = x.reshape(-1, x.shape[-1])                # [n, F] (rnn-safe)
        rdt = self._router_dtype()
        gates, probs = self._route(flat.astype(rdt), params["Wg"])

        cd = self.compute_dtype
        # capacity dispatch only under TRAINING: dropping over-capacity
        # assignments is a throughput/utilization device for the train step
        # (Switch semantics); inference routes exactly, so output()/score()/
        # rnn_time_step agree with each other regardless of batch shape —
        # capacity is a function of n, and streaming steps see tiny n
        if c.capacity_factor and c.capacity_factor > 0 and train:
            y = self._sparse_combine(params, flat, gates, cd)
        else:
            y = self._dense_combine(params, flat, gates, cd)
        y = y.reshape(x.shape[:-1] + (c.n_out,))

        if ctx is not None and c.aux_loss_weight > 0.0:
            # Switch load-balancing loss: E * Σ_e f_e · P_e, where f_e is the
            # fraction of tokens whose TOP-1 expert is e and P_e the mean
            # router probability for e; minimized (=1) at uniform routing
            top1 = jnp.argmax(probs, axis=-1)
            f = jnp.mean(jax.nn.one_hot(top1, c.num_experts, dtype=rdt),
                         axis=0)
            P = jnp.mean(probs, axis=0)
            aux = c.aux_loss_weight * c.num_experts * jnp.sum(f * P)
            ctx["aux_loss"] = ctx.get("aux_loss", 0.0) + aux

        return self.activation(y).astype(self.out_dtype), state
