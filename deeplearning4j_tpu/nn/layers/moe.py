"""Mixture-of-experts dense layer (expert parallelism).

Net-new vs the 0.9.x reference (SURVEY.md §2.4: data parallelism only), the
``expert`` counterpart to the net-new tensor/sequence/pipeline axes. Dense
top-k dispatch in einsum form so the expert dimension is a *shardable array
axis*: with ``W: [E, n_in, n_out]`` sharded over the mesh ``expert`` axis
(``parallel/expert.py``), XLA partitions the per-expert einsum so each device
computes only its expert shard and the final expert-dim reduction lowers to a
psum over ICI — expert parallelism without a hand-written all-to-all.

The Switch-Transformer load-balancing auxiliary loss (num_experts × Σ_e
fraction_of_tokens_routed_to_e × mean_gate_prob_e) accumulates through the
forward ``ctx`` into the training objective (``nn/multilayer.py`` /
``nn/graph.py`` add ``ctx['aux_loss']`` to loss+reg).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import LayerImpl, implements, pet_dtype


@implements("MoEDenseLayer")
class MoEDenseImpl(LayerImpl):
    def init(self, rng):
        c = self.conf
        E = c.num_experts
        kg, kw = jax.random.split(rng)
        params = {
            # router: small, always f32-precision-critical
            "Wg": self._init_w(kg, (c.n_in, E), c.n_in, E),
            # per-expert dense weights, expert dim leading (shardable)
            "W": self._init_w(kw, (E, c.n_in, c.n_out), c.n_in, c.n_out),
        }
        if c.has_bias:
            params["b"] = self._init_b((E, c.n_out))
        return params, {}

    def _router_dtype(self):
        """Router math runs at least f32 (precision-critical softmax), and
        full f64 under the gradient-check dtype policy."""
        return jnp.promote_types(jnp.float32, self.dtype)

    def _route(self, xr, Wg):
        """Top-k gates: softmax over experts, keep the k largest, renormalize.
        Returns gates [b, E] (zero outside the top-k) and the full probs."""
        c = self.conf
        logits = xr @ Wg.astype(xr.dtype)
        probs = jax.nn.softmax(logits, axis=-1)
        if c.top_k >= c.num_experts:
            return probs, probs
        # index-based mask: exactly top_k experts even on tied probs (a
        # threshold mask would gate ALL experts for an all-uniform row)
        _, idxs = jax.lax.top_k(probs, c.top_k)
        mask = jnp.sum(jax.nn.one_hot(idxs, c.num_experts, dtype=probs.dtype),
                       axis=-2)
        gates = probs * mask
        gates = gates / jnp.sum(gates, axis=-1, keepdims=True)
        return gates, probs

    def forward(self, params, state, x, train=False, rng=None, mask=None,
                ctx=None):
        c = self.conf
        x = self.maybe_dropout(x, train, rng)
        flat = x.reshape(-1, x.shape[-1])                # [n, F] (rnn-safe)
        rdt = self._router_dtype()
        gates, probs = self._route(flat.astype(rdt), params["Wg"])

        cd = self.compute_dtype
        # per-expert dense: [n, F] × [E, F, O] → [n, E, O]; expert dim E is
        # a plain array axis, shardable over the mesh 'expert' axis
        h = jnp.einsum("nf,efo->neo", flat.astype(cd),
                       params["W"].astype(cd),
                       preferred_element_type=pet_dtype(cd))
        if "b" in params:
            h = h + params["b"].astype(h.dtype)
        # gate-weighted combine; reduction over E → psum when E is sharded
        y = jnp.einsum("ne,neo->no", gates.astype(h.dtype), h,
                       preferred_element_type=pet_dtype(cd))
        y = y.reshape(x.shape[:-1] + (c.n_out,))

        if ctx is not None and c.aux_loss_weight > 0.0:
            # Switch load-balancing loss: E * Σ_e f_e · P_e, where f_e is the
            # fraction of tokens whose TOP-1 expert is e and P_e the mean
            # router probability for e; minimized (=1) at uniform routing
            top1 = jnp.argmax(probs, axis=-1)
            f = jnp.mean(jax.nn.one_hot(top1, c.num_experts, dtype=rdt),
                         axis=0)
            P = jnp.mean(probs, axis=0)
            aux = c.aux_loss_weight * c.num_experts * jnp.sum(f * P)
            ctx["aux_loss"] = ctx.get("aux_loss", 0.0) + aux

        return self.activation(y).astype(self.out_dtype), state
