"""Layer implementations (pure JAX, registry-keyed by config class name).

Importing this package registers every implementation; ``impl_for`` resolves a
config dataclass to its runtime impl (the TPU-native analog of the reference's
``Layer.instantiate`` dispatch in ``nn/conf/layers/*.java``).
"""
from .base import LayerImpl, NoParamLayerImpl, impl_for, implements  # noqa: F401
from . import feedforward  # noqa: F401
from . import convolution  # noqa: F401
from . import pooling  # noqa: F401
from . import normalization  # noqa: F401
from . import recurrent  # noqa: F401
from . import output  # noqa: F401
from . import variational  # noqa: F401
from . import objdetect  # noqa: F401
from . import attention  # noqa: F401
from . import moe  # noqa: F401
from . import wrapper  # noqa: F401
