"""Multi-head self-attention layer.

Net-new vs the 0.9.x reference (which has no attention layers — SURVEY.md §5
"Long-context: absent"), included because long-context support is first-class in
the TPU build. The layer is written so the sequence dimension can be sharded:
under ``parallel.sequence`` the same parameters run blockwise ring attention
across a mesh 'sp' axis (see ``deeplearning4j_tpu/parallel/sequence.py``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import LayerImpl, implements, acc_dtype, pet_dtype


def mha(q, k, v, causal, compute_dtype, dropout_rate=0.0, rng=None, train=False,
        key_mask=None):
    """q,k,v: [b, T, h, d]. Returns [b, T, h, d]. Scaled dot-product attention
    with f32 softmax accumulation (bf16-safe). ``key_mask``: [b, S] with 1 for
    real keys, 0 for padding — padded keys are excluded from the softmax.

    Long sequences route through the Pallas flash-attention kernel
    (``ops/flash_attention.py``): blockwise online softmax, O(T) memory
    instead of materializing the [b, h, T, T] logits. The dense path below
    remains the oracle and the fallback (dropout / key masks / odd lengths).
    """
    from ...ops import flash_attention as _fa

    T, d = q.shape[1], q.shape[-1]
    if (q.shape == k.shape and _fa.supported(T, d, dropout_rate if train
                                             else 0.0, key_mask)):
        return _fa.flash_attention(
            q.astype(compute_dtype), k.astype(compute_dtype),
            v.astype(compute_dtype), causal=causal)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(compute_dtype),
                        k.astype(compute_dtype),
                        preferred_element_type=pet_dtype(compute_dtype))
    logits = logits / jnp.sqrt(jnp.asarray(d, jnp.float32))
    if causal:
        T, S = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((T, S), bool))
        logits = jnp.where(mask, logits, -1e30)
    if key_mask is not None:
        logits = jnp.where(key_mask[:, None, None, :] > 0, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    if train and dropout_rate > 0.0 and rng is not None:
        keep = jax.random.bernoulli(rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(compute_dtype),
                      v.astype(compute_dtype), preferred_element_type=pet_dtype(compute_dtype))


@implements("SelfAttentionLayer")
class SelfAttentionImpl(LayerImpl):
    def _dims(self):
        c = self.conf
        h = c.num_heads
        d = c.head_dim or (c.n_out // h)
        return h, d

    def init(self, rng):
        c = self.conf
        h, d = self._dims()
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        params = {
            "Wq": self._init_w(k1, (c.n_in, h * d), c.n_in, h * d),
            "Wk": self._init_w(k2, (c.n_in, h * d), c.n_in, h * d),
            "Wv": self._init_w(k3, (c.n_in, h * d), c.n_in, h * d),
            "Wo": self._init_w(k4, (h * d, c.n_out), h * d, c.n_out),
            "b": self._init_b((c.n_out,)),
        }
        return params, {}

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        c = self.conf
        h, d = self._dims()
        b, T, _ = x.shape
        x = self.maybe_dropout(x, train, rng)
        cd = self.compute_dtype
        q = (x @ params["Wq"].astype(x.dtype)).reshape(b, T, h, d)
        k = (x @ params["Wk"].astype(x.dtype)).reshape(b, T, h, d)
        v = (x @ params["Wv"].astype(x.dtype)).reshape(b, T, h, d)
        o = mha(q, k, v, c.causal, cd, c.dropout_rate, rng, train,
                key_mask=mask)
        o = o.reshape(b, T, h * d)
        y = o @ params["Wo"].astype(o.dtype) + params["b"].astype(o.dtype)
        return self.activation(y).astype(self.out_dtype), state
