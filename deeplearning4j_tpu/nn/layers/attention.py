"""Multi-head self-attention layer.

Net-new vs the 0.9.x reference (which has no attention layers — SURVEY.md §5
"Long-context: absent"), included because long-context support is first-class in
the TPU build. The layer is written so the sequence dimension can be sharded:
under ``parallel.sequence`` the same parameters run blockwise ring attention
across a mesh 'sp' axis (see ``deeplearning4j_tpu/parallel/sequence.py``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import LayerImpl, implements, acc_dtype, pet_dtype


def mha(q, k, v, causal, compute_dtype, dropout_rate=0.0, rng=None, train=False,
        key_mask=None):
    """q,k,v: [b, T, h, d]. Returns [b, T, h, d]. Scaled dot-product attention
    with f32 softmax accumulation (bf16-safe). ``key_mask``: [b, S] with 1 for
    real keys, 0 for padding — padded keys are excluded from the softmax.

    Long sequences route through the Pallas flash-attention kernel
    (``ops/flash_attention.py``): blockwise online softmax, O(T) memory
    instead of materializing the [b, h, T, T] logits — key-padding masks
    AND train-time attention dropout included (both run in-kernel; the
    dropout mask is regenerated blockwise from a counter-hash PRNG). The
    dense path below remains the oracle and the fallback (short or
    non-block-divisible sequences).
    """
    from ...ops import flash_attention as _fa

    T, d = q.shape[1], q.shape[-1]
    rate = dropout_rate if (train and rng is not None) else 0.0
    if q.shape == k.shape and _fa.supported(T, d, rate, key_mask):
        seed = None
        if rate > 0.0:
            # per-step scalar seed for the in-kernel counter-hash dropout
            # PRNG (derived from the layer rng, so each train step draws a
            # fresh mask exactly like the dense path's jax.random.bernoulli)
            seed = jax.random.randint(rng, (), 0, jnp.iinfo(jnp.int32).max,
                                      dtype=jnp.int32)
        return _fa.flash_attention(
            q.astype(compute_dtype), k.astype(compute_dtype),
            v.astype(compute_dtype), causal=causal, key_mask=key_mask,
            dropout_rate=rate, dropout_seed=seed)
    visible = None
    if causal:
        T, S = q.shape[1], k.shape[1]
        visible = jnp.tril(jnp.ones((T, S), bool))[None, None]
    if key_mask is not None:
        km = (key_mask[:, None, None, :] > 0)
        visible = km if visible is None else (visible & km)
    return _dense_attention(q, k, v, visible, compute_dtype,
                            dropout_rate=dropout_rate, rng=rng, train=train)


def _dense_attention(q, k, v, visible, compute_dtype, dropout_rate=0.0,
                     rng=None, train=False):
    """Shared dense scaled-dot-product body (full-sequence AND KV-cache
    streaming paths — one implementation so masking/dropout/numerics cannot
    diverge). ``visible``: broadcastable-to-[b, h, Tq, Tk] bool mask or
    None."""
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(compute_dtype),
                        k.astype(compute_dtype),
                        preferred_element_type=pet_dtype(compute_dtype))
    logits = logits / jnp.sqrt(jnp.asarray(d, jnp.float32))
    if visible is not None:
        logits = jnp.where(visible, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    if visible is not None:
        # a query row with NO visible key outputs 0 (softmax over all -1e30
        # would silently average every value vector) — same convention as
        # the flash kernels, so the oracle and kernel cannot diverge on
        # fully-padded rows
        probs = jnp.where(jnp.any(visible, axis=-1, keepdims=True),
                          probs, 0.0)
    if train and dropout_rate > 0.0 and rng is not None:
        keep = jax.random.bernoulli(rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(compute_dtype),
                      v.astype(compute_dtype),
                      preferred_element_type=pet_dtype(compute_dtype))


@implements("SelfAttentionLayer")
class SelfAttentionImpl(LayerImpl):
    def _dims(self):
        c = self.conf
        h = c.num_heads
        d = c.head_dim or (c.n_out // h)
        return h, d

    def init(self, rng):
        c = self.conf
        h, d = self._dims()
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        params = {
            "Wq": self._init_w(k1, (c.n_in, h * d), c.n_in, h * d),
            "Wk": self._init_w(k2, (c.n_in, h * d), c.n_in, h * d),
            "Wv": self._init_w(k3, (c.n_in, h * d), c.n_in, h * d),
            "Wo": self._init_w(k4, (h * d, c.n_out), h * d, c.n_out),
            "b": self._init_b((c.n_out,)),
        }
        return params, {}

    #: training forward is scan-free — the stream state must not disable
    #: the conv-net remat policy the way true RNN carries do (base.py)
    scan_free_training = True

    def init_stream_state(self, batch):
        """KV cache for streaming inference / cross-segment TBPTT: circular
        buffer of ``stream_max_length`` capacity (static shapes keep one
        compiled step), PER-EXAMPLE per-slot global positions (-1 =
        empty/masked — per-example so non-uniform key padding across the
        batch stays exact), and the global token counter."""
        c = self.conf
        h, d = self._dims()
        L = int(c.stream_max_length)
        cd = self.compute_dtype
        return (jnp.zeros((batch, L, h, d), cd),
                jnp.zeros((batch, L, h, d), cd),
                jnp.full((batch, L), -1, jnp.int32),
                jnp.zeros((), jnp.int32))

    def _cached_attention(self, q, k, v, carry, cd, key_mask, dropout_rate,
                          rng, train):
        """Streaming attention against the circular KV cache (a SLIDING
        WINDOW — past capacity the OLDEST entries are evicted).

        Attention is computed BEFORE this chunk's writes land, over the
        concatenation [retained cache keys | this chunk's keys], so a
        multi-token chunk that rolls the buffer past capacity cannot evict
        keys still inside the window of the chunk's EARLIER queries: each
        causal query at global position p sees exactly the keys at positions
        in (p - L, p], byte-identical to feeding the chunk one token at a
        time. Key-mask-padded tokens advance time but are never visible,
        tracked per example. One shared dense body with ``mha`` —
        masking/dropout semantics cannot diverge."""
        k_c, v_c, pos_c, n = carry
        b, T, h, d = q.shape
        L = k_c.shape[1]
        if T > L:
            raise ValueError(
                f"SelfAttentionLayer stream chunk of {T} tokens exceeds "
                f"stream_max_length={L}; raise stream_max_length on the "
                f"layer config (it must cover the TBPTT segment length)")
        chunk_pos = jnp.broadcast_to(n + jnp.arange(T), (b, T))      # [b, T]
        if key_mask is not None:
            chunk_pos = jnp.where(key_mask > 0, chunk_pos, -1)
        # attend over [cache | chunk] with position-based visibility
        k_all = jnp.concatenate([k_c, k.astype(k_c.dtype)], axis=1)
        v_all = jnp.concatenate([v_c, v.astype(v_c.dtype)], axis=1)
        pos_all = jnp.concatenate([pos_c, chunk_pos], axis=1)        # [b, L+T]
        qpos = n + jnp.arange(T)                        # [T] global positions
        valid = pos_all[:, None, :] >= 0                # [b, Tq, L+T]
        if self.conf.causal:
            # window (p - L, p]: eviction emulated per query, not per chunk
            visible = (valid
                       & (pos_all[:, None, :] <= qpos[None, :, None])
                       & (pos_all[:, None, :] > qpos[None, :, None] - L))
        else:
            # non-causal streaming: every key retained after this chunk's
            # writes (positions > n + T - 1 - L), matching write-then-attend
            visible = valid & (pos_all[:, None, :] > n + T - 1 - L)
        o = _dense_attention(q, k_all, v_all, visible[:, None], cd,
                             dropout_rate=dropout_rate, rng=rng, train=train)
        # now land the chunk's writes (evicting the oldest slots)
        slots = (n + jnp.arange(T)) % L
        k_c = k_c.at[:, slots].set(k.astype(k_c.dtype))
        v_c = v_c.at[:, slots].set(v.astype(v_c.dtype))
        pos_c = pos_c.at[:, slots].set(chunk_pos)
        return o, (k_c, v_c, pos_c, n + T)

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        c = self.conf
        h, d = self._dims()
        b, T, _ = x.shape
        x = self.maybe_dropout(x, train, rng)
        cd = self.compute_dtype
        q = (x @ params["Wq"].astype(x.dtype)).reshape(b, T, h, d)
        k = (x @ params["Wk"].astype(x.dtype)).reshape(b, T, h, d)
        v = (x @ params["Wv"].astype(x.dtype)).reshape(b, T, h, d)
        idx = getattr(self, "index", None)
        carry = (ctx.get("rnn_state_in", {}).get(idx)
                 if ctx is not None and idx is not None else None)
        from ...parallel.sequence import current_sp_axis
        sp_axis = current_sp_axis()
        if carry is not None:
            o, new_carry = self._cached_attention(
                q, k, v, carry, cd, key_mask=mask,
                dropout_rate=c.dropout_rate, rng=rng, train=train)
            ctx.setdefault("rnn_state_out", {})[idx] = new_carry
        elif sp_axis is not None:
            # sequence-parallel step (parallel/sequence.py::
            # sequence_parallel_step): this forward runs PER DEVICE inside
            # shard_map with the time dim sharded over ``sp_axis`` — attend
            # via the ring (flash kernel per block when shapes allow).
            # Attention dropout runs IN the ring kernels at global
            # coordinates: rng is replicated across shards, so every shard
            # derives the same seed — the same derivation as mha's flash
            # path, giving each train step a fresh mask
            from ...parallel.sequence import sp_attend

            rate = c.dropout_rate if (train and rng is not None) else 0.0
            seed = None
            if rate > 0.0:
                seed = jax.random.randint(rng, (), 0,
                                          jnp.iinfo(jnp.int32).max,
                                          dtype=jnp.int32)
            o = sp_attend(q.astype(cd), k.astype(cd), v.astype(cd),
                          sp_axis, bool(c.causal), dropout_rate=rate,
                          dropout_seed=seed)
        else:
            o = mha(q, k, v, c.causal, cd, c.dropout_rate, rng, train,
                    key_mask=mask)
        o = o.reshape(b, T, h * d)
        y = o @ params["Wo"].astype(o.dtype) + params["b"].astype(o.dtype)
        return self.activation(y).astype(self.out_dtype), state
