"""Output layer implementations: OutputLayer, RnnOutputLayer, LossLayer,
CenterLossOutputLayer.

TPU-native equivalents of reference ``nn/layers/OutputLayer.java`` /
``BaseOutputLayer.java`` (``computeScore``). An output layer is a dense projection
plus a loss; ``loss_on`` evaluates the loss on *preoutput* so numerically fused
softmax/sigmoid cross-entropy paths apply (see ``nn.losses``). The network's
jitted train step calls ``loss_on``; ``forward`` gives inference activations.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import LayerImpl, NoParamLayerImpl, implements
from ..weights import host_full
from .feedforward import _dot
from ..losses import get_loss


class _OutputBase(LayerImpl):
    def preout(self, params, x):
        z = _dot(x, params["W"], self.compute_dtype)
        if "b" in params:
            z = z + params["b"].astype(z.dtype)
        return z

    def init(self, rng):
        c = self.conf
        params = {"W": self._init_w(rng, (c.n_in, c.n_out), c.n_in, c.n_out)}
        if getattr(c, "has_bias", True):
            params["b"] = self._init_b((c.n_out,))
        return params, {}

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        x = self.maybe_dropout(x, train, rng)
        # terminal layer: user-facing predictions stay full precision — the
        # bf16 inter-layer policy (out_dtype) is an HBM-bandwidth measure and
        # the one output cast costs nothing
        return self.activation(self.preout(params, x)).astype(self.dtype), state

    def loss_on(self, params, state, x, labels, mask=None, train=True, rng=None):
        x = self.maybe_dropout(x, train, rng)
        z = self.preout(params, x)
        return get_loss(self.conf.loss)(labels, z, self.activation_name, mask)


@implements("OutputLayer")
class OutputLayerImpl(_OutputBase):
    pass


@implements("RnnOutputLayer")
class RnnOutputLayerImpl(_OutputBase):
    """Per-timestep output over [b, T, nIn] (reference ``RnnOutputLayer.java``);
    loss is mask-aware over [b, T]."""
    pass


@implements("LossLayer")
class LossLayerImpl(NoParamLayerImpl):
    """Loss without weights (reference ``nn/layers/LossLayer.java``)."""

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        return self.activation(x), state

    def loss_on(self, params, state, x, labels, mask=None, train=True, rng=None):
        return get_loss(self.conf.loss)(labels, x, self.activation_name, mask)


@implements("CenterLossOutputLayer")
class CenterLossOutputLayerImpl(_OutputBase):
    """Softmax loss + lambda * center loss (reference
    ``nn/layers/training/CenterLossOutputLayer.java``). Class centers are state,
    EMA-updated toward batch feature means with rate ``alpha``."""

    def init(self, rng):
        params, _ = super().init(rng)
        c = self.conf
        state = {"centers": host_full((c.n_out, c.n_in), 0,
                                      jnp.float32)}
        return params, state

    def loss_on(self, params, state, x, labels, mask=None, train=True, rng=None):
        c = self.conf
        z = self.preout(params, x)
        base = get_loss(c.loss)(labels, z, self.activation_name, mask)
        centers = state["centers"]
        cls = jnp.argmax(labels, axis=-1)
        diffs = x - centers[cls]
        center_loss = 0.5 * jnp.mean(jnp.sum(diffs * diffs, axis=-1))
        return base + c.lambda_ * center_loss

    def update_state(self, state, x, labels):
        """EMA center update (called outside AD by the train step)."""
        c = self.conf
        cls = jnp.argmax(labels, axis=-1)
        onehot = jax.nn.one_hot(cls, c.n_out, dtype=jnp.float32)
        counts = jnp.maximum(onehot.sum(axis=0), 1.0)[:, None]
        batch_means = (onehot.T @ x.astype(jnp.float32)) / counts
        present = (onehot.sum(axis=0) > 0)[:, None]
        centers = state["centers"]
        new_centers = jnp.where(present,
                                centers + c.alpha * (batch_means - centers),
                                centers)
        return {"centers": new_centers}
