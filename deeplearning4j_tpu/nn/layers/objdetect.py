"""YOLOv2 output layer implementation.

TPU-native equivalent of reference ``nn/layers/objdetect/Yolo2OutputLayer.java``
(714 LoC). Exact layout/semantic parity with the reference:

 - input activations [b, gh, gw, 5B + C] NHWC (reference [mb, 5B+C, H, W],
   ``Yolo2OutputLayer.java:130-137``): B anchor blocks of (x, y, w, h, conf)
   followed by C per-CELL class logits (classes are shared across anchors).
 - labels [b, 4+C, gh, gw]: corner coords (x1, y1, x2, y2) in grid units +
   one-hot class map; object-presence mask inferred from the class one-hots
   (``:108-109``).
 - responsibility mask 1_ij^obj = IsMax over B of IOU(pred, label) × object
   present (``:155-157``); noobj mask is its complement (``:158``).
 - losses (all LossL2 sums, defaults ``conf/layers/objdetect/
   Yolo2OutputLayer.java:134-137``): position = (σ(xy) − frac(center))²,
   size = (√(prior·e^wh) − √(labelWH))², both responsibility-masked and
   λ_coord-scaled; confidence label is the IOU itself (gradients flow through
   it, ``:284-300``) with λ_noObj on the non-responsible term; class loss =
   per-cell softmax vs one-hot L2, object-masked (``:208-217``).
 - score divided by minibatch only (``:226``).

The reference hand-writes ~400 lines of backward (``:230-330``); here the
backward is AD of this loss — including the confidence-through-IOU terms the
reference derives manually.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import NoParamLayerImpl, implements


@implements("Yolo2OutputLayer")
class Yolo2OutputImpl(NoParamLayerImpl):
    def _boxes(self):
        return jnp.asarray(self.conf.boxes, jnp.float32)  # [B, 2] (w, h)

    def _split(self, x):
        """[b, gh, gw, 5B+C] → box block [b, gh, gw, B, 5] + class logits
        [b, gh, gw, C]."""
        B = self._boxes().shape[0]
        b, gh, gw, ch = x.shape
        boxes = x[..., :5 * B].reshape(b, gh, gw, B, 5)
        cls_logits = x[..., 5 * B:]
        return boxes, cls_logits

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        """Inference activations (reference ``activate`` :336-345): sigmoid on
        xy/conf, prior-scaled exp on wh, per-cell softmax on classes."""
        boxes, cls_logits = self._split(x)
        b, gh, gw, B, _ = boxes.shape
        xy = jax.nn.sigmoid(boxes[..., 0:2])
        wh = jnp.exp(boxes[..., 2:4]) * self._boxes()[None, None, None, :, :]
        conf = jax.nn.sigmoid(boxes[..., 4:5])
        out_boxes = jnp.concatenate([xy, wh, conf], axis=-1).reshape(
            b, gh, gw, 5 * B)
        out_cls = jax.nn.softmax(cls_logits, axis=-1)
        return jnp.concatenate([out_boxes, out_cls], axis=-1), state

    def loss_on(self, params, state, x, labels, mask=None, train=True, rng=None):
        c = self.conf
        anchors = self._boxes()                           # [B, 2]
        boxes, cls_logits = self._split(x)
        b, gh, gw, B, _ = boxes.shape

        # labels [b, 4+C, gh, gw] → bbox [b, gh, gw, 4], classmap [b, gh, gw, C]
        labels = jnp.transpose(labels, (0, 2, 3, 1))
        bbox = labels[..., :4]                            # x1, y1, x2, y2 (grid units)
        cls_label = labels[..., 4:]
        obj_mask = (jnp.sum(cls_label, axis=-1, keepdims=True) > 0)  # [b,gh,gw,1]

        # ground-truth center/size per cell
        gt_wh = jnp.stack([bbox[..., 2] - bbox[..., 0],
                           bbox[..., 3] - bbox[..., 1]], -1)
        gt_cxy = jnp.stack([0.5 * (bbox[..., 0] + bbox[..., 2]),
                            0.5 * (bbox[..., 1] + bbox[..., 3])], -1)
        # predicted box params
        cell_x = jnp.arange(gw, dtype=jnp.float32)[None, None, :, None]
        cell_y = jnp.arange(gh, dtype=jnp.float32)[None, :, None, None]
        p_xy_rel = jax.nn.sigmoid(boxes[..., 0:2])        # within-cell offset
        p_cx = p_xy_rel[..., 0] + cell_x
        p_cy = p_xy_rel[..., 1] + cell_y
        # wide clip for numerical safety only; reference exp is unclipped
        p_wh = jnp.exp(jnp.clip(boxes[..., 2:4], -20, 20)) * anchors[None, None, None]
        p_conf = jax.nn.sigmoid(boxes[..., 4])

        # IOU of each predicted box vs the GT box of its cell (:148)
        p_x1 = p_cx - 0.5 * p_wh[..., 0]
        p_x2 = p_cx + 0.5 * p_wh[..., 0]
        p_y1 = p_cy - 0.5 * p_wh[..., 1]
        p_y2 = p_cy + 0.5 * p_wh[..., 1]
        ix1 = jnp.maximum(p_x1, bbox[..., None, 0])
        iy1 = jnp.maximum(p_y1, bbox[..., None, 1])
        ix2 = jnp.minimum(p_x2, bbox[..., None, 2])
        iy2 = jnp.minimum(p_y2, bbox[..., None, 3])
        iw = jnp.maximum(ix2 - ix1, 0.0)
        ih = jnp.maximum(iy2 - iy1, 0.0)
        inter = iw * ih
        area_p = p_wh[..., 0] * p_wh[..., 1]
        area_g = (gt_wh[..., 0] * gt_wh[..., 1])[..., None]
        iou = inter / (area_p + area_g - inter + 1e-12)   # [b, gh, gw, B]

        # responsible predictor: IsMax over B × object present (:155-157)
        resp = jax.nn.one_hot(jnp.argmax(iou, axis=-1), B, dtype=x.dtype)
        resp = resp * obj_mask.astype(x.dtype)            # [b, gh, gw, B]

        # position + size losses, λ_coord-scaled (:213-215, :220)
        gt_xy_rel = gt_cxy - jnp.floor(gt_cxy)
        d_xy = jnp.sum((p_xy_rel - gt_xy_rel[..., None, :]) ** 2, axis=-1)
        d_wh = jnp.sum((jnp.sqrt(p_wh + 1e-12)
                        - jnp.sqrt(jnp.maximum(gt_wh, 0.0)[..., None, :] + 1e-12)) ** 2,
                       axis=-1)
        coord_loss = jnp.sum(resp * (d_xy + d_wh))

        # confidence: label = IOU·1_ij^obj, L2 on responsible + λ_noObj on the
        # complement (:165, :216-217); gradients flow through IOU as in the
        # reference's hand-derived dLc/dIOU (:284-300)
        conf_loss_obj = jnp.sum(resp * (p_conf - iou) ** 2)
        conf_loss_noobj = jnp.sum((1.0 - resp) * p_conf ** 2)

        # per-CELL class loss: softmax over C logits vs one-hot, object-masked
        # (:208-211, :218)
        p_cls = jax.nn.softmax(cls_logits, axis=-1)
        cls_loss = jnp.sum(obj_mask.astype(x.dtype) * (p_cls - cls_label) ** 2)

        total = (c.lambda_coord * coord_loss + conf_loss_obj
                 + c.lambda_no_obj * conf_loss_noobj + cls_loss)
        return total / b
