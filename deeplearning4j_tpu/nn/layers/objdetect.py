"""YOLOv2 output layer implementation.

TPU-native equivalent of reference ``nn/layers/objdetect/Yolo2OutputLayer.java``
(714 LoC). Input activations: [b, gh, gw, B*(5+C)] NHWC (reference: [b, B*(5+C),
gh, gw]); labels: [b, 4+C, gh, gw] as in the reference (class map + bbox corner
coords in grid units). Loss = lambda_coord * position/size SSE (sqrt w/h) +
object/no-object confidence SSE (vs IOU) + per-cell classification SSE, the
reference's YOLOv2 formulation. All box math is vectorized over the grid — no
per-cell host loops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import NoParamLayerImpl, implements


@implements("Yolo2OutputLayer")
class Yolo2OutputImpl(NoParamLayerImpl):
    def _boxes(self):
        return jnp.asarray(self.conf.boxes, jnp.float32)  # [B, 2] (h, w)

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        """Inference activations (reference ``activate``): sigmoid on xy/conf,
        exp-scaled wh, softmax on classes."""
        B = self._boxes().shape[0]
        b, gh, gw, ch = x.shape
        C = ch // B - 5
        x = x.reshape(b, gh, gw, B, 5 + C)
        xy = jax.nn.sigmoid(x[..., 0:2])
        wh = jnp.exp(x[..., 2:4]) * self._boxes()[None, None, None, :, :]
        conf = jax.nn.sigmoid(x[..., 4:5])
        cls = jax.nn.softmax(x[..., 5:], axis=-1)
        return jnp.concatenate([xy, wh, conf, cls], axis=-1).reshape(b, gh, gw, ch), state

    def loss_on(self, params, state, x, labels, mask=None, train=True, rng=None):
        c = self.conf
        anchors = self._boxes()                          # [B, 2]
        B = anchors.shape[0]
        b, gh, gw, ch = x.shape
        C = ch // B - 5
        x = x.reshape(b, gh, gw, B, 5 + C)

        # labels [b, 4+C, gh, gw] → bbox [b, gh, gw, 4], classmap [b, gh, gw, C]
        labels = jnp.transpose(labels, (0, 2, 3, 1))
        bbox = labels[..., :4]                            # x1, y1, x2, y2 (grid units)
        cls_label = labels[..., 4:]
        obj_mask = (jnp.sum(cls_label, axis=-1, keepdims=True) > 0)  # [b,gh,gw,1]

        # ground-truth center/size per cell
        gt_wh = jnp.stack([bbox[..., 2] - bbox[..., 0], bbox[..., 3] - bbox[..., 1]], -1)
        gt_cxy = jnp.stack([0.5 * (bbox[..., 0] + bbox[..., 2]),
                            0.5 * (bbox[..., 1] + bbox[..., 3])], -1)
        # predicted box params
        cell_x = jnp.arange(gw, dtype=jnp.float32)[None, None, :, None]
        cell_y = jnp.arange(gh, dtype=jnp.float32)[None, :, None, None]
        p_xy_rel = jax.nn.sigmoid(x[..., 0:2])            # within-cell offset
        p_cx = p_xy_rel[..., 0] + cell_x
        p_cy = p_xy_rel[..., 1] + cell_y
        p_wh = jnp.exp(jnp.clip(x[..., 2:4], -10, 6)) * anchors[None, None, None]
        p_conf = jax.nn.sigmoid(x[..., 4])

        # IOU of each predicted box vs GT box of its cell
        p_x1 = p_cx - 0.5 * p_wh[..., 0]
        p_x2 = p_cx + 0.5 * p_wh[..., 0]
        p_y1 = p_cy - 0.5 * p_wh[..., 1]
        p_y2 = p_cy + 0.5 * p_wh[..., 1]
        ix1 = jnp.maximum(p_x1, bbox[..., None, 0])
        iy1 = jnp.maximum(p_y1, bbox[..., None, 1])
        ix2 = jnp.minimum(p_x2, bbox[..., None, 2])
        iy2 = jnp.minimum(p_y2, bbox[..., None, 3])
        iw = jnp.maximum(ix2 - ix1, 0.0)
        ih = jnp.maximum(iy2 - iy1, 0.0)
        inter = iw * ih
        area_p = jnp.maximum(p_wh[..., 0] * p_wh[..., 1], 1e-9)
        area_g = jnp.maximum(gt_wh[..., 0] * gt_wh[..., 1], 1e-9)[..., None]
        iou = inter / (area_p + area_g - inter + 1e-9)    # [b, gh, gw, B]

        # responsible predictor = argmax IOU per cell (reference behavior)
        resp = jax.nn.one_hot(jnp.argmax(iou, axis=-1), B, dtype=jnp.float32)
        resp = resp * obj_mask.astype(jnp.float32)        # [b, gh, gw, B]

        # coordinate loss (sqrt on w/h as in YOLOv2)
        gt_xy_rel = gt_cxy - jnp.floor(gt_cxy)
        d_xy = jnp.sum((p_xy_rel - gt_xy_rel[..., None, :]) ** 2, axis=-1)
        d_wh = jnp.sum((jnp.sqrt(p_wh + 1e-9)
                        - jnp.sqrt(gt_wh[..., None, :] + 1e-9)) ** 2, axis=-1)
        coord_loss = jnp.sum(resp * (d_xy + d_wh))

        # confidence loss: responsible → target IOU; others → 0
        conf_loss_obj = jnp.sum(resp * (p_conf - iou) ** 2)
        conf_loss_noobj = jnp.sum((1.0 - resp) * p_conf ** 2)

        # classification loss per object cell (softmax SSE, reference default)
        p_cls = jax.nn.softmax(x[..., 5:], axis=-1)
        cell_cls = jnp.sum(resp[..., None] * p_cls, axis=3)
        cls_loss = jnp.sum(obj_mask[..., 0, None].astype(jnp.float32)
                           * (cell_cls - cls_label) ** 2)

        total = (c.lambda_coord * coord_loss + conf_loss_obj
                 + c.lambda_no_obj * conf_loss_noobj + cls_loss)
        return total / b
