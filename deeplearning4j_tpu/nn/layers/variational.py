"""Variational autoencoder implementation.

TPU-native equivalent of reference ``nn/layers/variational/VariationalAutoencoder.java``
(1163 LoC): MLP encoder → diagonal-Gaussian q(z|x) → MLP decoder → reconstruction
distribution. Supervised forward emits the mean of q(z|x) (reference behavior when
used mid-network); ``pretrain_loss`` is the negative ELBO with the reparameterization
trick, ``num_samples`` MC samples drawn inside the jitted step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import LayerImpl, implements
from .feedforward import _dot
from ..activations import get_activation


@implements("VariationalAutoencoder")
class VAEImpl(LayerImpl):
    def _sizes(self):
        c = self.conf
        enc = [c.n_in] + list(c.encoder_layer_sizes)
        dec = [c.n_out] + list(c.decoder_layer_sizes)
        return enc, dec

    def init(self, rng):
        c = self.conf
        enc, dec = self._sizes()
        params = {}
        keys = jax.random.split(rng, len(enc) + len(dec) + 2)
        ki = 0
        for i in range(len(enc) - 1):
            params[f"eW{i}"] = self._init_w(keys[ki], (enc[i], enc[i + 1]),
                                            enc[i], enc[i + 1])
            params[f"eb{i}"] = self._init_b((enc[i + 1],))
            ki += 1
        # q(z|x): mean and log-variance heads (reference "pZXMean"/"pZXLogStd2")
        params["zW"] = self._init_w(keys[ki], (enc[-1], 2 * c.n_out), enc[-1],
                                    2 * c.n_out)
        params["zb"] = self._init_b((2 * c.n_out,))
        ki += 1
        for i in range(len(dec) - 1):
            params[f"dW{i}"] = self._init_w(keys[ki], (dec[i], dec[i + 1]),
                                            dec[i], dec[i + 1])
            params[f"db{i}"] = self._init_b((dec[i + 1],))
            ki += 1
        # p(x|z) head: gaussian → mean (+ fixed unit variance), bernoulli → logits
        params["xW"] = self._init_w(keys[ki], (dec[-1], c.n_in), dec[-1], c.n_in)
        params["xb"] = self._init_b((c.n_in,))
        return params, {}

    def encode(self, params, x):
        enc, _ = self._sizes()
        h = x
        for i in range(len(enc) - 1):
            h = self.activation(_dot(h, params[f"eW{i}"], self.compute_dtype)
                                + params[f"eb{i}"])
        z = _dot(h, params["zW"], self.compute_dtype) + params["zb"]
        mean, log_var = jnp.split(z, 2, axis=-1)
        pzx_act = get_activation(self.conf.pzx_activation)
        return pzx_act(mean), log_var

    def decode(self, params, z):
        _, dec = self._sizes()
        h = z
        for i in range(len(dec) - 1):
            h = self.activation(_dot(h, params[f"dW{i}"], self.compute_dtype)
                                + params[f"db{i}"])
        return _dot(h, params["xW"], self.compute_dtype) + params["xb"]

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        x = self.maybe_dropout(x, train, rng)
        mean, _ = self.encode(params, x)
        return mean.astype(self.dtype), state

    def pretrain_loss(self, params, x, rng):
        c = self.conf
        mean, log_var = self.encode(params, x)
        kl = -0.5 * jnp.sum(1 + log_var - mean * mean - jnp.exp(log_var), axis=-1)
        total_recon = 0.0
        keys = jax.random.split(rng, c.num_samples)
        for k in keys:
            eps = jax.random.normal(k, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * log_var) * eps
            xhat = self.decode(params, z)
            if c.reconstruction_distribution == "bernoulli":
                recon = jnp.sum(
                    jnp.maximum(xhat, 0) - xhat * x + jnp.log1p(jnp.exp(-jnp.abs(xhat))),
                    axis=-1)
            else:  # gaussian, unit variance
                recon = 0.5 * jnp.sum((xhat - x) ** 2, axis=-1)
            total_recon = total_recon + recon
        recon = total_recon / c.num_samples
        return jnp.mean(recon + kl)

    def reconstruction_probability(self, params, x, rng, num_samples=None):
        """Reference ``VariationalAutoencoder.reconstructionProbability`` —
        importance-sampled estimate of log p(x)."""
        n = num_samples or self.conf.num_samples
        mean, log_var = self.encode(params, x)
        keys = jax.random.split(rng, n)
        logps = []
        for k in keys:
            eps = jax.random.normal(k, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * log_var) * eps
            xhat = self.decode(params, z)
            if self.conf.reconstruction_distribution == "bernoulli":
                logp = -jnp.sum(
                    jnp.maximum(xhat, 0) - xhat * x + jnp.log1p(jnp.exp(-jnp.abs(xhat))),
                    axis=-1)
            else:
                logp = -0.5 * jnp.sum((xhat - x) ** 2 + jnp.log(2 * jnp.pi), axis=-1)
            logps.append(logp)
        return jax.scipy.special.logsumexp(jnp.stack(logps), axis=0) - jnp.log(float(n))
