"""Variational autoencoder implementation.

TPU-native equivalent of reference
``nn/layers/variational/VariationalAutoencoder.java`` (1163 LoC): MLP encoder
→ diagonal-Gaussian q(z|x) → MLP decoder → pluggable reconstruction
distribution p(x|z) (``nn/conf/layers/variational/`` — Gaussian with learned
variance, Bernoulli, Exponential, Composite, LossFunctionWrapper; see
``..conf.reconstruction``). Supervised forward emits the mean of q(z|x)
(reference behavior when used mid-network); ``pretrain_loss`` is the negative
ELBO with the reparameterization trick, ``num_samples`` MC samples drawn
inside the jitted step. ``reconstruction_log_probability`` is the
importance-sampled estimate the reference exposes for anomaly scoring
(``reconstructionLogProbability``); ``reconstruction_error`` covers the
``hasLossFunction`` distributions the same way the reference splits the two
APIs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import LayerImpl, implements
from .feedforward import _dot
from ..activations import get_activation
from ..conf.reconstruction import resolve_distribution


@implements("VariationalAutoencoder")
class VAEImpl(LayerImpl):
    @property
    def recon_dist(self):
        return resolve_distribution(self.conf.reconstruction_distribution)

    def _sizes(self):
        c = self.conf
        enc = [c.n_in] + list(c.encoder_layer_sizes)
        dec = [c.n_out] + list(c.decoder_layer_sizes)
        return enc, dec

    def init(self, rng):
        c = self.conf
        enc, dec = self._sizes()
        params = {}
        keys = jax.random.split(rng, len(enc) + len(dec) + 2)
        ki = 0
        for i in range(len(enc) - 1):
            params[f"eW{i}"] = self._init_w(keys[ki], (enc[i], enc[i + 1]),
                                            enc[i], enc[i + 1])
            params[f"eb{i}"] = self._init_b((enc[i + 1],))
            ki += 1
        # q(z|x): mean and log-variance heads (reference "pZXMean"/"pZXLogStd2")
        params["zW"] = self._init_w(keys[ki], (enc[-1], 2 * c.n_out), enc[-1],
                                    2 * c.n_out)
        params["zb"] = self._init_b((2 * c.n_out,))
        ki += 1
        for i in range(len(dec) - 1):
            params[f"dW{i}"] = self._init_w(keys[ki], (dec[i], dec[i + 1]),
                                            dec[i], dec[i + 1])
            params[f"db{i}"] = self._init_b((dec[i + 1],))
            ki += 1
        # p(x|z) head: width = distribution param size ("pXZ" params; e.g.
        # Gaussian emits [mean, log var] = 2*nIn)
        px = self.recon_dist.param_size(c.n_in)
        params["xW"] = self._init_w(keys[ki], (dec[-1], px), dec[-1], px)
        params["xb"] = self._init_b((px,))
        return params, {}

    def encode(self, params, x):
        enc, _ = self._sizes()
        h = x
        for i in range(len(enc) - 1):
            h = self.activation(_dot(h, params[f"eW{i}"], self.compute_dtype)
                                + params[f"eb{i}"])
        z = _dot(h, params["zW"], self.compute_dtype) + params["zb"]
        mean, log_var = jnp.split(z, 2, axis=-1)
        pzx_act = get_activation(self.conf.pzx_activation)
        return pzx_act(mean), log_var

    def decode(self, params, z):
        """z → pre-activation distribution params of p(x|z)."""
        _, dec = self._sizes()
        h = z
        for i in range(len(dec) - 1):
            h = self.activation(_dot(h, params[f"dW{i}"], self.compute_dtype)
                                + params[f"db{i}"])
        return _dot(h, params["xW"], self.compute_dtype) + params["xb"]

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        x = self.maybe_dropout(x, train, rng)
        mean, _ = self.encode(params, x)
        return mean.astype(self.out_dtype), state

    def has_loss_function(self):
        """Reference ``hasLossFunction()`` — true for LossFunctionWrapper."""
        return self.recon_dist.has_loss_function

    hasLossFunction = has_loss_function

    def pretrain_loss(self, params, x, rng):
        """Negative ELBO (reference ``computeGradientAndScore`` pretrain
        path): KL(q(z|x) || N(0,I)) + E_q[−log p(x|z)], reparameterized."""
        c = self.conf
        dist = self.recon_dist
        mean, log_var = self.encode(params, x)
        kl = -0.5 * jnp.sum(1 + log_var - mean * mean - jnp.exp(log_var),
                            axis=-1)
        total_recon = 0.0
        keys = jax.random.split(rng, c.num_samples)
        for k in keys:
            eps = jax.random.normal(k, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * log_var) * eps
            total_recon = total_recon + dist.neg_log_prob(
                x, self.decode(params, z))
        recon = total_recon / c.num_samples
        return jnp.mean(recon + kl)

    # ------------------------------------------------- reference API surface
    def reconstruction_log_probability(self, params, x, rng, num_samples=None):
        """Importance-sampled estimate of log p(x) per example (reference
        ``reconstructionLogProbability``): log p(x) ≈ logsumexp_k[log p(x|z_k)
        + log p(z_k) − log q(z_k|x)] − log K, z_k ~ q(z|x). The reference's
        anomaly-scoring entry point."""
        if self.recon_dist.has_loss_function:
            raise ValueError(
                "reconstruction_log_probability is undefined for "
                "LossFunctionWrapper distributions — use reconstruction_error "
                "(reference throws the same way)")
        n = num_samples or self.conf.num_samples
        dist = self.recon_dist
        mean, log_var = self.encode(params, x)
        keys = jax.random.split(rng, n)
        logws = []
        for k in keys:
            eps = jax.random.normal(k, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * log_var) * eps
            log_p_xz = -dist.neg_log_prob(x, self.decode(params, z))
            log_prior = -0.5 * jnp.sum(z * z + jnp.log(2 * jnp.pi), axis=-1)
            log_q = -0.5 * jnp.sum(eps * eps + jnp.log(2 * jnp.pi) + log_var,
                                   axis=-1)
            logws.append(log_p_xz + log_prior - log_q)
        return (jax.scipy.special.logsumexp(jnp.stack(logws), axis=0)
                - jnp.log(float(n)))

    def reconstruction_probability(self, params, x, rng, num_samples=None):
        """exp of :meth:`reconstruction_log_probability` (reference
        ``reconstructionProbability``)."""
        return jnp.exp(self.reconstruction_log_probability(params, x, rng,
                                                           num_samples))

    def reconstruction_error(self, params, x):
        """Per-example deterministic reconstruction error (reference
        ``reconstructionError`` — only for ``hasLossFunction`` distributions)."""
        if not self.recon_dist.has_loss_function:
            raise ValueError(
                "reconstruction_error requires a LossFunctionWrapper "
                "distribution — use reconstruction_log_probability")
        mean, _ = self.encode(params, x)
        return self.recon_dist.neg_log_prob(x, self.decode(params, mean))

    def generate_at_mean_given_z(self, params, z):
        """Reference ``generateAtMeanGivenZ``."""
        return self.recon_dist.mean(self.decode(params, z))

    generateAtMeanGivenZ = generate_at_mean_given_z

    def generate_random_given_z(self, params, z, rng):
        """Reference ``generateRandomGivenZ``."""
        return self.recon_dist.sample(rng, self.decode(params, z))

    generateRandomGivenZ = generate_random_given_z
