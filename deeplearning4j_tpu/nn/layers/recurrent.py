"""Recurrent layer implementations: LSTM, GravesLSTM, GravesBidirectionalLSTM,
SimpleRnn, Bidirectional and LastTimeStep wrappers.

TPU-native equivalents of reference ``nn/layers/recurrent/`` — the shared
forward/backward math in ``LSTMHelpers.java:68`` (activateHelper) and the ifog
block gemm (:206-212) become a ``lax.scan`` whose *input projection is hoisted*
out of the loop: one big [b·T, nIn]×[nIn, 4H] gemm feeds the MXU, and the scan
body only does the [b, H]×[H, 4H] recurrent gemm plus elementwise gate math.
Backward-through-time is AD of the scan (no hand-written BPTT).

Sequence layout is [batch, time, features] (reference: [b, features, T]).
Gate order in the fused 4H dimension is i, f, o, g matching the reference's
IFOG convention (``LSTMParamInitializer``). Param keys: "W" (input weights
[nIn, 4H]), "RW" (recurrent [H, 4H]), "b" ([4H]); Graves peepholes "pi","pf","po".

Streaming state (``rnnTimeStep``) flows through ``ctx``: the network places
per-layer previous (h, c) under ``ctx['rnn_state_in'][layer_index]`` and collects
``ctx['rnn_state_out']`` — the functional replacement for the reference's mutable
``stateMap`` (``BaseRecurrentLayer.java``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .base import LayerImpl, implements, impl_for, acc_dtype
from ..weights import host_full
from ..activations import get_activation


def _match_vma(z, ref):
    """Give a fresh scan-carry init the shard_map varying-axes type of ``ref``.

    Under ``shard_map`` (ParallelWrapper local-SGD), batch inputs are
    device-varying while a ``jnp.zeros`` carry init is not; ``lax.scan``
    rejects the carry-type mismatch. Outside shard_map this is a no-op."""
    try:
        want = set(jax.typeof(ref).vma) - set(jax.typeof(z).vma)
    except (AttributeError, TypeError):
        # jax < typeof/vma (0.4.x), or a non-jax ref type: no varying-axis
        # typing exists to satisfy, so the zero init is already fine
        return z
    if not want:
        return z
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(z, tuple(want), to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(z, tuple(want))
    return z  # pre-vma jax (0.4.x): no varying-axis typing to satisfy


class _BaseLSTMImpl(LayerImpl):
    peepholes = False

    def init_stream_state(self, batch):
        """Zero (h, c) carry for rnnTimeStep / TBPTT streaming."""
        H = self.conf.n_out
        ad = acc_dtype(self.compute_dtype)
        return (jnp.zeros((batch, H), ad),
                jnp.zeros((batch, H), ad))

    def init(self, rng):
        c = self.conf
        H = c.n_out
        k1, k2, k3 = jax.random.split(rng, 3)
        params = {
            "W": self._init_w(k1, (c.n_in, 4 * H), c.n_in, H),
            "RW": self._init_w(k2, (H, 4 * H), H, H),
            "b": self._init_b((4 * H,)),
        }
        # forget-gate bias init (reference LSTMParamInitializer sets f-gate
        # slice of the bias to forgetGateBiasInit)
        fb = getattr(c, "forget_gate_bias_init", 1.0)
        params["b"] = params["b"].at[H:2 * H].set(fb)
        if self.peepholes:
            params["pi"] = host_full((H,), 0, self.dtype)
            params["pf"] = host_full((H,), 0, self.dtype)
            params["po"] = host_full((H,), 0, self.dtype)
        return params, {}

    def _run(self, params, x, mask, h0c0, reverse=False):
        c = self.conf
        H = c.n_out
        act = self.activation
        gate_act = get_activation(getattr(c, "gate_activation", "sigmoid"))
        b, T, _ = x.shape
        # the step mask is data, not a differentiable input: stop_gradient
        # here so the scan path's AD agrees with the persistent kernel's
        # custom_vjp (which returns a zero mask cotangent) — no silent
        # kernel-vs-fallback gradient divergence for soft masks
        mask = None if mask is None else lax.stop_gradient(mask)
        if reverse:
            x = jnp.flip(x, axis=1)
            mask = jnp.flip(mask, axis=1) if mask is not None else None
        ad = acc_dtype(self.compute_dtype)
        # hoisted input projection: [b*T, nIn] @ [nIn, 4H] on the MXU
        xp = (x.reshape(b * T, -1).astype(self.compute_dtype)
              @ params["W"].astype(self.compute_dtype)).astype(ad)
        xp = xp.reshape(b, T, 4 * H) + params["b"].astype(ad)
        if h0c0 is None:
            h0 = jnp.zeros((b, H), ad)
            c0 = jnp.zeros((b, H), ad)
        else:
            h0, c0 = h0c0
        h0, c0 = _match_vma(h0, xp), _match_vma(c0, xp)
        peep = ((params["pi"], params["pf"], params["po"])
                if self.peepholes else None)
        # recurrent weights ride in COMPUTE dtype (bf16 policy): the
        # per-step gemm is a native MXU bf16 pass accumulated in f32 (pet
        # below); h/c and the gate math stay in the accumulation dtype
        rw = params["RW"].astype(self.compute_dtype)

        # persistent-kernel fast path: the whole time loop as ONE Pallas
        # grid with RW resident in VMEM (ops/lstm_cell.py) — kills the
        # per-step HBM weight stream that bounds the scan path. The scan
        # below remains the oracle/fallback (odd dims, other activations).
        from ...ops import lstm_cell as _lk

        gate_name = getattr(c, "gate_activation", "sigmoid")
        if _lk.supported(b, T, H, self.activation_name, str(gate_name),
                         weight_bytes=jnp.dtype(rw.dtype).itemsize):
            y, (hT, cT) = _lk.lstm_scan(xp, rw, peep, h0, c0, mask)
            if reverse:
                y = jnp.flip(y, axis=1)
            return y.astype(self.out_dtype), (hT, cT)

        def step(carry, inp):
            h, cc = carry
            xp_t, m_t = inp
            z = xp_t + lax.dot_general(
                h.astype(rw.dtype), rw, (((1,), (0,)), ((), ())),
                preferred_element_type=xp_t.dtype)
            zi, zf, zo, zg = jnp.split(z, 4, axis=-1)
            if peep is not None:
                zi = zi + cc * peep[0]
                zf = zf + cc * peep[1]
            i = gate_act(zi)
            f = gate_act(zf)
            g = act(zg)
            c_new = f * cc + i * g
            zo2 = zo + c_new * peep[2] if peep is not None else zo
            o = gate_act(zo2)
            h_new = o * act(c_new)
            if m_t is not None:
                mm = m_t[:, None].astype(h_new.dtype)
                h_new = mm * h_new + (1 - mm) * h
                c_new = mm * c_new + (1 - mm) * cc
            return (h_new, c_new), h_new

        xs = jnp.swapaxes(xp, 0, 1)
        if mask is not None:
            ms = jnp.swapaxes(mask, 0, 1)
            (hT, cT), ys = lax.scan(step, (h0, c0), (xs, ms))
        else:
            (hT, cT), ys = lax.scan(lambda cr, xt: step(cr, (xt, None)), (h0, c0), xs)
        y = jnp.swapaxes(ys, 0, 1)
        if reverse:
            y = jnp.flip(y, axis=1)
        return y.astype(self.out_dtype), (hT, cT)

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        x = self.maybe_dropout(x, train, rng)
        h0c0 = None
        idx = getattr(self, "index", None)
        if ctx is not None and idx is not None:
            h0c0 = ctx.get("rnn_state_in", {}).get(idx)
        y, hc = self._run(params, x, mask, h0c0)
        if ctx is not None and idx is not None:
            ctx.setdefault("rnn_state_out", {})[idx] = hc
        return y, state


@implements("LSTM")
class LSTMImpl(_BaseLSTMImpl):
    peepholes = False


@implements("GravesLSTM")
class GravesLSTMImpl(_BaseLSTMImpl):
    peepholes = True


@implements("GravesBidirectionalLSTM")
class GravesBidirectionalLSTMImpl(_BaseLSTMImpl):
    """Two param sets (suffix F/B, reference ``GravesBidirectionalLSTMParamInitializer``);
    direction outputs are summed (output stays [b, T, nOut])."""
    peepholes = True

    def init(self, rng):
        kf, kb = jax.random.split(rng)
        pf, _ = super().init(kf)
        pb, _ = super().init(kb)
        params = {k + "F": v for k, v in pf.items()}
        params.update({k + "B": v for k, v in pb.items()})
        return params, {}

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        x = self.maybe_dropout(x, train, rng)
        pf = {k[:-1]: v for k, v in params.items() if k.endswith("F")}
        pb = {k[:-1]: v for k, v in params.items() if k.endswith("B")}
        yf, _ = self._run(pf, x, mask, None)
        yb, _ = self._run(pb, x, mask, None, reverse=True)
        return yf + yb, state


@implements("SimpleRnn")
class SimpleRnnImpl(LayerImpl):
    """h_t = act(x_t W + h_{t-1} RW + b) (post-0.9 reference ``SimpleRnn``)."""

    def init(self, rng):
        c = self.conf
        k1, k2 = jax.random.split(rng)
        params = {
            "W": self._init_w(k1, (c.n_in, c.n_out), c.n_in, c.n_out),
            "RW": self._init_w(k2, (c.n_out, c.n_out), c.n_out, c.n_out),
            "b": self._init_b((c.n_out,)),
        }
        return params, {}

    def init_stream_state(self, batch):
        return jnp.zeros((batch, self.conf.n_out),
                         acc_dtype(self.compute_dtype))

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        x = self.maybe_dropout(x, train, rng)
        b, T, _ = x.shape
        H = self.conf.n_out
        ad = acc_dtype(self.compute_dtype)
        xp = (x.reshape(b * T, -1).astype(self.compute_dtype)
              @ params["W"].astype(self.compute_dtype)).astype(ad)
        xp = xp.reshape(b, T, H) + params["b"].astype(ad)
        rw = params["RW"].astype(self.compute_dtype)   # bf16-gemm policy
        act = self.activation

        def step(h, inp):
            xt, mt = inp
            h_new = act(xt + lax.dot_general(
                h.astype(rw.dtype), rw, (((1,), (0,)), ((), ())),
                preferred_element_type=xt.dtype))
            if mt is not None:
                mm = mt[:, None].astype(h_new.dtype)
                h_new = mm * h_new + (1 - mm) * h
            return h_new, h_new

        idx = getattr(self, "index", None)
        h0 = None
        if ctx is not None and idx is not None:
            h0 = ctx.get("rnn_state_in", {}).get(idx)
        if h0 is None:
            h0 = jnp.zeros((b, H), ad)
        h0 = _match_vma(h0, xp)
        xs = jnp.swapaxes(xp, 0, 1)
        if mask is not None:
            ms = jnp.swapaxes(mask, 0, 1)
            hT, ys = lax.scan(step, h0, (xs, ms))
        else:
            hT, ys = lax.scan(lambda h, xt: step(h, (xt, None)), h0, xs)
        if ctx is not None and idx is not None:
            ctx.setdefault("rnn_state_out", {})[idx] = hT
        return jnp.swapaxes(ys, 0, 1).astype(self.out_dtype), state


class _WrapperImpl(LayerImpl):
    def __init__(self, conf, gc, input_type=None):
        super().__init__(conf, gc, input_type)
        self.inner = impl_for(conf.inner, gc, input_type)

    def regularization(self, params):
        return self.inner.regularization(params)


@implements("Bidirectional")
class BidirectionalImpl(_WrapperImpl):
    """Generic bidirectional wrapper (modes concat/add/mul/ave)."""

    def init(self, rng):
        kf, kb = jax.random.split(rng)
        pf, sf = self.inner.init(kf)
        pb, sb = self.inner.init(kb)
        return {"fwd": pf, "bwd": pb}, {"fwd": sf, "bwd": sb}

    def _merge(self, a, b):
        mode = self.conf.mode
        if mode == "concat":
            return jnp.concatenate([a, b], axis=-1)
        if mode == "add":
            return a + b
        if mode == "mul":
            return a * b
        if mode == "ave":
            return 0.5 * (a + b)
        raise ValueError(f"Unknown Bidirectional mode {mode}")

    def _run_directions(self, params, state, x, train, rng, mask):
        kf = kb = None
        if rng is not None:
            kf, kb = jax.random.split(rng)
        yf, sf = self.inner.forward(params["fwd"], state["fwd"], x, train=train,
                                    rng=kf, mask=mask, ctx=None)
        xr = jnp.flip(x, axis=1)
        mr = None if mask is None else jnp.flip(mask, axis=1)
        yb, sb = self.inner.forward(params["bwd"], state["bwd"], xr, train=train,
                                    rng=kb, mask=mr, ctx=None)
        return yf, yb, {"fwd": sf, "bwd": sb}

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        yf, yb, new_state = self._run_directions(params, state, x, train, rng,
                                                 mask)
        return self._merge(yf, jnp.flip(yb, axis=1)), new_state

    def regularization(self, params):
        return (self.inner.regularization(params["fwd"])
                + self.inner.regularization(params["bwd"]))

    def forward_last(self, params, state, x, train=False, rng=None, mask=None,
                     ctx=None):
        """Per-direction final outputs, merged (reference/Keras
        ``Bidirectional(..., return_sequences=False)`` semantics): the
        BACKWARD direction's last step is its state after consuming the whole
        reversed sequence — full left context — not the t=T-1 slot of the
        flipped output sequence. Mask-correct for right-padded sequences:
        the recurrent impls freeze state on masked steps, so each direction's
        final output IS its last valid state (forward: padding freezes after
        the data; backward: the flipped mask holds state zero through the
        leading padding)."""
        yf, yb, new_state = self._run_directions(params, state, x, train, rng,
                                                 mask)
        return self._merge(yf[:, -1, :], yb[:, -1, :]), new_state


@implements("LastTimeStep")
class LastTimeStepImpl(_WrapperImpl):
    """Mask-aware last-timestep extraction (reference ``LastTimeStepVertex`` /
    ``LastTimeStep`` wrapper)."""

    def init(self, rng):
        return self.inner.init(rng)

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        if hasattr(self.inner, "forward_last"):
            # bidirectional inner: each direction contributes ITS OWN final
            # step (full context both ways), not the t=T-1 concat slot
            return self.inner.forward_last(params, state, x, train=train,
                                           rng=rng, mask=mask, ctx=ctx)
        y, new_state = self.inner.forward(params, state, x, train=train, rng=rng,
                                          mask=mask, ctx=ctx)
        if mask is None:
            out = y[:, -1, :]
        else:
            last = jnp.maximum(jnp.sum(mask > 0, axis=1).astype(jnp.int32) - 1, 0)
            out = jnp.take_along_axis(y, last[:, None, None], axis=1)[:, 0, :]
        return out, new_state
