"""Feed-forward layer implementations: Dense, Activation, Dropout, Embedding,
AutoEncoder.

TPU-native equivalents of reference ``nn/layers/feedforward/`` +
``nn/layers/BaseLayer.java`` (dense preOutput/activate) — gemms hit the MXU via a
single fused XLA dot with bfloat16 compute / f32 accumulation when the dtype
policy asks for it.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import LayerImpl, NoParamLayerImpl, implements, acc_dtype, pet_dtype


def _dot(x, w, compute_dtype):
    # low-precision compute accumulates in f32 on the MXU (see acc_dtype)
    return jax.lax.dot_general(x.astype(compute_dtype), w.astype(compute_dtype),
                               (((x.ndim - 1,), (0,)), ((), ())),
                               preferred_element_type=pet_dtype(compute_dtype))


@implements("DenseLayer")
class DenseImpl(LayerImpl):
    """Reference ``nn/layers/feedforward/dense/DenseLayer.java`` (via BaseLayer
    preOutput: z = xW + b, ``nn/layers/BaseLayer.java``)."""

    def init(self, rng):
        c = self.conf
        w = self._init_w(rng, (c.n_in, c.n_out), c.n_in, c.n_out)
        params = {"W": w}
        if getattr(c, "has_bias", True):
            params["b"] = self._init_b((c.n_out,))
        return params, {}

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        x = self.maybe_dropout(x, train, rng)
        z = _dot(x, params["W"], self.compute_dtype)
        if "b" in params:
            z = z + params["b"].astype(z.dtype)
        return self.activation(z).astype(self.out_dtype), state


@implements("ActivationLayer")
class ActivationImpl(NoParamLayerImpl):
    save_output = False

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        return self.activation(x), state


@implements("DropoutLayer")
class DropoutImpl(NoParamLayerImpl):
    """Reference ``nn/layers/DropoutLayer.java``; dropout = retain probability."""

    save_output = False

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        return self.maybe_dropout(x, train, rng), state


@implements("EmbeddingLayer")
class EmbeddingImpl(LayerImpl):
    """Reference ``nn/layers/feedforward/embedding/EmbeddingLayer.java``: input is
    a column of integer indices [b] or one-hot [b, nIn]; output [b, nOut].
    Lookup is a gather (no one-hot matmul) — efficient on TPU HBM."""

    def init(self, rng):
        c = self.conf
        params = {"W": self._init_w(rng, (c.n_in, c.n_out), c.n_in, c.n_out)}
        if getattr(c, "has_bias", True):
            params["b"] = self._init_b((c.n_out,))
        return params, {}

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        if x.ndim == 2 and x.shape[-1] == 1:
            x = x[..., 0]
        if x.ndim == 2:  # one-hot
            idx = jnp.argmax(x, axis=-1)
        else:
            idx = x.astype(jnp.int32)
        z = jnp.take(params["W"], idx, axis=0)
        if "b" in params:
            z = z + params["b"]
        return self.activation(z).astype(self.out_dtype), state


@implements("EmbeddingSequenceLayer")
class EmbeddingSequenceImpl(LayerImpl):
    """Index sequence [b, T] (or [b, T, 1]) → [b, T, nOut]."""

    def init(self, rng):
        c = self.conf
        params = {"W": self._init_w(rng, (c.n_in, c.n_out), c.n_in, c.n_out)}
        if getattr(c, "has_bias", False):
            params["b"] = self._init_b((c.n_out,))
        return params, {}

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        if x.ndim == 3 and x.shape[-1] == 1:
            x = x[..., 0]
        idx = x.astype(jnp.int32)
        z = jnp.take(params["W"], idx, axis=0)
        if "b" in params:
            z = z + params["b"]
        return self.activation(z).astype(self.out_dtype), state


@implements("AutoEncoder")
class AutoEncoderImpl(LayerImpl):
    """Denoising autoencoder (reference ``nn/layers/feedforward/autoencoder/AutoEncoder.java``).
    Supervised forward = encoder only; ``pretrain_loss`` gives the reconstruction
    objective with input corruption."""

    def init(self, rng):
        c = self.conf
        k1, k2 = jax.random.split(rng)
        params = {
            "W": self._init_w(k1, (c.n_in, c.n_out), c.n_in, c.n_out),
            "b": self._init_b((c.n_out,)),
            "vb": self._init_b((c.n_in,)),  # visible bias (reference param key "vb")
        }
        return params, {}

    def encode(self, params, x):
        return self.activation(_dot(x, params["W"], self.compute_dtype)
                               + params["b"])

    def decode(self, params, h):
        return self.activation(_dot(h, params["W"].T, self.compute_dtype)
                               + params["vb"])

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        x = self.maybe_dropout(x, train, rng)
        return self.encode(params, x).astype(self.out_dtype), state

    def pretrain_loss(self, params, x, rng):
        from ..losses import get_loss
        c = self.conf
        if c.corruption_level and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - c.corruption_level, x.shape)
            xc = jnp.where(keep, x, jnp.zeros_like(x))
        else:
            xc = x
        recon = self.decode(params, self.encode(params, xc))
        return get_loss(c.loss)(x, recon, "identity", None)
