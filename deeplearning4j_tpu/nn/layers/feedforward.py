"""Feed-forward layer implementations: Dense, Activation, Dropout, Embedding,
AutoEncoder.

TPU-native equivalents of reference ``nn/layers/feedforward/`` +
``nn/layers/BaseLayer.java`` (dense preOutput/activate) — gemms hit the MXU via a
single fused XLA dot with bfloat16 compute / f32 accumulation when the dtype
policy asks for it.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import LayerImpl, NoParamLayerImpl, implements, acc_dtype, pet_dtype


def _dot(x, w, compute_dtype):
    # low-precision compute accumulates in f32 on the MXU (see acc_dtype)
    return jax.lax.dot_general(x.astype(compute_dtype), w.astype(compute_dtype),
                               (((x.ndim - 1,), (0,)), ((), ())),
                               preferred_element_type=pet_dtype(compute_dtype))


@implements("DenseLayer")
class DenseImpl(LayerImpl):
    """Reference ``nn/layers/feedforward/dense/DenseLayer.java`` (via BaseLayer
    preOutput: z = xW + b, ``nn/layers/BaseLayer.java``)."""

    def init(self, rng):
        c = self.conf
        w = self._init_w(rng, (c.n_in, c.n_out), c.n_in, c.n_out)
        params = {"W": w}
        if getattr(c, "has_bias", True):
            params["b"] = self._init_b((c.n_out,))
        return params, {}

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        x = self.maybe_dropout(x, train, rng)
        z = _dot(x, params["W"], self.compute_dtype)
        if "b" in params:
            z = z + params["b"].astype(z.dtype)
        return self.activation(z).astype(self.out_dtype), state


@implements("ActivationLayer")
class ActivationImpl(NoParamLayerImpl):
    save_output = False

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        return self.activation(x), state


@implements("DropoutLayer")
class DropoutImpl(NoParamLayerImpl):
    """Reference ``nn/layers/DropoutLayer.java``; dropout = retain probability."""

    save_output = False

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        return self.maybe_dropout(x, train, rng), state


@implements("EmbeddingLayer")
class EmbeddingImpl(LayerImpl):
    """Reference ``nn/layers/feedforward/embedding/EmbeddingLayer.java``: input is
    a column of integer indices [b] or one-hot [b, nIn]; output [b, nOut].
    Lookup is a gather (no one-hot matmul) — efficient on TPU HBM."""

    def init(self, rng):
        c = self.conf
        params = {"W": self._init_w(rng, (c.n_in, c.n_out), c.n_in, c.n_out)}
        if getattr(c, "has_bias", True):
            params["b"] = self._init_b((c.n_out,))
        return params, {}

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        if x.ndim == 2 and x.shape[-1] == 1:
            x = x[..., 0]
        if x.ndim == 2:  # one-hot
            idx = jnp.argmax(x, axis=-1)
        else:
            idx = x.astype(jnp.int32)
        z = jnp.take(params["W"], idx, axis=0)
        if "b" in params:
            z = z + params["b"]
        return self.activation(z).astype(self.out_dtype), state


@implements("EmbeddingSequenceLayer")
class EmbeddingSequenceImpl(LayerImpl):
    """Index sequence [b, T] (or [b, T, 1]) → [b, T, nOut]."""

    def init(self, rng):
        c = self.conf
        params = {"W": self._init_w(rng, (c.n_in, c.n_out), c.n_in, c.n_out)}
        if getattr(c, "has_bias", False):
            params["b"] = self._init_b((c.n_out,))
        return params, {}

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        if x.ndim == 3 and x.shape[-1] == 1:
            x = x[..., 0]
        idx = x.astype(jnp.int32)
        z = jnp.take(params["W"], idx, axis=0)
        if "b" in params:
            z = z + params["b"]
        return self.activation(z).astype(self.out_dtype), state


@implements("AutoEncoder")
class AutoEncoderImpl(LayerImpl):
    """Denoising autoencoder (reference ``nn/layers/feedforward/autoencoder/AutoEncoder.java``).
    Supervised forward = encoder only; ``pretrain_loss`` gives the reconstruction
    objective with input corruption."""

    def init(self, rng):
        c = self.conf
        k1, k2 = jax.random.split(rng)
        params = {
            "W": self._init_w(k1, (c.n_in, c.n_out), c.n_in, c.n_out),
            "b": self._init_b((c.n_out,)),
            "vb": self._init_b((c.n_in,)),  # visible bias (reference param key "vb")
        }
        return params, {}

    def encode(self, params, x):
        return self.activation(_dot(x, params["W"], self.compute_dtype)
                               + params["b"])

    def decode(self, params, h):
        return self.activation(_dot(h, params["W"].T, self.compute_dtype)
                               + params["vb"])

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        x = self.maybe_dropout(x, train, rng)
        return self.encode(params, x).astype(self.out_dtype), state

    def pretrain_loss(self, params, x, rng):
        from ..losses import get_loss
        c = self.conf
        if c.corruption_level and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - c.corruption_level, x.shape)
            xc = jnp.where(keep, x, jnp.zeros_like(x))
        else:
            xc = x
        recon = self.decode(params, self.encode(params, xc))
        return get_loss(c.loss)(x, recon, "identity", None)


@implements("RBM")
class RBMImpl(LayerImpl):
    """Restricted Boltzmann Machine (reference
    ``nn/layers/feedforward/rbm/RBM.java:1``: ``propUp`` :322, ``propDown``
    :388, ``contrastiveDivergence`` :103). Params follow the reference's
    pretrain-param layout: ``W`` [nIn, nOut], hidden bias ``b``, visible
    bias ``vb``.

    CD-k via the free-energy surrogate (see the config docstring): for
    binary hidden units F(v) = visible_term(v) - Σ softplus(vW+b), and
    differentiating ``mean(F(v0) - F(stop_grad(v_k)))`` reproduces the
    reference's ⟨v0 h0⟩ − ⟨vk hk⟩ update EXACTLY (checked against
    hand-computed outer products in tests). Gaussian hidden uses the
    quadratic free energy (also exact: mean activation = z). Rectified
    hidden has no closed-form free energy; the softplus form is the
    standard smooth surrogate — its implied hidden statistic is
    sigmoid(z), not relu(z), so updates approximate (rather than equal)
    the reference's noisy-ReLU CD statistics."""

    _HIDDEN = ("binary", "rectified", "gaussian", "identity")
    _VISIBLE = ("binary", "gaussian", "linear", "identity")

    def init(self, rng):
        c = self.conf
        if c.hidden_unit not in self._HIDDEN:
            raise ValueError(f"RBM hidden_unit '{c.hidden_unit}' not in "
                             f"{self._HIDDEN}")
        if c.visible_unit not in self._VISIBLE:
            raise ValueError(f"RBM visible_unit '{c.visible_unit}' not in "
                             f"{self._VISIBLE}")
        params = {
            "W": self._init_w(rng, (c.n_in, c.n_out), c.n_in, c.n_out),
            "b": self._init_b((c.n_out,)),
            "vb": self._init_b((c.n_in,)),
        }
        return params, {}

    # -- conditionals ------------------------------------------------------
    def _hidden_z(self, params, v):
        return _dot(v, params["W"], self.compute_dtype) + params["b"]

    def prop_up(self, params, v):
        """Mean hidden activation given visible (reference ``propUp``)."""
        z = self._hidden_z(params, v)
        hu = self.conf.hidden_unit
        if hu == "binary":
            return jax.nn.sigmoid(z)
        if hu == "rectified":
            return jax.nn.relu(z)
        return z  # gaussian / identity: mean = z

    def prop_down(self, params, h):
        """Mean visible activation given hidden (reference ``propDown``)."""
        z = _dot(h, params["W"].T, self.compute_dtype) + params["vb"]
        if self.conf.visible_unit == "binary":
            return jax.nn.sigmoid(z)
        return z  # gaussian / linear / identity

    def _sample_h(self, params, v, key):
        hu = self.conf.hidden_unit
        z = self._hidden_z(params, v)
        if hu == "binary":
            p = jax.nn.sigmoid(z)
            return jax.random.bernoulli(key, p).astype(z.dtype)
        if hu == "rectified":
            # reference: max(0, z + N(0, sigmoid(z))) noisy rectified units
            return jax.nn.relu(z + jnp.sqrt(jax.nn.sigmoid(z))
                               * jax.random.normal(key, z.shape, z.dtype))
        if hu == "gaussian":
            return z + jax.random.normal(key, z.shape, z.dtype)
        return z

    def _sample_v(self, params, h, key):
        vu = self.conf.visible_unit
        mean = self.prop_down(params, h)
        if vu == "binary":
            return jax.random.bernoulli(key, mean).astype(mean.dtype)
        if vu == "gaussian":
            return mean + jax.random.normal(key, mean.shape, mean.dtype)
        return mean  # linear / identity: mean-field

    def free_energy(self, params, v):
        """F(v); binary-visible term −v·vb, gaussian/linear ½‖v−vb‖²."""
        z = self._hidden_z(params, v)
        if self.conf.hidden_unit in ("gaussian", "identity"):
            # quadratic form: mean hidden activation is z for both, so the
            # surrogate gradient carries the same h = z statistics prop_up
            # reports (softplus would silently optimize a binary model)
            hidden = -0.5 * jnp.sum(z * z, axis=-1)
        else:
            hidden = -jnp.sum(jax.nn.softplus(z), axis=-1)
        if self.conf.visible_unit == "binary":
            vis = -v @ params["vb"]
        else:
            diff = v - params["vb"]
            vis = 0.5 * jnp.sum(diff * diff, axis=-1)
        return vis + hidden

    def gibbs_chain(self, params, v0, rng, k):
        """k alternating (h|v, v|h) sampling steps (reference
        ``contrastiveDivergence`` :103 'k steps of gibbs sampling')."""
        v = v0
        for i in range(k):
            kh, kv, rng = jax.random.split(rng, 3)
            h = self._sample_h(params, v, kh)
            v = self._sample_v(params, h, kv)
        return v

    def forward(self, params, state, x, train=False, rng=None, mask=None,
                ctx=None):
        """Supervised forward = propUp mean activation (reference
        ``activate`` :424-426)."""
        x = self.maybe_dropout(x, train, rng)
        return self.prop_up(params, x).astype(self.out_dtype), state

    def pretrain_loss(self, params, x, rng):
        c = self.conf
        rng = jax.random.PRNGKey(0) if rng is None else rng
        vk = jax.lax.stop_gradient(
            self.gibbs_chain(params, x, rng, max(1, int(c.k))))
        loss = jnp.mean(self.free_energy(params, x)
                        - self.free_energy(params, vk))
        if c.sparsity:
            # sparsity target on mean hidden activation (reference
            # applySparsity): penalize deviation from the target rate
            mean_h = jnp.mean(self.prop_up(params, x), axis=0)
            loss = loss + jnp.sum((mean_h - c.sparsity) ** 2)
        return loss

    def reconstruction_error(self, params, x):
        """Mean-squared reconstruction v → h_mean → v_mean (monitoring
        metric; CD's surrogate loss is not itself interpretable)."""
        recon = self.prop_down(params, self.prop_up(params, x))
        return jnp.mean((recon - x) ** 2)
