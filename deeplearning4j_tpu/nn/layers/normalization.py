"""Normalization implementations: BatchNormalization, LocalResponseNormalization.

TPU-native equivalents of reference ``nn/layers/normalization/{BatchNormalization,
LocalResponseNormalization}.java`` (cuDNN helper hooks at
``CudnnBatchNormalizationHelper``; here the XLA schedule plays that role).
Running mean/var live in the layer *state* pytree — the functional replacement
for the reference's mutable mean/var params — and are updated only when
``train=True``.

BN is pure HBM traffic, so the training path is written for the memory system
(see PERF.md):

 - batch statistics are a *single* fused pass over ``x``: two reductions
   (sum, sum-of-squares) with f32 accumulators via the reduce's ``dtype=`` —
   ``jnp.var``'s mean-then-deviations formulation costs an extra full
   traversal of every conv output.
 - the per-channel statistics are tagged ``checkpoint_name`` so the train
   step's remat policy (``GlobalConfig.remat``) stores them — tiny [C]
   vectors — while the normalized output itself is recomputed in the
   backward pass instead of being round-tripped through HBM
   (``save_output = False``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from .base import LayerImpl, implements, acc_dtype
from ..weights import host_full


@implements("BatchNormalization")
class BatchNormImpl(LayerImpl):
    """Per-channel BN for [b, f] and NHWC [b, h, w, c] activations.
    Params gamma/beta (reference keys), state mean/var with ``decay`` EMA
    (reference ``BatchNormalization.java`` decay semantics:
    running = decay * running + (1-decay) * batch)."""

    save_output = False  # normalize is elementwise given stats: recompute

    def init(self, rng):
        c = self.conf
        n = c.n_out
        params = {}
        if not c.lock_gamma_beta:
            params["gamma"] = host_full((n,), c.gamma, self.dtype)
            params["beta"] = host_full((n,), c.beta, self.dtype)
        sd = acc_dtype(self.compute_dtype)  # stats precision
        state = {"mean": host_full((n,), 0, sd),
                 "var": host_full((n,), 1, sd)}
        return params, state

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        c = self.conf
        sd = acc_dtype(self.compute_dtype)
        axes = tuple(range(x.ndim - 1))  # all but channel/feature
        if train:
            if jnp.dtype(x.dtype).itemsize < 4:
                # one fused traversal of x: f32-accumulated sum and
                # sum-of-squares. E[x^2]-E[x]^2 cancels catastrophically when
                # |mean| >> std, but sub-32-bit x cannot *represent* such
                # data (bf16's 8-bit mantissa bounds mean/std ≈ 256, keeping
                # the f32 error below the input quantization) — so the fused
                # form is safe exactly where it is fast. Guard is on x's own
                # dtype: full-precision inputs take the exact path below even
                # under a bf16 compute policy.
                mean = jnp.mean(x, axis=axes, dtype=sd)
                meansq = jnp.mean(jnp.square(x.astype(sd)), axis=axes)
                var = jnp.maximum(meansq - mean * mean, 0.0)
            else:
                # full-precision compute: shifted two-pass (jnp.var) — exact
                # for large-mean data; f32/f64 runs are correctness-first
                mean = jnp.mean(x, axis=axes, dtype=sd)
                var = jnp.var(x.astype(sd), axis=axes)
            mean = checkpoint_name(mean, "dl4j_stat")
            var = checkpoint_name(var, "dl4j_stat")
            new_state = {
                "mean": c.decay * state["mean"] + (1 - c.decay) * mean,
                "var": c.decay * state["var"] + (1 - c.decay) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = jax.lax.rsqrt((var + c.eps).astype(sd))
        y = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
        if "gamma" in params:
            y = y * params["gamma"].astype(x.dtype) + params["beta"].astype(x.dtype)
        else:
            y = y * c.gamma + c.beta
        return y, new_state

    def regularization(self, params):
        return 0.0  # reference: no l1/l2 on BN params by default


@implements("LayerNormalization")
class LayerNormImpl(LayerImpl):
    """Per-position LayerNorm over the last (feature) dim, learned
    gain/bias (net-new: the reference predates transformers — see the
    config class). Stateless; normalizes [b, F] or [b, T, F] tokens
    independently, so a sharded time dim needs no collectives and the
    whole op fuses into one elementwise XLA kernel around two f32-
    accumulated moments."""

    save_output = False  # elementwise given the two moments: recompute

    def init(self, rng):
        n = self.conf.n_out
        return {"gain": host_full((n,), 1, self.dtype),
                "bias": host_full((n,), 0, self.dtype)}, {}

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        sd = acc_dtype(self.compute_dtype)
        xs = x.astype(sd)
        mean = jnp.mean(xs, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xs - mean), axis=-1, keepdims=True)
        inv = jax.lax.rsqrt(var + self.conf.eps)
        y = (xs - mean) * inv
        y = (y * params["gain"].astype(sd) + params["bias"].astype(sd))
        return y.astype(x.dtype), state

    def regularization(self, params):
        return 0.0  # norm params free of l1/l2, like BN


@implements("LocalResponseNormalization")
class LRNImpl(LayerImpl):
    """Across-channel LRN on NHWC (reference ``LocalResponseNormalization.java``):
    y = x / (k + alpha * sum_{j in window} x_j^2)^beta."""

    save_output = False

    def init(self, rng):
        return {}, {}

    def regularization(self, params):
        return 0.0

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        c = self.conf
        half = int(c.n) // 2
        sq = x * x
        # sum over channel window via padded cumulative trick (static unroll of
        # the small window; XLA fuses this into one elementwise kernel)
        acc = jnp.zeros_like(sq)
        ch = x.shape[-1]
        for off in range(-half, half + 1):
            if off == 0:
                acc = acc + sq
            elif off < 0:
                acc = acc.at[..., :off].add(sq[..., -off:])
            else:
                acc = acc.at[..., off:].add(sq[..., :ch - off])
        denom = jnp.power(c.k + c.alpha * acc, c.beta)
        return x / denom, state
