"""Normalization implementations: BatchNormalization, LocalResponseNormalization.

TPU-native equivalents of reference ``nn/layers/normalization/{BatchNormalization,
LocalResponseNormalization}.java`` (cuDNN helper hooks in the reference; here XLA
fuses the normalization arithmetic into neighbors). Running mean/var live in the
layer *state* pytree — the functional replacement for the reference's mutable
mean/var params — and are updated only when ``train=True``.
"""
from __future__ import annotations

import jax.numpy as jnp

from .base import LayerImpl, implements, acc_dtype


@implements("BatchNormalization")
class BatchNormImpl(LayerImpl):
    """Per-channel BN for [b, f] and NHWC [b, h, w, c] activations.
    Params gamma/beta (reference keys), state mean/var with ``decay`` EMA
    (reference ``BatchNormalization.java`` decay semantics:
    running = decay * running + (1-decay) * batch)."""

    def init(self, rng):
        c = self.conf
        n = c.n_out
        params = {}
        if not c.lock_gamma_beta:
            params["gamma"] = jnp.full((n,), c.gamma, self.dtype)
            params["beta"] = jnp.full((n,), c.beta, self.dtype)
        sd = acc_dtype(self.compute_dtype)  # stats precision
        state = {"mean": jnp.zeros((n,), sd),
                 "var": jnp.ones((n,), sd)}
        return params, state

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        c = self.conf
        sd = acc_dtype(self.compute_dtype)
        axes = tuple(range(x.ndim - 1))  # all but channel/feature
        if train:
            mean = jnp.mean(x.astype(sd), axis=axes)
            var = jnp.var(x.astype(sd), axis=axes)
            new_state = {
                "mean": c.decay * state["mean"] + (1 - c.decay) * mean,
                "var": c.decay * state["var"] + (1 - c.decay) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = 1.0 / jnp.sqrt(var + c.eps)
        y = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
        if "gamma" in params:
            y = y * params["gamma"].astype(x.dtype) + params["beta"].astype(x.dtype)
        else:
            y = y * c.gamma + c.beta
        return y, new_state

    def regularization(self, params):
        return 0.0  # reference: no l1/l2 on BN params by default


@implements("LocalResponseNormalization")
class LRNImpl(LayerImpl):
    """Across-channel LRN on NHWC (reference ``LocalResponseNormalization.java``):
    y = x / (k + alpha * sum_{j in window} x_j^2)^beta."""

    def init(self, rng):
        return {}, {}

    def regularization(self, params):
        return 0.0

    def forward(self, params, state, x, train=False, rng=None, mask=None, ctx=None):
        c = self.conf
        half = int(c.n) // 2
        sq = x * x
        # sum over channel window via padded cumulative trick (static unroll of
        # the small window; XLA fuses this into one elementwise kernel)
        acc = jnp.zeros_like(sq)
        ch = x.shape[-1]
        for off in range(-half, half + 1):
            if off == 0:
                acc = acc + sq
            elif off < 0:
                acc = acc.at[..., :off].add(sq[..., -off:])
            else:
                acc = acc.at[..., off:].add(sq[..., :ch - off])
        denom = jnp.power(c.k + c.alpha * acc, c.beta)
        return x / denom, state
