"""Loss functions.

TPU-native equivalent of ND4J's ``ILossFunction`` implementations consumed by the
reference's output layers (``nn/conf/layers/OutputLayer`` et al.; the enum lives in
ND4J ``LossFunctions.LossFunction``). The reference computes ``computeScore`` and a
hand-written ``computeGradient`` per loss; here each loss exposes only a score —
gradients flow from AD of the jitted training step (SURVEY.md §7 Phase 0 idiom
shift: trace/compile instead of op-by-op dispatch).

Numerically sensitive combinations (softmax + MCXENT / NLL, sigmoid + XENT) are
fused on logits via ``log_softmax`` / ``log_sigmoid`` so bfloat16/float32 TPU runs
stay stable — the reference relies on float64 fallbacks instead.

Conventions (matching the reference):
 - ``labels`` and ``preoutput`` are ``[batch, ..., nOut]``.
 - ``mask`` is ``None`` or broadcastable to per-example/per-timestep weighting
   (reference: ``LossUtil.applyMask``).
 - returned score is the *sum over examples / minibatch-size* (the reference's
   ``computeScore(..., average=true)``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .activations import get_activation

__all__ = ["LossFunction", "get_loss", "LossFunctions"]

_EPS = 1e-7


def _apply_activation(preout, activation):
    return get_activation(activation)(preout)


def _reduce(per_elem, mask):
    """Sum loss over feature axis, apply mask, average over all leading axes.

    ``per_elem``: [batch, ..., nOut] elementwise loss contributions.
    ``mask``: None, [batch], [batch, T] (rnn), or broadcastable to per_elem[..., 0].
    Average divides by minibatch (and, with a time mask, by active timesteps),
    matching the reference's score-averaging semantics.
    """
    per_ex = jnp.sum(per_elem, axis=-1)  # [batch, ...]
    if mask is not None:
        mask = jnp.broadcast_to(mask.astype(per_ex.dtype), per_ex.shape)
        per_ex = per_ex * mask
    # Divide by minibatch size only (masked steps contribute 0 but do not shrink
    # the denominator) — reference semantics: LossUtil.applyMask zeroes entries,
    # computeScore(..., average=true) divides by minibatch.
    batch = per_ex.shape[0] if per_ex.ndim > 0 else 1
    return jnp.sum(per_ex) / max(batch, 1)


# ---------------------------------------------------------------------------
# Individual losses. Each: f(labels, preoutput, activation, mask) -> scalar
# ---------------------------------------------------------------------------

def _mse(labels, preout, activation, mask):
    out = _apply_activation(preout, activation)
    return _reduce((out - labels) ** 2, mask)


def _l2(labels, preout, activation, mask):
    # L2 = un-averaged-over-features squared error (reference LossL2); same as MSE
    # under our reduction conventions.
    return _mse(labels, preout, activation, mask)


def _mae(labels, preout, activation, mask):
    out = _apply_activation(preout, activation)
    return _reduce(jnp.abs(out - labels), mask)


def _mape(labels, preout, activation, mask):
    out = _apply_activation(preout, activation)
    return _reduce(100.0 * jnp.abs((labels - out) / (labels + _EPS)), mask)


def _msle(labels, preout, activation, mask):
    out = _apply_activation(preout, activation)
    d = jnp.log1p(jnp.maximum(out, -1 + _EPS)) - jnp.log1p(jnp.maximum(labels, -1 + _EPS))
    return _reduce(d * d, mask)


def _mcxent(labels, preout, activation, mask):
    act = str(activation).lower()
    if act == "softmax":
        logp = jax.nn.log_softmax(preout, axis=-1)
        return _reduce(-labels * logp, mask)
    out = _apply_activation(preout, activation)
    return _reduce(-labels * jnp.log(jnp.clip(out, _EPS, 1.0)), mask)


def _sparse_mcxent(labels, preout, activation, mask):
    # labels: integer class indices [batch, ...]
    labels = labels.astype(jnp.int32)
    logp = jax.nn.log_softmax(preout, axis=-1)
    picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)
    return _reduce(-picked, mask)


def _xent(labels, preout, activation, mask):
    act = str(activation).lower()
    if act == "sigmoid":
        # stable: -(y*log σ(x) + (1-y)*log σ(-x))
        per = -(labels * jax.nn.log_sigmoid(preout)
                + (1.0 - labels) * jax.nn.log_sigmoid(-preout))
        return _reduce(per, mask)
    out = jnp.clip(_apply_activation(preout, activation), _EPS, 1.0 - _EPS)
    per = -(labels * jnp.log(out) + (1.0 - labels) * jnp.log(1.0 - out))
    return _reduce(per, mask)


def _nll(labels, preout, activation, mask):
    # Reference treats NEGATIVELOGLIKELIHOOD as MCXENT (LossNegativeLogLikelihood
    # extends LossMCXENT).
    return _mcxent(labels, preout, activation, mask)


def _kld(labels, preout, activation, mask):
    out = jnp.clip(_apply_activation(preout, activation), _EPS, 1.0)
    lab = jnp.clip(labels, _EPS, 1.0)
    return _reduce(lab * (jnp.log(lab) - jnp.log(out)), mask)


def _poisson(labels, preout, activation, mask):
    out = _apply_activation(preout, activation)
    return _reduce(out - labels * jnp.log(jnp.maximum(out, _EPS)), mask)


def _cosine_proximity(labels, preout, activation, mask):
    out = _apply_activation(preout, activation)
    dot = jnp.sum(labels * out, axis=-1, keepdims=True)
    nl = jnp.linalg.norm(labels, axis=-1, keepdims=True)
    no = jnp.linalg.norm(out, axis=-1, keepdims=True)
    cos = dot / jnp.maximum(nl * no, _EPS)
    return _reduce(-cos, mask)


def _hinge(labels, preout, activation, mask):
    out = _apply_activation(preout, activation)
    # labels in {-1, +1} (reference converts {0,1} labels upstream)
    return _reduce(jnp.maximum(0.0, 1.0 - labels * out), mask)


def _squared_hinge(labels, preout, activation, mask):
    out = _apply_activation(preout, activation)
    return _reduce(jnp.maximum(0.0, 1.0 - labels * out) ** 2, mask)


def _l1(labels, preout, activation, mask):
    return _mae(labels, preout, activation, mask)


def _reconstruction_xent(labels, preout, activation, mask):
    return _xent(labels, preout, activation, mask)


_LOSSES = {
    "mse": _mse,
    "squared_loss": _mse,
    "l2": _l2,
    "l1": _l1,
    "mean_absolute_error": _mae,
    "mean_absolute_percentage_error": _mape,
    "mean_squared_logarithmic_error": _msle,
    "mcxent": _mcxent,
    "sparse_mcxent": _sparse_mcxent,
    "negativeloglikelihood": _nll,
    "xent": _xent,
    "reconstruction_crossentropy": _reconstruction_xent,
    "kl_divergence": _kld,
    "poisson": _poisson,
    "cosine_proximity": _cosine_proximity,
    "hinge": _hinge,
    "squared_hinge": _squared_hinge,
}


class LossFunction:
    """String-keyed registry mirroring ND4J ``LossFunctions.LossFunction``."""

    MSE = "mse"
    L1 = "l1"
    L2 = "l2"
    XENT = "xent"
    MCXENT = "mcxent"
    SPARSE_MCXENT = "sparse_mcxent"
    SQUARED_LOSS = "squared_loss"
    RECONSTRUCTION_CROSSENTROPY = "reconstruction_crossentropy"
    NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"
    COSINE_PROXIMITY = "cosine_proximity"
    HINGE = "hinge"
    SQUARED_HINGE = "squared_hinge"
    KL_DIVERGENCE = "kl_divergence"
    MEAN_ABSOLUTE_ERROR = "mean_absolute_error"
    MEAN_ABSOLUTE_PERCENTAGE_ERROR = "mean_absolute_percentage_error"
    MEAN_SQUARED_LOGARITHMIC_ERROR = "mean_squared_logarithmic_error"
    POISSON = "poisson"

    @staticmethod
    def names():
        return sorted(_LOSSES)


LossFunctions = LossFunction  # reference-style alias


def get_loss(name):
    """Resolve a loss by name; callables (custom ILossFunction equivalents) pass through."""
    if callable(name):
        return name
    key = str(name).lower()
    if key not in _LOSSES:
        raise ValueError(f"Unknown loss '{name}'. Known: {sorted(_LOSSES)}")
    return _LOSSES[key]
