"""Activation functions.

TPU-native equivalent of the ND4J ``IActivation``/``Activation`` enum surface the
reference consumes everywhere (e.g. ``NeuralNetConfiguration.Builder.activation``,
reference ``deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/conf/NeuralNetConfiguration.java:604``).

Unlike the reference — where each activation has a hand-written
``backprop(in, epsilon)`` executed op-by-op over JNI — activations here are pure
``jax.numpy`` functions fused by XLA into the surrounding computation, and their
gradients come from AD. That removes the per-op device-dispatch boundary that
dominates the reference's hot loop (SURVEY.md §3.1).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["Activation", "get_activation", "resolve_activation"]


def _identity(x):
    return x


@jax.custom_jvp
def _relu(x):
    return jnp.maximum(x, 0)


@_relu.defjvp
def _relu_jvp(primals, tangents):
    # Differentiate against the OUTPUT, not the input: relu' = 1{y > 0}
    # almost everywhere (the reference's hand-written backprop uses the same
    # subgradient at 0). On conv nets the output is already stored as the
    # next layer's AD residual, so keying the derivative off it lets XLA drop
    # the pre-activation tensor — one less full activation round-trip through
    # HBM per relu (PERF.md). The JVP rule is linear in the tangent, so JAX
    # transposes it for reverse mode and forward-mode AD keeps working
    # (a custom_vjp here would break jvp/jacfwd for library users).
    (x,), (t,) = primals, tangents
    y = jnp.maximum(x, 0)
    return y, jnp.where(y > 0, t, jnp.zeros_like(t))


def _relu6(x):
    return jnp.clip(x, 0.0, 6.0)


def _leakyrelu(x, alpha=0.01):
    return jnp.where(x >= 0, x, alpha * x)


def _elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


def _selu(x):
    return jax.nn.selu(x)


def _gelu(x):
    return jax.nn.gelu(x)


def _swish(x):
    return jax.nn.silu(x)


def _mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def _sigmoid(x):
    return jax.nn.sigmoid(x)


def _hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def _tanh(x):
    return jnp.tanh(x)


def _hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def _rationaltanh(x):
    # 1.7159 * tanh(2x/3) approximation via rational function, matching ND4J's
    # ActivationRationalTanh formula.
    a = jnp.abs(x)
    p = 1.0 + a + x * x * (1.41645 + a * 0.052357)
    return jnp.sign(x) * (1.0 - 1.0 / p) * 1.7159


def _rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


def _softmax(x):
    return jax.nn.softmax(x, axis=-1)


def _softplus(x):
    return jax.nn.softplus(x)


def _softsign(x):
    return jax.nn.soft_sign(x)


def _cube(x):
    return x * x * x


def _thresholdedrelu(x, theta=1.0):
    return jnp.where(x > theta, x, 0.0)


_ACTIVATIONS = {
    "identity": _identity,
    "linear": _identity,
    "relu": _relu,
    "relu6": _relu6,
    "leakyrelu": _leakyrelu,
    "elu": _elu,
    "selu": _selu,
    "gelu": _gelu,
    "swish": _swish,
    "silu": _swish,
    "mish": _mish,
    "sigmoid": _sigmoid,
    "hardsigmoid": _hardsigmoid,
    "tanh": _tanh,
    "hardtanh": _hardtanh,
    "rationaltanh": _rationaltanh,
    "rectifiedtanh": _rectifiedtanh,
    "softmax": _softmax,
    "softplus": _softplus,
    "softsign": _softsign,
    "cube": _cube,
    "thresholdedrelu": _thresholdedrelu,
}


class Activation:
    """String-keyed activation registry mirroring ND4J's ``Activation`` enum values."""

    CUBE = "cube"
    ELU = "elu"
    GELU = "gelu"
    HARDSIGMOID = "hardsigmoid"
    HARDTANH = "hardtanh"
    IDENTITY = "identity"
    LEAKYRELU = "leakyrelu"
    MISH = "mish"
    RATIONALTANH = "rationaltanh"
    RECTIFIEDTANH = "rectifiedtanh"
    RELU = "relu"
    RELU6 = "relu6"
    SELU = "selu"
    SIGMOID = "sigmoid"
    SOFTMAX = "softmax"
    SOFTPLUS = "softplus"
    SOFTSIGN = "softsign"
    SWISH = "swish"
    TANH = "tanh"
    THRESHOLDEDRELU = "thresholdedrelu"

    @staticmethod
    def names():
        return sorted(_ACTIVATIONS)


def get_activation(name):
    """Resolve an activation by name (case-insensitive) or pass callables
    through. Parametric spellings stay JSON-serializable strings:
    ``"leakyrelu:0.3"``, ``"elu:0.7"``, ``"thresholdedrelu:1.5"`` bind the
    parameter (the reference's IActivation fields, e.g.
    ``ActivationLReLU(alpha)``)."""
    if callable(name):
        return name
    key = str(name).lower()
    if ":" in key:
        base, _, arg = key.partition(":")
        val = float(arg)
        if base == "leakyrelu":
            return lambda x: _leakyrelu(x, val)
        if base == "elu":
            return lambda x: _elu(x, val)
        if base == "thresholdedrelu":
            return lambda x: _thresholdedrelu(x, val)
        raise ValueError(f"Unknown parametric activation '{name}'")
    if key not in _ACTIVATIONS:
        raise ValueError(f"Unknown activation '{name}'. Known: {sorted(_ACTIVATIONS)}")
    return _ACTIVATIONS[key]


# Alias used by config code.
resolve_activation = get_activation
