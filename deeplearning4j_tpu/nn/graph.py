"""ComputationGraph: DAG network container with multi-input/multi-output.

TPU-native equivalent of reference ``nn/graph/ComputationGraph.java`` (3363 LoC;
init/topo-sort :394/:1190, ``fit(DataSetIterator)`` :863,
``fit(MultiDataSetIterator)`` :988, ``computeGradientAndScore`` :1298,
``calcBackpropGradients(truncatedBPTT, externalEpsilons)`` :1629,
``feedForward`` :1361-1440).

As with MultiLayerNetwork, the architectural shift is whole-graph compilation:
one jitted XLA computation covers forward over the cached topological order,
loss on every output vertex, AD backward, gradient normalization, updater, and
the parameter update, with params/updater state donated. External-errors
training (the reference's externalEpsilons path, used to couple a graph to an
outside loss) is ``fit_external_errors``: VJP of the outputs against caller
epsilons inside the same jitted step.
"""
from __future__ import annotations

import contextlib
import logging
import time
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .conf import BackpropType, CacheMode, GradientNormalization
from ..monitor.jitwatch import monitored_jit
from .conf.graph import ComputationGraphConfiguration
from .conf.layers import Layer
from .conf.inputs import InputTypeConvolutional
from jax.ad_checkpoint import checkpoint_name

from .layers import impl_for
from .layers.base import remat_enabled, remat_policy
from .multilayer import _n_iterations, _scan_iterations
from ..datasets.dataset import (DataSet, MultiDataSet, DataSetIterator,
                                ListDataSetIterator)
from ..datasets.prefetch import wrap_for_training
from ..optimize.updater import NetworkUpdater, normalize_gradients
from .. import monitor as _mon

log = logging.getLogger(__name__)
_tm = jax.tree_util.tree_map


def fused_softmax_skip_set(conf, impls):
    """Output-layer vertices whose forwards the loss pass SKIPS: ``loss_on``
    consumes their *input* activations so the fused softmax/xent path
    applies to preoutput. Only safe when nothing downstream consumes the
    output activation. Shared by ``ComputationGraph._loss_fn`` and the
    pipeline-parallel head (``parallel/pipeline.py``) so the rule cannot
    diverge between the two loss paths."""
    consumed = {i for ins in conf.vertex_inputs.values() for i in ins}
    return frozenset(n for n in conf.network_outputs
                     if hasattr(impls.get(n), "loss_on")
                     and n not in consumed)


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.gc = conf.global_conf
        self.topo: List[str] = conf.topological_order()
        self.impls: Dict[str, object] = {}
        self.params = None
        self.states = None
        self.updater = None
        self.updater_state = None
        self.iteration_count = 0
        self.epoch_count = 0
        self.listeners: List = []
        self.score_ = float("nan")
        self.last_etl_ms = 0.0
        self.halt_requested = False  # TrainingHealthListener "halt" action
        self._rng = None
        self._jit_step = None
        self._jit_ext_step = None
        self._jit_output = {}
        self._types = None

    # ------------------------------------------------------------------ init
    def init(self, params=None):
        conf = self.conf
        # shape inference (idempotent; from_json configs arrive unresolved)
        types = conf.infer_shapes()
        self._types = types

        layer_names = [n for n in self.topo if isinstance(conf.vertices[n], Layer)]
        key = jax.random.PRNGKey(self.gc.seed)
        self._rng, *keys = jax.random.split(key, len(layer_names) + 1)
        for name in layer_names:
            in_name = conf.vertex_inputs[name][0] if conf.vertex_inputs[name] else None
            it = types.get(in_name) if in_name else None
            if name in conf.input_preprocessors and it is not None:
                it = conf.input_preprocessors[name].get_output_type(it)
            self.impls[name] = impl_for(conf.vertices[name], self.gc, it)
            self.impls[name].index = name
        if params is not None:
            self.params = params
            self.states = {n: self.impls[n].init(k)[1]
                           for n, k in zip(layer_names, keys)}
        else:
            self.params, self.states = {}, {}
            for name, k in zip(layer_names, keys):
                p, s = self.impls[name].init(k)
                self.params[name] = p
                self.states[name] = s
        layer_updaters = {}
        for name in layer_names:
            u = getattr(conf.vertices[name], "updater", None) or self.gc.updater
            layer_updaters[name] = u
        self.updater = NetworkUpdater(layer_updaters)
        self.updater_state = self.updater.init_state(self.params)
        return self

    # -------------------------------------------------------------- forward
    def _adapt_inputs(self, inputs):
        """User-facing conv inputs are NCHW; internal layout NHWC."""
        out = []
        its = self.conf.input_types or [None] * len(inputs)
        for x, it in zip(inputs, its):
            if (isinstance(it, InputTypeConvolutional) and x.ndim == 4
                    and x.shape[1] == it.channels and x.shape[2] == it.height):
                x = jnp.transpose(x, (0, 2, 3, 1))
            out.append(x)
        return out

    def _apply_graph(self, params, states, inputs, input_masks, train, rng,
                     skip=(), rnn_state_in=None):
        """Forward over the cached topo order. Returns (activations dict,
        new_states, masks dict, ctx). ``skip``: vertex names not to execute
        (the training loss path skips output-layer forwards; ``loss_on``
        evaluates them on preoutput with fused softmax/xent).
        ``rnn_state_in``: {layer name → carry} for TBPTT/streaming."""
        conf = self.conf
        acts: Dict[str, object] = dict(zip(conf.network_inputs, inputs))
        masks = dict(zip(conf.network_inputs,
                         input_masks or [None] * len(conf.network_inputs)))
        ctx = {"inputs": acts, "input_masks": masks}
        if rnn_state_in is not None:
            ctx["rnn_state_in"] = rnn_state_in
        new_states = dict(states)
        layer_names = [n for n in self.topo if n in self.impls]
        keys = (dict(zip(layer_names, jax.random.split(rng, len(layer_names))))
                if rng is not None and layer_names else {})
        for name in self.topo:
            if name in skip:
                continue
            v = conf.vertices[name]
            in_names = conf.vertex_inputs[name]
            xs = [acts[i] for i in in_names]
            if isinstance(v, Layer):
                x = xs[0]
                pre = conf.input_preprocessors.get(name)
                if pre is not None:
                    x = pre(x, ctx)
                # propagate the mask of the (single) input chain
                m = masks.get(in_names[0])
                impl = self.impls[name]
                p_n = impl.noised_params(params[name], train, keys.get(name))
                y, ns = impl.forward(p_n, states[name], x, train=train,
                                     rng=keys.get(name), mask=m, ctx=ctx)
                if impl.save_output:
                    # tag for the remat policy (identity outside jax.checkpoint)
                    y = checkpoint_name(y, "dl4j_act")
                new_states[name] = ns
                acts[name] = y
                masks[name] = m
            else:
                # vertex outputs are saved under the remat policy: junction
                # vertices (ElementWise/Merge) carry the residual spine, and
                # an unsaved spine would recompute-chain through every
                # upstream block during the backward pass
                acts[name] = checkpoint_name(v.forward(xs, ctx), "dl4j_act")
                masks[name] = v.propagate_mask([masks.get(i) for i in in_names])
        return acts, new_states, masks, ctx

    def _loss_fn(self, params, states, inputs, labels, input_masks, label_masks,
                 train, rng, rnn_state_in=None):
        conf = self.conf
        out_set = fused_softmax_skip_set(conf, self.impls)
        acts, new_states, masks, ctx = self._apply_graph(
            params, states, inputs, input_masks, train, rng, skip=out_set,
            rnn_state_in=rnn_state_in)
        total = 0.0
        for out_name, lbl, lm in zip(conf.network_outputs, labels,
                                     label_masks or [None] * len(labels)):
            impl = self.impls.get(out_name)
            if impl is None or not hasattr(impl, "loss_on"):
                raise ValueError(f"Output vertex '{out_name}' is not an output "
                                 f"layer — cannot compute training loss")
            in_name = conf.vertex_inputs[out_name][0]
            x = acts[in_name]
            pre = conf.input_preprocessors.get(out_name)
            if pre is not None:
                x = pre(x, ctx)
            mask = lm if lm is not None else (masks.get(in_name) if x.ndim == 3
                                              else None)
            total = total + impl.loss_on(params[out_name], states[out_name], x,
                                         lbl, mask=mask, train=train, rng=rng)
            if hasattr(impl, "update_state"):
                xs = jax.lax.stop_gradient(x)
                new_states[out_name] = impl.update_state(states[out_name], xs, lbl)
        reg = 0.0
        for name, impl in self.impls.items():
            reg = reg + impl.regularization(params[name])
        aux = ctx.get("aux_loss", 0.0)  # e.g. MoE load balancing
        return total + reg + aux, (new_states, ctx.get("rnn_state_out"))

    # ---------------------------------------------------------- train step
    def _raw_update_core(self, grads_reduce=None):
        """Shared step core (see MultiLayerNetwork._raw_update_core): returns
        ``(updates, new_states, new_upd, loss, rnn_out)`` without applying.
        ``grads_reduce``: optional cross-device reduction hook (same seam as
        the MLN core — ``sequence_parallel_step`` uses it)."""
        gn_mode = self.gc.gradient_normalization
        gn_thresh = self.gc.gradient_normalization_threshold
        minimize = self.gc.minimize

        use_remat = remat_enabled(self.gc, self.impls.values())

        def core(params, states, upd_state, iteration, rng, inputs, labels,
                 input_masks, label_masks, rnn_state_in=None):
            inputs = self._adapt_inputs(inputs)

            def loss_fn(p):
                return self._loss_fn(p, states, inputs, labels, input_masks,
                                     label_masks, True, rng, rnn_state_in)

            if use_remat:
                loss_fn = jax.checkpoint(loss_fn, policy=remat_policy())
            (loss, (new_states, rnn_out)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            if grads_reduce is not None:
                grads, loss, new_states = grads_reduce(grads, loss,
                                                       new_states)
            if not minimize:
                grads = _tm(lambda g: -g, grads)
            grads = normalize_gradients(grads, gn_mode, gn_thresh)
            updates, new_upd = self.updater.apply(upd_state, grads, iteration)
            return updates, new_states, new_upd, loss, rnn_out

        return core

    def _raw_step(self, with_rnn_state=False):
        core = self._raw_update_core()

        def step(params, states, upd_state, iteration, rng, inputs, labels,
                 input_masks, label_masks, rnn_state_in=None):
            updates, new_states, new_upd, loss, rnn_out = core(
                params, states, upd_state, iteration, rng, inputs, labels,
                input_masks, label_masks, rnn_state_in)
            new_params = _tm(lambda p, u: p - u.astype(p.dtype), params, updates)
            new_params = self._apply_constraints(new_params)
            if with_rnn_state:
                rnn_out = (_tm(jax.lax.stop_gradient, rnn_out)
                           if rnn_out else rnn_out)
                return new_params, new_states, new_upd, loss, rnn_out
            return new_params, new_states, new_upd, loss

        return step

    def _raw_update_step(self, with_rnn_state=False):
        """Updater-transformed update without application — SHARED_GRADIENTS
        wire seam (see MultiLayerNetwork._raw_update_step)."""
        core = self._raw_update_core()

        def step(params, states, upd_state, iteration, rng, inputs, labels,
                 input_masks, label_masks, rnn_state_in=None):
            updates, new_states, new_upd, loss, rnn_out = core(
                params, states, upd_state, iteration, rng, inputs, labels,
                input_masks, label_masks, rnn_state_in)
            if with_rnn_state:
                rnn_out = (_tm(jax.lax.stop_gradient, rnn_out)
                           if rnn_out else rnn_out)
                return updates, new_states, new_upd, loss, rnn_out
            return updates, new_states, new_upd, loss

        return step

    def _apply_constraints(self, params):
        from .conf.dropout import apply_constraints
        out = dict(params)
        for name in self.impls:
            lc = self.conf.vertices[name]
            cons = getattr(lc, "constraints", None) or \
                getattr(getattr(lc, "inner", None), "constraints", None)
            if cons:
                out[name] = apply_constraints(cons, params[name])
        return out

    def _build_step(self, with_rnn_state, single_iteration=False):
        step = self._raw_step(with_rnn_state=with_rnn_state)
        n_iter = 1 if single_iteration else _n_iterations(self.gc)
        if n_iter > 1:
            step = _scan_iterations(step, n_iter, with_rnn_state=with_rnn_state)
        return monitored_jit(step, name="cg/step",
                             donate_argnums=(0, 2))

    def _ensure_step(self, single_iteration=False):
        if single_iteration and _n_iterations(self.gc) > 1:
            if getattr(self, "_jit_step_single", None) is None:
                self._jit_step_single = self._build_step(
                    with_rnn_state=False, single_iteration=True)
            return self._jit_step_single
        if self._jit_step is None:
            self._jit_step = self._build_step(with_rnn_state=False)
        return self._jit_step

    def _ensure_tbptt_step(self, single_iteration=False):
        if single_iteration and _n_iterations(self.gc) > 1:
            if getattr(self, "_jit_tbptt_step_single", None) is None:
                self._jit_tbptt_step_single = self._build_step(
                    with_rnn_state=True, single_iteration=True)
            return self._jit_tbptt_step_single
        if getattr(self, "_jit_tbptt_step", None) is None:
            self._jit_tbptt_step = self._build_step(with_rnn_state=True)
        return self._jit_tbptt_step

    def _init_rnn_state(self, batch):
        state = {}
        for name, impl in self.impls.items():
            if hasattr(impl, "init_stream_state"):
                state[name] = impl.init_stream_state(batch)
        return state

    def _next_rng(self):
        self._rng, k = jax.random.split(self._rng)
        return k

    # ----------------------------------------------------------------- fit
    def fit(self, data, labels=None, epochs=1):
        """Train. Accepts DataSet/MultiDataSet, an iterator of either, or
        (features, labels) arrays (reference ``fit`` overloads :863/:988)."""
        if labels is not None:
            data = DataSet(np.asarray(data), np.asarray(labels))
        if isinstance(data, (DataSet, MultiDataSet)):
            data = ListDataSetIterator([data])
        # multi-worker prefetch + device-put-ahead (datasets/prefetch.py):
        # see MultiLayerNetwork.fit
        it, own_pipeline = wrap_for_training(
            data, cache_device=self.gc.cache_mode == CacheMode.DEVICE)
        # a new fit() supersedes a previous health halt — without this, one
        # halt would silently truncate every later fit to a single batch
        self.halt_requested = False
        _mon.get_health().clear_halt()
        try:
            for _ in range(epochs):
                for lst in self.listeners:
                    lst.on_epoch_start(self, self.epoch_count)
                with _mon.get_tracer().span("epoch", cat="train",
                                            epoch=self.epoch_count):
                    t_etl = time.perf_counter()
                    for ds in it:
                        self.last_etl_ms = (time.perf_counter() - t_etl) * 1e3
                        self._fit_batch(ds)
                        if self.halt_requested:
                            break
                        t_etl = time.perf_counter()
                for lst in self.listeners:
                    lst.on_epoch_end(self, self.epoch_count)
                self.epoch_count += 1
                if self.halt_requested:
                    log.warning("fit halted at epoch %d (halt_requested; see "
                                "TrainingHealthListener)", self.epoch_count)
                    break
        except BaseException as e:
            # error seam: listeners holding process-global resources (an
            # active ProfilerListener trace window) must release them
            # before the exception unwinds out of fit
            from ..optimize.listeners import dispatch_training_error
            dispatch_training_error(self, self.listeners, e)
            raise
        finally:
            if own_pipeline:
                it.shutdown()   # no prefetch worker outlives its fit
        return self

    def _as_multi(self, ds):
        if isinstance(ds, MultiDataSet):
            return ds
        return MultiDataSet([ds.features], [ds.labels],
                            None if ds.features_mask is None else [ds.features_mask],
                            None if ds.labels_mask is None else [ds.labels_mask])

    def _fit_batch(self, ds, single_iteration=False):
        """One minibatch. ``single_iteration=True`` applies exactly ONE
        optimizer update even under ``iterations(n)`` (ParallelWrapper
        tail-batch fallback — see MultiLayerNetwork._fit_batch)."""
        if isinstance(ds, DataSet):
            if self.gc.cache_mode == CacheMode.DEVICE:
                # cache on the CALLER's DataSet — _as_multi builds a fresh
                # wrapper per batch, so a wrapper-side cache would never hit
                f, l, fm, lm = ds.device_arrays()
            else:
                # direct, not via _as_multi: MultiDataSet.__init__ calls
                # np.asarray, which would pull a put-ahead (device-resident)
                # batch straight back to the host
                f = jnp.asarray(ds.features)
                l = jnp.asarray(ds.labels)
                fm = (None if ds.features_mask is None
                      else jnp.asarray(ds.features_mask))
                lm = (None if ds.labels_mask is None
                      else jnp.asarray(ds.labels_mask))
            inputs, labels = (f,), (l,)
            fms = None if fm is None else (fm,)
            lms = None if lm is None else (lm,)
        elif self.gc.cache_mode == CacheMode.DEVICE:
            inputs, labels, fms, lms = self._as_multi(ds).device_arrays()
        else:
            mds = self._as_multi(ds)
            inputs = tuple(jnp.asarray(f) for f in mds.features)
            labels = tuple(jnp.asarray(l) for l in mds.labels)
            fms = (None if mds.features_masks is None
                   else tuple(None if m is None else jnp.asarray(m)
                              for m in mds.features_masks))
            lms = (None if mds.labels_masks is None
                   else tuple(None if m is None else jnp.asarray(m)
                              for m in mds.labels_masks))
        if (self.conf.backprop_type == BackpropType.TruncatedBPTT
                and all(x.ndim == 3 for x in inputs)
                and inputs[0].shape[1] > self.conf.tbptt_fwd_length):
            self._fit_tbptt(inputs, labels, fms, lms,
                            single_iteration=single_iteration)
            return
        step = self._ensure_step(single_iteration=single_iteration)
        it = jnp.asarray(self.iteration_count, jnp.int32)
        self.last_batch_size = int(inputs[0].shape[0])
        observe = bool(self.listeners) or _mon.enabled()
        score = None
        t0 = time.perf_counter()
        # span only when observing: without the float(loss) barrier inside
        # it, a span would record dispatch time and be worse than no data
        with (_mon.step_span(self.iteration_count) if observe
              else contextlib.nullcontext()):
            self.params, self.states, self.updater_state, loss = step(
                self.params, self.states, self.updater_state, it,
                self._next_rng(), inputs, labels, fms, lms)
            if observe:
                # device→host VALUE fetch: the completion barrier that makes
                # the span (and step_ms) measure the step, not its dispatch
                score = float(loss)
        self.score_ = loss
        self.iteration_count += (1 if single_iteration
                                 else _n_iterations(self.gc))
        if observe:
            _mon.record_training_iteration(
                self, self.iteration_count - 1, score,
                batch_size=self.last_batch_size,
                step_ms=(time.perf_counter() - t0) * 1e3,
                etl_ms=self.last_etl_ms)
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration_count - 1, score)

    def _ensure_tbptt_scan_step(self, single_iteration=False):
        cache = getattr(self, "_jit_tbptt_scan", None)
        if cache is None:
            cache = self._jit_tbptt_scan = {}
        key = bool(single_iteration)
        if key not in cache:
            from .multilayer import _build_tbptt_scan
            n_iter = 1 if single_iteration else _n_iterations(self.gc)
            cache[key] = _build_tbptt_scan(self._raw_step(with_rnn_state=True),
                                           n_iter)
        return cache[key]

    def _fit_tbptt(self, inputs, labels, fms, lms, single_iteration=False):
        """Truncated BPTT over the DAG (reference CG ``doTruncatedBPTT``):
        time is chunked to ``tbptt_fwd_length``; per-recurrent-vertex (h, c)
        carries are detached between chunks. Equal segments run as ONE
        fused ``lax.scan`` program (one device dispatch per minibatch — see
        ``multilayer._build_tbptt_scan``); a ragged tail falls back to
        per-segment dispatch."""
        from .multilayer import _run_tbptt
        _run_tbptt(self, inputs, labels, fms, lms, single_iteration)

    # ------------------------------------------------------------- streaming
    def rnn_time_step(self, *inputs):
        """Stateful streaming inference over the DAG (reference CG
        ``rnnTimeStep``)."""
        xs = tuple(jnp.asarray(x) for x in inputs)
        single_step = xs[0].ndim == 2
        if single_step:
            xs = tuple(x[:, None, :] for x in xs)
        if getattr(self, "_rnn_state", None) is None:
            self._rnn_state = self._init_rnn_state(int(xs[0].shape[0]))
        if getattr(self, "_jit_rnn_step", None) is None:
            # cached on self: a fresh closure per call would recompile every
            # streaming step (jit still specializes per input shape)
            def fwd(params, states, fs, rnn_state):
                fs = self._adapt_inputs(fs)
                acts, _, _, ctx = self._apply_graph(params, states, fs, None,
                                                    False, None,
                                                    rnn_state_in=rnn_state)
                outs = tuple(acts[n] for n in self.conf.network_outputs)
                return outs, ctx.get("rnn_state_out")
            self._jit_rnn_step = monitored_jit(fwd,
                                               name="cg/rnn_step")
        outs, self._rnn_state = self._jit_rnn_step(self.params, self.states, xs,
                                                   self._rnn_state)
        if single_step:
            outs = tuple(o[:, -1, :] if o.ndim == 3 else o for o in outs)
        return outs[0] if len(outs) == 1 else list(outs)

    rnnTimeStep = rnn_time_step

    def rnn_clear_previous_state(self):
        self._rnn_state = None

    rnnClearPreviousState = rnn_clear_previous_state

    # ------------------------------------------------- external errors path
    def fit_external_errors(self, inputs, epsilons):
        """Reference external-epsilons training (``calcBackpropGradients``
        :1629 with externalEpsilons): apply d(outputs)·epsilons through VJP and
        update params. ``epsilons`` aligns with ``network_outputs``."""
        inputs = tuple(jnp.asarray(x) for x in (inputs if isinstance(inputs, (list, tuple)) else [inputs]))
        epsilons = tuple(jnp.asarray(e) for e in (epsilons if isinstance(epsilons, (list, tuple)) else [epsilons]))
        if self._jit_ext_step is None:
            gn_mode = self.gc.gradient_normalization
            gn_thresh = self.gc.gradient_normalization_threshold

            def ext_step(params, states, upd_state, iteration, xs, eps):
                xs = self._adapt_inputs(xs)

                def out_fn(p):
                    acts, _, _, _ = self._apply_graph(p, states, xs, None, True, None)
                    outs = []
                    for name in self.conf.network_outputs:
                        outs.append(acts[name])
                    return tuple(outs)

                _, vjp = jax.vjp(out_fn, params)
                grads = vjp(eps)[0]
                grads = normalize_gradients(grads, gn_mode, gn_thresh)
                updates, new_upd = self.updater.apply(upd_state, grads, iteration)
                new_params = _tm(lambda p, u: p - u.astype(p.dtype), params, updates)
                return new_params, new_upd

            self._jit_ext_step = monitored_jit(
                ext_step, name="cg/ext_grad_step", donate_argnums=(0, 2))
        it = jnp.asarray(self.iteration_count, jnp.int32)
        self.params, self.updater_state = self._jit_ext_step(
            self.params, self.states, self.updater_state, it, inputs, epsilons)
        self.iteration_count += 1
        return self

    # ------------------------------------------------------------- inference
    def output(self, *inputs, train=False, masks=None):
        """Activations of all output vertices (reference ``output``). Returns a
        single array when the graph has one output."""
        xs = tuple(jnp.asarray(x) for x in inputs)
        ms = (None if masks is None
              else tuple(None if m is None else jnp.asarray(m) for m in masks))
        key = (bool(train), ms is not None)
        if key not in self._jit_output:
            def fwd(params, states, xs, ms):
                xs = self._adapt_inputs(xs)
                acts, _, _, _ = self._apply_graph(params, states, xs, ms, train, None)
                return tuple(acts[n] for n in self.conf.network_outputs)
            self._jit_output[key] = monitored_jit(fwd,
                                                  name="cg/output")
        outs = self._jit_output[key](self.params, self.states, xs, ms)
        return outs[0] if len(outs) == 1 else list(outs)

    def feed_forward(self, *inputs, train=False):
        """All vertex activations as a dict (reference ``feedForward`` map)."""
        xs = self._adapt_inputs([jnp.asarray(x) for x in inputs])
        acts, _, _, _ = self._apply_graph(self.params, self.states, xs, None,
                                          train, None)
        return acts

    feedForward = feed_forward

    # ----------------------------------------------------------------- score
    def score(self, ds=None, training=False):
        if ds is None:
            return float(self.score_)
        mds = self._as_multi(ds)
        inputs = tuple(jnp.asarray(f) for f in mds.features)
        labels = tuple(jnp.asarray(l) for l in mds.labels)
        fms = (None if mds.features_masks is None
               else tuple(None if m is None else jnp.asarray(m)
                          for m in mds.features_masks))
        lms = (None if mds.labels_masks is None
               else tuple(None if m is None else jnp.asarray(m)
                          for m in mds.labels_masks))
        key = (bool(training), fms is not None, lms is not None)
        if not hasattr(self, "_jit_score"):
            self._jit_score = {}
        if key not in self._jit_score:
            # jitted: early stopping / evaluative listeners call score every
            # epoch — eager per-batch tracing would dominate evaluation on TPU
            def score_fn(params, states, inputs, labels, fms, lms):
                xs = self._adapt_inputs(inputs)
                loss, _ = self._loss_fn(params, states, xs, labels, fms,
                                        lms, training, None)
                return loss
            self._jit_score[key] = monitored_jit(score_fn,
                                                 name="cg/score")
        loss = self._jit_score[key](self.params, self.states, inputs, labels,
                                    fms, lms)
        return float(loss)

    def compute_gradient_and_score(self, ds):
        mds = self._as_multi(ds)
        inputs = self._adapt_inputs([jnp.asarray(f) for f in mds.features])
        labels = [jnp.asarray(l) for l in mds.labels]

        def loss_fn(p):
            loss, _ = self._loss_fn(p, self.states, inputs, labels, None, None,
                                    True, None)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(self.params)
        self.score_ = loss
        return grads, float(loss)

    # ------------------------------------------------------------ evaluation
    def evaluate(self, iterator, output_idx=0):
        """Classification evaluation on output ``output_idx`` (reference
        ``evaluate``; accepts DataSet or MultiDataSet iterators)."""
        from ..eval.evaluation import Evaluation
        ev = Evaluation()
        for ds in iterator:
            mds = self._as_multi(ds)
            outs = self.output(*mds.features, masks=mds.features_masks)
            out = outs[output_idx] if isinstance(outs, list) else outs
            lm = (None if mds.labels_masks is None
                  else mds.labels_masks[output_idx])
            if lm is None and mds.features_masks is not None:
                lm = mds.features_masks[0]
            ev.eval(mds.labels[output_idx], np.asarray(out), mask=lm)
        return ev

    # ------------------------------------------------------------ parameters
    def param_table(self):
        out = {}
        for name in self.topo:
            if name in self.params:
                for k, v in self.params[name].items():
                    out[f"{name}_{k}"] = v
        return out

    paramTable = param_table

    def num_params(self) -> int:
        return sum(int(v.size) for v in jax.tree_util.tree_leaves(self.params))

    numParams = num_params

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    setListeners = set_listeners

    def summary(self) -> str:
        lines = [f"{'vertex':<32} {'type':<28} {'params':>10}"]
        for name in self.topo:
            v = self.conf.vertices[name]
            n = (self.impls[name].num_params(self.params[name])
                 if name in self.impls else 0)
            lines.append(f"{name:<32} {type(v).__name__:<28} {n:>10}")
        lines.append(f"Total params: {self.num_params()}")
        return "\n".join(lines)
