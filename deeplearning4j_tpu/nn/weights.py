"""Weight initialization schemes.

TPU-native equivalent of the reference's ``WeightInit`` enum and ``WeightInitUtil``
(reference ``deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/weights/WeightInit.java``,
``WeightInitUtil.java``). Uses ``jax.random`` PRNG keys (counter-based, reproducible
across device meshes) instead of ND4J's global RNG.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["WeightInit", "Distribution", "NormalDistribution", "UniformDistribution",
           "init_weight"]


def _np_rng(rng):
    """Host numpy Generator deterministically seeded from a jax PRNG key, or
    None when the key is a tracer (init under jit keeps the jax.random path).

    Why host sampling: eager ``jax.random.normal`` compiles one tiny XLA
    program PER DISTINCT SHAPE. GoogLeNet's 57 convs have ~50 distinct
    weight shapes → ~170 device compiles before training even starts (70 s
    of an 81 s init on CPU; minutes over a remote TPU tunnel — the round-3
    'GoogLeNet first-compile blowup' was mostly THIS). numpy sampling is
    exact-deterministic from the same key and costs zero compiles."""
    if isinstance(rng, jax.core.Tracer):
        return None
    arr = np.asarray(jax.random.key_data(rng)
                     if jnp.issubdtype(rng.dtype, jax.dtypes.prng_key)
                     else rng).ravel()
    return np.random.default_rng([int(x) for x in arr])


def _normal(rng, shape, dtype, scale=1.0, shift=0.0):
    """Sampling, scaling and shifting all happen host-side in the eager path:
    an eager device multiply/add would compile one tiny program per distinct
    shape, re-creating the init blowup _np_rng exists to kill."""
    g = _np_rng(rng)
    if g is None:
        return jax.random.normal(rng, shape, dtype) * scale + shift
    return jnp.asarray(
        (g.standard_normal(size=shape) * scale + shift).astype(dtype))


def _uniform(rng, shape, dtype, lo, hi):
    g = _np_rng(rng)
    if g is None:
        return jax.random.uniform(rng, shape, dtype, lo, hi)
    return jnp.asarray(g.uniform(lo, hi, size=shape).astype(dtype))


def host_full(shape, value, dtype):
    """Eager constant init without an XLA compile: numpy fill + device_put.
    (Eager ``jnp.full``/``jnp.zeros`` compiles a tiny program per distinct
    shape — see ``_np_rng``.)"""
    return jnp.asarray(np.full(shape, value, dtype=np.dtype(dtype)))


class WeightInit:
    DISTRIBUTION = "distribution"
    ZERO = "zero"
    ONES = "ones"
    CONSTANT = "constant"
    SIGMOID_UNIFORM = "sigmoid_uniform"
    NORMAL = "normal"
    LECUN_NORMAL = "lecun_normal"
    LECUN_UNIFORM = "lecun_uniform"
    UNIFORM = "uniform"
    XAVIER = "xavier"
    XAVIER_UNIFORM = "xavier_uniform"
    XAVIER_FAN_IN = "xavier_fan_in"
    XAVIER_LEGACY = "xavier_legacy"
    RELU = "relu"
    RELU_UNIFORM = "relu_uniform"
    IDENTITY = "identity"
    VAR_SCALING_NORMAL_FAN_IN = "var_scaling_normal_fan_in"
    VAR_SCALING_NORMAL_FAN_OUT = "var_scaling_normal_fan_out"
    VAR_SCALING_NORMAL_FAN_AVG = "var_scaling_normal_fan_avg"
    VAR_SCALING_UNIFORM_FAN_IN = "var_scaling_uniform_fan_in"
    VAR_SCALING_UNIFORM_FAN_OUT = "var_scaling_uniform_fan_out"
    VAR_SCALING_UNIFORM_FAN_AVG = "var_scaling_uniform_fan_avg"


@dataclasses.dataclass
class Distribution:
    """Base for WeightInit.DISTRIBUTION (reference ``nn/conf/distribution/``)."""

    def sample(self, rng, shape, dtype):  # pragma: no cover - abstract
        raise NotImplementedError

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["@dist"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d):
        d = dict(d)
        kind = d.pop("@dist")
        cls = {c.__name__: c for c in (NormalDistribution, UniformDistribution,
                                       GaussianDistribution, ConstantDistribution,
                                       BinomialDistribution)}[kind]
        return cls(**d)


@dataclasses.dataclass
class NormalDistribution(Distribution):
    mean: float = 0.0
    std: float = 1.0

    def sample(self, rng, shape, dtype):
        return _normal(rng, shape, dtype, scale=self.std, shift=self.mean)


# Reference has both GaussianDistribution and NormalDistribution (synonyms).
@dataclasses.dataclass
class GaussianDistribution(NormalDistribution):
    pass


@dataclasses.dataclass
class UniformDistribution(Distribution):
    lower: float = -1.0
    upper: float = 1.0

    def sample(self, rng, shape, dtype):
        return _uniform(rng, shape, dtype, self.lower, self.upper)


@dataclasses.dataclass
class ConstantDistribution(Distribution):
    value: float = 0.0

    def sample(self, rng, shape, dtype):
        return host_full(shape, self.value, dtype)


@dataclasses.dataclass
class BinomialDistribution(Distribution):
    trials: int = 1
    p: float = 0.5

    def sample(self, rng, shape, dtype):
        g = _np_rng(rng)
        if g is None:
            return jax.random.binomial(rng, self.trials, self.p,
                                       shape).astype(dtype)
        return jnp.asarray(g.binomial(self.trials, self.p,
                                      size=shape).astype(dtype))


def init_weight(rng, shape, fan_in, fan_out, scheme=WeightInit.XAVIER,
                dist: Optional[Distribution] = None, dtype=jnp.float32):
    """Initialize one weight tensor.

    Formulas match reference ``WeightInitUtil.initWeights`` (e.g. XAVIER =
    N(0, 2/(fanIn+fanOut)), RELU = N(0, 2/fanIn), SIGMOID_UNIFORM =
    U(±4·sqrt(6/(fanIn+fanOut)))).
    """
    scheme = str(scheme).lower()
    fan_in = max(float(fan_in), 1.0)
    fan_out = max(float(fan_out), 1.0)

    if scheme == WeightInit.DISTRIBUTION:
        if dist is None:
            raise ValueError("WeightInit.DISTRIBUTION requires a Distribution")
        return dist.sample(rng, shape, dtype)
    if scheme == WeightInit.ZERO:
        return host_full(shape, 0, dtype)
    if scheme == WeightInit.ONES:
        return host_full(shape, 1, dtype)
    if scheme == WeightInit.IDENTITY:
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("IDENTITY init requires a square 2-D shape")
        return jnp.asarray(np.eye(shape[0], dtype=np.dtype(dtype)))
    if scheme == WeightInit.NORMAL:
        return _normal(rng, shape, dtype, scale=1.0 / math.sqrt(fan_in))
    if scheme == WeightInit.LECUN_NORMAL:
        return _normal(rng, shape, dtype, scale=math.sqrt(1.0 / fan_in))
    if scheme == WeightInit.UNIFORM:
        a = math.sqrt(1.0 / fan_in)
        return _uniform(rng, shape, dtype, -a, a)
    if scheme == WeightInit.LECUN_UNIFORM:
        a = math.sqrt(3.0 / fan_in)
        return _uniform(rng, shape, dtype, -a, a)
    if scheme == WeightInit.XAVIER:
        return _normal(rng, shape, dtype,
                       scale=math.sqrt(2.0 / (fan_in + fan_out)))
    if scheme == WeightInit.XAVIER_UNIFORM:
        a = math.sqrt(6.0 / (fan_in + fan_out))
        return _uniform(rng, shape, dtype, -a, a)
    if scheme == WeightInit.XAVIER_FAN_IN:
        return _normal(rng, shape, dtype, scale=1.0 / math.sqrt(fan_in))
    if scheme == WeightInit.XAVIER_LEGACY:
        return _normal(rng, shape, dtype,
                       scale=math.sqrt(1.0 / (fan_in + fan_out)))
    if scheme == WeightInit.RELU:
        return _normal(rng, shape, dtype, scale=math.sqrt(2.0 / fan_in))
    if scheme == WeightInit.RELU_UNIFORM:
        a = math.sqrt(6.0 / fan_in)
        return _uniform(rng, shape, dtype, -a, a)
    if scheme == WeightInit.SIGMOID_UNIFORM:
        a = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return _uniform(rng, shape, dtype, -a, a)
    if scheme.startswith("var_scaling"):
        if scheme.endswith("fan_in"):
            denom = fan_in
        elif scheme.endswith("fan_out"):
            denom = fan_out
        else:
            denom = 0.5 * (fan_in + fan_out)
        if "normal" in scheme:
            return _normal(rng, shape, dtype, scale=math.sqrt(1.0 / denom))
        a = math.sqrt(3.0 / denom)
        return _uniform(rng, shape, dtype, -a, a)
    raise ValueError(f"Unknown weight init scheme '{scheme}'")
