"""Regression evaluation: MSE, MAE, RMSE, RSE, PC, R^2 per column.

TPU-native equivalent of reference ``eval/RegressionEvaluation.java``.
"""
from __future__ import annotations

import numpy as np


class RegressionEvaluation:
    def __init__(self, n_columns=None):
        self.n = 0
        self.sum_sq_err = None
        self.sum_abs_err = None
        self.sum_label = None
        self.sum_label_sq = None
        self.sum_pred = None
        self.sum_pred_sq = None
        self.sum_label_pred = None

    _STAT_FIELDS = ("sum_sq_err", "sum_abs_err", "sum_label", "sum_label_sq",
                    "sum_pred", "sum_pred_sq", "sum_label_pred")

    def merge(self, other: "RegressionEvaluation"):
        """Sum another evaluation's sufficient statistics into this one
        (reference ``RegressionEvaluation.merge``)."""
        from .roc import merge_summed_fields
        merge_summed_fields(self, other, self._STAT_FIELDS,
                            empty=lambda e: e.n == 0)
        self.n += other.n
        return self

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:
            b, t, c = labels.shape
            labels = labels.reshape(b * t, c)
            predictions = predictions.reshape(b * t, c)
            if mask is not None:
                m = np.asarray(mask).reshape(b * t) > 0
                labels, predictions = labels[m], predictions[m]
        if self.sum_sq_err is None:
            c = labels.shape[-1]
            self.sum_sq_err = np.zeros(c)
            self.sum_abs_err = np.zeros(c)
            self.sum_label = np.zeros(c)
            self.sum_label_sq = np.zeros(c)
            self.sum_pred = np.zeros(c)
            self.sum_pred_sq = np.zeros(c)
            self.sum_label_pred = np.zeros(c)
        err = predictions - labels
        self.sum_sq_err += np.sum(err ** 2, axis=0)
        self.sum_abs_err += np.sum(np.abs(err), axis=0)
        self.sum_label += np.sum(labels, axis=0)
        self.sum_label_sq += np.sum(labels ** 2, axis=0)
        self.sum_pred += np.sum(predictions, axis=0)
        self.sum_pred_sq += np.sum(predictions ** 2, axis=0)
        self.sum_label_pred += np.sum(labels * predictions, axis=0)
        self.n += labels.shape[0]

    def mean_squared_error(self, col=None):
        mse = self.sum_sq_err / max(self.n, 1)
        return float(mse[col]) if col is not None else float(np.mean(mse))

    def mean_absolute_error(self, col=None):
        mae = self.sum_abs_err / max(self.n, 1)
        return float(mae[col]) if col is not None else float(np.mean(mae))

    def root_mean_squared_error(self, col=None):
        mse = self.sum_sq_err / max(self.n, 1)
        rmse = np.sqrt(mse)
        return float(rmse[col]) if col is not None else float(np.mean(rmse))

    def correlation_r2(self, col=None):
        n = max(self.n, 1)
        ss_tot = self.sum_label_sq - (self.sum_label ** 2) / n
        ss_res = self.sum_sq_err
        r2 = 1.0 - ss_res / np.maximum(ss_tot, 1e-12)
        return float(r2[col]) if col is not None else float(np.mean(r2))

    def pearson_correlation(self, col=None):
        n = max(self.n, 1)
        cov = self.sum_label_pred - self.sum_label * self.sum_pred / n
        vl = self.sum_label_sq - self.sum_label ** 2 / n
        vp = self.sum_pred_sq - self.sum_pred ** 2 / n
        pc = cov / np.maximum(np.sqrt(vl * vp), 1e-12)
        return float(pc[col]) if col is not None else float(np.mean(pc))

    def stats(self) -> str:
        return (f"MSE: {self.mean_squared_error():.6f}  "
                f"MAE: {self.mean_absolute_error():.6f}  "
                f"RMSE: {self.root_mean_squared_error():.6f}  "
                f"R^2: {self.correlation_r2():.6f}")
