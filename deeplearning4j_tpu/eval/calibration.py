"""Probability calibration evaluation.

TPU-native equivalent of reference ``eval/EvaluationCalibration.java``:
reliability diagram bins (mean predicted probability vs observed frequency per
bin), residual-plot histogram, and probability histograms, accumulated
streaming over ``eval`` calls.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .roc import _flatten_masked


class EvaluationCalibration:
    def __init__(self, reliability_bins: int = 10, histogram_bins: int = 10):
        self.reliability_bins = int(reliability_bins)
        self.histogram_bins = int(histogram_bins)
        # per class: sums of predicted prob, counts of positives, totals per bin
        self._prob_sum: Optional[np.ndarray] = None     # [C, bins]
        self._pos_count: Optional[np.ndarray] = None    # [C, bins]
        self._total: Optional[np.ndarray] = None        # [C, bins]
        self._residual_hist: Optional[np.ndarray] = None  # [hist_bins]
        self._prob_hist: Optional[np.ndarray] = None      # [C, hist_bins]

    def _ensure(self, c):
        if self._prob_sum is None:
            b = self.reliability_bins
            self._prob_sum = np.zeros((c, b))
            self._pos_count = np.zeros((c, b))
            self._total = np.zeros((c, b))
            self._residual_hist = np.zeros(self.histogram_bins)
            self._prob_hist = np.zeros((c, self.histogram_bins))

    def eval(self, labels, predictions, mask=None):
        labels, predictions = _flatten_masked(labels, predictions, mask)
        if labels.ndim == 1:  # single-output sigmoid model
            labels = labels[:, None]
            predictions = predictions[:, None]
        c = labels.shape[1]
        self._ensure(c)
        bins = np.clip((predictions * self.reliability_bins).astype(int), 0,
                       self.reliability_bins - 1)
        for cls in range(c):
            np.add.at(self._prob_sum[cls], bins[:, cls], predictions[:, cls])
            np.add.at(self._pos_count[cls], bins[:, cls], labels[:, cls])
            np.add.at(self._total[cls], bins[:, cls], 1.0)
        resid = np.abs(labels - predictions).mean(axis=1)
        rbins = np.clip((resid * self.histogram_bins).astype(int), 0,
                        self.histogram_bins - 1)
        np.add.at(self._residual_hist, rbins, 1.0)
        pbins = np.clip((predictions * self.histogram_bins).astype(int), 0,
                        self.histogram_bins - 1)
        for cls in range(c):
            np.add.at(self._prob_hist[cls], pbins[:, cls], 1.0)

    # ------------------------------------------------------------------
    def get_reliability_diagram(self, class_idx: int):
        """(mean predicted prob per bin, observed positive frequency per bin)."""
        t = self._total[class_idx]
        with np.errstate(invalid="ignore", divide="ignore"):
            mean_pred = np.where(t > 0, self._prob_sum[class_idx] / np.maximum(t, 1), np.nan)
            frac_pos = np.where(t > 0, self._pos_count[class_idx] / np.maximum(t, 1), np.nan)
        return mean_pred, frac_pos

    getReliabilityDiagram = get_reliability_diagram

    def expected_calibration_error(self, class_idx: int) -> float:
        mean_pred, frac_pos = self.get_reliability_diagram(class_idx)
        t = self._total[class_idx]
        n = t.sum()
        if n == 0:
            return 0.0
        valid = t > 0
        return float(np.sum(t[valid] * np.abs(mean_pred[valid] - frac_pos[valid])) / n)

    def get_residual_plot(self):
        return self._residual_hist.copy()

    getResidualPlot = get_residual_plot

    def get_probability_histogram(self, class_idx: int):
        return self._prob_hist[class_idx].copy()

    getProbabilityHistogram = get_probability_histogram
