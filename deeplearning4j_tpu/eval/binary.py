"""Per-label binary evaluation (multi-label sigmoid outputs).

TPU-native equivalent of reference ``eval/EvaluationBinary.java``: independent
binary counts (TP/FP/TN/FN at a decision threshold, default 0.5) per output
column, with accuracy/precision/recall/F1 per label.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .roc import _flatten_masked


class EvaluationBinary:
    def __init__(self, decision_threshold: float = 0.5):
        self.decision_threshold = float(decision_threshold)
        self.tp: Optional[np.ndarray] = None
        self.fp: Optional[np.ndarray] = None
        self.tn: Optional[np.ndarray] = None
        self.fn: Optional[np.ndarray] = None

    def _ensure(self, n):
        if self.tp is None:
            self.tp = np.zeros(n, np.int64)
            self.fp = np.zeros(n, np.int64)
            self.tn = np.zeros(n, np.int64)
            self.fn = np.zeros(n, np.int64)

    def merge(self, other: "EvaluationBinary"):
        """Sum per-label counts (reference ``EvaluationBinary.merge``)."""
        from .roc import merge_summed_fields
        return merge_summed_fields(self, other, ("tp", "fp", "tn", "fn"),
                                   empty=lambda e: e.tp is None)

    def eval(self, labels, predictions, mask=None):
        labels, predictions = _flatten_masked(labels, predictions, mask)
        if labels.ndim == 1:
            labels = labels[:, None]
            predictions = predictions[:, None]
        self._ensure(labels.shape[1])
        pred = predictions >= self.decision_threshold
        truth = labels > 0.5
        self.tp += (pred & truth).sum(axis=0)
        self.fp += (pred & ~truth).sum(axis=0)
        self.tn += (~pred & ~truth).sum(axis=0)
        self.fn += (~pred & truth).sum(axis=0)

    # ------------------------------------------------------------- metrics
    def num_labels(self) -> int:
        return 0 if self.tp is None else len(self.tp)

    numLabels = num_labels

    def total_count(self, i) -> int:
        return int(self.tp[i] + self.fp[i] + self.tn[i] + self.fn[i])

    def accuracy(self, i) -> float:
        t = self.total_count(i)
        return float(self.tp[i] + self.tn[i]) / t if t else 0.0

    def precision(self, i) -> float:
        d = self.tp[i] + self.fp[i]
        return float(self.tp[i]) / d if d else 0.0

    def recall(self, i) -> float:
        d = self.tp[i] + self.fn[i]
        return float(self.tp[i]) / d if d else 0.0

    def f1(self, i) -> float:
        p, r = self.precision(i), self.recall(i)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def average_accuracy(self) -> float:
        return float(np.mean([self.accuracy(i) for i in range(self.num_labels())]))

    averageAccuracy = average_accuracy

    def average_f1(self) -> float:
        return float(np.mean([self.f1(i) for i in range(self.num_labels())]))

    averageF1 = average_f1

    def stats(self) -> str:
        lines = [f"{'label':>5} {'acc':>8} {'prec':>8} {'rec':>8} {'f1':>8}"]
        for i in range(self.num_labels()):
            lines.append(f"{i:>5} {self.accuracy(i):>8.4f} {self.precision(i):>8.4f} "
                         f"{self.recall(i):>8.4f} {self.f1(i):>8.4f}")
        return "\n".join(lines)
