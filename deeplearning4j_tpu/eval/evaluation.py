"""Classification evaluation: accuracy/precision/recall/F1 + confusion matrix.

TPU-native equivalent of reference ``deeplearning4j-nn/.../eval/Evaluation.java``
(1627 LoC; SURVEY.md §2.1 "Evaluation"). Accumulates a confusion matrix over
``eval(labels, predictions)`` calls; time-series inputs [b, T, C] are flattened
with optional [b, T] masks like the reference's ``evalTimeSeries``.
"""
from __future__ import annotations

import numpy as np


class ConfusionMatrix:
    def __init__(self, num_classes):
        self.matrix = np.zeros((num_classes, num_classes), dtype=np.int64)

    def add(self, actual, predicted):
        np.add.at(self.matrix, (actual, predicted), 1)

    def get_count(self, actual, predicted):
        return int(self.matrix[actual, predicted])


class Evaluation:
    def __init__(self, num_classes=None, top_n=1):
        self.num_classes = num_classes
        self.top_n = top_n
        self.confusion = None
        self.top_n_correct = 0
        self.total = 0

    # ------------------------------------------------------------------
    def _ensure(self, n):
        if self.confusion is None:
            self.num_classes = self.num_classes or n
            self.confusion = ConfusionMatrix(self.num_classes)

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:  # [b, T, C] time series
            b, t, c = labels.shape
            labels = labels.reshape(b * t, c)
            predictions = predictions.reshape(b * t, c)
            if mask is not None:
                m = np.asarray(mask).reshape(b * t) > 0
                labels = labels[m]
                predictions = predictions[m]
        elif mask is not None:
            m = np.asarray(mask).ravel() > 0
            labels = labels[m]
            predictions = predictions[m]
        self._ensure(labels.shape[-1])
        actual = np.argmax(labels, axis=-1)
        pred = np.argmax(predictions, axis=-1)
        self.confusion.add(actual, pred)
        self.total += len(actual)
        if self.top_n > 1:
            topn = np.argsort(-predictions, axis=-1)[:, :self.top_n]
            self.top_n_correct += int(np.sum(topn == actual[:, None]))

    def merge(self, other: "Evaluation"):
        """Combine another Evaluation's counts into this one (reference
        ``Evaluation.merge`` — the reduce step of Spark's distributed
        evaluation, ``IEvaluationReduceFunction.java``)."""
        if other.confusion is None:
            return self
        if self.confusion is None:
            self._ensure(other.num_classes)
        self.confusion.matrix += other.confusion.matrix
        self.total += other.total
        self.top_n_correct += other.top_n_correct
        return self

    # ------------------------------------------------------------- metrics
    def _tp(self, i):
        return self.confusion.matrix[i, i]

    def _fp(self, i):
        return self.confusion.matrix[:, i].sum() - self._tp(i)

    def _fn(self, i):
        return self.confusion.matrix[i, :].sum() - self._tp(i)

    def accuracy(self) -> float:
        if self.total == 0:
            return 0.0
        return float(np.trace(self.confusion.matrix)) / self.total

    def top_n_accuracy(self) -> float:
        if self.total == 0 or self.top_n <= 1:
            return self.accuracy()
        return self.top_n_correct / self.total

    def precision(self, cls=None) -> float:
        if cls is not None:
            d = self._tp(cls) + self._fp(cls)
            return float(self._tp(cls)) / d if d else 0.0
        vals = [self.precision(i) for i in range(self.num_classes)
                if (self.confusion.matrix[i, :].sum() + self.confusion.matrix[:, i].sum()) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls=None) -> float:
        if cls is not None:
            d = self._tp(cls) + self._fn(cls)
            return float(self._tp(cls)) / d if d else 0.0
        vals = [self.recall(i) for i in range(self.num_classes)
                if self.confusion.matrix[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls=None) -> float:
        p = self.precision(cls)
        r = self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def false_positive_rate(self, cls) -> float:
        tn = self.total - self._tp(cls) - self._fp(cls) - self._fn(cls)
        d = self._fp(cls) + tn
        return float(self._fp(cls)) / d if d else 0.0

    def matthews_correlation(self, cls) -> float:
        tp, fp, fn = self._tp(cls), self._fp(cls), self._fn(cls)
        tn = self.total - tp - fp - fn
        num = tp * tn - fp * fn
        den = np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
        return float(num) / den if den else 0.0

    def stats(self) -> str:
        lines = [
            "==========================Scores========================================",
            f" Accuracy:        {self.accuracy():.4f}",
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
            "========================================================================",
        ]
        if self.top_n > 1:
            lines.insert(2, f" Top {self.top_n} Accuracy: {self.top_n_accuracy():.4f}")
        return "\n".join(lines)
