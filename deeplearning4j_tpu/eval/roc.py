"""ROC / AUC evaluation.

TPU-native equivalent of reference ``deeplearning4j-nn/.../eval/ROC.java``,
``ROCBinary.java``, ``ROCMultiClass.java`` (SURVEY.md §2.1 "Evaluation"): exact
mode (threshold_steps=0 — every distinct score is a threshold, trapezoidal AUC)
and thresholded mode (fixed threshold grid), matching the reference's two modes.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


def _flatten_masked(labels, predictions, mask):
    labels = np.asarray(labels, dtype=np.float64)
    predictions = np.asarray(predictions, dtype=np.float64)
    if labels.ndim == 3:
        b, t, c = labels.shape
        labels = labels.reshape(b * t, c)
        predictions = predictions.reshape(b * t, c)
        if mask is not None:
            m = np.asarray(mask).reshape(b * t) > 0
            labels, predictions = labels[m], predictions[m]
    elif mask is not None:
        m = np.asarray(mask).ravel() > 0
        labels, predictions = labels[m], predictions[m]
    return labels, predictions


def _auc(x: np.ndarray, y: np.ndarray) -> float:
    """Trapezoidal area under the curve, points already in sweep order
    (descending threshold → x ascending; vertical segments contribute 0)."""
    return float(np.trapezoid(y, x))


def _sweep_counts(scores: np.ndarray, truth: np.ndarray, threshold_steps: int):
    """(thresholds, tp, fp) for a descending-threshold sweep with ``>=``
    semantics. O(N log N): sort scores descending, cumulative-sum positives
    (the reference's exact-mode ROC.java strategy), never materializing an
    N×N threshold matrix. Endpoints: +inf (nothing positive) first, -inf
    (everything positive) last."""
    order = np.argsort(-scores, kind="stable")
    s_sorted = scores[order]
    t_sorted = truth[order] > 0
    cum_tp = np.cumsum(t_sorted)
    cum_fp = np.cumsum(~t_sorted)
    if threshold_steps > 0:
        thresholds = np.linspace(0.0, 1.0, threshold_steps + 1)[::-1]
    else:
        thresholds = np.unique(scores)[::-1]
    thresholds = np.concatenate([[np.inf], thresholds, [-np.inf]])
    # number of scores >= t  ==  position found by searchsorted on -s_sorted
    counts = np.searchsorted(-s_sorted, -thresholds, side="right")
    tp = np.where(counts > 0, cum_tp[np.maximum(counts - 1, 0)], 0)
    fp = np.where(counts > 0, cum_fp[np.maximum(counts - 1, 0)], 0)
    return thresholds, tp.astype(np.float64), fp.astype(np.float64)


def _roc_curve(scores: np.ndarray, truth: np.ndarray,
               threshold_steps: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(thresholds, fpr, tpr). Exact mode when threshold_steps == 0."""
    p = truth.sum()
    n = len(truth) - p
    thresholds, tp, fp = _sweep_counts(scores, truth, threshold_steps)
    tpr = tp / p if p else np.zeros_like(tp)
    fpr = fp / n if n else np.zeros_like(fp)
    return thresholds, fpr, tpr


def _pr_curve(scores: np.ndarray, truth: np.ndarray,
              threshold_steps: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(thresholds, recall, precision). The +inf start point pins
    (recall 0, precision 1) by convention."""
    p = truth.sum()
    thresholds, tp, fp = _sweep_counts(scores, truth, threshold_steps)
    pred_pos = tp + fp
    precision = np.where(pred_pos > 0, tp / np.maximum(pred_pos, 1), 1.0)
    recall = tp / p if p else np.zeros_like(tp)
    return thresholds, recall, precision


class RocCurve:
    def __init__(self, thresholds, fpr, tpr):
        self.thresholds = thresholds
        self.fpr = fpr
        self.tpr = tpr

    def calculate_auc(self) -> float:
        return _auc(self.fpr, self.tpr)

    calculateAUC = calculate_auc


class PrecisionRecallCurve:
    def __init__(self, thresholds, recall, precision):
        self.thresholds = thresholds
        self.recall = recall
        self.precision = precision

    def calculate_auprc(self) -> float:
        return _auc(self.recall, self.precision)

    calculateAUPRC = calculate_auprc


class ROC:
    """Binary ROC. Accepts single-column probabilities (positive class) or
    2-column one-hot/softmax output (column 1 = positive), like the reference.
    ``threshold_steps=0`` → exact mode."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = int(threshold_steps)
        self._scores: List[np.ndarray] = []
        self._truth: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None):
        labels, predictions = _flatten_masked(labels, predictions, mask)
        if labels.ndim == 2 and labels.shape[1] == 2:
            truth = labels[:, 1]
            scores = predictions[:, 1]
        else:
            truth = labels.ravel()
            scores = predictions.ravel()
        self._truth.append(truth)
        self._scores.append(scores)

    def _collect(self):
        if not self._scores:
            return np.zeros(0), np.zeros(0)
        return np.concatenate(self._scores), np.concatenate(self._truth)

    def get_roc_curve(self) -> RocCurve:
        scores, truth = self._collect()
        return RocCurve(*_roc_curve(scores, truth, self.threshold_steps))

    getRocCurve = get_roc_curve

    def get_precision_recall_curve(self) -> PrecisionRecallCurve:
        scores, truth = self._collect()
        return PrecisionRecallCurve(*_pr_curve(scores, truth,
                                               self.threshold_steps))

    getPrecisionRecallCurve = get_precision_recall_curve

    def calculate_auc(self) -> float:
        return self.get_roc_curve().calculate_auc()

    calculateAUC = calculate_auc

    def calculate_auprc(self) -> float:
        return self.get_precision_recall_curve().calculate_auprc()

    calculateAUPRC = calculate_auprc


class ROCBinary:
    """Per-output independent binary ROC (reference ``ROCBinary.java``) for
    multi-label sigmoid outputs [n, L]."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = int(threshold_steps)
        self._per_label: Optional[List[ROC]] = None

    def eval(self, labels, predictions, mask=None):
        labels, predictions = _flatten_masked(labels, predictions, mask)
        n_labels = labels.shape[1]
        if self._per_label is None:
            self._per_label = [ROC(self.threshold_steps) for _ in range(n_labels)]
        for i in range(n_labels):
            self._per_label[i].eval(labels[:, i], predictions[:, i])

    def num_labels(self) -> int:
        return 0 if self._per_label is None else len(self._per_label)

    def calculate_auc(self, label_idx: int) -> float:
        return self._per_label[label_idx].calculate_auc()

    calculateAUC = calculate_auc

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._per_label]))

    calculateAverageAUC = calculate_average_auc


class ROCMultiClass:
    """One-vs-all ROC per class on softmax output (reference
    ``ROCMultiClass.java``)."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = int(threshold_steps)
        self._per_class: Optional[List[ROC]] = None

    def eval(self, labels, predictions, mask=None):
        labels, predictions = _flatten_masked(labels, predictions, mask)
        n_classes = labels.shape[1]
        if self._per_class is None:
            self._per_class = [ROC(self.threshold_steps) for _ in range(n_classes)]
        for i in range(n_classes):
            self._per_class[i].eval(labels[:, i], predictions[:, i])

    def calculate_auc(self, class_idx: int) -> float:
        return self._per_class[class_idx].calculate_auc()

    calculateAUC = calculate_auc

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._per_class]))

    calculateAverageAUC = calculate_average_auc


def merge_summed_fields(dst, src, fields, empty):
    """Shared evaluation-merge machinery: field-wise count summation with
    empty-side handling (the reduce step of distributed evaluation). ``empty``
    tests whether an evaluation has seen data yet."""
    import numpy as np

    if empty(src):
        return dst
    if empty(dst):
        for f in fields:
            setattr(dst, f, np.zeros_like(getattr(src, f)))
    for f in fields:
        setattr(dst, f, getattr(dst, f) + getattr(src, f))
    return dst
