"""Evaluation suite (reference ``deeplearning4j-nn/.../eval/``, 5904 LoC:
Evaluation, EvaluationBinary, EvaluationCalibration, ROC family,
RegressionEvaluation — SURVEY.md §2.1)."""
from .evaluation import Evaluation, ConfusionMatrix
from .regression import RegressionEvaluation
from .roc import ROC, ROCBinary, ROCMultiClass, RocCurve, PrecisionRecallCurve
from .binary import EvaluationBinary
from .calibration import EvaluationCalibration

__all__ = ["Evaluation", "ConfusionMatrix", "RegressionEvaluation", "ROC",
           "ROCBinary", "ROCMultiClass", "RocCurve", "PrecisionRecallCurve",
           "EvaluationBinary", "EvaluationCalibration"]
