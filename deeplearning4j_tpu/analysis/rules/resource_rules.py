"""Resource hygiene: sockets / executors / servers without a close path.

The threaded modules here own real OS resources — TCP sockets on the
paramserver wire, ``ThreadPoolExecutor`` fan-out pools, accept-loop
server sockets. A leaked one is quieter than a leaked thread (THR002):
nothing hangs, the process just accumulates fds until a long training
run hits EMFILE, or CI leaks ports between tests. RES001 demands that
every creation site has a *visible* disposal story.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from . import Rule, register, terminal_name

#: constructors that allocate an OS-level resource, and what closes them
_SOCKET_CTORS = {"socket", "create_connection", "socketpair",
                 "create_server"}
_EXECUTOR_CTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor"}
_SERVER_CTORS = {"HTTPServer", "ThreadingHTTPServer", "TCPServer",
                 "UDPServer", "ThreadingTCPServer", "ThreadingUDPServer"}
#: receiver methods that count as disposal
_DISPOSERS = {"close", "shutdown", "stop", "server_close", "terminate"}


def _creation_kind(call: ast.Call) -> Optional[str]:
    callee = terminal_name(call.func)
    if callee in _EXECUTOR_CTORS:
        return "executor"
    if callee in _SERVER_CTORS:
        return "server"
    if callee in _SOCKET_CTORS:
        # sockets are attribute calls (socket.socket, socket.create_
        # connection) or bare imports of those names; 'socket' as a bare
        # Name call only counts when the module imports it from socket
        if isinstance(call.func, ast.Attribute):
            base = terminal_name(call.func.value)
            if base == "socket":
                return "socket"
            return None
        return None    # bare socket()/create_connection(): too ambiguous
    return None


def _bound_target(call: ast.Call, parents) -> Tuple[Optional[str], bool]:
    """(terminal name the resource is bound to, is_self_attr). None when
    the creation is unbound (an expression/argument) — unjoinable."""
    parent = parents.get(call)
    targets: List[ast.AST] = []
    if isinstance(parent, ast.Assign) and parent.value is call:
        targets = parent.targets
    elif isinstance(parent, (ast.AnnAssign, ast.AugAssign,
                             ast.NamedExpr)) and parent.value is call:
        targets = [parent.target]
    for t in targets:
        tt = t
        while isinstance(tt, ast.Subscript):
            tt = tt.value
        if isinstance(tt, ast.Attribute):
            return tt.attr, True
        if isinstance(tt, ast.Name):
            return tt.id, False
    return None, False


@register
class LeakedResource(Rule):
    id = "RES001"
    title = "socket/executor/server created without a close path"
    rationale = (
        "A socket, ThreadPoolExecutor, or server object with no "
        "with-block, close(), shutdown(), or stop() on any path leaks an "
        "OS resource per call — fds under the paramserver's reconnect "
        "loops, threads under a forgotten executor — until a long run "
        "dies on EMFILE with no hint where. Create it in a `with`, or "
        "bind it somewhere a close path provably reaches (locals: same "
        "function; self attributes/globals: anywhere in the module). "
        "Ownership that genuinely transfers out (a factory returning a "
        "live socket into a pool) is a deliberate pattern: pragma the "
        "line and name the closer (the pool-checkout idiom in "
        "paramserver/client.py is the exemplar).")

    def check(self, tree, lines, path) -> Iterator:
        parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node

        # module-wide disposal evidence: receiver terminal names of
        # close()/shutdown()/stop() calls, plus with-items
        disposed_module: Set[str] = set()
        withitems: Set[ast.Call] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _DISPOSERS:
                n = terminal_name(node.func.value)
                if n:
                    disposed_module.add(n)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if isinstance(item.context_expr, ast.Call):
                        withitems.add(item.context_expr)
        # one alias hop: `for s in self._peers.values(): s.close()` and
        # the exception-safe swap `ex, self._exec = self._exec, None` +
        # `ex.shutdown()` both dispose the ATTRIBUTE through a local name
        for node in ast.walk(tree):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                src = node.iter
                if isinstance(src, ast.Call):
                    src = src.func
                if isinstance(src, ast.Attribute) \
                        and src.attr in ("values", "items", "keys"):
                    src = src.value        # the container, not the view
                container = terminal_name(src)
                tgt = terminal_name(node.target)
                if container and tgt and tgt in disposed_module:
                    disposed_module.add(container)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                t, v = node.targets[0], node.value
                pairs = []
                if isinstance(t, ast.Tuple) and isinstance(v, ast.Tuple) \
                        and len(t.elts) == len(v.elts):
                    pairs = list(zip(t.elts, v.elts))
                else:
                    pairs = [(t, v)]
                for te, ve in pairs:
                    tn, vn = terminal_name(te), terminal_name(ve)
                    if tn and vn and tn in disposed_module:
                        disposed_module.add(vn)

        # per-function disposal evidence for LOCAL names: a local `s` in
        # one function is not the `s` of another
        func_of: Dict[ast.AST, ast.AST] = {}
        for fn in ast.walk(tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for node in ast.walk(fn):
                    func_of.setdefault(node, fn)
        disposed_local: Dict[ast.AST, Set[str]] = {}
        #: per function: local name -> attr names it was stored into
        #: (`self._peers[q] = s` hands ownership to the instance; the
        #: attr's module-wide close path then covers the local)
        stored_into: Dict[ast.AST, Dict[str, Set[str]]] = {}
        for node in ast.walk(tree):
            fn = func_of.get(node)
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _DISPOSERS:
                n = terminal_name(node.func.value)
                if n and fn is not None:
                    disposed_local.setdefault(fn, set()).add(n)
            elif isinstance(node, ast.Assign) and fn is not None \
                    and isinstance(node.value, ast.Name):
                for t in node.targets:
                    tt = t
                    while isinstance(tt, ast.Subscript):
                        tt = tt.value
                    if isinstance(tt, ast.Attribute):
                        stored_into.setdefault(fn, {}).setdefault(
                            node.value.id, set()).add(tt.attr)

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _creation_kind(node)
            if kind is None:
                continue
            if node in withitems:
                continue                      # `with ctor() as x:` closes
            if isinstance(parents.get(node), ast.Return):
                # `return socket.create_connection(...)`: a pure factory —
                # ownership transfers whole to the caller by construction
                continue
            bound, is_attr = _bound_target(node, parents)
            if bound is None:
                yield self.finding(
                    node, lines, path,
                    f"{kind} created but never bound — nothing can ever "
                    f"close it; bind it and close/shutdown it, or use a "
                    f"with-block")
                continue
            if is_attr:
                ok = bound in disposed_module
            else:
                fn = func_of.get(node)
                ok = bound in disposed_local.get(fn, set())
                if not ok:
                    attrs = stored_into.get(fn, {}).get(bound, set())
                    ok = any(a in disposed_module for a in attrs)
            if ok:
                continue
            where = ("no close()/shutdown() on it anywhere in this "
                     "module" if is_attr else
                     "no close()/shutdown() on it in this function")
            yield self.finding(
                node, lines, path,
                f"{kind} bound to {bound!r} but {where}; close it on "
                f"every path (with-block / try-finally), or — if "
                f"ownership transfers out — pragma this line naming who "
                f"closes it")
