"""Interprocedural data-race rule: unguarded shared-field access
(THR005).

A **project rule** (``project = True``), like THR003/THR004: shared-field
races are, by construction, a property of two *different* threads' code
paths — a single-function scan cannot see that ``_loop`` writes a field
under a lock while ``snapshot()`` reads it bare on the caller's thread.
The backend is :mod:`~deeplearning4j_tpu.analysis.racegraph` (Eraser-style
lockset inference over the lockgraph's resolution layer): a field written
at >= 2 distinct sites, always holding one common lock identity, acquires
that lock as its inferred guard; any access to the field reachable from a
*different* thread entry without the guard is a race, reported with BOTH
witness paths (every hop ``file:line``).

The runtime half of the pass is ``monitor/lockwatch.py``'s acquisition
census: ``tests/test_lockwatch.py`` pins that every guard this analyzer
infers for the batcher/collector names a lock the instrumented runs
actually acquire (inferred ⊆ observed), the dual of the lockgraph's
observed ⊆ static edge pin.

Escapes are part of the contract, not suppression folklore: ctor-only
fields (published before ``start()``) and internally-synchronized fields
(``deque``/``Queue``/``Event``...) are exempt by construction; a
deliberately lock-free site carries ``# tpulint: thread-safe[reason]``
on the access line — the reason is mandatory, and a pragma'd *write*
also leaves guard inference so one lock-free writer doesn't turn off
checking for the rest of the class (docs/STATIC_ANALYSIS.md has the
catalog entry and runbook).

Subset-run caveat (same as THR003): ``lint --changed`` analyzes only the
files given, so thread spawns and accesses living outside the subset are
invisible there. The tier-1 self-host guard always runs the whole
package.
"""
from __future__ import annotations

from typing import Iterator, Sequence

from . import Rule, register, make_finding
from ..racegraph import RaceGraph, RaceGraphAnalyzer
from ..lockgraph import ModuleSource


#: one-slot cache keyed on module-list identity (the linter passes one
#: list object to every project rule), same contract as lockgraph_rules
_LAST: list = [None, None]


def _analyze(modules: Sequence[ModuleSource]) -> RaceGraph:
    if _LAST[0] is modules:
        return _LAST[1]
    graph = RaceGraphAnalyzer(modules).build_races()
    _LAST[0], _LAST[1] = modules, graph
    return graph


@register
class UnguardedSharedField(Rule):
    id = "THR005"
    title = "shared field accessed without its inferred guard lock"
    project = True
    rationale = (
        "Every recent incident class here was a shared-field race, not a "
        "lock-order bug: a daemon thread writes `self._field` under a "
        "lock while the caller's thread reads or writes it bare — torn "
        "snapshots, lost updates, use-after-close. This rule infers each "
        "field's guard from the code's own behavior (>= 2 write sites, "
        "one common lock identity held at all of them) and reports any "
        "cross-thread access where that guard is provably not held, with "
        "both witness paths. Fix: take the guard at the access site, or "
        "— if the site is lock-free by design (GIL-atomic read of an "
        "int, publication-before-start) — mark the line with "
        "`# tpulint: thread-safe[reason]` so the decision is recorded "
        "where the next reader will look.")

    def check(self, tree, lines, path) -> Iterator:
        # single-file entry (lint_source): analyze just this module —
        # project runs use check_project with the whole file set
        yield from self.check_project(
            [ModuleSource(path, tree, lines)])

    def check_project(self, modules: Sequence[ModuleSource]) -> Iterator:
        graph = _analyze(modules)
        lines_by_path = {m.path: m.lines for m in modules}
        for race in graph.races:
            lines = lines_by_path.get(race["path"], [])
            node = _Anchor(race["line"])
            verb = ("written" if race["kind"] == "write" else "read")
            yield make_finding(
                self.id, node, lines, race["path"],
                f"{race['classname']}.{race['attr']} is guarded by "
                f"{race['guard']!r} but {verb} without it here: "
                f"guarded-write path [{race['write_witness']}] vs "
                f"unguarded-access path [{race['access_witness']}] — "
                f"these threads race on the field; take the guard at "
                f"this site, or mark the line "
                f"`# tpulint: thread-safe[reason]` if it is lock-free "
                f"by design")


class _Anchor:
    """Minimal node stand-in for make_finding (line-anchored findings)."""

    def __init__(self, line: int, col: int = 0):
        self.lineno = int(line)
        self.col_offset = int(col)
