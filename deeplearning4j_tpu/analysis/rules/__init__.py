"""tpulint rule registry + shared AST helpers.

A rule is a class with an ``id`` (``JAX001``…), a one-line ``title``, a
``rationale`` (why this is a real hazard *in this stack* — surfaces in
``--format json`` and docs), and ``check(tree, lines, path)`` yielding
:class:`~deeplearning4j_tpu.analysis.linter.Finding` objects. Register
with ``@register``; the registry is what the CLI's ``--select`` /
``--ignore`` and the docs' rule catalog enumerate.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Type

from ..linter import Finding

__all__ = ["Rule", "register", "all_rules", "get_rule",
           "terminal_name", "call_callee", "make_finding"]

_REGISTRY: Dict[str, Type["Rule"]] = {}


class Rule:
    """Base class for tpulint rules.

    ``project = True`` marks a rule whose analysis spans files (the
    interprocedural lock rules): the linter calls :meth:`check_project`
    ONCE with every parsed module of the run instead of :meth:`check`
    per file. Such rules still work through ``check`` for single-source
    entry points, just with a one-module horizon.
    """
    id: str = ""
    title: str = ""
    rationale: str = ""
    project: bool = False

    def check(self, tree: ast.AST, lines: Sequence[str],
              path: str) -> Iterator[Finding]:
        raise NotImplementedError

    def check_project(self, modules) -> Iterator[Finding]:
        raise NotImplementedError

    # convenience for subclasses
    def finding(self, node: ast.AST, lines: Sequence[str], path: str,
                message: str) -> Finding:
        return make_finding(self.id, node, lines, path, message)


def make_finding(rule_id: str, node: ast.AST, lines: Sequence[str],
                 path: str, message: str) -> Finding:
    line = getattr(node, "lineno", 1)
    col = getattr(node, "col_offset", 0)
    snippet = lines[line - 1].strip() if 1 <= line <= len(lines) else ""
    return Finding(rule_id, path, line, col, message, snippet=snippet)


def register(cls: Type[Rule]) -> Type[Rule]:
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id}")
    _REGISTRY[cls.id] = cls
    return cls


def all_rules() -> Dict[str, Type[Rule]]:
    """Every registered rule, id-sorted. Importing the rule modules here
    (not at package import) keeps ``analysis.linter`` import-light and
    cycle-free."""
    from . import (control_rules, exception_rules, jax_rules,  # noqa: F401
                   lockgraph_rules, monitor_rules, perf_rules,  # noqa: F401
                   race_rules, resource_rules, threading_rules)  # noqa: F401
    return dict(sorted(_REGISTRY.items()))


def get_rule(rule_id: str) -> Type[Rule]:
    rules = all_rules()
    try:
        return rules[rule_id.upper()]
    except KeyError:
        raise KeyError(f"unknown rule {rule_id!r} "
                       f"(have: {', '.join(rules)})") from None


# -------------------------------------------------------------- AST helpers
def terminal_name(node: ast.AST) -> Optional[str]:
    """Last identifier of a Name/Attribute/Subscript chain:
    ``self._send_locks[s]`` → ``_send_locks``, ``a.b.c`` → ``c``."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def call_callee(call: ast.Call) -> Optional[str]:
    """Terminal identifier of a call's callee (or None for exotic ones)."""
    return terminal_name(call.func)


def assigned_names(stmt: ast.AST) -> List[str]:
    """Terminal identifiers (re)bound by an assignment-like statement."""
    out: List[str] = []

    def targets_of(t):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                targets_of(e)
        elif isinstance(t, ast.Starred):
            targets_of(t.value)
        else:
            n = terminal_name(t)
            if n:
                out.append(n)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            targets_of(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets_of(stmt.target)
    elif isinstance(stmt, ast.NamedExpr):
        targets_of(stmt.target)
    return out
