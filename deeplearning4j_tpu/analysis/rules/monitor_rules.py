"""Monitor-registry hygiene: metric names must carry their units.

The alert engine (``monitor/alerts.py``) and every dashboard built on the
registry interpret series semantically from the NAME alone — windowed
``rate()`` is only meaningful on a monotonic counter, ``quantile_over``
only on a histogram whose unit it can report, a ``_bytes`` threshold only
when the value really is bytes. One misnamed series (a gauge spelled like
a counter, a seconds histogram on ms bucket geometry, a unit buried
mid-name) silently corrupts every downstream consumer. MON001 pins the
convention the package settled on:

- **counters end ``_total``** (Prometheus convention; the registry even
  refuses ``dec`` on them — the name should promise the same).
- **gauges do NOT end ``_total``** — that spelling promises monotonicity
  a gauge cannot keep.
- **histograms end in a unit**: ``_ms``, ``_seconds``, ``_bytes``, or
  ``_examples`` (the dimensionless-count spelling
  ``training_examples_total`` established).
- **``_seconds`` histograms pass ``unit="s"``** — the name claims
  seconds, so the bucket geometry must be the seconds geometry
  (``registry.py``); on the default ms geometry every sub-100 ms sample
  collapses into bucket 0 and the quantiles lie.
- **unit tokens sit at the END of the name** (or directly before
  ``_total``, the Prometheus counter spelling ``*_bytes_total``):
  ``device_memory_in_use_bytes``, never ``device_memory_bytes_in_use``.
- **hit/miss series are monotonic event counts** (names whose stem ends
  ``_hits``/``_misses`` — ISSUE 11's ``serving_cache_*`` response-cache
  series, any future cache): they must be counters ending ``_total`` — a
  gauge or histogram spelling would break the hit-rate math every
  consumer (the /profile ``cache`` column, the bench's
  ``cache_hit_rate``) derives from windowed counter deltas.

The rule fires on direct registry-handle creations — ``X.counter("name",
...)`` / ``X.gauge`` / ``X.histogram`` with a literal (or
literal-suffixed f-string) name — anywhere in the package.
"""
from __future__ import annotations

import ast
from typing import Iterator, Optional

from . import Rule, register, terminal_name

#: tokens that denote a unit; they may only appear terminally (or right
#: before a counter's _total)
_UNIT_TOKENS = {"ms", "seconds", "bytes", "examples"}

#: suffixes a histogram name may end with
_HIST_SUFFIXES = ("_ms", "_seconds", "_bytes", "_examples")

_KINDS = {"counter", "gauge", "histogram"}


def _literal_name(call: ast.Call) -> Optional[str]:
    """The metric-name literal of a registry call: a plain string, or an
    f-string (the ``paramserver_{k}_total`` idiom) flattened with ``*``
    placeholders for the dynamic parts so suffix checks still work.
    None when the name is fully dynamic (nothing to check)."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        parts = []
        for v in arg.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        name = "".join(parts)
        return name if name.strip("*") else None
    return None


def _unit_kwarg(call: ast.Call) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == "unit" and isinstance(kw.value, ast.Constant):
            return kw.value.value
    return None


def _hits_misses_stem(name: str) -> bool:
    """Whether the name is a hit/miss EVENT COUNT: its token sequence
    ends with ``hits``/``misses``, optionally followed by unit tokens
    and/or a final ``total``. Such series must be ``_total`` counters —
    hit-rate math everywhere derives from monotonic counter deltas.
    (``cache_hit_latency_ms`` — singular, mid-name — is not one.)"""
    tokens = name.split("_")
    if tokens and tokens[-1].endswith("*"):
        # trailing "*" = dynamic f-string suffix, unknowable statically
        # (the counter branch's same escape — the suffix may well be
        # "total" at runtime, the paramserver_{k}_total idiom)
        return False
    while tokens and (tokens[-1] in _UNIT_TOKENS or tokens[-1] == "total"):
        tokens.pop()
    return bool(tokens) and tokens[-1] in ("hits", "misses")


def _misplaced_unit(name: str) -> Optional[str]:
    """The first unit token that is neither terminal nor directly before a
    final ``_total`` (None when the name is clean). ``*`` placeholder
    tokens from f-strings are ignored."""
    tokens = name.split("_")
    for i, tok in enumerate(tokens):
        if tok not in _UNIT_TOKENS:
            continue
        terminal = i == len(tokens) - 1
        pre_total = i == len(tokens) - 2 and tokens[-1] == "total"
        if not (terminal or pre_total):
            return tok
    return None


@register
class MetricNameUnitSuffix(Rule):
    id = "MON001"
    title = "metric name breaks the unit-suffix convention"
    rationale = (
        "Alert rules and dashboards interpret registry series from the "
        "name alone: rate() needs a counter (`_total`), quantile math "
        "needs the unit the name claims, and a `_seconds` histogram on "
        "the default ms bucket geometry reports quantiles that are flat "
        "lies below 100 ms. Counters end `_total`; gauges must not; "
        "histograms end `_ms`/`_seconds`/`_bytes`/`_examples` (with "
        "`unit=\"s\"` for `_seconds`); unit tokens go at the END of the "
        "name (`..._bytes`), or directly before a counter's `_total` "
        "(`..._bytes_total`).")

    def check(self, tree, lines, path) -> Iterator:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            kind = terminal_name(node.func)
            if kind not in _KINDS or not isinstance(node.func,
                                                    ast.Attribute):
                continue
            name = _literal_name(node)
            if name is None:
                continue
            bad = self._verdict(kind, name, node)
            if bad:
                yield self.finding(node, lines, path, bad)

    def _verdict(self, kind: str, name: str,
                 call: ast.Call) -> Optional[str]:
        tok = _misplaced_unit(name)
        if tok:
            return (f"{kind} {name!r} buries the unit token {tok!r} "
                    f"mid-name — units go at the end "
                    f"(…_{tok}, or …_{tok}_total for a counter)")
        if _hits_misses_stem(name):
            # hit/miss series (response cache, any future cache) are
            # monotonic events by definition — any non-counter spelling
            # silently breaks every hit-rate consumer downstream
            if kind != "counter" or not name.endswith("_total"):
                return (f"{kind} {name!r}: hit/miss series must be "
                        f"counters ending '_total' (e.g. "
                        f"serving_cache_hits_total / "
                        f"serving_cache_misses_total) — hit-rate math "
                        f"needs monotonic counter deltas")
        if kind == "counter":
            if not name.endswith("_total") and not name.endswith("*"):
                return (f"counter {name!r} must end '_total' (the name "
                        f"should promise the monotonicity the registry "
                        f"enforces)")
        elif kind == "gauge":
            if name.endswith("_total"):
                return (f"gauge {name!r} must not end '_total' — that "
                        f"suffix promises a monotonic counter")
        else:  # histogram
            if not name.endswith(_HIST_SUFFIXES) \
                    and not name.endswith("*"):
                # trailing "*" = dynamic f-string suffix, unknowable
                # statically (same escape as the counter branch)
                return (f"histogram {name!r} must end one of "
                        f"{'/'.join(_HIST_SUFFIXES)} so readers know the "
                        f"sample unit")
            if name.endswith("_seconds") and _unit_kwarg(call) != "s":
                return (f"histogram {name!r} claims seconds but does not "
                        f"pass unit=\"s\" — on the default ms bucket "
                        f"geometry its quantiles saturate below 100 ms "
                        f"(monitor/registry.py)")
        return None
