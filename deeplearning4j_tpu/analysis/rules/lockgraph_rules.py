"""Interprocedural concurrency rules: lock-order cycles (THR003) and
lock-held-across-blocking-call (THR004).

Both are **project rules** (``project = True``): they run once over the
whole file set of a lint run, on the package-wide acquisition graph the
:mod:`~deeplearning4j_tpu.analysis.lockgraph` analyzer builds — because a
lock-order inversion is, by construction, a property of two *different*
code paths that no single-function scan can see. The runtime half of the
pass is ``monitor/lockwatch.py``; ``tests/test_lockwatch.py`` pins that
every lock-order edge the sanitizer observes at runtime is derivable by
this analyzer (the static side is not allowed to be blind to real
behavior).

Caveat worth knowing when reading reports: a subset run (``lint
--changed``, explicit paths) analyzes only the files given — call chains
and cycle partners living outside the subset are invisible there. The
tier-1 self-host guard always runs the whole package.
"""
from __future__ import annotations

from typing import Iterator, List, Sequence, Set, Tuple

from . import Rule, register, make_finding
from ..lockgraph import LockGraph, LockGraphAnalyzer, ModuleSource


#: one-slot cache so THR003 and THR004 running over the SAME module list
#: (the linter passes one list object to every project rule) build the
#: package-wide graph once, not once per rule; the strong reference to
#: the module list keeps the identity check sound
_LAST: list = [None, None]


def _analyze(modules: Sequence[ModuleSource]) -> LockGraph:
    if _LAST[0] is modules:
        return _LAST[1]
    graph = LockGraphAnalyzer(modules).build()
    _LAST[0], _LAST[1] = modules, graph
    return graph


@register
class LockOrderInversion(Rule):
    id = "THR003"
    title = "lock-order inversion (cycle in the acquisition graph)"
    project = True
    rationale = (
        "Two code paths acquiring the same locks in opposite orders "
        "deadlock the moment they run concurrently — and 16 modules here "
        "hold locks across the paramserver fleet, the prefetch pipeline "
        "and the monitor stack, with Fanout executors interleaving them "
        "freely. The analyzer resolves locks to stable identities "
        "(ClassName.attr / module.GLOBAL / the lockwatch factory name), "
        "follows calls made while a lock is held, and reports any cycle "
        "with BOTH witness paths. Fix: pick one canonical order (document "
        "it where the locks are created) and restructure the losing path "
        "— usually by snapshotting under the first lock and calling out "
        "after releasing it (docs/STATIC_ANALYSIS.md has the runbook).")

    def check(self, tree, lines, path) -> Iterator:
        # single-file entry (lint_source): analyze just this module —
        # project runs use check_project with the whole file set
        yield from self.check_project(
            [ModuleSource(path, tree, lines)])

    def check_project(self, modules: Sequence[ModuleSource]) -> Iterator:
        graph = _analyze(modules)
        lines_by_path = {m.path: m.lines for m in modules}
        for cyc in graph.cycles:
            lines = lines_by_path.get(cyc["path"], [])
            node = _Anchor(cyc["line"])
            yield make_finding(
                self.id, node, lines, cyc["path"],
                f"lock-order inversion between "
                f"{' and '.join(cyc['locks'])}: path 1 [{cyc['forward']}] "
                f"vs path 2 [{cyc['reverse']}] — these orders deadlock "
                f"under contention; pick one canonical order and "
                f"restructure the other path")


@register
class LockHeldAcrossBlockingCall(Rule):
    id = "THR004"
    title = "lock held across a blocking call in a called function"
    project = True
    rationale = (
        "THR001 sees a sleep/socket/join under `with lock:` only when "
        "both live in one function — but the hazard hides just as well "
        "one call away: a helper that looks cheap at the call site "
        "sends a frame or sleeps three frames down. This rule follows "
        "every resolvable call made while a lock is held to the "
        "blocking primitive it reaches, and reports the full chain. Fix "
        "like THR001: snapshot under the lock, do the blocking work "
        "after releasing it — or make the callee non-blocking.")

    def check(self, tree, lines, path) -> Iterator:
        yield from self.check_project(
            [ModuleSource(path, tree, lines)])

    def check_project(self, modules: Sequence[ModuleSource]) -> Iterator:
        graph = _analyze(modules)
        lines_by_path = {m.path: m.lines for m in modules}
        seen: Set[Tuple[str, int, str, str]] = set()
        for b in graph.blocking:
            key = (b["path"], b["line"], b["lock"], b["reason"])
            if key in seen:
                continue
            seen.add(key)
            lines = lines_by_path.get(b["path"], [])
            node = _Anchor(b["line"])
            yield make_finding(
                self.id, node, lines, b["path"],
                f"call made while holding {b['lock']!r} reaches a "
                f"blocking {b['reason']} through [{b['chain']}]; every "
                f"thread touching that lock stalls for the full I/O "
                f"latency — snapshot under the lock, call after "
                f"releasing it")


class _Anchor:
    """Minimal node stand-in for make_finding (line-anchored findings)."""

    def __init__(self, line: int, col: int = 0):
        self.lineno = int(line)
        self.col_offset = int(col)
