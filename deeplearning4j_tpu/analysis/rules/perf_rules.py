"""PERF rules: host↔device traffic shapes that serialize a hot loop.

PERF001 targets the exact shape the latency-hiding training pass removed
from ``paramserver/training.py`` and ``parallel/distributed.py``: a
``tree_map(np.asarray, ...)`` (or ``jax.device_get``) over a jit output
inside a training loop. Each leaf's conversion BLOCKS on its own
device→host transfer, so an N-leaf update tree pays N serialized stalls
per step — and the whole fetch sits between dispatch and comms, where
``paramserver.overlap.async_device_get`` would overlap the transfers
(and the overlap pipeline would hide them entirely).
"""
from __future__ import annotations

import ast
from typing import Iterator, Sequence, Set, Tuple

from . import Rule, register, call_callee
from ..linter import Finding

#: path components that mark training hot-loop packages — the rule only
#: fires where a blocking fetch actually stalls an accelerator step
_HOT_PACKAGES = ("paramserver", "parallel")


def _is_blocking_fetch(node: ast.AST) -> bool:
    """A reference to ``np.asarray`` / ``numpy.asarray`` /
    ``jax.device_get`` (or bare ``device_get``) — the per-leaf blocking
    device→host fetches. ``jnp.asarray`` is NOT one (device-resident)."""
    if isinstance(node, ast.Attribute):
        if node.attr == "asarray":
            return (isinstance(node.value, ast.Name)
                    and node.value.id in ("np", "numpy"))
        return node.attr == "device_get"
    return isinstance(node, ast.Name) and node.id == "device_get"


@register
class BlockingFetchInHotLoop(Rule):
    id = "PERF001"
    title = ("blocking device→host fetch (tree_map over np.asarray/"
             "device_get) inside a training hot loop")
    rationale = (
        "tree_map(np.asarray, update) in a paramserver//parallel/ loop "
        "blocks once PER LEAF on a device→host transfer, serializing the "
        "accelerator behind the host exactly where throughput is decided; "
        "paramserver.overlap.async_device_get starts every transfer first "
        "and gathers once, and the overlap pipeline (overlap=True) hides "
        "the whole fetch+push behind the next step's compute.")

    def check(self, tree: ast.AST, lines: Sequence[str],
              path: str) -> Iterator[Finding]:
        parts = path.replace("\\", "/").split("/")
        if not any(p in _HOT_PACKAGES for p in parts):
            return
        seen: Set[Tuple[int, int]] = set()
        for loop in ast.walk(tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            for node in self._loop_nodes(loop):
                if not (isinstance(node, ast.Call) and node.args):
                    continue
                if call_callee(node) != "tree_map":
                    continue
                if not _is_blocking_fetch(node.args[0]):
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen:        # nested loops walk the body twice
                    continue
                seen.add(key)
                fetch = ("device_get"
                         if not (isinstance(node.args[0], ast.Attribute)
                                 and node.args[0].attr == "asarray")
                         else "np.asarray")
                yield self.finding(
                    node, lines, path,
                    f"tree_map({fetch}, ...) inside a loop blocks the "
                    f"hot path once per leaf on a device→host transfer; "
                    f"use paramserver.overlap.async_device_get (starts "
                    f"all transfers, gathers once) or keep the update "
                    f"device-resident")

    @staticmethod
    def _loop_nodes(loop: ast.AST) -> Iterator[ast.AST]:
        """Walk a loop's body without descending into nested function or
        lambda definitions — code merely *defined* in a loop does not run
        per iteration."""
        stack = list(loop.body) + list(getattr(loop, "orelse", []))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))
