"""JAX hazards: host-sync barriers under trace, PRNG key reuse.

Calibrated for this repo's idioms: jit shows up both as a decorator
(``@jax.jit``) and — dominantly — as ``jax.jit(step, donate_argnums=...)``
wrapping a locally-defined function (``nn/multilayer.py``, ``nn/graph.py``,
``paramserver/training.py``), so JAX001 resolves first-argument names back
to ``def``\\ s in the same module. PRNG flows through ``rng``/``key``
threading with ``jax.random.split``/``fold_in`` (``nn/layers/*``), so
JAX002 treats ``split`` as a *consuming* use (feeding a key to ``split``
and then to ``normal`` correlates the draws) but exempts ``fold_in``
(reuse with distinct fold data is the sanctioned pattern,
``nn/layers/base.py``).
"""
from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Sequence, Set, Tuple

from . import Rule, register, terminal_name, assigned_names

# attrs that CONSUME a key's entropy; same key into two of these (without a
# rebinding split in between) repeats the stream
_KEY_EXEMPT = {"PRNGKey", "key", "fold_in", "key_data", "wrap_key_data",
               "key_impl", "clone"}
# host-sync method calls: each forces the device queue to drain
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NUMPY_NAMES = {"np", "numpy", "onp"}


def _walk_pruned(root: ast.AST):
    """ast.walk minus nested function/lambda/class subtrees — those are
    separate execution scopes."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)


def _is_jit_expr(node: ast.AST) -> bool:
    """``jit`` / ``jax.jit`` (any attribute chain ending in .jit), plus the
    package's own ``monitored_jit`` wrapper (``monitor/jitwatch.py``) — a
    function routed through jitwatch is every bit as traced as a bare-jit
    one, so JAX001's barrier analysis must follow it."""
    return terminal_name(node) in ("jit", "monitored_jit")


def _jit_decorated(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", ()):
        if _is_jit_expr(dec):
            return True
        if isinstance(dec, ast.Call):
            if _is_jit_expr(dec.func):          # @jax.jit(static_argnums=…)
                return True
            if terminal_name(dec.func) == "partial" and any(
                    _is_jit_expr(a) for a in dec.args):
                return True
    return False


@register
class HostSyncInJit(Rule):
    id = "JAX001"
    title = "host-sync barrier inside a jit-traced function"
    rationale = (
        "float()/.item()/.tolist()/.block_until_ready()/np.asarray on a "
        "traced value either crashes at trace time (ConcretizationTypeError)"
        " or, via a constant-folded escape hatch, silently pins a host "
        "round-trip into the hot step. The repo's contract (docs/"
        "OBSERVABILITY.md) is that the ONE sanctioned device→host fetch per "
        "step is the fit loop's float(loss), placed inside the step span — "
        "traced code must stay barrier-free.")

    def check(self, tree, lines, path) -> Iterator:
        traced: List[ast.AST] = []
        # scope-aware wrap resolution: `jax.jit(step, ...)` marks the
        # `def step` of the SAME scope as traced (the repo idiom is both
        # inside one factory function) — a same-named eager def in another
        # factory must not be dragged in
        self._collect_scope(tree, traced)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and _jit_decorated(node):
                traced.append(node)
        seen: Set[tuple] = set()
        for fn in traced:
            for f in self._scan(fn, lines, path):
                key = (f.line, f.col)
                if key not in seen:          # nested traced defs overlap
                    seen.add(key)
                    yield f

    def _collect_scope(self, scope: ast.AST, traced: List[ast.AST],
                       inherited: Optional[dict] = None):
        """One execution scope: a jit call here marks the def it can SEE
        (defined here or in a lexically enclosing scope — closure
        capture) as traced, plus lambdas passed to jit directly. Nested
        defs/classes are their own scopes (recursed into) — so an eager
        helper that merely shares a jitted def's name in some unrelated
        scope is never dragged in."""
        visible = dict(inherited or {})
        wrapped: Set[str] = set()
        child_scopes: List[ast.AST] = []
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                if not isinstance(node, ast.ClassDef):
                    visible[node.name] = node       # local shadows outer
                child_scopes.append(node)
                continue               # its body is a separate scope
            if isinstance(node, ast.Lambda):
                continue               # bare lambda body: separate scope
            if isinstance(node, ast.Call) and _is_jit_expr(node.func) \
                    and node.args:
                target = node.args[0]
                if isinstance(target, ast.Name):
                    wrapped.add(target.id)
                elif isinstance(target, ast.Lambda):
                    traced.append(target)
            stack.extend(ast.iter_child_nodes(node))
        for name in wrapped:
            if name in visible:
                traced.append(visible[name])
        for child in child_scopes:
            # class bodies are not closure scopes: methods see what the
            # CLASS saw, not their sibling methods
            self._collect_scope(
                child, traced,
                inherited if isinstance(scope, ast.ClassDef) else visible)

    def _scan(self, fn: ast.AST, lines, path):
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                callee = terminal_name(node.func)
                if callee == "float" and isinstance(node.func, ast.Name):
                    if node.args and not isinstance(node.args[0],
                                                    ast.Constant):
                        yield self.finding(
                            node, lines, path,
                            "float() inside a jit-traced function is a "
                            "device→host sync barrier (or a trace-time "
                            "crash); compute on-device and fetch once, "
                            "outside the traced step")
                elif isinstance(node.func, ast.Attribute) \
                        and callee in _SYNC_METHODS and not node.args:
                    yield self.finding(
                        node, lines, path,
                        f".{callee}() inside a jit-traced function forces "
                        f"a host round-trip; keep traced code barrier-free")
                elif isinstance(node.func, ast.Attribute) \
                        and callee in {"asarray", "array", "frombuffer"}:
                    base = node.func.value
                    if isinstance(base, ast.Name) \
                            and base.id in _NUMPY_NAMES:
                        yield self.finding(
                            node, lines, path,
                            f"np.{callee}() inside a jit-traced function "
                            f"materializes on host; use jnp.{callee} (or "
                            f"move the conversion outside the trace)")
                elif callee == "device_get":
                    yield self.finding(
                        node, lines, path,
                        "jax.device_get inside a jit-traced function is a "
                        "host transfer; fetch outside the traced step")


@register
class PRNGKeyReuse(Rule):
    id = "JAX002"
    title = "PRNG key fed to two jax.random consumers without a split"
    rationale = (
        "jax.random is splittable, not stateful: the same key yields the "
        "SAME draw from every consumer, so dropout masks repeat, VAE "
        "samples collapse, and init correlates across layers — silently. "
        "The sanctioned flow (nn/layers/*) is split/fold_in per consumer: "
        "`k1, k2 = jax.random.split(rng)`, never rng twice.")

    def check(self, tree, lines, path) -> Iterator:
        consumer_bare = self._bare_imports(tree)
        scopes: List[ast.AST] = [tree]
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node)
        for scope in scopes:
            yield from self._scan_scope(scope, consumer_bare, lines, path)

    @staticmethod
    def _bare_imports(tree) -> Set[str]:
        """Names imported with `from jax.random import X` count as
        consumers when called bare."""
        out: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) \
                    and node.module == "jax.random":
                for a in node.names:
                    name = a.asname or a.name
                    if a.name not in _KEY_EXEMPT:
                        out.add(name)
        return out

    @staticmethod
    def _is_consumer(call: ast.Call, bare: Set[str]) -> bool:
        f = call.func
        if isinstance(f, ast.Name):
            return f.id in bare
        if not isinstance(f, ast.Attribute) or f.attr in _KEY_EXEMPT:
            return False
        base = f.value
        if isinstance(base, ast.Attribute) and base.attr == "random" \
                and terminal_name(base.value) == "jax":
            return True              # jax.random.X / xxx.jax.random.X
        if isinstance(base, ast.Name) and base.id in {"jrandom", "jr"}:
            return True              # import jax.random as jrandom
        return False

    def _scan_scope(self, scope, bare, lines, path):
        """Branch-aware linear scan. State maps key name → line of the use
        that consumed it (cleared on rebinding). `if`/`try` arms run on
        COPIES of the incoming state and merge by union afterwards, so
        mutually-exclusive consumers (the RBM sampler's if/elif arms in
        nn/layers/feedforward.py) never conflict, while a use AFTER the
        branch still conflicts with a use on either arm."""
        findings: List = []
        # cross-iteration pass bookkeeping
        loop_uses: List[Tuple[str, ast.AST, frozenset]] = []
        loop_stack: List[ast.AST] = []
        bound_in_loop: dict = {}   # name -> {id(loop) where it's rebound}

        def consumed_key(call: ast.Call):
            key = call.args[0] if call.args else None
            if key is None:
                for kw in call.keywords:
                    if kw.arg == "key":
                        key = kw.value
            return key.id if isinstance(key, ast.Name) else None

        def apply_expr(expr, state):
            """Uses (in walk order) then walrus-assigns for one
            expression tree; nested scopes excluded."""
            if expr is None:
                return
            for node in _walk_pruned(expr):
                if isinstance(node, ast.Call) \
                        and self._is_consumer(node, bare):
                    name = consumed_key(node)
                    if name is None:
                        continue
                    if name in state:
                        findings.append(self.finding(
                            node, lines, path,
                            f"PRNG key {name!r} already consumed at line "
                            f"{state[name]}; split it first (`k1, k2 = "
                            f"jax.random.split({name})`) — reusing a key "
                            f"repeats the exact same draw"))
                    else:
                        state[name] = node.lineno
                    if loop_stack:
                        loop_uses.append(
                            (name, node,
                             frozenset(id(lp) for lp in loop_stack)))
                elif isinstance(node, ast.NamedExpr):
                    note_assign(assigned_names(node), state)

        def note_assign(names, state):
            for n in names:
                state.pop(n, None)
                for lp in loop_stack:
                    bound_in_loop.setdefault(n, set()).add(id(lp))

        def merge(into, *branches):
            # union of consumed keys: reuse after the join conflicts with
            # a consumer on ANY arm
            for st in branches:
                for n, line in st.items():
                    into[n] = max(line, into.get(n, 0))
            return into

        def analyze_block(stmts, state):
            """Returns True when the block always leaves the enclosing
            flow (return/raise/break/continue) — a terminated arm's state
            must not merge into the join, so guard-style sequential
            ``if …: return consume(key)`` arms never conflict."""
            for stmt in stmts:
                if analyze_stmt(stmt, state):
                    return True
            return False

        def analyze_stmt(stmt, state):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return False                        # separate scope
            if isinstance(stmt, ast.If):
                apply_expr(stmt.test, state)
                s1, s2 = dict(state), dict(state)
                t1 = analyze_block(stmt.body, s1)
                t2 = analyze_block(stmt.orelse, s2)
                live = [s for s, t in ((s1, t1), (s2, t2)) if not t]
                state.clear()
                merge(state, *live)
                return not live
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                apply_expr(stmt.iter, state)
                loop_stack.append(stmt)
                body_state = dict(state)
                # the for target rebinds every iteration, inside the loop
                tgt = [terminal_name(t) for t in ast.walk(stmt.target)
                       if isinstance(t, (ast.Name, ast.Attribute))]
                note_assign([t for t in tgt if t], body_state)
                t1 = analyze_block(stmt.body, body_state)
                loop_stack.pop()
                analyze_block(stmt.orelse, state)
                if not t1:            # zero-iteration path keeps `state`
                    merge(state, body_state)
                return False
            if isinstance(stmt, ast.While):
                apply_expr(stmt.test, state)
                loop_stack.append(stmt)
                body_state = dict(state)
                t1 = analyze_block(stmt.body, body_state)
                loop_stack.pop()
                analyze_block(stmt.orelse, state)
                if not t1:
                    merge(state, body_state)
                return False
            if isinstance(stmt, ast.Try):
                s1 = dict(state)
                t1 = analyze_block(stmt.body, s1)
                arms = [(s1, t1)]
                for h in stmt.handlers:
                    sh = dict(state)
                    arms.append((sh, analyze_block(h.body, sh)))
                so = dict(s1)
                to = t1 or analyze_block(stmt.orelse, so)
                arms.append((so, to))
                live = [s for s, t in arms if not t]
                state.clear()
                merge(state, *live)
                tfin = analyze_block(stmt.finalbody, state)
                return tfin or not live
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    apply_expr(item.context_expr, state)
                    if item.optional_vars is not None:
                        n = terminal_name(item.optional_vars)
                        if n:
                            note_assign([n], state)
                return analyze_block(stmt.body, state)
            # simple statement: uses from the expression parts, then the
            # statement-level bindings
            apply_expr(stmt, state)
            note_assign(assigned_names(stmt), state)
            return isinstance(stmt, (ast.Return, ast.Raise, ast.Break,
                                     ast.Continue))

        analyze_block(scope.body, {})
        yield from findings
        # loop reuse: a consumer inside a loop whose key is never rebound
        # within ANY enclosing loop draws the SAME value every iteration
        for name, node, loops in loop_uses:
            if not (bound_in_loop.get(name, set()) & loops):
                yield self.finding(
                    node, lines, path,
                    f"PRNG key {name!r} consumed inside a loop but never "
                    f"rebound there — every iteration repeats the same "
                    f"draw; split or fold_in per iteration")


@register
class BareJit(Rule):
    id = "JAX003"
    title = "bare jax.jit not routed through monitored_jit"
    rationale = (
        "A bare jax.jit compiles invisibly: no compile counter, no "
        "compile-time histogram, no compile/<fn> span on /trace, no "
        "cost_analysis capture, and — critically — no retrace-storm "
        "detection, so shape/dtype churn silently re-traces the step and "
        "training gets 10x slower with nothing on /metrics to say why. "
        "monitor.jitwatch.monitored_jit(name=...) is a drop-in wrapper "
        "that records all of the above (docs/OBSERVABILITY.md "
        "'Compilation & memory'). Exempt: tests/ and jitwatch.py itself "
        "(the one sanctioned jax.jit call). Ratchet-only via "
        "analysis/baseline.json for sites that genuinely cannot migrate.")

    def check(self, tree, lines, path) -> Iterator:
        p = path.replace("\\", "/")
        if "tests" in p.split("/") or p.endswith("monitor/jitwatch.py"):
            return
        # `from jax import jit [as alias]` makes the bare name a jit
        # ref; `import jax as j` makes `j.jit` one (evading the guard
        # through a module alias must not lint clean)
        bare: Set[str] = set()
        jax_mods: Set[str] = {"jax"}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module == "jax":
                for a in node.names:
                    if a.name == "jit":
                        bare.add(a.asname or "jit")
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax" and a.asname:
                        jax_mods.add(a.asname)
        # flagging the REFERENCE (Attribute/Name), not just calls, covers
        # every spelling in one pass: jax.jit(f, ...), @jax.jit,
        # @jax.jit(static_argnums=...), functools.partial(jax.jit, ...)
        seen: Set[tuple] = set()
        for node in ast.walk(tree):
            hit = None
            if isinstance(node, ast.Attribute) and node.attr == "jit" \
                    and terminal_name(node.value) in jax_mods:
                hit = node
            elif isinstance(node, ast.Name) and node.id in bare \
                    and isinstance(node.ctx, ast.Load):
                hit = node
            if hit is None:
                continue
            key = (hit.lineno, hit.col_offset)
            if key in seen:
                continue
            seen.add(key)
            yield self.finding(
                hit, lines, path,
                "bare jax.jit — route it through monitor.jitwatch."
                "monitored_jit(name=\"area/fn\") so compiles are counted, "
                "timed, traced, cost-profiled, and retrace-storm-watched")


@register
class RawMeshConstruction(Rule):
    id = "JAX004"
    title = "raw Mesh/shard_map construction outside the parallel substrate"
    rationale = (
        "parallel/mesh.py is the ONE sanctioned mesh construction site: a "
        "MeshSpec validates axis names, auto-factorizes extents over the "
        "available devices (a raw Mesh(...) reshape silently builds the "
        "degenerate [n, 1, ...] topology or crashes on a non-dividing "
        "shape), stays multi-process consistent, and registers the "
        "topology on GET /profile's mesh block. A raw "
        "jax.sharding.Mesh(...) or shard_map(...) call outside "
        "parallel/ bypasses all of that — the fit runs on a topology no "
        "operator can see and no validation ever checked. Route meshes "
        "through parallel.mesh (MeshSpec/make_mesh) and shard_map-style "
        "steps through the parallel/ step factories. Exempt: tests/, the "
        "parallel/ substrate package itself, and compat.py (the "
        "version-shim that DEFINES the sanctioned shard_map wrapper). "
        "Ratchet-only via analysis/baseline.json for sites that "
        "genuinely cannot migrate.")

    def check(self, tree, lines, path) -> Iterator:
        p = path.replace("\\", "/")
        parts = p.split("/")
        if "tests" in parts or "parallel" in parts \
                or p.endswith("compat.py"):
            return
        # names bound to the constructors by import: `from jax.sharding
        # import Mesh [as m]`, `from jax.experimental.shard_map import
        # shard_map`, `from jax import shard_map`, and the repo idiom
        # `from ..compat import shard_map`
        mesh_names: Set[str] = set()
        sm_names: Set[str] = set()
        jax_mods: Set[str] = {"jax"}
        # module aliases whose .shard_map attribute IS the constructor
        # (`from jax.experimental import shard_map as smod`,
        # `import jax.experimental.shard_map as sm`, compat imports) — an
        # unrelated object's own .shard_map method must NOT flag
        sm_mods: Set[str] = {"compat"}
        # aliases of the jax.sharding MODULE itself (`import jax.sharding
        # as jsh`, `from jax import sharding [as x]`) — jsh.Mesh(...) is
        # just as raw as jax.sharding.Mesh(...)
        sharding_mods: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                mod = node.module
                for a in node.names:
                    bound = a.asname or a.name
                    if a.name == "Mesh" and mod.startswith("jax"):
                        mesh_names.add(bound)
                    elif a.name == "shard_map":
                        if mod.startswith("jax") \
                                or mod.split(".")[-1] == "compat":
                            sm_names.add(bound)
                        if mod == "jax.experimental":
                            sm_mods.add(bound)   # module, not function
                    elif a.name == "compat":
                        sm_mods.add(bound)
                    elif a.name == "sharding" and mod == "jax":
                        sharding_mods.add(bound)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax" and a.asname:
                        jax_mods.add(a.asname)
                    elif a.name == "jax.experimental.shard_map":
                        sm_mods.add(a.asname or "shard_map")
                    elif a.name == "jax.sharding" and a.asname:
                        sharding_mods.add(a.asname)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            hit = None
            if isinstance(f, ast.Name):
                if f.id in mesh_names:
                    hit = "Mesh"
                elif f.id in sm_names:
                    hit = "shard_map"
            elif isinstance(f, ast.Attribute):
                root = terminal_name(f.value)
                if f.attr == "Mesh" and (
                        root in jax_mods
                        or (isinstance(f.value, ast.Name)
                            and f.value.id in sharding_mods)
                        or (isinstance(f.value, ast.Attribute)
                            and f.value.attr == "sharding")):
                    hit = "Mesh"          # jax.sharding.Mesh / jsh.Mesh
                elif f.attr == "shard_map" and (
                        root in jax_mods or root in sm_mods):
                    hit = "shard_map"     # compat.shard_map / jax.shard_map
            if hit is None:
                continue
            yield self.finding(
                node, lines, path,
                f"raw {hit}(...) outside the parallel/ substrate — build "
                f"meshes with parallel.mesh.MeshSpec/make_mesh (validated, "
                f"auto-factorized, visible on /profile) and mapped steps "
                f"through the parallel/ step factories")
