"""CTL rules: keep fleet/serving actuation inside the control plane.

PR 16's closed loop works precisely because every automated actuator
invocation funnels through one auditable seam: a
:class:`~deeplearning4j_tpu.control.plane.ControlPolicy` action, edge-
triggered, cooldown-latched, recorded as a ``control_action`` flight
event. An actuator call sprinkled anywhere else — a training script
that quietly ``scale_to``\\ s its own fleet, a handler that mutates a
model's admission cap inline — is an automated action no operator can
see on ``GET /control``, no cooldown ever latches, and no flight event
reconstructs. CTL001 fences those call sites.
"""
from __future__ import annotations

import ast
from typing import Iterator

from . import Rule, register

#: the actuator surface the control plane owns: fleet membership
#: (scale_to/remap/restart) and serving admission mutation
_ACTUATORS = {"scale_to", "remap", "restart", "set_admission"}


@register
class ActuatorOutsideControlPlane(Rule):
    id = "CTL001"
    title = "fleet/serving actuator call outside the control plane"
    rationale = (
        "scale_to/remap/restart/set_admission are the actuators the "
        "closed-loop control plane (control/) owns: invoked there, every "
        "action is edge-triggered, hysteresis/cooldown-latched against "
        "flapping, counted in control_actions_total, and recorded as a "
        "control_action flight event carrying the triggering alert's "
        "rule and exemplar trace — the whole incident reconstructs from "
        "GET /events. The same call anywhere else is an invisible "
        "mutation of fleet membership or serving admission: no operator "
        "surface shows it, no cooldown bounds it, and a flapping caller "
        "can shred the fleet. Route automated actions through a "
        "ControlPolicy; manual/runbook invocations belong in the "
        "paramserver package itself, tests, or bench harnesses (all "
        "exempt, as are self.* forwards — the definition pattern, e.g. "
        "ServedModel.set_admission delegating to its own batcher).")

    def check(self, tree, lines, path) -> Iterator:
        p = path.replace("\\", "/")
        parts = p.split("/")
        if "tests" in parts or "control" in parts \
                or "paramserver" in parts or parts[-1].startswith("bench"):
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute) \
                    or f.attr not in _ACTUATORS:
                continue
            # self.X(...) / self.attr.X(...): a class forwarding to its
            # own component defines the actuator, it does not actuate
            base = f.value
            while isinstance(base, (ast.Attribute, ast.Subscript)):
                base = base.value
            if isinstance(base, ast.Name) and base.id == "self":
                continue
            yield self.finding(
                node, lines, p,
                f"actuator call .{f.attr}(...) outside the control "
                f"plane — route automated fleet/serving actions through "
                f"a ControlPolicy (control/) so they are cooldown-"
                f"latched, counted, and flight-recorded")
