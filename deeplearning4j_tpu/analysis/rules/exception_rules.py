"""Exception hygiene: broad handlers that swallow silently.

The failure mode this guards (and has bitten this stack): a ``try`` around
a jax/socket/IO call grows an ``except Exception: pass`` "for robustness",
and from then on REAL defects — a renamed attribute after a jax upgrade, a
protocol error, a corrupted stats row — vanish instead of failing loudly
or at least leaving a log line. The monitor subsystem exists to make this
system observable; silent swallows are the anti-observability primitive.
"""
from __future__ import annotations

import ast
from typing import Iterator

from . import Rule, register, terminal_name

_BROAD = {"Exception", "BaseException"}
#: a call to any of these inside the handler counts as "the failure was
#: reported somewhere" — logging methods, warnings, print, health hooks
_REPORTING = {"debug", "info", "warning", "warn", "error", "exception",
              "critical", "log", "print", "write", "record_ps_error",
              "record_exception", "fail"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True                                    # bare except:
    if isinstance(t, ast.Tuple):
        return any(terminal_name(e) in _BROAD for e in t.elts)
    return terminal_name(t) in _BROAD


def _reports_or_reraises(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            callee = terminal_name(node.func)
            if callee in _REPORTING:
                return True
        # `except Exception as e:` + any READ of e — the exception is kept
        # and routed elsewhere (stored for a later re-raise, sent to the
        # peer, put on a Future), not swallowed
        if handler.name and isinstance(node, ast.Name) \
                and node.id == handler.name \
                and isinstance(node.ctx, ast.Load):
            return True
    return False


@register
class SilentBroadExcept(Rule):
    id = "EXC001"
    title = "broad except that neither logs nor re-raises"
    rationale = (
        "`except Exception:` with a silent body turns every future defect "
        "in the protected block into invisible data loss. Narrow the type "
        "to what the fallback actually handles (OSError, ImportError, "
        "AttributeError…), or keep it broad and LOG the swallow "
        "(log.debug/warning with exc_info) so the monitor story stays "
        "true. A deliberate must-never-raise path gets a line pragma WITH "
        "a comment saying why (see monitor/tracer.py).")

    def check(self, tree, lines, path) -> Iterator:
        for node in ast.walk(tree):
            if isinstance(node, ast.ExceptHandler) and _is_broad(node) \
                    and not _reports_or_reraises(node):
                what = ("bare except:" if node.type is None
                        else f"except {terminal_name(node.type) if not isinstance(node.type, ast.Tuple) else 'Exception'}:")
                yield self.finding(
                    node, lines, path,
                    f"{what} swallows without logging or re-raising; "
                    f"narrow the exception type, log the swallow, or "
                    f"pragma it with a reason")
