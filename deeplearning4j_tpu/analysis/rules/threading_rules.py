"""Concurrency hazards: blocking calls under a lock, leaked threads.

Calibrated for this stack's threaded modules (``paramserver/server.py``,
``monitor/registry.py``, ``parallel/transport.py``, ``datasets/
streaming.py``): locks are ``threading.Lock``/``RLock`` instances held in
attributes whose terminal identifier contains ``lock`` (``self._lock``,
``self._send_locks[s]``, a bare ``lock``), and the wire layer's blocking
primitives are the ``send_frame``/``recv_frame`` helpers from
``parallel/transport.py`` as much as raw socket methods.
"""
from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Sequence, Set

from . import Rule, register, terminal_name

#: socket/OS methods that park the calling thread
_BLOCKING_METHODS = {
    "accept": "socket accept",
    "recv": "socket recv",
    "recvfrom": "socket recv",
    "recv_into": "socket recv",
    "send": "socket send",
    "sendall": "socket send",
    "connect": "socket connect",
    "sleep": "sleep",
    "urlopen": "HTTP request",
    "getresponse": "HTTP response read",
}
#: repo wire helpers (parallel/transport.py, datasets/streaming.py) — the
#: actual blocking layer most of this stack calls instead of raw sockets
_BLOCKING_FUNCS = {"send_frame", "recv_frame", "_send_frame", "_recv_frame",
                   "urlopen", "sleep"}


def _is_lock_expr(node: ast.AST) -> bool:
    name = terminal_name(node)
    return bool(name) and "lock" in name.lower()


def _blocking_reason(call: ast.Call) -> Optional[str]:
    """Why this call blocks, or None if it doesn't (statically)."""
    callee = terminal_name(call.func)
    if callee is None:
        return None
    if isinstance(call.func, ast.Name):
        return ("blocking call" if callee in _BLOCKING_FUNCS else None)
    # attribute call
    if callee in _BLOCKING_METHODS:
        if callee == "send" and isinstance(call.func, ast.Attribute):
            # generator.send(x) false-positive guard: socket send takes
            # bytes-ish, still 1 arg — keep, but skip obvious str targets
            base = call.func.value
            if isinstance(base, ast.Constant):
                return None
        return _BLOCKING_METHODS[callee]
    if callee == "join" and not call.args:
        # thread/process join: zero positional args (str.join/os.path.join
        # always take the iterable/components positionally)
        has_timeout = any(kw.arg == "timeout" and
                          not (isinstance(kw.value, ast.Constant)
                               and kw.value.value is None)
                          for kw in call.keywords)
        return None if has_timeout else "join() without timeout"
    if callee == "get" and not call.args:
        # queue get: zero positional args (dict.get always passes the key
        # positionally); a timeout= or block=False makes it bounded
        for kw in call.keywords:
            if kw.arg == "timeout" and not (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value is None):
                return None            # timeout=None blocks forever: flag
            if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return None
        return "queue get() without timeout"
    return None


@register
class BlockingUnderLock(Rule):
    id = "THR001"
    title = "blocking call while holding a lock"
    rationale = (
        "Every other thread touching that lock stalls for the full socket/"
        "sleep/join latency — the paramserver serve loop, the monitor "
        "scrape path and the transport fan-out all share locks with the "
        "training thread, so one slow peer under a lock becomes a "
        "training-wide latency cliff (or a deadlock when the blocked "
        "operation itself needs another lock). Copy state out under the "
        "lock, do the blocking work outside (see MetricsRegistry."
        "render_prometheus, ParameterServer._handle).")

    def check(self, tree, lines, path) -> Iterator:
        seen: set = set()      # nested locks: report each call site once
        for node in ast.walk(tree):
            if isinstance(node, (ast.With, ast.AsyncWith)) and any(
                    _is_lock_expr(i.context_expr) for i in node.items):
                lock_name = next(
                    (terminal_name(i.context_expr) for i in node.items
                     if _is_lock_expr(i.context_expr)), "lock")
                for f in self._scan_body(node.body, lock_name, lines,
                                         path):
                    if (f.line, f.col) not in seen:
                        seen.add((f.line, f.col))
                        yield f

    def _scan_body(self, body: Sequence[ast.stmt], lock_name, lines, path):
        for stmt in body:
            for node in self._walk_same_thread(stmt):
                if isinstance(node, ast.Call):
                    reason = _blocking_reason(node)
                    if reason:
                        yield self.finding(
                            node, lines, path,
                            f"{reason} while holding {lock_name!r}; move "
                            f"the blocking work outside the lock (snapshot "
                            f"under the lock, send/sleep/join after)")

    @staticmethod
    def _walk_same_thread(stmt: ast.AST):
        """ast.walk minus nested function/lambda bodies — a closure defined
        under the lock usually RUNS outside it."""
        stack = [stmt]
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue                   # closure body runs later
            stack.extend(ast.iter_child_nodes(node))


@register
class LeakedThread(Rule):
    id = "THR002"
    title = "non-daemon thread started and never joined"
    rationale = (
        "A forgotten non-daemon thread keeps the process alive after "
        "main() returns — CLI runs and tests hang on exit instead of "
        "failing loudly. Every long-lived service thread here is either "
        "daemon=True with an explicit stop() (paramserver accept loop, UI "
        "httpd) or joined on shutdown (transport exchange). Pick one.")

    def check(self, tree, lines, path) -> Iterator:
        joined: Set[str] = set()          # names X with X.join(...) present
        daemoned: Set[str] = set()        # names X with X.daemon = True
        ctors: List[tuple] = []           # (call node, bound name or None)

        parents = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node

        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                callee = terminal_name(node.func)
                if callee == "join" and isinstance(node.func,
                                                   ast.Attribute):
                    n = terminal_name(node.func.value)
                    if n:
                        joined.add(n)
                if callee in {"Thread", "Timer"} and self._is_threading(
                        node.func, tree):
                    ctors.append((node, self._bound_name(node, parents)))
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and t.attr == "daemon" \
                            and isinstance(node.value, ast.Constant) \
                            and node.value.value is True:
                        n = terminal_name(t.value)
                        if n:
                            daemoned.add(n)

        for call, bound in ctors:
            if self._daemon_kw(call):
                continue
            if bound is not None and (bound in joined or bound in daemoned):
                continue
            where = (f"bound to {bound!r} but" if bound is not None
                     else "never bound, so it")
            yield self.finding(
                call, lines, path,
                f"thread {where} is neither daemon=True nor .join()ed "
                f"anywhere in this module — it outlives the process's "
                f"intent; pass daemon=True (with an explicit stop path) "
                f"or join it on shutdown")

    @staticmethod
    def _is_threading(func: ast.AST, tree: ast.AST) -> bool:
        """threading.Thread(...) always; bare Thread(...) only when the
        module imports it from threading."""
        if isinstance(func, ast.Attribute):
            return terminal_name(func.value) == "threading"
        if isinstance(func, ast.Name):
            for node in ast.walk(tree):
                if isinstance(node, ast.ImportFrom) \
                        and node.module == "threading" \
                        and any((a.asname or a.name) == func.id
                                for a in node.names):
                    return True
        return False

    @staticmethod
    def _daemon_kw(call: ast.Call) -> bool:
        return any(kw.arg == "daemon"
                   and isinstance(kw.value, ast.Constant)
                   and kw.value.value is True for kw in call.keywords)

    @staticmethod
    def _bound_name(call: ast.Call, parents) -> Optional[str]:
        """`t = Thread(...)` / `self._thread = Thread(...)` → the terminal
        target name; chained `Thread(...).start()` or bare expression →
        None (can never be joined)."""
        parent = parents.get(call)
        if isinstance(parent, ast.Assign) and parent.value is call:
            for t in parent.targets:
                n = terminal_name(t)
                if n:
                    return n
        if isinstance(parent, (ast.AnnAssign, ast.AugAssign)) \
                and parent.value is call:
            return terminal_name(parent.target)
        if isinstance(parent, ast.NamedExpr) and parent.value is call:
            return terminal_name(parent.target)
        return None
