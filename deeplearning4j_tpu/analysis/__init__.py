"""tpulint — AST-based static analysis for this stack's real hazards.

Three subsystems here enforce whole bug classes only by convention: the
device→host value-fetch barrier rule in the fit loops, lock discipline
across the threaded paramserver/monitor/transport stack, and exception
hygiene. ``tpulint`` machine-checks those conventions the same way
``tests/test_listener_contract.py`` guards listener drift — as a tier-1
test over the whole package (``tests/test_analysis.py``) and a CLI::

    python -m deeplearning4j_tpu.main lint [--format json] [--baseline P]

Rule catalog + fix guidance: docs/STATIC_ANALYSIS.md. Suppress a single
line with ``# tpulint: disable=RULE`` and a comment saying why; everything
pre-existing lives in ``analysis/baseline.json`` (ratchet-only — the
tier-1 run fails on any NEW finding).
"""
from .linter import (Finding, Linter, load_baseline, load_baseline_reasons,
                     save_baseline, DEFAULT_BASELINE_PATH, PACKAGE_ROOT,
                     REPO_ROOT)
from .rules import all_rules, get_rule

__all__ = ["Finding", "Linter", "load_baseline", "load_baseline_reasons",
           "save_baseline", "DEFAULT_BASELINE_PATH", "PACKAGE_ROOT",
           "REPO_ROOT", "all_rules", "get_rule"]
