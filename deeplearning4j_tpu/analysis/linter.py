"""tpulint core: file walker, pragma suppression, baseline, output.

Pieces (docs/STATIC_ANALYSIS.md has the user-facing story):

- :class:`Finding` — one violation, fingerprinted by ``(path, rule,
  stripped source line)`` so baselines survive unrelated line-number
  drift.
- pragma suppression — ``# tpulint: disable=RULE1,RULE2`` (or a bare
  ``# tpulint: disable``) on the *reported* line of the finding. Pragmas
  are for deliberate, commented exceptions; everything else belongs in
  code fixes or the baseline.
- baseline — ``analysis/baseline.json`` grandfathers pre-existing
  findings so the pass lands green and becomes ratchet-only: the tier-1
  run (``tests/test_analysis.py``) fails on any finding NOT covered by a
  baseline entry, and entries can only be removed (by fixing the code),
  never silently added.
- :class:`Linter` — walks files, parses once per file, runs every
  registered rule's AST check, applies pragmas, partitions findings
  against the baseline. Output is deterministic (sorted by path, line,
  column, rule) so CI diffs are stable.
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["Finding", "Linter", "load_baseline", "load_baseline_reasons",
           "save_baseline", "DEFAULT_BASELINE_PATH", "PACKAGE_ROOT",
           "REPO_ROOT", "SKIP_DIRS"]

#: deeplearning4j_tpu package directory (the default lint target)
PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
#: repository root — findings carry paths relative to this
REPO_ROOT = os.path.dirname(PACKAGE_ROOT)
#: shipped grandfather list
DEFAULT_BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json")

SKIP_DIRS = {".git", "__pycache__", ".venv", "venv", "node_modules",
             "build", "dist", ".eggs"}

_PRAGMA = re.compile(r"#\s*tpulint:\s*disable(?:=([A-Za-z0-9_,\s]+))?")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""
    rule: str
    path: str          # repo-root-relative posix path (abs if outside)
    line: int          # 1-based
    col: int           # 0-based, ast convention
    message: str
    snippet: str = ""  # stripped source line — the fingerprint component

    @property
    def fingerprint(self) -> Tuple[str, str, str]:
        """Line-number-free identity used for baseline matching: the same
        (file, rule, source text) keeps matching after unrelated edits
        shift line numbers."""
        return (self.path, self.rule, self.snippet)

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "snippet": self.snippet}

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: {self.rule} " \
               f"{self.message}"


# ------------------------------------------------------------------ baseline
def load_baseline(path: str) -> Dict[Tuple[str, str, str], int]:
    """Baseline JSON → ``{(path, rule, snippet): allowed_count}``.

    Schema (``analysis/baseline.json``)::

        {"version": 1, "findings": [
            {"rule": "THR001", "path": "deeplearning4j_tpu/x.py",
             "snippet": "...stripped flagged line...",
             "count": 1, "reason": "why this is deliberate"}]}

    ``reason`` is documentation for humans; the matcher ignores it.
    ``count`` (default 1) allows that many identical fingerprints —
    extras are NEW findings (the ratchet).
    """
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    out: Dict[Tuple[str, str, str], int] = {}
    for e in data.get("findings", ()):
        key = (str(e["path"]), str(e["rule"]), str(e.get("snippet", "")))
        out[key] = out.get(key, 0) + int(e.get("count", 1))
    return out


def load_baseline_reasons(path: str) -> Dict[Tuple[str, str, str], str]:
    """``{fingerprint: reason}`` for the entries that carry one — so a
    baseline rewrite (``lint --write-baseline``) preserves the written
    justifications instead of silently dropping them."""
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    out: Dict[Tuple[str, str, str], str] = {}
    for e in data.get("findings", ()):
        if e.get("reason"):
            out[(str(e["path"]), str(e["rule"]),
                 str(e.get("snippet", "")))] = str(e["reason"])
    return out


def save_baseline(path: str, findings: Iterable[Finding],
                  reasons: Optional[Dict[Tuple[str, str, str], str]] = None):
    """Write the given findings as a fresh baseline (``lint
    --write-baseline``). Counts collapse identical fingerprints."""
    counts: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
    entries = []
    for (fpath, rule, snippet), n in sorted(counts.items()):
        e: Dict[str, object] = {"rule": rule, "path": fpath,
                                "snippet": snippet}
        if n != 1:
            e["count"] = n
        reason = (reasons or {}).get((fpath, rule, snippet))
        if reason:
            e["reason"] = reason
        entries.append(e)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "tool": "tpulint", "findings": entries},
                  fh, indent=2, sort_keys=False)
        fh.write("\n")


# -------------------------------------------------------------------- linter
@dataclass
class LintResult:
    """Partitioned outcome of one lint run."""
    files_checked: int = 0
    new: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    #: baseline fingerprints never matched this run — fixed code whose
    #: entry should now be deleted (reported, never fatal: the ratchet
    #: only tightens on NEW findings)
    stale_baseline: List[Tuple[str, str, str]] = field(default_factory=list)

    @property
    def exit_code(self) -> int:
        return 1 if self.new else 0

    def to_dict(self) -> Dict[str, object]:
        return {
            "version": 1, "tool": "tpulint",
            "files_checked": self.files_checked,
            "new_count": len(self.new),
            "baselined_count": len(self.baselined),
            "findings": [dict(f.to_dict(), baselined=False)
                         for f in self.new]
                        + [dict(f.to_dict(), baselined=True)
                           for f in self.baselined],
            "stale_baseline": [
                {"path": p, "rule": r, "snippet": s}
                for (p, r, s) in self.stale_baseline],
        }

    def render_text(self) -> str:
        lines = [f.render() for f in self.new]
        for p, r, s in self.stale_baseline:
            lines.append(f"# stale baseline entry (fixed? delete it): "
                         f"{p}: {r} {s!r}")
        lines.append(f"tpulint: {self.files_checked} files, "
                     f"{len(self.new)} new finding(s), "
                     f"{len(self.baselined)} baselined")
        return "\n".join(lines)


class Linter:
    """Run the registered rules over files/trees.

    ``rules``: rule id list to run (default: every registered rule).
    ``root``: directory findings' paths are made relative to
    (default: the repository root).
    """

    def __init__(self, rules: Optional[Sequence[str]] = None,
                 root: Optional[str] = None):
        from .rules import all_rules, get_rule
        if rules is None:
            self.rules = [cls() for cls in all_rules().values()]
        else:
            self.rules = [get_rule(r)() for r in rules]
        self.root = os.path.abspath(root or REPO_ROOT)

    # ------------------------------------------------------------ plumbing
    def _relpath(self, path: str) -> str:
        ap = os.path.abspath(path)
        if ap.startswith(self.root + os.sep):
            ap = os.path.relpath(ap, self.root)
        return ap.replace(os.sep, "/")

    @staticmethod
    def _suppressed(finding: Finding, lines: Sequence[str]) -> bool:
        if not 1 <= finding.line <= len(lines):
            return False
        m = _PRAGMA.search(lines[finding.line - 1])
        if not m:
            return False
        which = m.group(1)
        if which is None:
            return True                      # bare disable: every rule
        ids = {w.strip().upper() for w in which.split(",") if w.strip()}
        return finding.rule.upper() in ids

    # ------------------------------------------------------------- linting
    @staticmethod
    def _parse(source: str, path: str, rel: str, lines: Sequence[str]):
        """(tree, None) or (None, SYN000 finding)."""
        try:
            return ast.parse(source, filename=path), None
        except SyntaxError as e:
            return None, Finding(
                "SYN000", rel, int(e.lineno or 1),
                int((e.offset or 1) - 1), f"syntax error: {e.msg}",
                snippet=(lines[e.lineno - 1].strip()
                         if e.lineno and e.lineno <= len(lines) else ""))

    def lint_source(self, source: str, path: str) -> List[Finding]:
        """Lint one already-read source blob with the per-file rules.
        Project rules (THR003/THR004) see a one-module horizon here; use
        :meth:`run_sources` to lint a SET of sources as one project."""
        rel = self._relpath(path)
        lines = source.splitlines()
        tree, syn = self._parse(source, path, rel, lines)
        if syn is not None:
            return [syn]
        out: List[Finding] = []
        for rule in self.rules:
            for f in rule.check(tree, lines, rel):
                if not self._suppressed(f, lines):
                    out.append(f)
        out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return out

    def lint_file(self, path: str) -> List[Finding]:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
        except (OSError, UnicodeDecodeError) as e:
            # one unreadable file must not kill the verdict for the rest
            # of the tree — report it as a finding (always new → exit 1)
            return [Finding("SYN000", self._relpath(path), 1, 0,
                            f"cannot read file: {e}")]
        return self.lint_source(source, path)

    @staticmethod
    def iter_files(paths: Sequence[str]) -> List[str]:
        """Expand files/dirs into a sorted, de-duplicated .py file list."""
        out: List[str] = []
        for p in paths:
            if os.path.isdir(p):
                for dirpath, dirnames, filenames in os.walk(p):
                    dirnames[:] = sorted(d for d in dirnames
                                         if d not in SKIP_DIRS)
                    for fn in sorted(filenames):
                        if fn.endswith(".py"):
                            out.append(os.path.join(dirpath, fn))
            else:
                out.append(p)
        seen, uniq = set(), []
        for p in out:
            ap = os.path.abspath(p)
            if ap not in seen:
                seen.add(ap)
                uniq.append(p)
        return uniq

    def run(self, paths: Sequence[str],
            baseline: Optional[Dict[Tuple[str, str, str], int]] = None
            ) -> LintResult:
        """Lint paths and partition findings against ``baseline``. File
        rules run per file; project rules (THR003/THR004) run ONCE over
        every parseable module of the run — which is what makes their
        interprocedural analysis see cross-file lock orders."""
        blobs: List[Tuple[str, str, Optional[str]]] = []
        for fp in self.iter_files(paths):
            try:
                with open(fp, "r", encoding="utf-8") as fh:
                    blobs.append((fp, fh.read(), None))
            except (OSError, UnicodeDecodeError) as e:
                # one unreadable file must not kill the verdict for the
                # rest of the tree — report it (always new → exit 1)
                blobs.append((fp, "", f"cannot read file: {e}"))
        return self._run_blobs(blobs, baseline)

    def run_sources(self, sources: Dict[str, str],
                    baseline: Optional[Dict[Tuple[str, str, str],
                                            int]] = None) -> LintResult:
        """Lint a dict of in-memory ``{path: source}`` blobs as ONE
        project (fixtures for the interprocedural rules)."""
        return self._run_blobs(
            [(p, src, None) for p, src in sorted(sources.items())],
            baseline)

    def _run_blobs(self, blobs, baseline=None) -> LintResult:
        from .lockgraph import ModuleSource
        res = LintResult()
        findings: List[Finding] = []
        checked: set = set()
        modules: List[ModuleSource] = []
        line_map: Dict[str, Sequence[str]] = {}
        file_rules = [r for r in self.rules if not r.project]
        project_rules = [r for r in self.rules if r.project]
        for fp, source, err in blobs:
            rel = self._relpath(fp)
            checked.add(rel)
            res.files_checked += 1
            if err is not None:
                findings.append(Finding("SYN000", rel, 1, 0, err))
                continue
            lines = source.splitlines()
            tree, syn = self._parse(source, fp, rel, lines)
            if syn is not None:
                findings.append(syn)
                continue
            line_map[rel] = lines
            modules.append(ModuleSource(rel, tree, lines))
            for rule in file_rules:
                for f in rule.check(tree, lines, rel):
                    if not self._suppressed(f, lines):
                        findings.append(f)
        if project_rules and modules:
            for rule in project_rules:
                for f in rule.check_project(modules):
                    if not self._suppressed(f, line_map.get(f.path, ())):
                        findings.append(f)
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        remaining = dict(baseline or {})
        for f in findings:
            if remaining.get(f.fingerprint, 0) > 0:
                remaining[f.fingerprint] -= 1
                res.baselined.append(f)
            else:
                res.new.append(f)
        # staleness is only decidable for entries this run could have
        # re-observed: a subset-path or --select run must not advise
        # deleting entries it never looked at
        active = {r.id for r in self.rules}
        res.stale_baseline = sorted(
            k for k, n in remaining.items()
            if n > 0 and k[0] in checked and k[1] in active)
        return res
