"""Interprocedural lock-graph analysis (THR003 / THR004 backend).

THR001/THR002 see one function at a time; this module sees the package.
It resolves every lock to a **stable identity**, follows calls made while
a lock is held, and derives two whole-program artifacts:

- the **acquisition graph**: an edge ``A -> B`` means some code path
  acquires lock ``B`` while holding lock ``A`` (directly nested ``with``,
  or through any resolvable call chain). A cycle in this graph is a
  lock-order inversion — the schedule that deadlocks under contention —
  reported as **THR003** with BOTH witness paths in the message.
- **cross-function blocking**: a call made under a lock whose transitive
  callee reaches a blocking primitive (the THR001 set: sleep, socket
  I/O, the ``send_frame``/``recv_frame`` wire helpers, untimed
  ``join``/queue ``get``) — reported as **THR004** at the call site,
  with the full call path to the block. Direct in-region blocking stays
  THR001's report; THR004 only fires across a function boundary, so the
  two never double-report one line.

Lock identities
---------------
- ``ClassName.attr`` for ``self.attr = threading.Lock()/RLock()/
  Condition()`` (assigned in any method of the class), and
- ``module.NAME`` for module-level globals,
- the **string literal** passed to ``monitor.lockwatch``'s
  ``make_lock("Name")`` / ``make_rlock`` / ``make_condition`` factories
  when the lock is created through them — which is exactly the name the
  runtime sanitizer labels its observed edges with, so
  ``tests/test_lockwatch.py`` can require every runtime-observed edge to
  be statically derivable from this graph.

Call resolution (the JAX001 scope-resolution idea, widened to types):
``self.m()`` resolves through the enclosing class and its (same-package)
bases; bare ``f()`` to module functions and package-internal
``from . import`` targets; ``obj.m()`` through parameter annotations
(``def _pull(self, ep: _Epoch)``), local ``var = ClassName(...)`` /
``var = factory()`` assignments where the factory has a class return
annotation (``def get_registry() -> MetricsRegistry``). Unresolvable
calls are skipped — this is a may-analysis used as an under-approximation
for blocking/cycles and checked against runtime observation for recall.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .rules.threading_rules import _blocking_reason, _is_lock_expr
from .rules import terminal_name

__all__ = ["LockGraph", "LockGraphAnalyzer", "ModuleSource",
           "analyze_package"]


def analyze_package(root: Optional[str] = None) -> "LockGraph":
    """Parse every .py under ``root`` (default: the installed package)
    and build its lock graph — the static half of the runtime cross-check
    in ``tests/test_lockwatch.py``."""
    from .linter import Linter, PACKAGE_ROOT
    linter = Linter(rules=[])
    modules: List[ModuleSource] = []
    for fp in Linter.iter_files([root or PACKAGE_ROOT]):
        try:
            with open(fp, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=fp)
        except (OSError, UnicodeDecodeError, SyntaxError):
            continue
        modules.append(ModuleSource(linter._relpath(fp), tree,
                                    source.splitlines()))
    return LockGraphAnalyzer(modules).build()

#: monitor.lockwatch factory callees — first string arg IS the identity
_LOCK_FACTORIES = {"make_lock", "make_rlock", "make_condition"}
#: threading constructors that create a lock object
_LOCK_CTORS = {"Lock", "RLock", "Condition"}
#: how deep the call-chain closure follows before giving up (cycles in
#: the call graph are handled by memoization; the cap bounds pathology)
_MAX_DEPTH = 8


class ModuleSource:
    """One parsed module handed to the analyzer."""

    __slots__ = ("path", "tree", "lines", "modkey", "modbase")

    def __init__(self, path: str, tree: ast.AST, lines: Sequence[str]):
        self.path = path.replace(os.sep, "/")
        self.tree = tree
        self.lines = lines
        # dotted module key without extension: "deeplearning4j_tpu.
        # paramserver.server"; modbase is the stem used in global-lock ids
        self.modkey = self.path[:-3].replace("/", ".") \
            if self.path.endswith(".py") else self.path.replace("/", ".")
        self.modbase = self.modkey.rsplit(".", 1)[-1]


class _FuncInfo:
    """Per-function facts: lock regions, direct acquisitions/blocking/
    calls (same-thread walk: nested def/lambda bodies excluded — a
    closure defined under a lock runs later)."""

    __slots__ = ("key", "node", "mod", "classname", "regions",
                 "acquires", "blocking", "calls", "display")

    def __init__(self, key, node, mod, classname):
        self.key = key                  # (modkey, classname|None, name)
        self.node = node
        self.mod = mod
        self.classname = classname
        self.display = (f"{classname}.{node.name}" if classname
                        else node.name)
        self.regions: List[tuple] = []  # (lockid, line, events)
        self.acquires: List[tuple] = [] # (lockid, line)
        self.blocking: List[tuple] = [] # (reason, line, callee)
        self.calls: List[tuple] = []    # (callee_key, line, display)


class LockGraph:
    """The analysis result: edges, witnesses, cycles, THR004 chains."""

    def __init__(self):
        #: {(lockA, lockB): witness} — witness is a human-readable hop
        #: list ending at lockB's acquisition
        self.edges: Dict[Tuple[str, str], str] = {}
        #: [(path, line, snippet-line, lockid, witness-pair)] per cycle
        self.cycles: List[dict] = []
        #: [(path, line, lockid, reason, chain)] blocking-under-lock
        #: reached across a function boundary
        self.blocking: List[dict] = []

    def edge_set(self) -> Set[Tuple[str, str]]:
        return set(self.edges)


def _walk_same_thread(root: ast.AST, include_root_children=True):
    """Walk skipping nested function/lambda bodies (separate execution)."""
    stack = list(ast.iter_child_nodes(root)) if include_root_children \
        else [root]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _ann_class(ann: Optional[ast.AST]) -> Optional[str]:
    """Annotation expression -> class name (handles Optional["X"] not;
    plain Name / Attribute / string constants only — the repo's idiom)."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.rsplit(".", 1)[-1]
    name = terminal_name(ann)
    return name


class LockGraphAnalyzer:
    """Build the package lock graph from parsed modules."""

    def __init__(self, modules: Iterable[ModuleSource]):
        self.modules = list(modules)
        #: class name -> (modkey, ClassDef, [base names])
        self.classes: Dict[str, Tuple[str, ast.ClassDef, List[str]]] = {}
        #: (classname, attr) -> lock identity
        self.attr_locks: Dict[Tuple[str, str], str] = {}
        #: attr -> {classname} (unique-owner fallback resolution)
        self.attr_owners: Dict[str, Set[str]] = {}
        #: (modkey, global name) -> identity
        self.global_locks: Dict[Tuple[str, str], str] = {}
        #: (classname, attr) -> class name (``self.X = ClassName(...)`` /
        #: annotated attr assignments) — lets ``self._fan.run()`` resolve
        self.attr_types: Dict[Tuple[str, str], str] = {}
        #: function index
        self.funcs: Dict[tuple, _FuncInfo] = {}
        #: (modkey, imported name) -> (modkey2, name2) package-internal
        self.imports: Dict[Tuple[str, str], Tuple[str, str]] = {}
        #: function key -> class name it returns (return annotation)
        self.returns: Dict[tuple, str] = {}
        self._closure_memo: Dict[tuple, tuple] = {}
        self._index()
        self._summarize()

    # ------------------------------------------------------------ indexing
    def _index(self):
        for mod in self.modules:
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    bases = [terminal_name(b) for b in node.bases]
                    self.classes.setdefault(
                        node.name,
                        (mod.modkey, node, [b for b in bases if b]))
                elif isinstance(node, (ast.ImportFrom,)):
                    self._index_import(mod, node)
                elif isinstance(node, ast.Assign):
                    ident = self._lock_ctor_identity(node.value)
                    for t in node.targets:
                        if isinstance(t, ast.Name) and ident is not None:
                            self.global_locks[(mod.modkey, t.id)] = (
                                ident if isinstance(ident, str)
                                else f"{mod.modbase}.{t.id}")
        # functions + self-attr lock definitions + return annotations
        for mod in self.modules:
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    self._index_func(mod, None, node)
                elif isinstance(node, ast.ClassDef):
                    for item in node.body:
                        if isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            self._index_func(mod, node.name, item)

    def _index_import(self, mod: ModuleSource, node: ast.ImportFrom):
        target = self._resolve_import_module(mod, node)
        if target is None:
            return
        for a in node.names:
            self.imports[(mod.modkey, a.asname or a.name)] = (target,
                                                              a.name)

    def _resolve_import_module(self, mod: ModuleSource,
                               node: ast.ImportFrom) -> Optional[str]:
        """Relative (and package-absolute) import -> target modkey, when
        the target is one of the analyzed modules."""
        if node.level == 0:
            target = node.module or ""
        else:
            parts = mod.modkey.split(".")
            # strip the module name itself plus (level-1) packages
            base = parts[:-node.level]
            target = ".".join(base + ((node.module or "").split(".")
                                      if node.module else []))
        known = {m.modkey for m in self.modules}
        if target in known:
            return target
        # "from X import Y" where X is a package: Y may be a module —
        # not needed for lock analysis; ignore
        return None

    def _lock_ctor_identity(self, value: ast.AST):
        """Is ``value`` a lock construction? Returns the literal name for
        factory calls, True for bare threading ctors, None otherwise.
        Sees through conditional construction — the batcher's
        ``make_lock(...) if caching else None`` — so the optional lock
        still gets its stable factory identity."""
        if isinstance(value, ast.IfExp):
            return (self._lock_ctor_identity(value.body)
                    or self._lock_ctor_identity(value.orelse))
        if not isinstance(value, ast.Call):
            return None
        callee = terminal_name(value.func)
        if callee in _LOCK_FACTORIES:
            if value.args and isinstance(value.args[0], ast.Constant) \
                    and isinstance(value.args[0].value, str):
                return value.args[0].value
            return True
        if callee in _LOCK_CTORS:
            # threading.Lock() / Lock() / threading.Condition(...)
            return True
        return None

    def _index_func(self, mod: ModuleSource, classname: Optional[str],
                    node: ast.AST):
        key = (mod.modkey, classname, node.name)
        self.funcs[key] = _FuncInfo(key, node, mod, classname)
        ret = _ann_class(getattr(node, "returns", None))
        if ret and ret in self.classes or ret and classname == ret:
            self.returns[key] = ret
        # self-attr lock definitions + self-attr types
        for stmt in ast.walk(node):
            if not isinstance(stmt, ast.Assign) or classname is None:
                continue
            if isinstance(stmt.value, ast.Call):
                ctor = terminal_name(stmt.value.func)
                if ctor in self.classes \
                        and isinstance(stmt.value.func, ast.Name):
                    for t in stmt.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            self.attr_types.setdefault(
                                (classname, t.attr), ctor)
            ident = self._lock_ctor_identity(stmt.value)
            if ident is None:
                continue
            for t in stmt.targets:
                attr = None
                tt = t
                while isinstance(tt, ast.Subscript):
                    tt = tt.value
                if isinstance(tt, ast.Attribute) \
                        and isinstance(tt.value, ast.Name) \
                        and tt.value.id == "self":
                    attr = tt.attr
                if attr is None:
                    continue
                identity = (ident if isinstance(ident, str)
                            else f"{classname}.{attr}")
                self.attr_locks[(classname, attr)] = identity
                self.attr_owners.setdefault(attr, set()).add(classname)

    # --------------------------------------------------------- resolution
    def _class_chain(self, classname: str) -> List[str]:
        """classname + same-package ancestors (by name, cycle-safe)."""
        out, stack, seen = [], [classname], set()
        while stack:
            c = stack.pop(0)
            if c in seen or c not in self.classes:
                continue
            seen.add(c)
            out.append(c)
            stack.extend(self.classes[c][2])
        return out

    def _attr_lock_identity(self, classname: Optional[str],
                            attr: str) -> Optional[str]:
        if classname is not None:
            for c in self._class_chain(classname):
                ident = self.attr_locks.get((c, attr))
                if ident is not None:
                    return ident
        owners = self.attr_owners.get(attr, set())
        if len(owners) == 1:
            return self.attr_locks[(next(iter(owners)), attr)]
        return None

    def _local_types(self, fn: _FuncInfo) -> Dict[str, str]:
        """param annotations + simple ``var = ClassName(...)`` /
        ``var = annotated_factory()`` assignments -> class names."""
        types: Dict[str, str] = {}
        args = fn.node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            c = _ann_class(a.annotation)
            if c and c in self.classes:
                types[a.arg] = c
        for stmt in _walk_same_thread(fn.node):
            value, targets = None, []
            if isinstance(stmt, ast.Assign):
                value, targets = stmt.value, stmt.targets
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                c = _ann_class(stmt.annotation)
                if c and c in self.classes and isinstance(stmt.target,
                                                          ast.Name):
                    types[stmt.target.id] = c
                continue
            if value is None or not isinstance(value, ast.Call):
                continue
            cls = self._call_result_class(value, fn)
            if cls is None:
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    types[t.id] = cls
        return types

    def _call_result_class(self, call: ast.Call,
                           fn: _FuncInfo) -> Optional[str]:
        """Class constructed / returned by ``call`` (ctor or annotated
        factory), else None."""
        callee = terminal_name(call.func)
        if callee in self.classes and isinstance(call.func, ast.Name):
            return callee
        key = self._resolve_call_key(call, fn, types=None)
        if key is not None:
            return self.returns.get(key)
        return None

    def _resolve_call_key(self, call: ast.Call, fn: _FuncInfo,
                          types: Optional[Dict[str, str]]) -> Optional[tuple]:
        f = call.func
        modkey = fn.mod.modkey
        if isinstance(f, ast.Name):
            key = (modkey, None, f.id)
            if key in self.funcs:
                return key
            imp = self.imports.get((modkey, f.id))
            if imp is not None:
                key = (imp[0], None, imp[1])
                if key in self.funcs:
                    return key
            return None
        if not isinstance(f, ast.Attribute):
            return None
        base = f.value
        # self.m() -> method through the class chain
        if isinstance(base, ast.Name) and base.id == "self" \
                and fn.classname is not None:
            return self._method_key(fn.classname, f.attr)
        # var.m() via local/param types
        if isinstance(base, ast.Name) and types is not None:
            cls = types.get(base.id)
            if cls is not None:
                return self._method_key(cls, f.attr)
        # self.attr.m() via self-attr types (self._fan = Fanout(...))
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self" and fn.classname is not None:
            for c in self._class_chain(fn.classname):
                cls = self.attr_types.get((c, base.attr))
                if cls is not None:
                    return self._method_key(cls, f.attr)
        # factory().m() via return annotations
        if isinstance(base, ast.Call):
            cls = self._call_result_class(base, fn)
            if cls is not None:
                return self._method_key(cls, f.attr)
        return None

    def _method_key(self, classname: str, method: str) -> Optional[tuple]:
        for c in self._class_chain(classname):
            modkey = self.classes[c][0]
            key = (modkey, c, method)
            if key in self.funcs:
                return key
        return None

    def _resolve_lock(self, expr: ast.AST, fn: _FuncInfo,
                      types: Dict[str, str]) -> Optional[str]:
        """Lock identity of a with-item / acquire receiver, or None when
        the expression is not recognizably a lock."""
        node = expr
        while isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute):
            attr = node.attr
            base = node.value
            if isinstance(base, ast.Name) and base.id == "self":
                ident = self._attr_lock_identity(fn.classname, attr)
            elif isinstance(base, ast.Name):
                cls = types.get(base.id)
                ident = self._attr_lock_identity(cls, attr)
            else:
                ident = self._attr_lock_identity(None, attr)
            if ident is not None:
                return ident
            return f"?.{attr}" if _is_lock_expr(node) else None
        if isinstance(node, ast.Name):
            ident = self.global_locks.get((fn.mod.modkey, node.id))
            if ident is not None:
                return ident
            imp = self.imports.get((fn.mod.modkey, node.id))
            if imp is not None:
                ident = self.global_locks.get(imp)
                if ident is not None:
                    return ident
            return f"?.{node.id}" if _is_lock_expr(node) else None
        return None

    # --------------------------------------------------------- summaries
    def _summarize(self):
        for fn in self.funcs.values():
            types = self._local_types(fn)
            self._scan_fn(fn, types)

    def _scan_fn(self, fn: _FuncInfo, types: Dict[str, str]):
        # whole-body direct facts
        for node in _walk_same_thread(fn.node):
            if not isinstance(node, ast.Call):
                continue
            lockid = self._acquire_lockid(node, fn, types)
            if lockid is not None:
                fn.acquires.append((lockid, node.lineno))
                continue
            reason = _blocking_reason(node)
            if reason:
                fn.blocking.append((reason, node.lineno,
                                    terminal_name(node.func) or "?"))
                continue
            key = self._resolve_call_key(node, fn, types)
            if key is not None and key != fn.key:
                fn.calls.append((key, node.lineno,
                                 self.funcs[key].display))
        # with-lock regions (nested regions recorded independently; the
        # same-thread walk of an outer region sees the inner acquisitions)
        for node in _walk_same_thread(fn.node):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    continue        # context managers, not lock objects
                lockid = self._resolve_lock(item.context_expr, fn, types)
                if lockid is None:
                    continue
                events = self._region_events(node, item.context_expr, fn,
                                             types)
                fn.regions.append((lockid, node.lineno, events))
        # with-acquires count into fn.acquires too (a caller holding L
        # that calls us must see our with-regions as acquisitions)
        for lockid, line, _ in fn.regions:
            fn.acquires.append((lockid, line))

    def _acquire_lockid(self, call: ast.Call, fn: _FuncInfo,
                        types: Dict[str, str]) -> Optional[str]:
        """``X.acquire(...)`` on a resolvable lock -> identity."""
        if not isinstance(call.func, ast.Attribute) \
                or call.func.attr != "acquire":
            return None
        return self._resolve_lock(call.func.value, fn, types)

    def _region_events(self, region: ast.AST, lock_expr: ast.AST,
                       fn: _FuncInfo, types: Dict[str, str]) -> List[tuple]:
        """Events inside one with-lock region (same-thread walk of the
        BODY; the with-items themselves are excluded)."""
        events: List[tuple] = []
        for stmt in region.body:
            for node in _walk_same_thread(stmt, include_root_children=False):
                if not isinstance(node, ast.Call):
                    continue
                lockid = self._acquire_lockid(node, fn, types)
                if lockid is not None:
                    events.append(("acquire", lockid, node.lineno))
                    continue
                # direct in-region blocking yields NO event: that line is
                # THR001's single-function report, and the `continue` also
                # keeps a resolvable blocking WRAPPER (streaming's
                # _send_frame) from re-entering as a "call" — THR004 only
                # fires across a function boundary the line can't show
                if _blocking_reason(node):
                    continue
                key = self._resolve_call_key(node, fn, types)
                if key is not None and key != fn.key:
                    events.append(("call", key, node.lineno))
        # nested with-locks inside the region body
        for stmt in region.body:
            for node in _walk_same_thread(stmt,
                                          include_root_children=False):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                for item in node.items:
                    if isinstance(item.context_expr, ast.Call):
                        continue
                    lockid = self._resolve_lock(item.context_expr, fn,
                                                types)
                    if lockid is not None:
                        events.append(("acquire", lockid, node.lineno))
        return events

    # ----------------------------------------------------------- closures
    def closure(self, key: tuple, _depth: int = 0,
                _stack: Optional[frozenset] = None) -> tuple:
        """Transitive effects of calling ``key``:
        ``(acquires: {lockid: hops}, blocking: {(reason, line-desc):
        hops})`` where hops is a tuple of "display (path:line)" strings
        from the entry call to the effect."""
        memo = self._closure_memo.get(key)
        if memo is not None:
            return memo
        stack = _stack or frozenset()
        if key in stack or _depth > _MAX_DEPTH:
            return {}, {}
        fn = self.funcs.get(key)
        if fn is None:
            return {}, {}
        acq: Dict[str, tuple] = {}
        blk: Dict[tuple, tuple] = {}
        here = fn.mod.path
        for lockid, line in fn.acquires:
            acq.setdefault(lockid,
                           (f"{fn.display} acquires {lockid} "
                            f"({here}:{line})",))
        for reason, line, callee in fn.blocking:
            blk.setdefault((reason, callee),
                           (f"{fn.display} calls {callee} [{reason}] "
                            f"({here}:{line})",))
        for callee_key, line, display in fn.calls:
            sub_acq, sub_blk = self.closure(
                callee_key, _depth + 1, stack | {key})
            hop = f"{fn.display} -> {display} ({here}:{line})"
            for lockid, hops in sub_acq.items():
                acq.setdefault(lockid, (hop,) + hops)
            for bkey, hops in sub_blk.items():
                blk.setdefault(bkey, (hop,) + hops)
        result = (acq, blk)
        if _depth == 0:
            self._closure_memo[key] = result
        return result

    # -------------------------------------------------------------- build
    def build(self) -> LockGraph:
        graph = LockGraph()
        edge_meta: Dict[Tuple[str, str], dict] = {}
        blocking: List[dict] = []
        for fn in self.funcs.values():
            here = fn.mod.path
            for held, region_line, events in fn.regions:
                for ev in events:
                    if ev[0] == "acquire":
                        _, lockid, line = ev
                        if lockid == held:
                            continue
                        edge_meta.setdefault((held, lockid), {
                            "path": here, "line": line,
                            "witness": (f"{fn.display} holds {held} "
                                        f"({here}:{region_line}) and "
                                        f"acquires {lockid} "
                                        f"({here}:{line})"),
                        })
                    elif ev[0] == "call":
                        _, key, line = ev
                        sub_acq, sub_blk = self.closure(key)
                        hop = (f"{fn.display} holds {held} "
                               f"({here}:{region_line}), calls "
                               f"{self.funcs[key].display} "
                               f"({here}:{line})")
                        for lockid, hops in sub_acq.items():
                            if lockid == held:
                                continue
                            edge_meta.setdefault((held, lockid), {
                                "path": here, "line": line,
                                "witness": " -> ".join((hop,) + hops),
                            })
                        for (reason, callee), hops in sub_blk.items():
                            blocking.append({
                                "path": here, "line": line,
                                "lock": held, "reason": reason,
                                "callee": callee,
                                "chain": " -> ".join((hop,) + hops),
                            })
        graph.edges = {k: m["witness"] for k, m in edge_meta.items()}
        graph.blocking = blocking
        graph.cycles = self._find_cycles(edge_meta)
        return graph

    def _find_cycles(self, edge_meta: Dict[Tuple[str, str], dict]
                     ) -> List[dict]:
        adj: Dict[str, Set[str]] = {}
        for a, b in edge_meta:
            adj.setdefault(a, set()).add(b)
        seen_cycles: Set[frozenset] = set()
        out: List[dict] = []
        for (a, b), meta in sorted(edge_meta.items()):
            back = self._path(adj, b, a)
            if back is None:
                continue
            nodes = frozenset([a, b] + back[:-1])
            if nodes in seen_cycles:
                continue
            seen_cycles.add(nodes)
            rev = " ; ".join(edge_meta[(x, y)]["witness"]
                             for x, y in zip([b] + back, back))
            out.append({"path": meta["path"], "line": meta["line"],
                        "locks": sorted(nodes),
                        "forward": meta["witness"], "reverse": rev})
        return out

    @staticmethod
    def _path(adj: Dict[str, Set[str]], src: str,
              dst: str) -> Optional[List[str]]:
        stack: List[Tuple[str, List[str]]] = [(src, [])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in sorted(adj.get(node, ())):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None
