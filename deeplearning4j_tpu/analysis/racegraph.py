"""Static data-race detection (THR005 backend): guarded-field inference.

THR003/THR004 see *lock-order* hazards; this module sees *shared-field*
hazards — the race class behind every recent incident here (the
batcher's cache/close races, the control plane's remove-mid-action race,
the collector's cursor races). It is the Eraser lockset idea grafted
onto :mod:`~deeplearning4j_tpu.analysis.lockgraph`'s existing machinery
(stable lock identities, class-attr type resolution, call resolution),
in three passes:

1. **Thread entries.** Every way code enters a second thread is
   enumerated: ``threading.Thread(target=...)`` / ``Timer`` spawns
   (target resolved like any lockgraph call — ``self._loop``, imported
   functions, annotated receivers), ``executor.submit(fn)``, ``run``
   methods of ``Thread`` subclasses, and ``do_GET``-style HTTP handler
   methods (each request runs on its own thread). Every class owning a
   thread-entry *method* additionally gets one ``caller:`` pseudo-entry
   covering its public methods — the submit/stop/snapshot surface that
   runs on the *calling* thread and races the daemon.

2. **Guard inference.** A depth-bounded DFS from each entry walks the
   resolvable call graph carrying the set of lock identities provably
   held (lexical ``with``-regions plus everything inherited from the
   call path), recording every ``self._field`` access with its held set
   and its ``file:line`` hop chain. A field with **>= 2 distinct write
   sites, all holding one common lock identity**, acquires that lock as
   its inferred guard. Writes sited in ``__init__`` are publication
   (before ``start()``) and never count.

3. **Race detection.** Any access to a guarded field, reachable from a
   *different* entry than some guarded write, where the guard is not in
   the held set, is a race — reported with BOTH witness paths
   (THR003's two-witness shape): the guarded write chain and the
   unguarded access chain, every hop ``file:line``.

Honest escapes (the repo's deliberate lock-free patterns):

- ctor-only fields (published before the thread starts) are exempt by
  construction — no non-ctor writes, no guard, no reports;
- fields bound to internally-synchronized objects — ``deque`` (the
  control plane's edge queue), ``queue.Queue``, ``threading.Event``,
  semaphores — are exempt: their operations are GIL-atomic/lock-backed
  by design (rebinding such a field remains out of scope);
- a ``# tpulint: thread-safe[reason]`` pragma on an access line exempts
  that site; on a *write* site it also removes the write from guard
  inference, so one deliberate lock-free writer does not disable
  checking for everyone else. The reason is mandatory — the bracket
  form will not parse without it.

The inferred guard map is runtime-cross-checked: ``tests/
test_lockwatch.py`` drives the real batcher/collector flows under
``monitor/lockwatch.py`` and asserts every inferred guard names a lock
the instrumented run actually acquired (inferred ⊆ observed), the dual
of the lockgraph's observed ⊆ static edge pin — so the inference can't
silently rot as the code evolves.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from .lockgraph import (LockGraphAnalyzer, ModuleSource, _FuncInfo,
                        _MAX_DEPTH, _walk_same_thread)
from .rules import terminal_name

__all__ = ["RaceGraph", "RaceGraphAnalyzer", "FieldAccess",
           "analyze_package_races", "THREAD_SAFE_PRAGMA"]

#: ``# tpulint: thread-safe[reason]`` — site-level lock-free-by-design
#: marker. The reason inside the brackets is mandatory.
THREAD_SAFE_PRAGMA = re.compile(r"#\s*tpulint:\s*thread-safe\[([^\]]+)\]")

#: ctors whose instances synchronize themselves — field operations on
#: them are lock-free by design (the control plane's edge deque, stop
#: Events, bounded queues); the *rebinding* hazard is out of scope
_SELF_SYNCING_CTORS = {
    "Event", "deque", "Queue", "SimpleQueue", "LifoQueue",
    "PriorityQueue", "Semaphore", "BoundedSemaphore", "Barrier",
}

#: method calls on a field that mutate the container in place — writes
#: for lockset purposes (``self._queue.append(...)`` guards like
#: ``self._queue = ...``)
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popleft", "popitem", "remove",
    "discard", "clear", "sort", "reverse", "move_to_end",
}

#: spawn callees whose ``target=`` becomes a new thread's entry point
_SPAWN_CTORS = {"Thread", "Timer"}

_HTTP_HANDLER_METHODS = re.compile(r"^do_[A-Z]+$")


class FieldAccess:
    """One read/write of ``self.<attr>`` observed on some entry's DFS."""

    __slots__ = ("classname", "attr", "kind", "path", "line", "held",
                 "hops", "entry")

    def __init__(self, classname: str, attr: str, kind: str, path: str,
                 line: int, held: FrozenSet[str], hops: Tuple[str, ...],
                 entry: str):
        self.classname = classname
        self.attr = attr
        self.kind = kind            # "read" | "write"
        self.path = path
        self.line = line
        self.held = held            # lock identities provably held
        self.hops = hops            # entry -> ... -> this access
        self.entry = entry          # entry id ("thread:..." / "caller:C")

    @property
    def site(self) -> Tuple[str, int]:
        return (self.path, self.line)


class _Entry:
    """One thread entry point: where a second thread begins executing."""

    __slots__ = ("id", "key", "kind", "anchor")

    def __init__(self, entry_id: str, key: tuple, kind: str, anchor: str):
        self.id = entry_id          # unique; "caller:C" shared per class
        self.key = key              # function key in analyzer.funcs
        self.kind = kind            # thread|run|handler|submit|caller
        self.anchor = anchor        # first hop: spawn/def site file:line


class RaceGraph:
    """The analysis result: inferred guards + race reports."""

    def __init__(self):
        #: {(classname, attr): guard lock identity}
        self.guards: Dict[Tuple[str, str], str] = {}
        #: [{path, line, classname, attr, guard, kind,
        #:   write_witness, access_witness, write_entry, access_entry}]
        self.races: List[dict] = []
        #: entry ids discovered (introspection / tests)
        self.entries: List[dict] = []
        #: access sites exempted by a thread-safe[...] pragma:
        #: [{path, line, classname, attr, reason}]
        self.pragma_exempt: List[dict] = []

    def guard_names(self, classes: Optional[Iterable[str]] = None
                    ) -> Set[str]:
        """Distinct guard lock identities, optionally restricted to the
        given classes — the set the lockwatch cross-check compares with
        the runtime-observed acquisition census."""
        want = set(classes) if classes is not None else None
        return {g for (cls, _attr), g in self.guards.items()
                if want is None or cls in want}


def analyze_package_races(root: Optional[str] = None) -> RaceGraph:
    """Parse every .py under ``root`` (default: the installed package)
    and build its race graph — the static half of the inferred ⊆
    observed cross-check in ``tests/test_lockwatch.py``."""
    from .linter import Linter, PACKAGE_ROOT
    linter = Linter(rules=[])
    modules: List[ModuleSource] = []
    for fp in Linter.iter_files([root or PACKAGE_ROOT]):
        try:
            with open(fp, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=fp)
        except (OSError, UnicodeDecodeError, SyntaxError):
            continue
        modules.append(ModuleSource(linter._relpath(fp), tree,
                                    source.splitlines()))
    return RaceGraphAnalyzer(modules).build_races()


class RaceGraphAnalyzer(LockGraphAnalyzer):
    """Guarded-field inference + race detection over parsed modules.

    Subclasses :class:`LockGraphAnalyzer` for its whole resolution layer
    (class index, attr lock identities, imports, ``_resolve_call_key``,
    ``_resolve_lock``, ``_local_types``) and adds the lockset pass.
    """

    def __init__(self, modules: Iterable[ModuleSource]):
        super().__init__(modules)
        self._lines_by_path = {m.path: m.lines for m in self.modules}
        #: per-func body scan memo: key -> list of items (see _body_items)
        self._body_memo: Dict[tuple, list] = {}
        self._types_memo: Dict[tuple, Dict[str, str]] = {}
        #: (classname, attr) accessed by that class's own methods
        self._attr_access_owners: Set[Tuple[str, str]] = set()
        #: (classname, attr) bound to a self-syncing ctor result
        self._self_syncing: Set[Tuple[str, str]] = set()
        self._index_field_facts()

    # ------------------------------------------------------------ indexing
    def _index_field_facts(self):
        for fn in self.funcs.values():
            if fn.classname is None:
                continue
            for node in _walk_same_thread(fn.node):
                targets, value = [], None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign):
                    targets, value = [node.target], node.value
                else:
                    continue
                ctor = (terminal_name(value.func)
                        if isinstance(value, ast.Call) else None)
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self" \
                            and ctor in _SELF_SYNCING_CTORS:
                        self._self_syncing.add((fn.classname, t.attr))

    # ----------------------------------------------------- entry discovery
    def _resolve_func_ref(self, expr: ast.AST, fn: _FuncInfo,
                          types: Dict[str, str]) -> Optional[tuple]:
        """A function *reference* (Thread target, submit arg) -> func
        key, mirroring ``_resolve_call_key``'s resolution for calls."""
        if isinstance(expr, ast.Name):
            key = (fn.mod.modkey, None, expr.id)
            if key in self.funcs:
                return key
            imp = self.imports.get((fn.mod.modkey, expr.id))
            if imp is not None:
                key = (imp[0], None, imp[1])
                if key in self.funcs:
                    return key
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        base = expr.value
        if isinstance(base, ast.Name) and base.id == "self" \
                and fn.classname is not None:
            return self._method_key(fn.classname, expr.attr)
        if isinstance(base, ast.Name):
            cls = types.get(base.id)
            if cls is not None:
                return self._method_key(cls, expr.attr)
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self" and fn.classname is not None:
            for c in self._class_chain(fn.classname):
                cls = self.attr_types.get((c, base.attr))
                if cls is not None:
                    return self._method_key(cls, expr.attr)
        return None

    def _find_entries(self) -> List[_Entry]:
        entries: Dict[str, _Entry] = {}

        def add(kind: str, key: tuple, anchor: str):
            tfn = self.funcs.get(key)
            if tfn is None:
                return
            eid = f"thread:{tfn.display}"
            entries.setdefault(eid, _Entry(eid, key, kind, anchor))

        for fn in self.funcs.values():
            types = self._types(fn)
            here = fn.mod.path
            for node in _walk_same_thread(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = terminal_name(node.func)
                if callee in _SPAWN_CTORS:
                    target = None
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = kw.value
                    if target is None and callee == "Timer" \
                            and len(node.args) >= 2:
                        target = node.args[1]
                    if target is None:
                        continue
                    key = self._resolve_func_ref(target, fn, types)
                    if key is not None:
                        add("thread", key,
                            f"[thread spawned at {here}:{node.lineno}]")
                elif callee == "submit" and node.args:
                    key = self._resolve_func_ref(node.args[0], fn, types)
                    if key is not None:
                        add("submit", key,
                            f"[submitted to executor at "
                            f"{here}:{node.lineno}]")
        # Thread subclasses: run() is the entry
        for classname, (modkey, node, _bases) in self.classes.items():
            if "Thread" in self._class_chain(classname) \
                    or "Thread" in (self.classes[classname][2]):
                key = self._method_key(classname, "run")
                if key is not None:
                    tfn = self.funcs[key]
                    add("run", key,
                        f"[{classname}(Thread).run at "
                        f"{tfn.mod.path}:{tfn.node.lineno}]")
            # HTTP handlers: each do_* serves on its own thread
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and _HTTP_HANDLER_METHODS.match(item.name):
                    hkey = (modkey, classname, item.name)
                    hfn = self.funcs.get(hkey)
                    if hfn is not None:
                        add("handler", hkey,
                            f"[HTTP handler {classname}.{item.name} at "
                            f"{hfn.mod.path}:{item.lineno}]")

        # caller pseudo-entries: the public surface of every class that
        # owns a thread-entry method runs on OTHER threads than its loop
        thread_classes = sorted({
            e.key[1] for e in entries.values() if e.key[1] is not None})
        out = sorted(entries.values(), key=lambda e: e.id)
        for classname in thread_classes:
            modkey, cnode, _bases = self.classes.get(
                classname, (None, None, None))
            if cnode is None:
                continue
            eid = f"caller:{classname}"
            for item in cnode.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if item.name.startswith("_") \
                        and item.name not in ("__enter__", "__exit__"):
                    continue
                key = (modkey, classname, item.name)
                if key in self.funcs:
                    out.append(_Entry(
                        eid, key, "caller",
                        f"[public API {classname}.{item.name}, caller "
                        f"thread]"))
        return out

    # --------------------------------------------------------- body scans
    def _types(self, fn: _FuncInfo) -> Dict[str, str]:
        t = self._types_memo.get(fn.key)
        if t is None:
            t = self._types_memo[fn.key] = self._local_types(fn)
        return t

    def _field_owner(self, classname: str, attr: str) -> str:
        """Canonical owning class for a field: the base-most class in
        the chain whose own methods touch it (so a subclass override and
        its base method talk about ONE field)."""
        chain = self._class_chain(classname)
        owner = classname
        for c in chain:
            if (c, attr) in self._attr_access_owners:
                owner = c
        return owner

    def _body_items(self, key: tuple) -> list:
        """Scan one function body once: source-ordered list of
        ``("access", attr, kind, line, held)`` and
        ``("call", callee_key, line, held)`` items, where ``held`` is
        the frozenset of lock identities lexically held at that point
        (``with``-region aware, same-thread walk)."""
        memo = self._body_memo.get(key)
        if memo is not None:
            return memo
        fn = self.funcs[key]
        types = self._types(fn)
        items: list = []

        def self_attr(node: ast.AST) -> Optional[str]:
            n = node
            while isinstance(n, ast.Subscript):
                n = n.value
            if isinstance(n, ast.Attribute) \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id == "self":
                return n.attr
            return None

        def record(attr: str, kind: str, line: int,
                   held: FrozenSet[str]):
            items.append(("access", attr, kind, line, held))
            if fn.classname is not None:
                self._attr_access_owners.add((fn.classname, attr))

        def write_target(t: ast.AST, held: FrozenSet[str]):
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    write_target(e, held)
                return
            if isinstance(t, ast.Starred):
                write_target(t.value, held)
                return
            if isinstance(t, ast.Subscript):
                visit(t.slice, held)        # index expr may read fields
                attr = self_attr(t)
                if attr is not None:
                    record(attr, "write", t.lineno, held)
                else:
                    visit(t.value, held)
                return
            if isinstance(t, ast.Attribute):
                if isinstance(t.value, ast.Name) and t.value.id == "self":
                    record(t.attr, "write", t.lineno, held)
                else:
                    visit(t.value, held)    # other-object attr store:
                return                      # base expr may read fields

        def visit(node: ast.AST, held: FrozenSet[str]):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return                      # separate execution
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = set(held)
                for item in node.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Call):
                        visit(ce, held)     # context manager, not a lock
                        continue
                    lockid = self._resolve_lock(ce, fn, types)
                    if lockid is not None:
                        inner.add(lockid)
                    else:
                        visit(ce, held)
                inner_f = frozenset(inner)
                for stmt in node.body:
                    visit(stmt, inner_f)
                return
            if isinstance(node, ast.Assign):
                visit(node.value, held)
                for t in node.targets:
                    write_target(t, held)
                return
            if isinstance(node, ast.AugAssign):
                visit(node.value, held)
                write_target(node.target, held)   # read+write: write wins
                return
            if isinstance(node, ast.AnnAssign):
                if node.value is not None:
                    visit(node.value, held)
                    write_target(node.target, held)
                return
            if isinstance(node, ast.Delete):
                for t in node.targets:
                    write_target(t, held)
                return
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute):
                    attr = self_attr(f.value)
                    if attr is not None and f.attr in _MUTATORS:
                        # self._q.append(x): in-place container write
                        record(attr, "write", node.lineno, held)
                        for a in node.args:
                            visit(a, held)
                        for kw in node.keywords:
                            visit(kw.value, held)
                        return
                callee_key = self._resolve_call_key(node, fn, types)
                if callee_key is not None and callee_key != fn.key:
                    items.append(("call", callee_key, node.lineno, held))
                for child in ast.iter_child_nodes(node):
                    visit(child, held)
                return
            if isinstance(node, ast.Compare):
                # `self._f is None` / `is not None`: a GIL-atomic
                # identity test of a publish-once reference — it
                # observes no mutable state, so the bare self-attr
                # operands are exempt (the batcher's optional-cache
                # checks). `self._f[k] is None` still records: the
                # subscript DOES observe container contents.
                operands = [node.left] + list(node.comparators)
                if all(isinstance(o, (ast.Is, ast.IsNot))
                       for o in node.ops) \
                        and any(isinstance(o, ast.Constant)
                                and o.value is None for o in operands):
                    for o in operands:
                        if isinstance(o, ast.Attribute) \
                                and isinstance(o.value, ast.Name) \
                                and o.value.id == "self":
                            continue
                        visit(o, held)
                    return
                for child in ast.iter_child_nodes(node):
                    visit(child, held)
                return
            if isinstance(node, ast.Attribute):
                if isinstance(node.value, ast.Name) \
                        and node.value.id == "self":
                    record(node.attr, "read", node.lineno, held)
                    return
                visit(node.value, held)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.node.body:
            visit(stmt, frozenset())
        self._body_memo[key] = items
        return items

    # ------------------------------------------------------------ the DFS
    def _explore(self, entry: _Entry, accesses: List[FieldAccess]):
        visited: Set[tuple] = set()

        def go(key: tuple, held: FrozenSet[str],
               hops: Tuple[str, ...], depth: int):
            if depth > _MAX_DEPTH:
                return
            state = (key, held)
            if state in visited:
                return
            visited.add(state)
            fn = self.funcs.get(key)
            if fn is None:
                return
            here = fn.mod.path
            for item in self._body_items(key):
                if item[0] == "access":
                    _, attr, kind, line, local = item
                    if fn.classname is None:
                        continue
                    eff = held | local
                    cls = self._field_owner(fn.classname, attr)
                    verb = "writes" if kind == "write" else "reads"
                    accesses.append(FieldAccess(
                        cls, attr, kind, here, line, eff,
                        hops + (f"{fn.display} {verb} {cls}.{attr} "
                                f"({here}:{line})",),
                        entry.id))
                else:
                    _, callee_key, line, local = item
                    callee = self.funcs.get(callee_key)
                    if callee is None:
                        continue
                    go(callee_key, held | local,
                       hops + (f"{fn.display} -> {callee.display} "
                               f"({here}:{line})",),
                       depth + 1)

        go(entry.key, frozenset(), (entry.anchor,), 0)

    # ------------------------------------------------------------- pragma
    def _thread_safe_reason(self, path: str, line: int) -> Optional[str]:
        lines = self._lines_by_path.get(path)
        if not lines or not 1 <= line <= len(lines):
            return None
        m = THREAD_SAFE_PRAGMA.search(lines[line - 1])
        return m.group(1).strip() if m else None

    def _field_exempt(self, classname: str, attr: str) -> bool:
        """Locks themselves and self-syncing objects never race-check."""
        for c in self._class_chain(classname):
            if (c, attr) in self.attr_locks \
                    or (c, attr) in self._self_syncing:
                return True
        return False

    # -------------------------------------------------------------- build
    def build_races(self) -> RaceGraph:
        graph = RaceGraph()
        # pre-scan every body so _field_owner sees the complete
        # (class, attr) access index before any DFS consults it
        for key in list(self.funcs):
            self._body_items(key)
        entries = self._find_entries()
        graph.entries = [{"id": e.id, "kind": e.kind,
                          "func": self.funcs[e.key].display}
                         for e in entries if e.key in self.funcs]
        accesses: List[FieldAccess] = []
        for e in entries:
            self._explore(e, accesses)

        # which classes own a thread entry — only THEIR fields are
        # checked (a helper class shared by accident of call graphs
        # would drown the report in instance-identity guesses)
        race_classes = {
            self.funcs[e.key].classname for e in entries
            if e.kind != "caller" and e.key in self.funcs
            and self.funcs[e.key].classname is not None}

        # field -> write accesses (non-ctor, non-pragma'd)
        writes: Dict[Tuple[str, str], List[FieldAccess]] = {}
        reads_and_writes: Dict[Tuple[str, str], List[FieldAccess]] = {}
        for a in accesses:
            field = (a.classname, a.attr)
            if a.classname not in race_classes \
                    or self._field_exempt(a.classname, a.attr):
                continue
            reads_and_writes.setdefault(field, []).append(a)
            if a.kind != "write":
                continue
            fn_name = a.hops[-1].split(" ", 1)[0]
            if fn_name.endswith(".__init__"):
                continue                    # publication before start()
            reason = self._thread_safe_reason(a.path, a.line)
            if reason is not None:
                graph.pragma_exempt.append(
                    {"path": a.path, "line": a.line,
                     "classname": a.classname, "attr": a.attr,
                     "reason": reason})
                continue
            writes.setdefault(field, []).append(a)

        # guard inference: >= 2 distinct LOCKED write sites, one common
        # lock. The intersection runs over writes that hold anything at
        # all — a bare write must not dissolve the guard it violates
        # (it gets reported against it instead, Eraser-style).
        for field, ws in sorted(writes.items()):
            locked = [w for w in ws if w.held]
            sites = {w.site for w in locked}
            if len(sites) < 2:
                continue
            common = frozenset.intersection(*[w.held for w in locked])
            if not common:
                continue
            cls = field[0]
            guard = sorted(
                common,
                key=lambda g: (0 if g.startswith(cls + ".") else 1, g))[0]
            graph.guards[field] = guard

        # race detection: unguarded access from a different entry
        reported: Set[Tuple[str, int, str, str]] = set()
        for field, guard in sorted(graph.guards.items()):
            cls, attr = field
            all_acc = reads_and_writes.get(field, [])
            guarded_writes = sorted(
                (w for w in writes.get(field, []) if guard in w.held),
                key=lambda w: (w.path, w.line, w.entry))
            if not guarded_writes:
                continue
            for a in sorted(all_acc,
                            key=lambda x: (x.path, x.line, x.entry)):
                if guard in a.held:
                    continue
                if a.kind == "write" and self._thread_safe_reason(
                        a.path, a.line) is not None:
                    continue                # pragma'd lock-free writer
                witness = next(
                    (w for w in guarded_writes if w.entry != a.entry),
                    None)
                if witness is None:
                    continue                # same thread end to end
                rkey = (a.path, a.line, cls, attr)
                if rkey in reported:
                    continue
                reason = self._thread_safe_reason(a.path, a.line)
                if reason is not None:
                    graph.pragma_exempt.append(
                        {"path": a.path, "line": a.line,
                         "classname": cls, "attr": attr,
                         "reason": reason})
                    reported.add(rkey)
                    continue
                reported.add(rkey)
                graph.races.append({
                    "path": a.path, "line": a.line,
                    "classname": cls, "attr": attr, "guard": guard,
                    "kind": a.kind,
                    "write_witness": " -> ".join(witness.hops)
                    + f" [holding {guard}]",
                    "access_witness": " -> ".join(a.hops)
                    + f" [{guard} NOT held]",
                    "write_entry": witness.entry,
                    "access_entry": a.entry,
                })
        graph.races.sort(key=lambda r: (r["path"], r["line"],
                                        r["classname"], r["attr"]))
        return graph
