"""Random-walk iterators (reference ``graph/iterator/RandomWalkIterator.java``
and ``WeightedRandomWalkGraphIteratorProvider``): uniform and edge-weighted
walks, with NoEdgeHandling semantics (self-loop on dead ends)."""
from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from .api import Graph


class RandomWalkIterator:
    """Uniform random walks of fixed length from every vertex."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 123,
                 walks_per_vertex: int = 1):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self.walks_per_vertex = walks_per_vertex

    def _next_vertex(self, rng, current: int) -> int:
        nbrs = self.graph.get_connected_vertices(current)
        if not nbrs:
            return current  # SELF_LOOP_ON_DISCONNECTED
        return int(nbrs[rng.integers(0, len(nbrs))])

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng(self.seed)
        for _ in range(self.walks_per_vertex):
            for start in range(self.graph.num_vertices()):
                walk = [start]
                cur = start
                for _ in range(self.walk_length - 1):
                    cur = self._next_vertex(rng, cur)
                    walk.append(cur)
                yield walk


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional transition probabilities."""

    def _next_vertex(self, rng, current: int) -> int:
        nbrs = self.graph.get_connected_with_weights(current)
        if not nbrs:
            return current
        weights = np.asarray([w for _, w in nbrs], np.float64)
        p = weights / weights.sum()
        return int(nbrs[rng.choice(len(nbrs), p=p)][0])
