"""Random-walk iterators (reference ``graph/iterator/RandomWalkIterator.java``
and ``WeightedRandomWalkGraphIteratorProvider``): uniform and edge-weighted
walks, with NoEdgeHandling semantics (self-loop on dead ends)."""
from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from .api import Graph


class RandomWalkIterator:
    """Uniform random walks of fixed length from every vertex."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 123,
                 walks_per_vertex: int = 1):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self.walks_per_vertex = walks_per_vertex

    def _next_vertex(self, rng, current: int) -> int:
        nbrs = self.graph.get_connected_vertices(current)
        if not nbrs:
            return current  # SELF_LOOP_ON_DISCONNECTED
        return int(nbrs[rng.integers(0, len(nbrs))])

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng(self.seed)
        for _ in range(self.walks_per_vertex):
            for start in range(self.graph.num_vertices()):
                walk = [start]
                cur = start
                for _ in range(self.walk_length - 1):
                    cur = self._next_vertex(rng, cur)
                    walk.append(cur)
                yield walk


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional transition probabilities."""

    def _next_vertex(self, rng, current: int) -> int:
        nbrs = self.graph.get_connected_with_weights(current)
        if not nbrs:
            return current
        weights = np.asarray([w for _, w in nbrs], np.float64)
        p = weights / weights.sum()
        return int(nbrs[rng.choice(len(nbrs), p=p)][0])


class Node2VecWalkIterator(RandomWalkIterator):
    """Second-order biased walks (node2vec, Grover & Leskovec 2016; reference
    ``models/node2vec/Node2Vec.java:34`` drives them through a GraphWalker).

    Transition weight from the previous vertex ``t`` through current ``v`` to
    neighbor ``x``: ``1/p`` to return (x == t), ``1`` when x is also a
    neighbor of t (BFS-ish), ``1/q`` otherwise (DFS-ish). ``p`` high + ``q``
    low → outward exploration; ``p`` low → local backtracking walks.
    """

    def __init__(self, graph: Graph, walk_length: int, p: float = 1.0,
                 q: float = 1.0, seed: int = 123, walks_per_vertex: int = 1):
        super().__init__(graph, walk_length, seed, walks_per_vertex)
        self.p = float(p)
        self.q = float(q)
        # neighbor sets for the dist(t, x) == 1 test
        self._nbr_sets = [set(graph.get_connected_vertices(i))
                          for i in range(graph.num_vertices())]

    def _biased_next(self, rng, prev: Optional[int], current: int) -> int:
        nbrs = self.graph.get_connected_vertices(current)
        if not nbrs:
            return current  # SELF_LOOP_ON_DISCONNECTED
        if prev is None:
            return int(nbrs[rng.integers(0, len(nbrs))])
        w = np.empty(len(nbrs), np.float64)
        prev_nbrs = self._nbr_sets[prev]
        for i, x in enumerate(nbrs):
            if x == prev:
                w[i] = 1.0 / self.p
            elif x in prev_nbrs:
                w[i] = 1.0
            else:
                w[i] = 1.0 / self.q
        w /= w.sum()
        return int(nbrs[rng.choice(len(nbrs), p=w)])

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng(self.seed)
        for _ in range(self.walks_per_vertex):
            for start in range(self.graph.num_vertices()):
                walk = [start]
                prev: Optional[int] = None
                cur = start
                for _ in range(self.walk_length - 1):
                    nxt = self._biased_next(rng, prev, cur)
                    prev, cur = cur, nxt
                    walk.append(cur)
                yield walk
