"""Graph API (reference ``deeplearning4j-graph/.../graph/api/IGraph.java``,
``graph/graph/Graph.java``): vertices with optional values, directed or
undirected weighted edges, adjacency queries."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple


@dataclass
class Vertex:
    idx: int
    value: Any = None


@dataclass
class Edge:
    frm: int
    to: int
    weight: float = 1.0
    directed: bool = False


class Graph:
    """Adjacency-list graph (reference ``Graph.java``)."""

    def __init__(self, num_vertices: int, directed: bool = False):
        self.directed = directed
        self.vertices = [Vertex(i) for i in range(num_vertices)]
        self._adj: List[List[Tuple[int, float]]] = [[] for _ in range(num_vertices)]

    def num_vertices(self) -> int:
        return len(self.vertices)

    numVertices = num_vertices

    def add_edge(self, frm: int, to: int, weight: float = 1.0,
                 directed: Optional[bool] = None):
        directed = self.directed if directed is None else directed
        self._adj[frm].append((to, weight))
        if not directed:
            self._adj[to].append((frm, weight))
        return self

    addEdge = add_edge

    def get_connected_vertices(self, idx: int) -> List[int]:
        return [t for t, _ in self._adj[idx]]

    getConnectedVertices = get_connected_vertices

    def get_connected_with_weights(self, idx: int) -> List[Tuple[int, float]]:
        return list(self._adj[idx])

    def degree(self, idx: int) -> int:
        return len(self._adj[idx])

    def get_vertex(self, idx: int) -> Vertex:
        return self.vertices[idx]

    getVertex = get_vertex
