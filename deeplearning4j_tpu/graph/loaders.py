"""Graph loaders (reference ``deeplearning4j-graph/.../graph/data/``):
edge-list and weighted edge-list text files."""
from __future__ import annotations

from .api import Graph


class GraphLoader:
    @staticmethod
    def load_undirected_graph_edge_list_file(path: str, num_vertices: int,
                                             delimiter: str = None) -> Graph:
        """Each line: ``from to`` (reference
        ``GraphLoader.loadUndirectedGraphEdgeListFile``)."""
        g = Graph(num_vertices, directed=False)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(delimiter)
                g.add_edge(int(parts[0]), int(parts[1]))
        return g

    loadUndirectedGraphEdgeListFile = load_undirected_graph_edge_list_file

    @staticmethod
    def load_weighted_edge_list_file(path: str, num_vertices: int,
                                     delimiter: str = ",",
                                     directed: bool = False) -> Graph:
        """Each line: ``from,to,weight`` (reference
        ``loadWeightedEdgeListFile``)."""
        g = Graph(num_vertices, directed=directed)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(delimiter)
                g.add_edge(int(parts[0]), int(parts[1]), float(parts[2]),
                           directed=directed)
        return g

    loadWeightedEdgeListFile = load_weighted_edge_list_file
