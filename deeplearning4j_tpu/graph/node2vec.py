"""Node2Vec: vertex embeddings from p/q-biased second-order walks.

TPU-native equivalent of reference
``models/node2vec/Node2Vec.java:34`` (a SequenceVectors over a GraphWalker).
Identical engine path to DeepWalk — walks become token sequences trained by
the batched-JAX skip-gram kernels (``nlp/sequencevectors.py``) — with the
walk bias replaced by :class:`~deeplearning4j_tpu.graph.walks.Node2VecWalkIterator`'s
second-order p/q transition weighting.
"""
from __future__ import annotations

from typing import Optional

from .api import Graph
from .deepwalk import GraphVectors
from .walks import Node2VecWalkIterator
from ..nlp.sequencevectors import SequenceVectors


class Node2Vec:
    """Builder surface mirrors DeepWalk plus the node2vec ``p``/``q`` knobs
    (reference ``Node2Vec.Builder`` wires a walker + VectorsConfiguration)."""

    class Builder:
        def __init__(self):
            self._kw = {}
            self._walk_length = 40
            self._walks_per_vertex = 4
            self._p = 1.0
            self._q = 1.0

        def vector_size(self, n):
            self._kw["vector_length"] = int(n)
            return self

        vectorSize = vector_size

        def window_size(self, n):
            self._kw["window"] = int(n)
            return self

        windowSize = window_size

        def learning_rate(self, v):
            self._kw["learning_rate"] = float(v)
            return self

        learningRate = learning_rate

        def walk_length(self, n):
            self._walk_length = int(n)
            return self

        walkLength = walk_length

        def walks_per_vertex(self, n):
            self._walks_per_vertex = int(n)
            return self

        def p(self, v):
            self._p = float(v)
            return self

        def q(self, v):
            self._q = float(v)
            return self

        def seed(self, n):
            self._kw["seed"] = int(n)
            return self

        def epochs(self, n):
            self._kw["epochs"] = int(n)
            return self

        def build(self) -> "Node2Vec":
            return Node2Vec(walk_length=self._walk_length,
                            walks_per_vertex=self._walks_per_vertex,
                            p=self._p, q=self._q, **self._kw)

    @staticmethod
    def builder():
        return Node2Vec.Builder()

    def __init__(self, walk_length: int = 40, walks_per_vertex: int = 4,
                 p: float = 1.0, q: float = 1.0, **kw):
        kw.setdefault("min_word_frequency", 1)
        self._sv = SequenceVectors(**kw)
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.p = float(p)
        self.q = float(q)

    @property
    def vector_size(self):
        return self._sv.vector_length

    def fit(self, graph: Graph,
            walk_iterator: Optional[Node2VecWalkIterator] = None
            ) -> GraphVectors:
        it = walk_iterator or Node2VecWalkIterator(
            graph, self.walk_length, p=self.p, q=self.q, seed=self._sv.seed,
            walks_per_vertex=self.walks_per_vertex)

        def provider():
            for walk in it:
                yield [str(v) for v in walk]

        self._sv.fit(provider)
        return GraphVectors(self._sv, graph)
