"""Graph embeddings (reference ``deeplearning4j-graph`` — SURVEY.md §2.7):
graph API, loaders, random-walk iterators, DeepWalk, GraphVectors."""
from .api import Graph, Vertex, Edge
from .loaders import GraphLoader
from .walks import (RandomWalkIterator, WeightedRandomWalkIterator,
                    Node2VecWalkIterator)
from .deepwalk import DeepWalk, GraphVectors
from .node2vec import Node2Vec

__all__ = ["Graph", "Vertex", "Edge", "GraphLoader", "RandomWalkIterator",
           "WeightedRandomWalkIterator", "Node2VecWalkIterator", "DeepWalk",
           "GraphVectors", "Node2Vec"]
