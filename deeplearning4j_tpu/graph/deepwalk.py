"""DeepWalk: vertex embeddings from random walks.

TPU-native equivalent of reference
``graph/models/deepwalk/DeepWalk.java`` + ``GraphHuffman.java`` +
``GraphVectorsImpl``: random walks become "sentences" over vertex-id tokens and
train through the SequenceVectors engine (hierarchical softmax over a Huffman
tree of vertex degrees — same math, same batched-JAX kernels as Word2Vec).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from .api import Graph
from .walks import RandomWalkIterator
from ..nlp.sequencevectors import SequenceVectors


class GraphVectors:
    """Query surface (reference ``GraphVectorsImpl``)."""

    def __init__(self, sv: SequenceVectors, graph: Graph):
        self._sv = sv
        self.graph = graph

    def get_vertex_vector(self, idx: int) -> Optional[np.ndarray]:
        return self._sv.word_vector(str(idx))

    getVertexVector = get_vertex_vector

    def similarity(self, a: int, b: int) -> float:
        return self._sv.similarity(str(a), str(b))

    def verticies_nearest(self, idx: int, n: int = 10) -> List[int]:
        return [int(w) for w in self._sv.words_nearest(str(idx), n)]

    verticesNearest = verticies_nearest


class DeepWalk:
    """Reference ``DeepWalk.Builder`` surface: walkLength, windowSize,
    vectorSize, learningRate; ``fit(graph)`` runs walks → embedding training."""

    class Builder:
        def __init__(self):
            self._kw = {}
            self._walk_length = 40
            self._walks_per_vertex = 4

        def vector_size(self, n):
            self._kw["vector_length"] = int(n)
            return self

        vectorSize = vector_size

        def window_size(self, n):
            self._kw["window"] = int(n)
            return self

        windowSize = window_size

        def learning_rate(self, v):
            self._kw["learning_rate"] = float(v)
            return self

        learningRate = learning_rate

        def walk_length(self, n):
            self._walk_length = int(n)
            return self

        walkLength = walk_length

        def walks_per_vertex(self, n):
            self._walks_per_vertex = int(n)
            return self

        def seed(self, n):
            self._kw["seed"] = int(n)
            return self

        def epochs(self, n):
            self._kw["epochs"] = int(n)
            return self

        def build(self) -> "DeepWalk":
            return DeepWalk(walk_length=self._walk_length,
                            walks_per_vertex=self._walks_per_vertex,
                            **self._kw)

    @staticmethod
    def builder():
        return DeepWalk.Builder()

    def __init__(self, walk_length: int = 40, walks_per_vertex: int = 4, **kw):
        kw.setdefault("min_word_frequency", 1)
        self._sv = SequenceVectors(**kw)
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex

    @property
    def vector_size(self):
        return self._sv.vector_length

    def fit(self, graph: Graph, walk_iterator: Optional[RandomWalkIterator] = None
            ) -> GraphVectors:
        it = walk_iterator or RandomWalkIterator(
            graph, self.walk_length, seed=self._sv.seed,
            walks_per_vertex=self.walks_per_vertex)

        def provider():
            for walk in it:
                yield [str(v) for v in walk]

        self._sv.fit(provider)
        return GraphVectors(self._sv, graph)
