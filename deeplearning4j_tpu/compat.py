"""jax version-compatibility shims.

The framework targets current jax APIs, but deployment containers pin older
runtimes (the CI floor is jax 0.4.x). Every renamed/moved symbol the
codebase relies on resolves here, in ONE place, so call sites stay written
against the modern names:

- ``shard_map``: top-level ``jax.shard_map`` (new) vs
  ``jax.experimental.shard_map.shard_map`` (0.4.x); the new ``check_vma``
  kwarg maps onto the old ``check_rep``.
- ``enable_x64``: ``jax.enable_x64`` context manager (new) vs
  ``jax.experimental.enable_x64`` (0.4.x).
- ``set_cpu_devices``: ``jax_num_cpu_devices`` config (new) vs the
  ``--xla_force_host_platform_device_count`` XLA flag (0.4.x). Must run
  before the backend initializes, like both underlying mechanisms.
"""
from __future__ import annotations

import os

import jax

try:
    from jax import shard_map as _shard_map
    _NEW_SHARD_MAP = True
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map
    _NEW_SHARD_MAP = False


def shard_map(f, mesh=None, in_specs=None, out_specs=None, check_vma=None,
              **kw):
    """``jax.shard_map`` with the modern signature on every supported jax."""
    if check_vma is not None:
        kw["check_vma" if _NEW_SHARD_MAP else "check_rep"] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kw)


def enable_x64(enabled: bool = True):
    """Context manager enabling 64-bit types (gradient checking)."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(enabled)
    from jax.experimental import enable_x64 as _enable_x64
    return _enable_x64(enabled)


def cost_analysis(compiled) -> dict:
    """``Compiled.cost_analysis()`` as one flat dict on every supported jax
    (0.4.x returns a one-dict-per-device list)."""
    ca = compiled.cost_analysis() or {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca)


def set_cpu_devices(n: int):
    """Configure an ``n``-device virtual CPU backend. Call before any jax
    computation (both mechanisms are read at backend initialization).

    Any inherited ``--xla_force_host_platform_device_count`` is STRIPPED
    from ``XLA_FLAGS`` first: test runners export it for their own device
    count, subprocesses inherit the environment, and a stale flag would
    either duplicate (0.4.x: relies on last-wins parsing) or fight the
    ``jax_num_cpu_devices`` config (newer jax)."""
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count=")]
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", int(n))
    except AttributeError:  # jax 0.4.x: only the XLA flag exists
        flags.append(f"--xla_force_host_platform_device_count={int(n)}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
