"""Sharded parameter-server fleet: N real server nodes + a fan-out client.

PR 1's :class:`~deeplearning4j_tpu.paramserver.server.ParameterServer` is
one TCP process holding the entire flat parameter vector — its round-robin
*virtual* shards are computed in-process, so every worker's push/pull
serializes through a single accept loop and ships full-vector-sized
frames. This module splits the shards across **real server nodes** (the
aggregation-topology fix the MPI characterization paper in PAPERS.md
names as what actually dominates distributed DNN training):

- :class:`ShardedParameterServerGroup` owns N ``ParameterServer`` nodes;
  node ``j`` holds the round-robin slice ``vec[j::N]`` of the global
  vector (the arXiv:2004.13336 cross-replica layout, now spread across
  processes instead of inside one). Supports fault injection
  (``kill``/``restart`` with snapshot restore) and **elastic rebalancing**
  (``scale_to(m)`` re-splits the merged state across a new node count).
- :class:`ShardedParameterServerClient` fans every op out **per shard in
  parallel** (one :class:`~.client.ParameterServerClient` per node, a
  shared :class:`~.client.Fanout` executor, per-client connection pools).
  Pushes split the threshold-encoded update by shard (element ``i``
  belongs to shard ``i % N`` at intra-shard index ``i // N``); pulls ride
  the **proto v3 delta wire** (``OP_PULL_DELTA``): each client keeps a
  per-shard *shadow* (the last reconstructed server state) and replays the
  server's journaled applied-update frames onto it, so a resync ships
  kilobytes of sparse frames instead of the full vector — bit-exact with
  a dense pull, version-negotiated down to full pulls against v1/v2
  servers.

Partial-failure semantics (never a fleet-wide stall): a dead shard node
surfaces per shard as the typed
:class:`~.client.ServerUnavailableError` after that client's retry/backoff
budget, flips the shard into a down-backoff window (fail-fast, no repeated
budget burn), and records a ``shard_server_down`` flight event. Pulls
continue on the surviving shards (the dead shard serves its shadow —
bounded staleness per shard); a failed push hands the shard's decoded
quantized mass back to the caller (``push_encoded``'s second return), so
the training master reinjects it into the accumulator residual and no
update mass is ever lost. See docs/PARALLELISM.md "Sharded parameter-server
fleet" for the topology diagram and the rebalance runbook.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..monitor import get_flight_recorder, get_registry
from ..monitor.lockwatch import make_lock
from ..parallel.accumulation import (deserialize_encoded, serialize_encoded,
                                     threshold_decode)
from .client import (Fanout, ParameterServerClient, ParameterServerError,
                     ServerUnavailableError)
from .metrics import ParamServerMetrics
from .server import DELTA_FRAMES, DELTA_FRESH, DELTA_FULL, ParameterServer

log = logging.getLogger(__name__)

__all__ = ["ShardedParameterServerGroup", "ShardedParameterServerClient",
           "parse_addresses", "shard_slice_length"]


def parse_addresses(spec: Union[str, Sequence[str]]) -> List[str]:
    """Normalize a server spec — ``"h:p1,h:p2"`` (Builder-friendly) or a
    list/tuple of addresses — into the address list the fan-out client
    runs over. Order IS the shard assignment: address ``j`` holds shard
    ``j`` (the slice ``vec[j::N]``)."""
    if isinstance(spec, str):
        addrs = [a.strip() for a in spec.split(",") if a.strip()]
    else:
        addrs = [str(a) for a in spec]
    if not addrs:
        raise ValueError("no parameter-server addresses given")
    return addrs


def shard_slice_length(shard: int, n: int, num_shards: int) -> int:
    """Element count of round-robin shard ``shard`` of a length-``n``
    vector (``vec[shard::num_shards]``)."""
    return len(range(int(shard), int(n), int(num_shards)))


class ShardedParameterServerGroup:
    """Own N :class:`~.server.ParameterServer` nodes, one round-robin slice
    each. In-process spawning is the tier-1 shape — every node is a REAL
    TCP server on its own port and only the process boundary is elided
    (the same loopback contract as ``ParameterServer(port=0)``);
    production runs one node per host and fronts them with the same
    client by handing :class:`ShardedParameterServerClient` the address
    list instead of a group.

    ``threshold``/``journal`` pass through to every node. ``kill(j)``
    stops node ``j`` and returns ``(port, snapshot)`` for a later
    ``restart(j, snapshot)`` (fault injection + the crash-recovery path);
    ``scale_to(m)`` is the elastic-membership seam (see the rebalance
    runbook in docs/PARALLELISM.md).
    """

    def __init__(self, num_servers: int = 2, host: str = "127.0.0.1",
                 threshold: float = 0.0, journal: int = 256,
                 ports: Optional[Sequence[int]] = None, tracer=None,
                 fleet=None):
        if num_servers < 1:
            raise ValueError(f"num_servers must be >= 1, got {num_servers}")
        self.host = host
        self.threshold = float(threshold)
        self.journal = int(journal)
        self._tracer = tracer
        self._fleet = fleet
        self._last_snapshots: Dict[int, tuple] = {}
        self.servers: List[ParameterServer] = [
            self._spawn(j, port=(ports[j] if ports else 0))
            for j in range(int(num_servers))]
        get_flight_recorder().record(
            "shard_group_start", servers=self.num_servers,
            addresses=list(self.addresses))

    def _spawn(self, shard: int, port: int = 0,
               restore: Optional[tuple] = None) -> ParameterServer:
        return ParameterServer(
            host=self.host, port=port, threshold=self.threshold,
            journal=self.journal, restore=restore, shard_label=str(shard),
            tracer=self._tracer, fleet=self._fleet)

    # --------------------------------------------------------- addressing
    @property
    def num_servers(self) -> int:
        return len(self.servers)

    @property
    def addresses(self) -> List[str]:
        """Per-shard addresses, shard order (a stopped node keeps its
        address — the restart path rebinds the same port)."""
        return [s.address for s in self.servers]

    @property
    def address(self) -> str:
        """Comma-joined form for
        ``ParameterServerTrainingMaster.Builder(group.address)``."""
        return ",".join(self.addresses)

    # ------------------------------------------------------- fault / state
    def kill(self, shard: int) -> Tuple[int, tuple]:
        """Fault injection: stop node ``shard`` (its clients start seeing
        ``ServerUnavailableError``) and return ``(port, snapshot)`` so
        :meth:`restart` can resurrect it with state and version numbering
        intact."""
        srv = self.servers[shard]
        snap = srv.snapshot()
        port = srv.port
        srv.stop()
        # latch for the control plane's auto-restart path: a policy
        # reacting to shard_server_down asks last_snapshot(shard) instead
        # of threading the kill() return value through the alert loop
        self._last_snapshots[int(shard)] = snap
        get_flight_recorder().record(
            "shard_server_leave", shard=int(shard), address=srv.address,
            reason="killed")
        return port, snap

    def last_snapshot(self, shard: int) -> Optional[tuple]:
        """The most recent snapshot latched for ``shard`` (by
        :meth:`kill`), or None — the control plane's restart-from-latest
        source. A None means a cold restart (empty journal, clients
        resync DELTA_FULL once), which is still correct, just slower."""
        return self._last_snapshots.get(int(shard))

    def restart(self, shard: int, snapshot: Optional[tuple] = None,
                port: Optional[int] = None) -> ParameterServer:
        """Resurrect node ``shard`` on its old port (clients' retry loops
        reconnect transparently; their next delta pull resyncs DELTA_FULL
        once — the restarted journal is empty — then rides frames again)."""
        old = self.servers[shard]
        srv = self._spawn(shard, port=(old.port if port is None else port),
                          restore=snapshot)
        self.servers[shard] = srv
        get_flight_recorder().record(
            "shard_server_join", shard=int(shard), address=srv.address,
            restored=snapshot is not None)
        return srv

    def assemble(self) -> Tuple[List[int], np.ndarray,
                                Optional[np.ndarray]]:
        """(per-node versions, merged full vector, merged residual) from
        live node snapshots — the round-robin reassembly ``scale_to`` and
        group-level checkpointing build on."""
        snaps = [s.snapshot() for s in self.servers]
        n_total = sum(int(vec.size) for _, vec, _ in snaps)
        full = np.zeros(n_total, np.float32)
        res = np.zeros(n_total, np.float32)
        has_res = False
        for j, (_, vec, residual) in enumerate(snaps):
            full[j::self.num_servers] = vec
            if residual is not None:
                res[j::self.num_servers] = residual
                has_res = True
        return ([int(v) for v, _, _ in snaps], full,
                res if has_res else None)

    def scale_to(self, num_servers: int) -> List[str]:
        """Elastic rebalance: re-split the CURRENT merged state (values
        AND server-side residuals) across ``num_servers`` nodes, growing or
        shrinking the fleet. Every surviving node's version continues from
        ``max(old versions) + 1`` so rejoining clients' staleness
        bookkeeping never runs backwards; journals clear (the layout
        changed — no frame replay crosses a reshard), so the first delta
        pull after a rebalance is a full resync per shard. Callers must
        ``remap(...)`` their clients afterwards — in-flight pushes against
        the old layout are the usual async-SGD at-least-once noise. Returns
        the new address list."""
        num_servers = int(num_servers)
        if num_servers < 1:
            raise ValueError(f"num_servers must be >= 1, got {num_servers}")
        if num_servers == self.num_servers:
            return self.addresses
        versions, full, res = self.assemble()
        fr = get_flight_recorder()
        old_n = self.num_servers
        if num_servers > old_n:
            for j in range(old_n, num_servers):
                self.servers.append(self._spawn(j))
                fr.record("shard_server_join", shard=j,
                          address=self.servers[j].address, restored=False)
        else:
            for j in range(old_n - 1, num_servers - 1, -1):
                srv = self.servers.pop(j)
                srv.stop()
                fr.record("shard_server_leave", shard=j,
                          address=srv.address, reason="scale_down")
        ver = max(versions) + 1 if versions else 1
        for j, srv in enumerate(self.servers):
            values = np.ascontiguousarray(full[j::num_servers], np.float32)
            residual = (None if res is None else
                        np.ascontiguousarray(res[j::num_servers],
                                             np.float32))
            # direct state swap under the node's own lock (same-package
            # surgery, equivalent to restart(restore=...) without dropping
            # the port or the live connections)
            with srv._lock:
                srv._store(values)
                srv._residual = residual
                srv._version = ver
                srv._journal.clear()
        fr.record("shard_group_rebalance", servers=num_servers,
                  was=old_n, version=int(ver),
                  addresses=list(self.addresses))
        return self.addresses

    def stop(self):
        for srv in self.servers:
            srv.stop()
        get_flight_recorder().record("shard_group_stop",
                                     servers=self.num_servers)

    close = stop

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


class ShardedParameterServerClient:
    """Fan-out client over N shard servers: the same op surface as
    :class:`~.client.ParameterServerClient` where that makes sense, with
    versions as PER-SHARD lists. All sub-clients share ONE
    :class:`~.metrics.ParamServerMetrics` (so ``metrics.snapshot()``
    aggregates the whole fan-out — a "push" there counts per shard server
    touched) and one :class:`~.client.Fanout` executor.

    ``delta=True`` (default) rides the proto v3 delta wire wherever a
    server advertises it; servers that negotiate < 3 silently fall back to
    version-check + full pulls per shard. ``down_backoff`` is the fail-fast
    window after a shard exhausts its retry budget.
    """

    def __init__(self, addresses: Union[str, Sequence[str]],
                 staleness: int = 0, delta: bool = True,
                 max_retries: int = 5, backoff: float = 0.05,
                 backoff_max: float = 2.0, jitter: float = 0.25,
                 timeout: float = 30.0, pool_size: int = 2,
                 worker_id: Optional[str] = None, tracer=None,
                 down_backoff: float = 1.0,
                 metrics: Optional[ParamServerMetrics] = None,
                 push_delay_s: float = 0.0):
        # compile-once fleet seam (compilecache/): constructing this
        # client is what a worker does on join, REJOIN after a death, and
        # remap after scale_to — exactly the moments its next fit would
        # recompile. With DL4J_TPU_COMPILE_CACHE_DIR shared fleet-wide
        # those become disk hits; no-op when the dial is unset
        from ..compilecache.cache import maybe_enable
        maybe_enable()
        self.addresses = parse_addresses(addresses)
        self.address = ",".join(self.addresses)
        self.staleness = int(staleness)
        self.delta = bool(delta)
        self.down_backoff = float(down_backoff)
        self.metrics = metrics or ParamServerMetrics(role="client")
        self._client_kw = dict(
            staleness=staleness, max_retries=max_retries, backoff=backoff,
            backoff_max=backoff_max, jitter=jitter, timeout=timeout,
            pool_size=pool_size, push_delay_s=push_delay_s)
        self.clients = [ParameterServerClient(
            a, metrics=self.metrics, worker_id=worker_id, tracer=tracer,
            shard=j, **self._client_kw)
            for j, a in enumerate(self.addresses)]
        self.worker_id = self.clients[0].worker_id
        self.tracer = self.clients[0].tracer
        self._fan = Fanout(min(2 * self.num_servers, 16))
        self._state_lock = make_lock("ShardedParameterServerClient._state_lock")
        self._shadow: List[Optional[np.ndarray]] = [None] * self.num_servers
        #: per-shard version of the shadow (the server state the client
        #: can reconstruct) — distinct from the MASTER's local_version,
        #: which may run ahead under count_own_pushes=False
        self.versions: List[int] = [0] * self.num_servers
        self._down_until: List[float] = [0.0] * self.num_servers
        self._thresholds: List[Optional[float]] = [None] * self.num_servers
        self._n = 0

    # ------------------------------------------------------------ plumbing
    @property
    def num_servers(self) -> int:
        return len(self.clients)

    def _skip_down(self, shard: int) -> bool:
        """True while ``shard`` sits inside its down-backoff window — ops
        fail fast instead of re-burning the retry budget every step."""
        with self._state_lock:
            until = self._down_until[shard]
        return until > 0.0 and time.monotonic() < until

    def _count_unavailable(self, shard: int):
        get_registry().counter(
            "paramserver_shard_unavailable_total",
            "per-shard ops lost to a down shard server", role="client",
            shard=str(shard)).inc()

    def _mark_down(self, shard: int, err: BaseException):
        now = time.monotonic()
        with self._state_lock:
            first = self._down_until[shard] <= 0.0
            self._down_until[shard] = now + self.down_backoff
        if first:
            get_flight_recorder().record(
                "shard_server_down", worker=self.worker_id,
                shard=int(shard), server=self.addresses[shard],
                error=str(err))
            log.warning("shard server %d (%s) unavailable: %s",
                        shard, self.addresses[shard], err)
        self._count_unavailable(shard)

    def _mark_up(self, shard: int):
        with self._state_lock:
            was_down = self._down_until[shard] > 0.0
            self._down_until[shard] = 0.0
        if was_down:
            get_flight_recorder().record(
                "shard_server_restored", worker=self.worker_id,
                shard=int(shard), server=self.addresses[shard])
            log.info("shard server %d (%s) reachable again", shard,
                     self.addresses[shard])

    def _per_shard(self, fn, shards: Optional[Sequence[int]] = None,
                   ignore_backoff: bool = False) -> Dict[int, object]:
        """Run ``fn(shard, client)`` for every (or the given) shard on the
        fan-out executor. Returns ``{shard: result-or-
        ServerUnavailableError}`` — unavailability is a per-shard VALUE
        (the partial-failure contract), while typed server rejections
        (:class:`ParameterServerError`) raise through: retrying or
        degrading can't fix a protocol error. ``ignore_backoff`` bypasses
        the down-window fail-fast (the join/seed path: a deliberate
        reconnect right after a restart must actually try the wire)."""
        shards = (list(range(self.num_servers)) if shards is None
                  else list(shards))

        def call(j: int):
            if not ignore_backoff and self._skip_down(j):
                self._count_unavailable(j)  # a lost op, just a cheap one
                return ServerUnavailableError(
                    f"shard {j} ({self.addresses[j]}) in its down-backoff "
                    f"window")
            try:
                out = fn(j, self.clients[j])
            except ServerUnavailableError as e:
                self._mark_down(j, e)
                return e
            self._mark_up(j)
            return out

        results = self._fan.run([(lambda j=j: call(j)) for j in shards])
        return dict(zip(shards, results))

    def _server_threshold(self, shard: int) -> float:
        """The node's server-side residual threshold (cached after the
        first successful stats). A residual-merging node (> 0) must see
        EVERY push — even an empty sub-frame — so its residual rule runs
        on the same rounds a dense single server's would. The probe obeys
        the same down-backoff discipline as every other per-shard op: a
        down node answers 0.0 fast (skip the empty frame — degraded
        anyway) instead of burning the retry budget each push, and the
        probe failure itself opens the down window."""
        with self._state_lock:
            thr = self._thresholds[shard]
        if thr is not None:
            return thr
        if self._skip_down(shard):
            return 0.0
        try:
            thr = float(self.clients[shard].stats().get("threshold", 0.0))
        except ServerUnavailableError as e:
            self._mark_down(shard, e)
            return 0.0  # uncached: re-probe once the node answers
        except (ConnectionError, ParameterServerError) as e:
            log.debug("threshold probe for shard %d failed: %s", shard, e)
            return 0.0
        self._mark_up(shard)
        with self._state_lock:
            self._thresholds[shard] = thr
        return thr

    def negotiate(self) -> int:
        """Fleet protocol floor: the minimum negotiated version across
        reachable shard servers (1 when none answer)."""
        res = self._per_shard(lambda j, c: c.negotiate())
        versions = [v for v in res.values() if not isinstance(v, Exception)]
        return min(versions) if versions else 1

    # ----------------------------------------------------------------- ops
    def init_params(self, vec: np.ndarray) -> Tuple[List[int], bool]:
        """Seed every shard server iff it holds nothing yet (the join
        path). Returns ``(versions, created)``; ``created`` is True only
        when EVERY shard was seeded by this call — any pre-seeded shard
        means the caller should pull the merged state (a concurrent-join
        race can leave a mixed seed behind; the pull reconciles it, and
        async SGD absorbs the one-step noise). A down shard here raises:
        a partial seed would strand inconsistent state."""
        vec = np.ascontiguousarray(vec, np.float32)
        self._n = int(vec.size)
        N = self.num_servers
        res = self._per_shard(lambda j, c: c.init_params(vec[j::N]),
                              ignore_backoff=True)
        versions: List[int] = []
        created: List[bool] = []
        for j in range(N):
            out = res[j]
            if isinstance(out, Exception):
                raise ServerUnavailableError(
                    f"shard {j} ({self.addresses[j]}) unavailable during "
                    f"init: {out}") from out
            v, flag = out
            versions.append(int(v))
            created.append(bool(flag))
        with self._state_lock:
            for j in range(N):
                # the shadow is only trustworthy where WE seeded; a
                # pre-seeded shard's shadow arrives with the caller's pull
                self._shadow[j] = (np.array(vec[j::N], np.float32)
                                   if created[j] else None)
                self.versions[j] = versions[j] if created[j] else 0
        if any(created) and not all(created):
            log.warning("mixed init across shard servers (a concurrent "
                        "worker raced the seed on %d/%d shards); pulling "
                        "the merged state reconciles it",
                        sum(created), N)
        return versions, all(created)

    def set_params(self, vec: np.ndarray) -> List[int]:
        """Unconditional overwrite of every shard. A down shard raises —
        like init, a partial overwrite would strand mixed state."""
        vec = np.ascontiguousarray(vec, np.float32)
        self._n = int(vec.size)
        N = self.num_servers
        res = self._per_shard(lambda j, c: c.set_params(vec[j::N]),
                              ignore_backoff=True)
        versions: List[int] = []
        for j in range(N):
            out = res[j]
            if isinstance(out, Exception):
                raise ServerUnavailableError(
                    f"shard {j} ({self.addresses[j]}) unavailable during "
                    f"set_params: {out}") from out
            versions.append(int(out))
        with self._state_lock:
            for j in range(N):
                self._shadow[j] = np.array(vec[j::N], np.float32)
                self.versions[j] = versions[j]
        return versions

    def push_encoded(self, encoded
                     ) -> Tuple[List[Optional[int]], Optional[np.ndarray]]:
        """Split one threshold-encoded full-vector update by shard (element
        ``i`` → shard ``i % N`` at intra-shard index ``i // N``) and push
        the sub-frames in parallel. Returns ``(versions, failed_mass)``:

        - ``versions[j]`` — node ``j``'s version after its push, ``None``
          when nothing was sent there (empty sub-frame against a
          non-residual server) or the node was down;
        - ``failed_mass`` — the decoded update the down shard(s) never
          received, as a dense full-length vector, or ``None``. Callers
          feed it back into their accumulator residual
          (``EncodedGradientsAccumulator.reinject``) so the mass re-rides
          the next encode instead of vanishing.
        """
        idx, signs, thr, n = encoded
        idx = np.ascontiguousarray(idx, np.int32)
        signs = np.asarray(signs)
        # float32 "signs" are an exact frame (lossless accumulator) and
        # must keep their dtype through the split — serialize_encoded
        # branches on it
        exact = signs.dtype == np.float32
        signs = np.ascontiguousarray(signs,
                                     np.float32 if exact else np.int8)
        n = int(n)
        self._n = n
        N = self.num_servers
        owner = idx % N
        frames: Dict[int, bytes] = {}
        masks: Dict[int, np.ndarray] = {}
        for j in range(N):
            m = owner == j
            if not m.any() and self._server_threshold(j) <= 0.0:
                # nothing for this shard and no server-side residual rule
                # to run — skip the round trip (and the version bump)
                continue
            masks[j] = m
            frames[j] = serialize_encoded(
                ((idx[m] // N).astype(np.int32), signs[m], thr,
                 shard_slice_length(j, n, N)))
        if not frames:
            return [None] * N, None
        res = self._per_shard(lambda j, c: c.push_update(frames[j]),
                              shards=sorted(frames))
        versions: List[Optional[int]] = [None] * N
        failed_mass: Optional[np.ndarray] = None
        for j, out in res.items():
            if isinstance(out, Exception):
                m = masks[j]
                if m.any():
                    if failed_mass is None:
                        failed_mass = np.zeros(n, np.float32)
                    # what decode(frame) would have applied: ±thr at the
                    # encoded indices (the raw values for an exact frame) —
                    # hand it back for residual reinjection
                    failed_mass[idx[m]] += (
                        signs[m] if exact
                        else signs[m].astype(np.float32) * np.float32(thr))
            else:
                versions[j] = int(out)
        return versions, failed_mass

    def pull(self) -> Tuple[List[int], np.ndarray]:
        """Full merged pull: every shard in parallel, reassembled. A down
        shard serves its shadow (last reconstructed state — the bounded-
        staleness degraded read); only a down shard with NO shadow raises,
        because then no coherent vector exists to hand back."""
        N = self.num_servers
        res = self._per_shard(lambda j, c: c.pull())
        parts: List[Optional[np.ndarray]] = [None] * N
        versions = [0] * N
        for j in range(N):
            out = res[j]
            if isinstance(out, Exception):
                with self._state_lock:
                    shadow = self._shadow[j]
                    ver = self.versions[j]
                if shadow is None:
                    raise ServerUnavailableError(
                        f"shard {j} ({self.addresses[j]}) unavailable and "
                        f"no local copy exists yet: {out}") from out
                parts[j], versions[j] = shadow, ver
            else:
                versions[j] = int(out[0])
                part = np.array(out[1], np.float32)
                parts[j] = part
                with self._state_lock:
                    self._shadow[j] = part
                    self.versions[j] = versions[j]
        n = sum(int(p.size) for p in parts)
        vec = np.empty(n, np.float32)
        for j in range(N):
            vec[j::N] = parts[j]
        self._n = n
        return versions, vec

    def _pull_shard(self, j: int, client: ParameterServerClient,
                    since: int) -> Tuple[int, Optional[np.ndarray]]:
        """One shard's bounded-staleness resync. Returns
        ``(server_version, values-or-None)`` — None means within the
        staleness bound. The delta wire needs a shadow base: frames replay
        from the SHADOW's version, while the staleness decision runs
        against the caller's ``since`` (which may be ahead of the shadow
        under count_own_pushes=False), so the slack sent to the server is
        ``staleness + (since - shadow_version)``."""
        since = int(since)
        with self._state_lock:
            shadow = self._shadow[j]
            base_ver = self.versions[j]
        if self.delta and shadow is not None and client.negotiate() >= 3:
            slack = self.staleness + max(since - base_ver, 0)
            ver, mode, body = client.pull_delta(base_ver, slack)
            if mode == DELTA_FRESH:
                return ver, None
            if mode == DELTA_FULL:
                part = np.array(body, np.float32)
            else:
                part = shadow.copy()
                for frame in body:
                    fi, fs, fthr, fn = deserialize_encoded(frame)
                    if fn != part.size:
                        raise ParameterServerError(
                            f"shard {j} delta frame length {fn} != local "
                            f"copy {part.size}")
                    part -= threshold_decode(fi, fs, fthr, (fn,))
            with self._state_lock:
                self._shadow[j] = part
                self.versions[j] = int(ver)
            return int(ver), part.copy()
        # v1/v2 fallback (or no shadow yet): version round trip + full pull
        ver, _ = client.server_version()
        if since <= ver and ver - since <= self.staleness \
                and shadow is not None:
            return ver, None
        ver, part = client.pull()
        part = np.array(part, np.float32)
        with self._state_lock:
            self._shadow[j] = part
            self.versions[j] = int(ver)
        return int(ver), part.copy()

    def pull_if_stale(self, local_versions: Sequence[int]
                      ) -> Optional[Tuple[List[int],
                                          Dict[int, np.ndarray]]]:
        """Per-shard bounded staleness: resync ONLY the shards whose server
        ran more than ``staleness`` versions past ``local_versions`` (one
        delta round trip each, in parallel). Returns ``None`` when every
        reachable shard is within the bound; ``(new_versions, vector)``
        (a full assembled ndarray) when EVERY shard refreshed — the
        staleness=0 hot path, sparing the caller a full flatten of its
        local state; else ``(new_versions, {shard: values})`` — the caller
        scatters only the refreshed slices (``vec[j::N] = values``),
        keeping its own optimistic local state on the fresh ones. Down
        shards are skipped (their staleness keeps growing — the survivors
        never stall)."""
        local = [int(v) for v in local_versions]
        if len(local) != self.num_servers:
            raise ValueError(
                f"{len(local)} local versions for {self.num_servers} "
                f"shard servers (remap out of sync?)")
        res = self._per_shard(
            lambda j, c: self._pull_shard(j, c, local[j]))
        new_versions = list(local)
        changed: Dict[int, np.ndarray] = {}
        reg = get_registry()
        for j in range(self.num_servers):
            out = res[j]
            if isinstance(out, Exception):
                continue  # down shard: survivors carry on
            ver, values = out
            reg.gauge("paramserver_shard_staleness",
                      "versions the local copy trails the shard server by",
                      role="client", shard=str(j)).set(
                          max(ver - local[j], 0))
            if values is None:
                self.metrics.add("staleness_hits")
                continue
            changed[j] = values
            new_versions[j] = ver
        if not changed:
            return None
        if len(changed) == self.num_servers:
            n = sum(int(v.size) for v in changed.values())
            vec = np.empty(n, np.float32)
            for j, values in changed.items():
                vec[j::self.num_servers] = values
            return new_versions, vec
        return new_versions, changed

    def server_version(self) -> Tuple[List[int], int]:
        """Per-shard versions + total element count (parallel)."""
        res = self._per_shard(lambda j, c: c.server_version())
        versions, total = [], 0
        for j in range(self.num_servers):
            out = res[j]
            if isinstance(out, Exception):
                raise out
            versions.append(int(out[0]))
            total += int(out[1])
        return versions, total

    def stats(self) -> List[dict]:
        """Per-shard OP_STATS snapshots; a down shard's slot carries
        ``{"error": ...}`` instead (partial visibility beats none)."""
        res = self._per_shard(lambda j, c: c.stats())
        return [res[j] if not isinstance(res[j], Exception)
                else {"error": str(res[j]), "shard": str(j)}
                for j in range(self.num_servers)]

    def send_telemetry(self, registry=None, tracer=None,
                       flight_events=None) -> bool:
        """Fleet telemetry ships to shard server 0 — the group's
        aggregation point (its process serves ``GET /fleet``)."""
        return self.clients[0].send_telemetry(
            registry=registry, tracer=tracer, flight_events=flight_events)

    # ------------------------------------------------------------- elastic
    def remap(self, addresses: Union[str, Sequence[str]]):
        """Elastic membership: rebind to a new shard-server set (after a
        group ``scale_to`` or an address change). Shadows and versions
        reset — the next pull is a full per-shard resync against the new
        layout. Flight event ``client_remap`` closes the audit trail the
        group's join/leave events open."""
        addrs = parse_addresses(addresses)
        old_clients = self.clients
        self.clients = [ParameterServerClient(
            a, metrics=self.metrics, worker_id=self.worker_id,
            tracer=self.tracer, shard=j, **self._client_kw)
            for j, a in enumerate(addrs)]
        self.addresses = addrs
        self.address = ",".join(addrs)
        with self._state_lock:
            self._shadow = [None] * len(addrs)
            self.versions = [0] * len(addrs)
            self._down_until = [0.0] * len(addrs)
            self._thresholds = [None] * len(addrs)
        for c in old_clients:
            c.close()
        get_flight_recorder().record(
            "client_remap", worker=self.worker_id, servers=len(addrs),
            addresses=list(addrs))

    def close(self):
        for c in self.clients:
            c.close()
        self._fan.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
